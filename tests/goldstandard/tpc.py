"""TPC-H / TPC-DS schema registration + representative query plans.

The reference registers all TPC-DS tables as schema-only external tables and
diffs normalized physical plans against approved golden files
(goldstandard/PlanStabilitySuite.scala:84, TPCDSBase.scala:1-570). Here the
tables are registered as deterministic tiny parquet datasets (fixed seed,
fixed content) so the rewrite rules, rankers, and hybrid-scan candidacy run
exactly as in production, and the *optimized logical plan* strings are the
stability surface.
"""

from __future__ import annotations

import datetime
import os
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

# ---------------------------------------------------------------------------
# Schemas. Canonical column subsets (full column lists for the queried
# tables; types follow the spec: identifiers int64, money float64,
# dates date32, flags dictionary strings).
# ---------------------------------------------------------------------------

_EPOCH = datetime.date(1970, 1, 1)


def _dates(rng, n, lo=8000, hi=11000):
    return pa.array((rng.integers(lo, hi, n)).astype(np.int32),
                    type=pa.int32()).cast(pa.date32())


def _tpch_tables(rng) -> Dict[str, pa.Table]:
    n_li, n_od, n_pt = 120, 40, 25
    return {
        "lineitem": pa.table({
            "l_orderkey": pa.array(rng.integers(0, n_od, n_li).astype(np.int64)),
            "l_partkey": pa.array(rng.integers(0, n_pt, n_li).astype(np.int64)),
            "l_quantity": pa.array(rng.integers(1, 50, n_li).astype(np.int64)),
            "l_extendedprice": pa.array(np.round(rng.uniform(900, 105000, n_li), 2)),
            "l_discount": pa.array(np.round(rng.uniform(0, 0.1, n_li), 2)),
            "l_tax": pa.array(np.round(rng.uniform(0, 0.08, n_li), 2)),
            "l_returnflag": pa.array(rng.choice(["A", "N", "R"], n_li)),
            "l_linestatus": pa.array(rng.choice(["O", "F"], n_li)),
            "l_shipdate": _dates(rng, n_li),
            "l_shipmode": pa.array(rng.choice(["MAIL", "SHIP", "AIR", "TRUCK"], n_li)),
        }),
        "orders": pa.table({
            "o_orderkey": pa.array(np.arange(n_od, dtype=np.int64)),
            "o_custkey": pa.array(rng.integers(0, 20, n_od).astype(np.int64)),
            "o_orderstatus": pa.array(rng.choice(["O", "F", "P"], n_od)),
            "o_totalprice": pa.array(np.round(rng.uniform(1000, 400000, n_od), 2)),
            "o_orderdate": _dates(rng, n_od),
            "o_orderpriority": pa.array(rng.choice(
                ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"], n_od)),
            "o_shippriority": pa.array(np.zeros(n_od, dtype=np.int32)),
        }),
        "part": pa.table({
            "p_partkey": pa.array(np.arange(n_pt, dtype=np.int64)),
            "p_brand": pa.array(rng.choice(["Brand#11", "Brand#23", "Brand#45"], n_pt)),
            "p_container": pa.array(rng.choice(["SM BOX", "MED BOX", "LG BOX"], n_pt)),
            "p_size": pa.array(rng.integers(1, 50, n_pt).astype(np.int64)),
        }),
    }


# Dimension cardinalities shared by both TPC-DS fact generators: store_sales
# foreign keys must stay in range of the dimensions _tpcds_tables builds.
N_DD, N_CU, N_ST = 60, 30, 6


def _tpcds_tables(rng) -> Dict[str, pa.Table]:
    n_sr, n_dd, n_cu, n_st = 90, N_DD, N_CU, N_ST
    return {
        "store_returns": pa.table({
            "sr_returned_date_sk": pa.array(rng.integers(0, n_dd, n_sr).astype(np.int64)),
            "sr_customer_sk": pa.array(rng.integers(0, n_cu, n_sr).astype(np.int64)),
            "sr_store_sk": pa.array(rng.integers(0, n_st, n_sr).astype(np.int64)),
            "sr_return_amt": pa.array(np.round(rng.uniform(1, 2000, n_sr), 2)),
        }),
        "date_dim": pa.table({
            "d_date_sk": pa.array(np.arange(n_dd, dtype=np.int64)),
            "d_year": pa.array((2000 + (np.arange(n_dd) % 3)).astype(np.int64)),
            "d_moy": pa.array((1 + (np.arange(n_dd) % 12)).astype(np.int64)),
        }),
        "customer": pa.table({
            "c_customer_sk": pa.array(np.arange(n_cu, dtype=np.int64)),
            "c_customer_id": pa.array([f"C{i:08d}" for i in range(n_cu)]),
        }),
        "store": pa.table({
            "s_store_sk": pa.array(np.arange(n_st, dtype=np.int64)),
            "s_state": pa.array(rng.choice(["TN", "CA"], n_st)),
        }),
    }


def _tpcds_sales_tables(rng) -> Dict[str, pa.Table]:
    """The store_sales/item fact/dim pair backing the q42/q52/q55 family.
    Separate rng seed so the original tables' draws (and the pre-existing
    golden files) stay byte-stable."""
    n_ss, n_it, n_dd, n_cu, n_st = 150, 20, N_DD, N_CU, N_ST
    return {
        "store_sales": pa.table({
            "ss_sold_date_sk": pa.array(rng.integers(0, n_dd, n_ss).astype(np.int64)),
            "ss_item_sk": pa.array(rng.integers(0, n_it, n_ss).astype(np.int64)),
            "ss_customer_sk": pa.array(rng.integers(0, n_cu, n_ss).astype(np.int64)),
            "ss_store_sk": pa.array(rng.integers(0, n_st, n_ss).astype(np.int64)),
            "ss_quantity": pa.array(rng.integers(1, 100, n_ss).astype(np.int64)),
            "ss_sales_price": pa.array(np.round(rng.uniform(1, 300, n_ss), 2)),
        }),
        "item": pa.table({
            "i_item_sk": pa.array(np.arange(n_it, dtype=np.int64)),
            "i_brand": pa.array(rng.choice(
                ["amalgimporto #1", "edu packscholar #2", "scholarbrand #3"], n_it)),
            "i_category": pa.array(rng.choice(["Music", "Books", "Sports"], n_it)),
            "i_current_price": pa.array(np.round(rng.uniform(1, 100, n_it), 2)),
        }),
    }


def _orders_nested_table(rng) -> pa.Table:
    """Struct-typed orders analogue: nested leaves flatten to dotted names
    (`detail.price`, `detail.ship.days`) end-to-end — the golden surface
    for the resolver's nested-column path (ref CreateIndexNestedTest)."""
    n = 80
    price = np.round(rng.uniform(10, 900, n), 2)
    days = rng.integers(1, 30, n).astype(np.int64)
    return pa.table({
        "no_key": pa.array(np.arange(n, dtype=np.int64)),
        "detail": pa.array([
            {"price": float(price[i]), "ship": {"days": int(days[i])}}
            for i in range(n)]),
    })


def _web_events_table(rng) -> pa.Table:
    """Date-sorted event fact written as FOUR files (see register_tables):
    each file covers a date quarter, so per-file MinMax sketches prune —
    the data-skipping golden surface."""
    n = 200
    dates = np.sort(rng.integers(9000, 9400, n)).astype(np.int32)
    return pa.table({
        "we_event_date": pa.array(dates, type=pa.int32()).cast(pa.date32()),
        "we_user_sk": pa.array(rng.integers(0, 30, n).astype(np.int64)),
        "we_amount": pa.array(np.round(rng.uniform(1, 500, n), 2)),
    })


def register_tables(session, root: str) -> Dict[str, "object"]:
    """Write the deterministic datasets (once per directory) and return
    name → DataFrame."""
    rng = np.random.default_rng(42)
    tables = {**_tpch_tables(rng), **_tpcds_tables(rng),
              **_tpcds_sales_tables(np.random.default_rng(7))}
    dfs = {}
    for name, tbl in tables.items():
        d = os.path.join(root, name)
        if not os.path.isdir(d):
            os.makedirs(d)
            pq.write_table(tbl, os.path.join(d, "part0.parquet"))
        dfs[name] = session.read.parquet(d)
    # web_events: 4 date-range part files (sketch-prunable layout).
    we = _web_events_table(np.random.default_rng(13))
    d = os.path.join(root, "web_events")
    if not os.path.isdir(d):
        os.makedirs(d)
        step = we.num_rows // 4
        for i in range(4):
            lo = i * step
            hi = (i + 1) * step if i < 3 else we.num_rows
            pq.write_table(we.slice(lo, hi - lo),
                           os.path.join(d, f"part{i}.parquet"))
    dfs["web_events"] = session.read.parquet(d)
    # orders_nested: struct leaves → dotted flat columns.
    on = _orders_nested_table(np.random.default_rng(23))
    d = os.path.join(root, "orders_nested")
    if not os.path.isdir(d):
        os.makedirs(d)
        pq.write_table(on, os.path.join(d, "part0.parquet"))
    dfs["orders_nested"] = session.read.parquet(d)
    # A temp view over filtered lineitem: rewrites must reach through
    # views (ref E2E covers views; here the PLAN is the golden surface).
    session.create_temp_view(
        "recent_lineitem",
        dfs["lineitem"],
        replace=True)
    dfs["__view__recent_lineitem"] = session.table("recent_lineitem")
    return dfs


# ---------------------------------------------------------------------------
# Indexes the enabled suite creates (covering the query set below).
# ---------------------------------------------------------------------------

def index_configs():
    from hyperspace_tpu.api import (DataSkippingIndexConfig, IndexConfig,
                                    MinMaxSketch)
    return [
        DataSkippingIndexConfig("we_skip",
                                [MinMaxSketch("we_event_date")]),
        # Nested-leaf covering index (dotted flat names end-to-end).
        IndexConfig("on_days_idx", ["detail.ship.days"],
                    ["detail.price", "no_key"]),
        IndexConfig("li_ok_idx", ["l_orderkey"],
                    ["l_extendedprice", "l_discount", "l_shipdate"]),
        IndexConfig("od_ok_idx", ["o_orderkey"],
                    ["o_custkey", "o_orderdate", "o_shippriority"]),
        IndexConfig("li_ship_idx", ["l_shipdate"],
                    ["l_discount", "l_quantity", "l_extendedprice"]),
        IndexConfig("sr_cust_idx", ["sr_customer_sk"],
                    ["sr_store_sk", "sr_return_amt", "sr_returned_date_sk"]),
        IndexConfig("li_pk_idx", ["l_partkey"], ["l_quantity"]),
        # store_sales/item pair: both join sides indexed on the q42/q52/q55
        # join keys so the JoinIndexRule's compatible-pair search has real
        # candidates on the new fact table.
        IndexConfig("ss_item_idx", ["ss_item_sk"],
                    ["ss_sold_date_sk", "ss_store_sk", "ss_sales_price",
                     "ss_quantity"]),
        IndexConfig("it_sk_idx", ["i_item_sk"], ["i_brand", "i_category"]),
    ]

INDEXED_TABLES = {"li_ok_idx": "lineitem", "od_ok_idx": "orders",
                  "li_ship_idx": "lineitem", "sr_cust_idx": "store_returns",
                  "li_pk_idx": "lineitem", "ss_item_idx": "store_sales",
                  "it_sk_idx": "item", "we_skip": "web_events",
                  "on_days_idx": "orders_nested"}


# ---------------------------------------------------------------------------
# Query set. TPC-H/TPC-DS shaped plans in the DataFrame API (no SQL parser
# yet — the stability surface is the optimized plan, which is what the
# reference's golden files capture too).
# ---------------------------------------------------------------------------

# Collection-time list of every query name below (pytest parametrizes from
# this without building the datasets; queries() asserts it stays in sync).
QUERY_NAMES = [
    "tpch_q1", "tpch_q3", "tpch_q6", "tpch_q12", "tpcds_q1_like",
    "self_join", "tpch_q14", "tpch_q17", "tpch_q18", "tpch_q19",
    "groupby_index", "tpcds_q3_like", "multi_key_join",
    "pushdown_select_where", "pushdown_alias", "tpch_q5_like",
    "tpch_q10_like", "having_over_groupby", "filter_topk_rows",
    "tpcds_q7_like", "join_on_aggregate", "in_list_indexed",
    "minmax_aggregates", "multi_dir_sort", "string_range_scan",
    "or_of_ranges", "count_distinct_groups", "join_chain_filters",
    "not_in_exclusion", "proj_arith_groupby", "distinct_flags",
    "union_of_ranges", "left_outer_orders",
    # Round-3 growth: the store_sales/item family + TPC-H shapes q2/q4/q11/
    # q13/q15/q16/q20/q22 + new-surface shapes (with_column/drop/right/full
    # outer/second-level aggregates/cross-fact m:n join).
    "tpcds_q42_like", "tpcds_q52_like", "tpcds_q55_like",
    "store_channel_mix", "returns_vs_sales", "with_column_charge",
    "drop_columns_scan", "right_outer_items", "full_outer_store_keys",
    "tpch_q4_like", "tpch_q13_like", "tpch_q15_like", "tpch_q16_like",
    "tpch_q20_like", "tpch_q22_like", "tpch_q2_like", "tpch_q11_like",
    "in_list_strings", "float_between_discount", "second_level_agg",
    "union_sales_returns", "distinct_join", "cross_fact_join",
    # Data-skipping surface (multi-file web_events + MinMax sketch).
    "skipping_date_window", "skipping_unprunable_amount",
    # Nested-struct leaves + temp-view query shapes.
    "nested_filter_rewrite", "nested_group_rollup",
    "view_filter_pushdown", "view_join_orders",
    # COUNT(DISTINCT) — the real TPC-H Q16 aggregate.
    "tpch_q16_distinct",
    # Edge shapes: 3-way union, limit 0, always-true literal predicate,
    # two-level distinct composition, any-case column references.
    "union_three_way", "limit_zero",
    "literal_true_filter", "count_distinct_two_level",
    "case_insensitive_cols",
]


def queries(dfs):
    from hyperspace_tpu.plan.expr import (avg, col, count,
                                          max_, min_, sum_)

    li, od, pt = dfs["lineitem"], dfs["orders"], dfs["part"]
    sr, dd, cu = dfs["store_returns"], dfs["date_dim"], dfs["customer"]

    d = datetime.date
    q = {}

    # TPC-H Q1: pricing summary report.
    q["tpch_q1"] = (
        li.filter(col("l_shipdate") <= d(1998, 9, 2))
        .group_by("l_returnflag", "l_linestatus")
        .agg(sum_(col("l_quantity")).alias("sum_qty"),
             sum_(col("l_extendedprice")).alias("sum_base_price"),
             sum_(col("l_extendedprice") * (1 - col("l_discount"))).alias("sum_disc_price"),
             avg(col("l_quantity")).alias("avg_qty"),
             count(col("l_quantity")).alias("count_order"))
        .sort("l_returnflag", "l_linestatus"))

    # TPC-H Q3: shipping priority (the BASELINE join query).
    cutoff = d(1995, 3, 15)
    q["tpch_q3"] = (
        li.filter(col("l_shipdate") > cutoff)
        .join(od.filter(col("o_orderdate") < cutoff),
              on=col("l_orderkey") == col("o_orderkey"))
        .group_by("l_orderkey", "o_orderdate", "o_shippriority")
        .agg(sum_(col("l_extendedprice") * (1 - col("l_discount"))).alias("revenue"))
        .sort(("revenue", False), "o_orderdate").limit(10))

    # TPC-H Q6: forecasting revenue change.
    q["tpch_q6"] = (
        li.filter(col("l_shipdate").between(d(1994, 1, 1), d(1994, 12, 31))
                  & col("l_discount").between(0.05, 0.07)
                  & (col("l_quantity") < 24))
        .agg(sum_(col("l_extendedprice") * col("l_discount")).alias("revenue")))

    # TPC-H Q12-lite: shipmode priority counts.
    q["tpch_q12"] = (
        li.filter(col("l_shipmode").isin(["MAIL", "SHIP"])
                  & col("l_shipdate").between(d(1994, 1, 1), d(1994, 12, 31)))
        .join(od, on=col("l_orderkey") == col("o_orderkey"))
        .group_by("l_shipmode")
        .agg(count(col("o_orderkey")).alias("n"))
        .sort("l_shipmode"))

    # TPC-DS Q1-like: customers with large returns per store.
    q["tpcds_q1_like"] = (
        sr.join(dd.filter(col("d_year") == 2000),
                on=col("sr_returned_date_sk") == col("d_date_sk"))
        .group_by("sr_customer_sk", "sr_store_sk")
        .agg(sum_(col("sr_return_amt")).alias("total_return"))
        .join(cu, on=col("sr_customer_sk") == col("c_customer_sk"))
        .sort(("total_return", False)).limit(20))

    # Self-join over the same indexed key (reference E2E covers self-join).
    q["self_join"] = (
        li.select("l_orderkey", "l_discount")
        .join(li.select(col("l_orderkey").alias("r_orderkey"),
                        col("l_extendedprice")),
              on=col("l_orderkey") == col("r_orderkey")))

    # TPC-H Q14-lite: promotion effect — date filter + part join.
    q["tpch_q14"] = (
        li.filter(col("l_shipdate").between(d(1995, 9, 1), d(1995, 9, 30)))
        .join(pt, on=col("l_partkey") == col("p_partkey"))
        .group_by("p_brand")
        .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
             .alias("revenue"))
        .sort("p_brand"))

    # TPC-H Q17 shape: small-quantity avg subquery + rejoin (exercises the
    # group-by index rewrite + sort-skip path).
    thr = (li.group_by("l_partkey")
           .agg(avg(col("l_quantity")).alias("avg_qty"))
           .select(col("l_partkey").alias("t_partkey"),
                   (col("avg_qty") * 0.2).alias("qty_thr")))
    q["tpch_q17"] = (
        li.join(pt.filter((col("p_brand") == "Brand#23")
                          & (col("p_container") == "MED BOX")),
                on=col("l_partkey") == col("p_partkey"))
        .join(thr, on=col("l_partkey") == col("t_partkey"))
        .filter(col("l_quantity") < col("qty_thr"))
        .agg(sum_(col("l_extendedprice")).alias("price_sum")))

    # TPC-H Q18-lite: large-volume customers (group HAVING-ish shape via
    # join on the aggregated keys).
    big = (li.group_by("l_orderkey")
           .agg(sum_(col("l_quantity")).alias("total_qty"))
           .filter(col("total_qty") > 150)
           .select(col("l_orderkey").alias("b_orderkey"), "total_qty"))
    q["tpch_q18"] = (
        od.join(big, on=col("o_orderkey") == col("b_orderkey"))
        .select("o_orderkey", "o_orderdate", "o_totalprice", "total_qty")
        .sort(("o_totalprice", False), "o_orderdate").limit(20))

    # TPC-H Q19-lite: OR-of-ANDs part/brand predicate after the join.
    q["tpch_q19"] = (
        li.join(pt, on=col("l_partkey") == col("p_partkey"))
        .filter(((col("p_brand") == "Brand#11")
                 & (col("p_container") == "SM BOX")
                 & (col("l_quantity") <= 15))
                | ((col("p_brand") == "Brand#45")
                   & (col("p_container") == "LG BOX")
                   & (col("l_quantity") >= 10)))
        .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
             .alias("revenue")))

    # Unfiltered group-by over an indexed key: the GroupByIndexRule shape.
    q["groupby_index"] = (
        li.group_by("l_partkey")
        .agg(avg(col("l_quantity")).alias("aq"),
             count(None).alias("n"))
        .sort("l_partkey").limit(15))

    # TPC-DS Q3-like: date_dim ⋈ store_returns with month filter.
    q["tpcds_q3_like"] = (
        sr.join(dd.filter((col("d_year") == 2001) & (col("d_moy") == 11)),
                on=col("sr_returned_date_sk") == col("d_date_sk"))
        .group_by("sr_store_sk")
        .agg(sum_(col("sr_return_amt")).alias("ret"),
             count(None).alias("n"))
        .sort("sr_store_sk"))

    # Multi-key join (exercises the dense-rank / packed-composite path).
    q["multi_key_join"] = (
        sr.join(dfs["store"], on=col("sr_store_sk") == col("s_store_sk"))
        .join(cu, on=col("sr_customer_sk") == col("c_customer_sk"))
        .group_by("s_state")
        .agg(sum_(col("sr_return_amt")).alias("ret"))
        .sort("s_state"))

    # select-then-where: the filter must sink through the projection and
    # still hit the covering index (rules/pushdown.py surface; columns
    # chosen to be covered by li_ship_idx so the rewrite fires).
    q["pushdown_select_where"] = (
        li.select("l_quantity", "l_extendedprice", "l_shipdate")
        .where(col("l_shipdate") > d(1997, 1, 1))
        .select("l_quantity", "l_extendedprice"))

    # Pushdown through an alias: predicate names the projected alias.
    q["pushdown_alias"] = (
        li.select(col("l_shipdate").alias("ship"), col("l_extendedprice"))
        .where(col("ship").between(d(1995, 1, 1), d(1995, 12, 31))))

    # TPC-H Q5-like: three-table chain join, revenue by order priority.
    q["tpch_q5_like"] = (
        li.join(od, on=col("l_orderkey") == col("o_orderkey"))
        .join(cu.select(col("c_customer_sk").alias("cust_sk"),
                        "c_customer_id"),
              on=col("o_custkey") == col("cust_sk"))
        .group_by("o_orderpriority")
        .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
             .alias("revenue"))
        .sort("o_orderpriority"))

    # TPC-H Q10-like: customer revenue from a date-bounded order window.
    q["tpch_q10_like"] = (
        od.filter(col("o_orderdate").between(d(1993, 10, 1), d(1994, 1, 1)))
        .join(li, on=col("o_orderkey") == col("l_orderkey"))
        .group_by("o_custkey")
        .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
             .alias("revenue"))
        .sort(("revenue", False)).limit(20))

    # HAVING over an indexed group-by (filter above aggregate must NOT be
    # pushed below it — the pushdown rule's stop condition).
    q["having_over_groupby"] = (
        li.group_by("l_partkey")
        .agg(sum_(col("l_quantity")).alias("qty"))
        .filter(col("qty") > 100)
        .sort("l_partkey"))

    # Row-returning filter + order + top-k, no aggregate (the plain
    # covering-index scan path with a sort above it).
    q["filter_topk_rows"] = (
        li.filter(col("l_shipdate") > d(1997, 6, 1))
        .select("l_orderkey", "l_extendedprice", "l_shipdate")
        .sort(("l_extendedprice", False)).limit(25))

    # TPC-DS Q7-like: two dimension filters on the fact scan + group-by.
    q["tpcds_q7_like"] = (
        sr.filter(col("sr_return_amt") > 50)
        .join(dd.filter(col("d_moy") <= 6),
              on=col("sr_returned_date_sk") == col("d_date_sk"))
        .group_by("sr_customer_sk")
        .agg(avg(col("sr_return_amt")).alias("avg_ret"),
             count(None).alias("n"))
        .sort("sr_customer_sk").limit(30))

    # Join whose probe side is itself an aggregate over an indexed key
    # (exercises index-assisted build under a join consumer).
    per_store = (sr.group_by("sr_store_sk")
                 .agg(sum_(col("sr_return_amt")).alias("store_ret"))
                 .select(col("sr_store_sk").alias("agg_store_sk"),
                         "store_ret"))
    q["join_on_aggregate"] = (
        dfs["store"].join(per_store,
                          on=col("s_store_sk") == col("agg_store_sk"))
        .select("s_state", "store_ret")
        .sort(("store_ret", False)))

    # IN-list predicate over the first indexed column (In → bucket-subset
    # pruning in the index scan).
    q["in_list_indexed"] = (
        li.filter(col("l_orderkey").isin([1, 5, 9, 13]))
        .select("l_orderkey", "l_extendedprice"))

    # Min/Max aggregates (only sum/avg/count appear in the TPC shapes
    # above); grouped on a non-indexed flag column.
    q["minmax_aggregates"] = (
        li.group_by("l_returnflag")
        .agg(min_(col("l_extendedprice")).alias("lo"),
             max_(col("l_extendedprice")).alias("hi"),
             count(None).alias("n"))
        .sort("l_returnflag"))

    # Multi-key sort with mixed directions, no filter/aggregate.
    q["multi_dir_sort"] = (
        li.select("l_orderkey", "l_shipdate", "l_extendedprice")
        .sort("l_orderkey", ("l_extendedprice", False)).limit(40))

    # Range predicate over a string column (the engine dictionary-encodes
    # all strings order-preservingly at the IO boundary, so this compares
    # int32 codes on device regardless of the parquet encoding).
    q["string_range_scan"] = (
        od.filter((col("o_orderpriority") >= "2-HIGH")
                  & (col("o_orderpriority") < "4-NOT SPECIFIED"))
        .select("o_orderkey", "o_orderpriority"))

    # OR of two disjoint ranges on the indexed filter column.
    q["or_of_ranges"] = (
        li.filter(col("l_shipdate").between(d(1993, 1, 1), d(1993, 3, 31))
                  | col("l_shipdate").between(d(1997, 1, 1),
                                              d(1997, 3, 31)))
        .select("l_quantity", "l_extendedprice", "l_shipdate"))

    # Group count over a two-column key (count of groups per flag).
    q["count_distinct_groups"] = (
        li.group_by("l_returnflag", "l_linestatus")
        .agg(count(None).alias("n"))
        .group_by("l_returnflag")
        .agg(count(None).alias("distinct_statuses"))
        .sort("l_returnflag"))

    # Join with independent filters on both inputs plus one above the join.
    q["join_chain_filters"] = (
        li.filter(col("l_quantity") > 10)
        .join(od.filter(col("o_orderpriority") == "1-URGENT"),
              on=col("l_orderkey") == col("o_orderkey"))
        .filter(col("l_extendedprice") > 50_000)
        .group_by("o_orderpriority")
        .agg(sum_(col("l_extendedprice")).alias("rev")))

    # NOT(IN(...)) exclusion on the indexed key (hybrid scan's deleted-row
    # mask shape, as a user predicate).
    q["not_in_exclusion"] = (
        li.filter(~col("l_orderkey").isin([0, 1, 2, 3]))
        .group_by("l_returnflag")
        .agg(count(None).alias("n"))
        .sort("l_returnflag"))

    # Arithmetic projection feeding a group-by (expr columns as group key
    # input, revenue-style derived measure).
    q["proj_arith_groupby"] = (
        li.select("l_returnflag",
                  (col("l_extendedprice") * (1 - col("l_discount"))
                   * (1 + col("l_tax"))).alias("charge"))
        .group_by("l_returnflag")
        .agg(sum_(col("charge")).alias("sum_charge"),
             avg(col("charge")).alias("avg_charge"))
        .sort("l_returnflag"))

    # Distinct rides the grouped-agg machinery (group by every column).
    q["distinct_flags"] = (
        li.select("l_returnflag", "l_linestatus").distinct()
        .sort("l_returnflag", "l_linestatus"))

    # Union of two disjoint filtered ranges, re-aggregated.
    q["union_of_ranges"] = (
        li.filter(col("l_shipdate") < d(1994, 1, 1)).select("l_orderkey",
                                                            "l_quantity")
        .union(li.filter(col("l_shipdate") >= d(1997, 1, 1))
               .select("l_orderkey", "l_quantity"))
        .group_by("l_orderkey").agg(sum_(col("l_quantity")).alias("q"))
        .sort("l_orderkey").limit(25))

    # Left outer join (engine executes it; the join rule must NOT rewrite).
    q["left_outer_orders"] = (
        od.select(col("o_orderkey").alias("ok"), "o_totalprice")
        .join(li.select("l_orderkey", "l_extendedprice"),
              on=col("ok") == col("l_orderkey"), how="left")
        .group_by("ok").agg(count(col("l_extendedprice")).alias("n_items"))
        .sort("ok").limit(30))

    ss, it, st = dfs["store_sales"], dfs["item"], dfs["store"]

    # TPC-DS Q42-like: category revenue for a month (both join sides carry
    # covering indexes on the join keys — the ss⋈item pair is the
    # JoinIndexRule target).
    q["tpcds_q42_like"] = (
        ss.join(dd.filter((col("d_year") == 2000) & (col("d_moy") == 11)),
                on=col("ss_sold_date_sk") == col("d_date_sk"))
        .join(it, on=col("ss_item_sk") == col("i_item_sk"))
        .group_by("i_category")
        .agg(sum_(col("ss_sales_price")).alias("revenue"))
        .sort(("revenue", False), "i_category"))

    # TPC-DS Q52-like: brand revenue in December, top sellers first.
    q["tpcds_q52_like"] = (
        ss.join(dd.filter(col("d_moy") == 12),
                on=col("ss_sold_date_sk") == col("d_date_sk"))
        .join(it, on=col("ss_item_sk") == col("i_item_sk"))
        .group_by("i_brand")
        .agg(sum_(col("ss_sales_price")).alias("brand_rev"))
        .sort(("brand_rev", False), "i_brand").limit(10))

    # TPC-DS Q55-like: same family without the date filter — the pure
    # indexed ss⋈item join under an aggregate.
    q["tpcds_q55_like"] = (
        ss.join(it, on=col("ss_item_sk") == col("i_item_sk"))
        .group_by("i_brand")
        .agg(sum_(col("ss_sales_price")).alias("brand_rev"),
             count(None).alias("n"))
        .sort("i_brand"))

    # Channel mix: fact ⋈ tiny dimension (store), state rollup.
    q["store_channel_mix"] = (
        ss.join(st, on=col("ss_store_sk") == col("s_store_sk"))
        .group_by("s_state")
        .agg(sum_(col("ss_sales_price")).alias("sales"),
             avg(col("ss_quantity")).alias("avg_qty"))
        .sort("s_state"))

    # Per-customer sales vs returns: two grouped facts joined, derived
    # ratio via with_column (aggregate-on-aggregate join shape).
    sales_per_cust = (ss.group_by("ss_customer_sk")
                      .agg(sum_(col("ss_sales_price")).alias("bought")))
    rets_per_cust = (sr.group_by("sr_customer_sk")
                     .agg(sum_(col("sr_return_amt")).alias("returned")))
    q["returns_vs_sales"] = (
        sales_per_cust.join(rets_per_cust,
                            on=col("ss_customer_sk") == col("sr_customer_sk"))
        .with_column("ratio", col("returned") / col("bought"))
        .select("ss_customer_sk", "ratio")
        .sort(("ratio", False)).limit(15))

    # with_column feeding a group-by (same charge expression as
    # proj_arith_groupby but through the with_column surface).
    q["with_column_charge"] = (
        li.with_column("charge",
                       col("l_extendedprice") * (1 - col("l_discount"))
                       * (1 + col("l_tax")))
        .group_by("l_linestatus")
        .agg(sum_(col("charge")).alias("sum_charge"),
             max_(col("charge")).alias("max_charge"))
        .sort("l_linestatus"))

    # drop() then an indexed filter: the scan must shrink to the kept
    # columns and still hit li_ship_idx (all survivors are covered).
    q["drop_columns_scan"] = (
        li.select("l_quantity", "l_extendedprice", "l_discount",
                  "l_shipdate")
        .drop("l_discount")
        .filter(col("l_shipdate") > d(1997, 1, 1)))

    # Right outer: the sales side is filtered to items 0..9, so items 10..19
    # are null-padded by construction — count(ss_sales_price) must skip the
    # padded nulls per category.
    q["right_outer_items"] = (
        ss.select("ss_item_sk", "ss_sales_price")
        .filter(col("ss_item_sk") < 10)
        .join(it.select(col("i_item_sk"), col("i_category")),
              on=col("ss_item_sk") == col("i_item_sk"), how="right")
        .group_by("i_category")
        .agg(count(col("ss_sales_price")).alias("n_sales"))
        .sort("i_category"))

    # Full outer over two overlapping-but-distinct store-key ranges: stores
    # 0..3 on the sales side, 2..5 on the returns side, so both sides emit
    # null-padded rows AND the nullable sort keys see real nulls.
    q["full_outer_store_keys"] = (
        ss.filter(col("ss_store_sk") <= 3)
        .group_by("ss_store_sk").agg(sum_(col("ss_sales_price")).alias("sold"))
        .join(sr.filter(col("sr_store_sk") >= 2)
              .group_by("sr_store_sk")
              .agg(sum_(col("sr_return_amt")).alias("ret")),
              on=col("ss_store_sk") == col("sr_store_sk"), how="full")
        .sort("ss_store_sk", "sr_store_sk"))

    # TPC-H Q4-like: order-priority counts for orders having a late
    # lineitem (EXISTS emulated as distinct-key inner join).
    late = (li.filter(col("l_shipdate") > d(1997, 1, 1))
            .select("l_orderkey").distinct())
    q["tpch_q4_like"] = (
        od.join(late, on=col("o_orderkey") == col("l_orderkey"))
        .group_by("o_orderpriority")
        .agg(count(None).alias("order_count"))
        .sort("o_orderpriority"))

    # TPC-H Q13-like: distribution of orders per customer (left outer so
    # zero-order customers keep a row, then a second-level group-by).
    per_cust = (cu.select(col("c_customer_sk"))
                .join(od.select("o_custkey", "o_orderkey"),
                      on=col("c_customer_sk") == col("o_custkey"), how="left")
                .group_by("c_customer_sk")
                .agg(count(col("o_orderkey")).alias("c_count")))
    q["tpch_q13_like"] = (
        per_cust.group_by("c_count").agg(count(None).alias("custdist"))
        .sort(("custdist", False), ("c_count", False)))

    # TPC-H Q15-like: top revenue generator (argmax via sort+limit 1).
    q["tpch_q15_like"] = (
        li.filter(col("l_shipdate").between(d(1996, 1, 1), d(1996, 3, 31)))
        .group_by("l_orderkey")
        .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
             .alias("total_rev"))
        .sort(("total_rev", False), "l_orderkey").limit(1))

    # TPC-H Q16-like: part counts by brand/container excluding one brand.
    q["tpch_q16_like"] = (
        pt.filter(~col("p_brand").isin(["Brand#45"]))
        .group_by("p_brand", "p_container")
        .agg(count(col("p_partkey")).alias("part_cnt"))
        .sort(("part_cnt", False), "p_brand", "p_container"))

    # TPC-H Q20-like: parts whose stocked quantity exceeds a threshold
    # (grouped fact joined back to the dimension).
    heavy = (li.group_by("l_partkey")
             .agg(sum_(col("l_quantity")).alias("qty_sum"))
             .filter(col("qty_sum") > 120))
    q["tpch_q20_like"] = (
        pt.join(heavy, on=col("p_partkey") == col("l_partkey"))
        .select("p_brand", "p_container", "qty_sum")
        .sort(("qty_sum", False), "p_brand"))

    # TPC-H Q22-like: customers with no orders (anti-join emulated as left
    # outer + count == 0).
    q["tpch_q22_like"] = (
        cu.select(col("c_customer_sk"), col("c_customer_id"))
        .join(od.select("o_custkey", "o_orderkey"),
              on=col("c_customer_sk") == col("o_custkey"), how="left")
        .group_by("c_customer_sk", "c_customer_id")
        .agg(count(col("o_orderkey")).alias("n_orders"))
        .filter(col("n_orders") == 0)
        .sort("c_customer_sk"))

    # TPC-H Q2-like: cheapest offer per part among small parts.
    min_price = (li.group_by("l_partkey")
                 .agg(min_(col("l_extendedprice")).alias("min_price")))
    q["tpch_q2_like"] = (
        pt.filter(col("p_size") < 15)
        .join(min_price, on=col("p_partkey") == col("l_partkey"))
        .select("p_partkey", "p_brand", "min_price")
        .sort("min_price", "p_partkey").limit(10))

    # TPC-H Q11-like: high-value part positions (grouped sum over the
    # indexed l_partkey, thresholded — the group-by index + HAVING shape).
    q["tpch_q11_like"] = (
        li.group_by("l_partkey")
        .agg(sum_(col("l_extendedprice") * col("l_quantity")).alias("value"))
        .filter(col("value") > 1_000_000)
        .sort(("value", False)))

    # IN-list over a string column (dictionary-code translation at the
    # planning boundary, not a range).
    q["in_list_strings"] = (
        od.filter(col("o_orderpriority").isin(["1-URGENT", "2-HIGH"]))
        .group_by("o_orderpriority")
        .agg(count(None).alias("n"), max_(col("o_totalprice")).alias("top"))
        .sort("o_orderpriority"))

    # Float between on non-leading index columns: no rewrite, pure engine
    # range scan over f64.
    q["float_between_discount"] = (
        li.filter(col("l_discount").between(0.02, 0.04)
                  & (col("l_quantity") < 30))
        .select("l_orderkey", "l_discount", "l_quantity")
        .sort("l_orderkey", "l_discount").limit(40))

    # Second-level aggregate: avg over per-store revenue (aggregate of an
    # aggregate, no join).
    q["second_level_agg"] = (
        ss.group_by("ss_store_sk")
        .agg(sum_(col("ss_sales_price")).alias("store_rev"))
        .agg(avg(col("store_rev")).alias("avg_store_rev"),
             count(None).alias("n_stores")))

    # Union across two different fact tables with aligned projections.
    q["union_sales_returns"] = (
        ss.select(col("ss_customer_sk").alias("cust"),
                  col("ss_sales_price").alias("amt"))
        .union(sr.select(col("sr_customer_sk").alias("cust"),
                         col("sr_return_amt").alias("amt")))
        .group_by("cust").agg(sum_(col("amt")).alias("volume"))
        .sort(("volume", False)).limit(20))

    # Distinct keys then dimension join (semi-join-flavoured count).
    q["distinct_join"] = (
        ss.select("ss_item_sk").distinct()
        .join(it, on=col("ss_item_sk") == col("i_item_sk"))
        .group_by("i_category")
        .agg(count(None).alias("n_items"))
        .sort("i_category"))

    # Cross-fact m:n join on the customer key (neither side unique).
    q["cross_fact_join"] = (
        sr.select("sr_customer_sk", "sr_return_amt")
        .join(ss.select("ss_customer_sk", "ss_store_sk"),
              on=col("sr_customer_sk") == col("ss_customer_sk"))
        .group_by("ss_store_sk")
        .agg(count(None).alias("n"), sum_(col("sr_return_amt")).alias("amt"))
        .sort("ss_store_sk"))

    we = dfs["web_events"]

    # Narrow date window → the MinMax sketch refutes most part files; the
    # enabled golden pins the "[k/4 files after skipping]" scan annotation.
    q["skipping_date_window"] = (
        we.filter(col("we_event_date").between(d(1994, 9, 1),
                                               d(1994, 10, 15)))
        .group_by("we_user_sk")
        .agg(sum_(col("we_amount")).alias("amt"))
        .sort("we_user_sk"))

    # Predicate on an unsketeched column: the rule must keep all files
    # (conservative no-op; enabled plan equals disabled).
    q["skipping_unprunable_amount"] = (
        we.filter(col("we_amount") > 450)
        .select("we_user_sk", "we_amount")
        .sort(("we_amount", False)).limit(10))

    on = dfs["orders_nested"]
    view = dfs["__view__recent_lineitem"]

    # Filter on the nested indexed leaf, every referenced column covered.
    q["nested_filter_rewrite"] = (
        on.filter(col("detail.ship.days") < 7)
        .select("no_key", "detail.price"))

    # Group-by over the nested leaf (group-by index shape on dotted name).
    q["nested_group_rollup"] = (
        on.group_by("detail.ship.days")
        .agg(avg(col("detail.price")).alias("avg_price"),
             count(None).alias("n"))
        .sort("detail.ship.days"))

    # Rewrites reach THROUGH temp views: the view resolves to the same
    # scan, so li_ship_idx must still fire.
    q["view_filter_pushdown"] = (
        view.select("l_quantity", "l_extendedprice", "l_shipdate")
        .where(col("l_shipdate") > d(1997, 1, 1))
        .select("l_quantity", "l_extendedprice"))

    # And the join rule too (view ⋈ orders on the indexed pair).
    q["view_join_orders"] = (
        view.filter(col("l_shipdate") > d(1995, 3, 15))
        .join(od, on=col("l_orderkey") == col("o_orderkey"))
        .group_by("o_shippriority")
        .agg(sum_(col("l_extendedprice")).alias("rev"))
        .sort("o_shippriority"))

    # TPC-H Q16 with its true aggregate: distinct suppliers per
    # (brand, container) — here distinct orders per (brand, container)
    # since the schema has no supplier axis.
    from hyperspace_tpu.plan.expr import count_distinct
    q["tpch_q16_distinct"] = (
        li.join(pt.filter(~col("p_brand").isin(["Brand#45"])),
                on=col("l_partkey") == col("p_partkey"))
        .group_by("p_brand", "p_container")
        .agg(count_distinct(col("l_orderkey")).alias("supplier_cnt"))
        .sort(("supplier_cnt", False), "p_brand", "p_container"))

    # Wrong-case column references resolve (hyperspace.caseSensitive
    # defaults false, like Spark) and the rewrite still fires; the plan
    # carries the SCHEMA's spelling.
    q["case_insensitive_cols"] = (
        li.filter(col("L_SHIPDATE") > d(1997, 1, 1))
        .select("L_QUANTITY", "l_extendedprice", "L_SHIPDATE"))

    # Three-way union of disjoint ranges, re-aggregated.
    q["union_three_way"] = (
        li.filter(col("l_shipdate") < d(1993, 6, 1)).select("l_orderkey")
        .union(li.filter(col("l_shipdate").between(d(1994, 1, 1),
                                                   d(1994, 6, 1)))
               .select("l_orderkey"))
        .union(li.filter(col("l_shipdate") > d(1997, 6, 1))
               .select("l_orderkey"))
        .group_by("l_orderkey").agg(count(None).alias("n"))
        .sort("l_orderkey").limit(20))

    # limit(0): schema survives, zero rows.
    q["limit_zero"] = (
        od.select("o_orderkey", "o_totalprice").sort("o_orderkey").limit(0))

    # Always-true literal predicate: must not break rewrites or pruning.
    q["literal_true_filter"] = (
        li.filter((col("l_quantity") >= 1)
                  & (col("l_shipdate") > d(1996, 1, 1)))
        .select("l_quantity", "l_extendedprice", "l_shipdate"))

    # count_distinct feeding a second-level aggregate.
    from hyperspace_tpu.plan.expr import count_distinct as _cd
    q["count_distinct_two_level"] = (
        li.group_by("l_returnflag", "l_linestatus")
        .agg(_cd(col("l_orderkey")).alias("nd"))
        .group_by("l_returnflag")
        .agg(sum_(col("nd")).alias("total_nd"))
        .sort("l_returnflag"))

    assert sorted(q) == sorted(QUERY_NAMES), \
        f"QUERY_NAMES out of sync: {sorted(set(q) ^ set(QUERY_NAMES))}"
    return q
