"""Verbatim TPC-DS queries over a synthetic mini-catalog.

The texts below are the published TPC-DS v1.4 benchmark queries with the
reference's parameter substitutions (the same queries the reference runs
through Spark for its 99 approved-plan goldens —
goldstandard/TPCDSBase.scala:41, src/test/resources/tpcds/queries/).
Only single-SELECT queries inside the SQL front-end's grammar are
included — no CTEs, window functions, or ROLLUP (16 of the 99 today);
growing this list is a matter of grammar, not harness.

The catalog generator builds every referenced table with exactly the
columns these queries touch, seeded and sized so each query returns a
non-empty answer (each query's literal predicates — manager ids,
manufacturer ids, price bands, date windows — are guaranteed hits by
construction below).
"""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

# Calendar span covering every query's date predicates (1998..2002).
_D0 = datetime.date(1998, 1, 1)
N_DD = 1700

_DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
              "Saturday", "Sunday"]


def tables(rng: np.random.Generator) -> Dict[str, pa.Table]:
    n_it, n_cu, n_ca, n_st, n_cd, n_pr, n_hd, n_td, n_wh = \
        60, 120, 80, 6, 40, 12, 15, 200, 4
    n_sm, n_web, n_cc = 5, 4, 3
    n_ss, n_cs, n_inv, n_ws = 1600, 1200, 900, 1000

    dates = [_D0 + datetime.timedelta(days=i) for i in range(N_DD)]
    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(N_DD, dtype=np.int64)),
        "d_date": pa.array(dates, type=pa.date32()),
        "d_year": pa.array(np.array([d.year for d in dates], np.int64)),
        "d_moy": pa.array(np.array([d.month for d in dates], np.int64)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in dates],
                                   np.int64)),
        "d_day_name": pa.array([_DAY_NAMES[d.weekday()] for d in dates]),
        # TPC-DS month sequence: 2000-01 = 1200 (q62/q99's window).
        "d_month_seq": pa.array(np.array(
            [(d.year - 1998) * 12 + (d.month - 1) + 1176 for d in dates],
            np.int64)),
    })

    # Items: cycle manager/manufacturer ids through every value the query
    # texts name, and force price-band coverage (q21: [0.99,1.49],
    # q37: [68,98], q82: [62,92]).
    managers = np.array([1, 8, 28] + list(range(2, 8)) + [9, 10],
                        dtype=np.int64)
    manufacts = np.array([128, 677, 940, 694, 808, 129, 270, 821, 423, 55],
                         dtype=np.int64)
    prices = np.round(rng.uniform(1, 110, n_it), 2)
    prices[0:6] = [1.10, 1.25, 70.0, 80.0, 65.0, 90.0]
    cats = ["Music", "Books", "Sports", "Home", "Shoes"]
    item = pa.table({
        "i_item_sk": pa.array(np.arange(n_it, dtype=np.int64)),
        "i_item_id": pa.array([f"ITEM{i:08d}" for i in range(n_it)]),
        "i_item_desc": pa.array([f"desc of item {i}" for i in range(n_it)]),
        "i_brand_id": pa.array((np.arange(n_it, dtype=np.int64) % 9) + 1),
        "i_brand": pa.array([f"brand#{(i % 9) + 1}" for i in range(n_it)]),
        "i_manufact_id": pa.array(manufacts[np.arange(n_it) % len(manufacts)]),
        "i_manufact": pa.array(
            [f"manufact{int(m)}" for m in
             manufacts[np.arange(n_it) % len(manufacts)]]),
        "i_category_id": pa.array((np.arange(n_it, dtype=np.int64) % 5) + 1),
        "i_category": pa.array([cats[i % 5] for i in range(n_it)]),
        "i_class": pa.array([f"class{i % 4}" for i in range(n_it)]),
        "i_current_price": pa.array(prices),
        "i_manager_id": pa.array(managers[np.arange(n_it) % len(managers)]),
    })

    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(n_cu, dtype=np.int64)),
        "c_current_addr_sk": pa.array(
            rng.integers(0, n_ca, n_cu).astype(np.int64)),
    })
    zips = ["85669", "86197", "60601", "10001", "94111", "30301", "73301",
            "88274"]
    states = ["CA", "WA", "GA", "TN", "TX", "NY", "OH", "OR", "NM",
              "KY", "VA", "MS", "IN", "WI", "MO"]
    customer_address = pa.table({
        "ca_address_sk": pa.array(np.arange(n_ca, dtype=np.int64)),
        "ca_zip": pa.array([zips[i % len(zips)] + "0000" for i in
                            range(n_ca)]),
        "ca_state": pa.array([states[i % len(states)] for i in range(n_ca)]),
        "ca_country": pa.array(["United States"] * n_ca),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(n_st, dtype=np.int64)),
        "s_store_id": pa.array([f"S{i:04d}" for i in range(n_st)]),
        "s_store_name": pa.array(
            ["ese" if i % 3 == 0 else f"store{i}" for i in range(n_st)]),
        "s_zip": pa.array([zips[(i + 3) % len(zips)] + "0000"
                           for i in range(n_st)]),
        "s_gmt_offset": pa.array(
            np.where(np.arange(n_st) % 2 == 0, -5, -6).astype(np.int64)),
    })
    maritals = ["M", "S", "W", "D", "U"]
    educations = ["Advanced Degree", "College", "2 yr Degree",
                  "4 yr Degree", "Secondary"]
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(np.arange(n_cd, dtype=np.int64)),
        "cd_gender": pa.array(["M" if i % 2 == 0 else "F"
                               for i in range(n_cd)]),
        # Independent small cycles: every (marital, education) pair the
        # query texts name co-occurs within n_cd=40 rows (q7/q26 need
        # (S, College); q13 (M, Advanced Degree), (S, College),
        # (W, 2 yr Degree); q48 (M, 4 yr Degree), (D, 2 yr Degree)).
        "cd_marital_status": pa.array(
            [maritals[i % 5] for i in range(n_cd)]),
        "cd_education_status": pa.array(
            [educations[(i + i // 5) % 5] for i in range(n_cd)]),
    })
    promotion = pa.table({
        "p_promo_sk": pa.array(np.arange(n_pr, dtype=np.int64)),
        "p_channel_email": pa.array(["N" if i % 2 == 0 else "Y"
                                     for i in range(n_pr)]),
        "p_channel_event": pa.array(["N" if i % 3 == 0 else "Y"
                                     for i in range(n_pr)]),
    })
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(np.arange(n_hd, dtype=np.int64)),
        "hd_dep_count": pa.array((np.arange(n_hd, dtype=np.int64) % 10)),
    })
    time_dim = pa.table({
        "t_time_sk": pa.array(np.arange(n_td, dtype=np.int64)),
        "t_hour": pa.array((np.arange(n_td, dtype=np.int64) % 24)),
        "t_minute": pa.array(
            ((np.arange(n_td, dtype=np.int64) * 7) % 60)),
    })
    warehouse = pa.table({
        "w_warehouse_sk": pa.array(np.arange(n_wh, dtype=np.int64)),
        "w_warehouse_name": pa.array([f"Warehouse number {i}"
                                      for i in range(n_wh)]),
    })
    ship_mode = pa.table({
        "sm_ship_mode_sk": pa.array(np.arange(n_sm, dtype=np.int64)),
        "sm_type": pa.array(["EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY",
                             "LIBRARY"][:n_sm]),
    })
    web_site = pa.table({
        "web_site_sk": pa.array(np.arange(n_web, dtype=np.int64)),
        "web_name": pa.array([f"site_{i}" for i in range(n_web)]),
    })
    call_center = pa.table({
        "cc_call_center_sk": pa.array(np.arange(n_cc, dtype=np.int64)),
        "cc_name": pa.array([f"call center {i}" for i in range(n_cc)]),
    })
    rng2 = np.random.default_rng(99)
    ws_sold = rng.integers(0, N_DD - 150, n_ws).astype(np.int64)
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(ws_sold),
        "ws_ship_date_sk": pa.array(
            ws_sold + rng.integers(1, 140, n_ws).astype(np.int64)),
        "ws_warehouse_sk": pa.array(
            rng.integers(0, n_wh, n_ws).astype(np.int64)),
        "ws_ship_mode_sk": pa.array(
            rng.integers(0, n_sm, n_ws).astype(np.int64)),
        "ws_web_site_sk": pa.array(
            rng.integers(0, n_web, n_ws).astype(np.int64)),
    })

    # Constructed hit rows make the q13/q48 compound predicates TRUE by
    # construction, not seed luck: both are scalar aggregates that return
    # one row even with zero matches, so an accidentally-empty match set
    # would never fail the non-empty guard (r4 review finding).
    ss_sold = rng.integers(0, N_DD, n_ss).astype(np.int64)
    ss_cdemo = rng.integers(0, n_cd, n_ss).astype(np.int64)
    ss_hdemo = rng.integers(0, n_hd, n_ss).astype(np.int64)
    ss_price = np.round(rng.uniform(1, 290, n_ss), 2)
    d2001 = (datetime.date(2001, 6, 15) - _D0).days
    for j in range(4):
        ss_sold[j] = d2001 + j
        ss_cdemo[j] = 0       # (M, Advanced Degree) — q13 branch 1
        ss_hdemo[j] = 3       # hd_dep_count == 3
        ss_price[j] = 120.0   # in [100, 150]
    for j in range(4, 8):
        ss_sold[j] = d2001 + j
        ss_cdemo[j] = 1       # i=1: marital S, education College (q48 b3)
        ss_price[j] = 170.0   # in [150, 200]
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(ss_sold),
        "ss_sold_time_sk": pa.array(
            rng.integers(0, n_td, n_ss).astype(np.int64)),
        "ss_item_sk": pa.array(rng.integers(0, n_it, n_ss).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(0, n_cu, n_ss).astype(np.int64)),
        "ss_cdemo_sk": pa.array(ss_cdemo),
        "ss_hdemo_sk": pa.array(ss_hdemo),
        "ss_promo_sk": pa.array(rng.integers(0, n_pr, n_ss).astype(np.int64)),
        "ss_store_sk": pa.array(rng.integers(0, n_st, n_ss).astype(np.int64)),
        "ss_quantity": pa.array(rng.integers(1, 100, n_ss).astype(np.int64)),
        "ss_list_price": pa.array(np.round(rng.uniform(1, 300, n_ss), 2)),
        "ss_coupon_amt": pa.array(np.round(rng.uniform(0, 40, n_ss), 2)),
        "ss_sales_price": pa.array(ss_price),
        "ss_ext_sales_price": pa.array(
            np.round(rng.uniform(5, 4000, n_ss), 2)),
        # q13/q48 columns from a SEPARATE generator: appending draws to
        # the shared one would shift every later table and churn the
        # whole corpus' data.
        "ss_ext_wholesale_cost": pa.array(
            np.round(rng2.uniform(1, 100, n_ss), 2)),
        "ss_addr_sk": pa.array(np.concatenate(
            [np.full(8, 4, np.int64),  # ca 4 = TX, United States
             rng2.integers(0, n_ca, n_ss - 8).astype(np.int64)])),
        "ss_net_profit": pa.array(np.concatenate(
            [np.full(8, 150.0),       # inside every profit band used
             np.round(rng2.uniform(0, 330, n_ss - 8), 2)])),
    })
    cs_sold = rng.integers(0, N_DD - 150, n_cs).astype(np.int64)
    catalog_sales = pa.table({
        "cs_sold_date_sk": pa.array(cs_sold),
        "cs_ship_date_sk": pa.array(
            cs_sold + rng.integers(1, 140, n_cs).astype(np.int64)),
        "cs_warehouse_sk": pa.array(
            rng.integers(0, n_wh, n_cs).astype(np.int64)),
        "cs_ship_mode_sk": pa.array(
            rng.integers(0, n_sm, n_cs).astype(np.int64)),
        "cs_call_center_sk": pa.array(
            rng.integers(0, n_cc, n_cs).astype(np.int64)),
        "cs_item_sk": pa.array(rng.integers(0, n_it, n_cs).astype(np.int64)),
        "cs_bill_customer_sk": pa.array(
            rng.integers(0, n_cu, n_cs).astype(np.int64)),
        "cs_bill_cdemo_sk": pa.array(
            rng.integers(0, n_cd, n_cs).astype(np.int64)),
        "cs_promo_sk": pa.array(rng.integers(0, n_pr, n_cs).astype(np.int64)),
        "cs_quantity": pa.array(rng.integers(1, 100, n_cs).astype(np.int64)),
        "cs_list_price": pa.array(np.round(rng.uniform(1, 300, n_cs), 2)),
        "cs_coupon_amt": pa.array(np.round(rng.uniform(0, 40, n_cs), 2)),
        "cs_sales_price": pa.array(np.round(rng.uniform(1, 600, n_cs), 2)),
        "cs_ext_sales_price": pa.array(
            np.round(rng.uniform(5, 4000, n_cs), 2)),
    })
    # Inventory dates concentrated around the q21/q37/q82 windows so the
    # ±30/60-day BETWEENs keep rows.
    inv_base = (datetime.date(2000, 2, 1) - _D0).days
    inventory = pa.table({
        "inv_item_sk": pa.array(rng.integers(0, n_it, n_inv).astype(np.int64)),
        "inv_warehouse_sk": pa.array(
            rng.integers(0, n_wh, n_inv).astype(np.int64)),
        "inv_date_sk": pa.array(
            (inv_base + rng.integers(0, 160, n_inv)).astype(np.int64)),
        "inv_quantity_on_hand": pa.array(
            rng.integers(0, 600, n_inv).astype(np.int64)),
    })

    return {
        "date_dim": date_dim, "item": item, "customer": customer,
        "customer_address": customer_address, "store": store,
        "customer_demographics": customer_demographics,
        "promotion": promotion,
        "household_demographics": household_demographics,
        "time_dim": time_dim, "warehouse": warehouse,
        "ship_mode": ship_mode, "web_site": web_site,
        "call_center": call_center, "web_sales": web_sales,
        "store_sales": store_sales, "catalog_sales": catalog_sales,
        "inventory": inventory,
    }


def register_tables(session, root: str) -> None:
    import os

    import pyarrow.parquet as pq

    rng = np.random.default_rng(2024)
    for name, t in tables(rng).items():
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        pq.write_table(t, os.path.join(d, "part0.parquet"))
        session.create_temp_view(name, session.read.parquet(d))


def index_configs():
    """Covering indexes matching the corpus's FIRST joins: the join rule
    (like the reference's isPlanLinear check) only rewrites joins whose
    both sides are linear, i.e. the bottom of each left-deep star-join
    tree. FROM-order puts date_dim⋈store_sales at the bottom of the
    q3/q42/q43/q52/q55 family and item⋈inventory under q21/q37/q82, so
    those four tables carry the indexes — both sides of a rewritten join
    need one (JoinIndexRule compatible-pair requirement)."""
    from hyperspace_tpu.api import IndexConfig

    return [
        ("date_dim", IndexConfig(
            "ds_dd_sk", ["d_date_sk"],
            ["d_date", "d_year", "d_moy", "d_qoy", "d_day_name"])),
        ("store_sales", IndexConfig(
            "ds_ss_date", ["ss_sold_date_sk"],
            ["ss_item_sk", "ss_store_sk", "ss_ext_sales_price",
             "ss_sales_price"])),
        ("item", IndexConfig(
            "ds_item_sk", ["i_item_sk"],
            ["i_item_id", "i_item_desc", "i_brand_id", "i_brand",
             "i_manufact_id", "i_manufact", "i_category_id", "i_category",
             "i_class", "i_current_price", "i_manager_id"])),
        ("inventory", IndexConfig(
            "ds_inv_item", ["inv_item_sk"],
            ["inv_date_sk", "inv_warehouse_sk", "inv_quantity_on_hand"])),
    ]


# The verbatim texts (TPC-DS v1.4, reference parameter substitutions).
QUERY_TEXTS: Dict[str, str] = {
    "tpcds_real_q3": """
SELECT
  dt.d_year,
  item.i_brand_id brand_id,
  item.i_brand brand,
  SUM(ss_ext_sales_price) sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manufact_id = 128
  AND dt.d_moy = 11
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, sum_agg DESC, brand_id
LIMIT 100
""",
    "tpcds_real_q7": """
SELECT
  i_item_id,
  avg(ss_quantity) agg1,
  avg(ss_list_price) agg2,
  avg(ss_coupon_amt) agg3,
  avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk AND
  ss_item_sk = i_item_sk AND
  ss_cdemo_sk = cd_demo_sk AND
  ss_promo_sk = p_promo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  (p_channel_email = 'N' OR p_channel_event = 'N') AND
  d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q13": """
SELECT
  avg(ss_quantity),
  avg(ss_ext_sales_price),
  avg(ss_ext_wholesale_cost),
  sum(ss_ext_wholesale_cost)
FROM store_sales
  , store
  , customer_demographics
  , household_demographics
  , customer_address
  , date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk
  AND cd_demo_sk = ss_cdemo_sk
  AND cd_marital_status = 'M'
  AND cd_education_status = 'Advanced Degree'
  AND ss_sales_price BETWEEN 100.00 AND 150.00
  AND hd_dep_count = 3
) OR
  (ss_hdemo_sk = hd_demo_sk
    AND cd_demo_sk = ss_cdemo_sk
    AND cd_marital_status = 'S'
    AND cd_education_status = 'College'
    AND ss_sales_price BETWEEN 50.00 AND 100.00
    AND hd_dep_count = 1
  ) OR
  (ss_hdemo_sk = hd_demo_sk
    AND cd_demo_sk = ss_cdemo_sk
    AND cd_marital_status = 'W'
    AND cd_education_status = '2 yr Degree'
    AND ss_sales_price BETWEEN 150.00 AND 200.00
    AND hd_dep_count = 1
  ))
  AND ((ss_addr_sk = ca_address_sk
  AND ca_country = 'United States'
  AND ca_state IN ('TX', 'OH', 'TX')
  AND ss_net_profit BETWEEN 100 AND 200
) OR
  (ss_addr_sk = ca_address_sk
    AND ca_country = 'United States'
    AND ca_state IN ('OR', 'NM', 'KY')
    AND ss_net_profit BETWEEN 150 AND 300
  ) OR
  (ss_addr_sk = ca_address_sk
    AND ca_country = 'United States'
    AND ca_state IN ('VA', 'TX', 'MS')
    AND ss_net_profit BETWEEN 50 AND 250
  ))
""",
    "tpcds_real_q48": """
SELECT sum(ss_quantity)
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND
  (
    (
      cd_demo_sk = ss_cdemo_sk
        AND
        cd_marital_status = 'M'
        AND
        cd_education_status = '4 yr Degree'
        AND
        ss_sales_price BETWEEN 100.00 AND 150.00
    )
      OR
      (
        cd_demo_sk = ss_cdemo_sk
          AND
          cd_marital_status = 'D'
          AND
          cd_education_status = '2 yr Degree'
          AND
          ss_sales_price BETWEEN 50.00 AND 100.00
      )
      OR
      (
        cd_demo_sk = ss_cdemo_sk
          AND
          cd_marital_status = 'S'
          AND
          cd_education_status = 'College'
          AND
          ss_sales_price BETWEEN 150.00 AND 200.00
      )
  )
  AND
  (
    (
      ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('CO', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000
    )
      OR
      (ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('OR', 'MN', 'KY')
        AND ss_net_profit BETWEEN 150 AND 3000
      )
      OR
      (ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('VA', 'CA', 'MS')
        AND ss_net_profit BETWEEN 50 AND 25000
      )
  )
""",
    "tpcds_real_q15": """
SELECT
  ca_zip,
  sum(cs_sales_price)
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
  OR ca_state IN ('CA', 'WA', 'GA')
  OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
""",
    "tpcds_real_q21": """
SELECT *
FROM (
       SELECT
         w_warehouse_name,
         i_item_id,
         sum(CASE WHEN (cast(d_date AS DATE) < cast('2000-03-11' AS DATE))
           THEN inv_quantity_on_hand
             ELSE 0 END) AS inv_before,
         sum(CASE WHEN (cast(d_date AS DATE) >= cast('2000-03-11' AS DATE))
           THEN inv_quantity_on_hand
             ELSE 0 END) AS inv_after
       FROM inventory, warehouse, item, date_dim
       WHERE i_current_price BETWEEN 0.99 AND 1.49
         AND i_item_sk = inv_item_sk
         AND inv_warehouse_sk = w_warehouse_sk
         AND inv_date_sk = d_date_sk
         AND d_date BETWEEN (cast('2000-03-11' AS DATE) - INTERVAL 30 days)
       AND (cast('2000-03-11' AS DATE) + INTERVAL 30 days)
       GROUP BY w_warehouse_name, i_item_id) x
WHERE (CASE WHEN inv_before > 0
  THEN inv_after / inv_before
       ELSE NULL
       END) BETWEEN 2.0 / 3.0 AND 3.0 / 2.0
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
""",
    "tpcds_real_q26": """
SELECT
  i_item_id,
  avg(cs_quantity) agg1,
  avg(cs_list_price) agg2,
  avg(cs_coupon_amt) agg3,
  avg(cs_sales_price) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND
  cs_item_sk = i_item_sk AND
  cs_bill_cdemo_sk = cd_demo_sk AND
  cs_promo_sk = p_promo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  (p_channel_email = 'N' OR p_channel_event = 'N') AND
  d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q37": """
SELECT
  i_item_id,
  i_item_desc,
  i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 68 AND 68 + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN cast('2000-02-01' AS DATE) AND (cast('2000-02-01' AS DATE) + INTERVAL 60 days)
  AND i_manufact_id IN (677, 940, 694, 808)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q42": """
SELECT
  dt.d_year,
  item.i_category_id,
  item.i_category,
  sum(ss_ext_sales_price)
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = 11
  AND dt.d_year = 2000
GROUP BY dt.d_year
  , item.i_category_id
  , item.i_category
ORDER BY sum(ss_ext_sales_price) DESC, dt.d_year
  , item.i_category_id
  , item.i_category
LIMIT 100
""",
    "tpcds_real_q43": """
SELECT
  s_store_name,
  s_store_id,
  sum(CASE WHEN (d_day_name = 'Sunday')
    THEN ss_sales_price
      ELSE NULL END) sun_sales,
  sum(CASE WHEN (d_day_name = 'Monday')
    THEN ss_sales_price
      ELSE NULL END) mon_sales,
  sum(CASE WHEN (d_day_name = 'Tuesday')
    THEN ss_sales_price
      ELSE NULL END) tue_sales,
  sum(CASE WHEN (d_day_name = 'Wednesday')
    THEN ss_sales_price
      ELSE NULL END) wed_sales,
  sum(CASE WHEN (d_day_name = 'Thursday')
    THEN ss_sales_price
      ELSE NULL END) thu_sales,
  sum(CASE WHEN (d_day_name = 'Friday')
    THEN ss_sales_price
      ELSE NULL END) fri_sales,
  sum(CASE WHEN (d_day_name = 'Saturday')
    THEN ss_sales_price
      ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk AND
  s_store_sk = ss_store_sk AND
  s_gmt_offset = -5 AND
  d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales, wed_sales,
  thu_sales, fri_sales, sat_sales
LIMIT 100
""",
    "tpcds_real_q52": """
SELECT
  dt.d_year,
  item.i_brand_id brand_id,
  item.i_brand brand,
  sum(ss_ext_sales_price) ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = 11
  AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, ext_price DESC, brand_id
LIMIT 100
""",
    "tpcds_real_q55": """
SELECT
  i_brand_id brand_id,
  i_brand brand,
  sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, brand_id
LIMIT 100
""",
    "tpcds_real_q82": """
SELECT
  i_item_id,
  i_item_desc,
  i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 62 AND 62 + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN cast('2000-05-25' AS DATE) AND (cast('2000-05-25' AS DATE) + INTERVAL 60 days)
  AND i_manufact_id IN (129, 270, 821, 423)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q62": """
SELECT
  substr(w_warehouse_name, 1, 20),
  sm_type,
  web_name,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 30) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 60) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 90) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  web_sales, warehouse, ship_mode, web_site, date_dim
WHERE
  d_month_seq BETWEEN 1200 AND 1200 + 11
    AND ws_ship_date_sk = d_date_sk
    AND ws_warehouse_sk = w_warehouse_sk
    AND ws_ship_mode_sk = sm_ship_mode_sk
    AND ws_web_site_sk = web_site_sk
GROUP BY
  substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY
  substr(w_warehouse_name, 1, 20), sm_type, web_name
LIMIT 100
""",
    "tpcds_real_q99": """
SELECT
  substr(w_warehouse_name, 1, 20),
  sm_type,
  cc_name,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 30) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 60) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 90) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE
  d_month_seq BETWEEN 1200 AND 1200 + 11
    AND cs_ship_date_sk = d_date_sk
    AND cs_warehouse_sk = w_warehouse_sk
    AND cs_ship_mode_sk = sm_ship_mode_sk
    AND cs_call_center_sk = cc_call_center_sk
GROUP BY
  substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
LIMIT 100
""",
    "tpcds_real_q96": """
SELECT count(*)
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = time_dim.t_time_sk
  AND ss_hdemo_sk = household_demographics.hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND time_dim.t_hour = 20
  AND time_dim.t_minute >= 30
  AND household_demographics.hd_dep_count = 7
  AND store.s_store_name = 'ese'
ORDER BY count(*)
LIMIT 100
""",
}

QUERY_NAMES = sorted(QUERY_TEXTS)
