"""Verbatim TPC-DS queries over a synthetic mini-catalog.

The texts below are the published TPC-DS v1.4 benchmark queries with the
reference's parameter substitutions (the same queries the reference runs
through Spark for its 99 approved-plan goldens —
goldstandard/TPCDSBase.scala:41, src/test/resources/tpcds/queries/).
55 of the 99 run today — including CTE queries (q1/q30/q81, the
union-of-channels family q33/q56/q60, the year-over-year family
q11/q74), window-function queries (q12/q20/q47/q53/q57/q63/q89/q98),
ROLLUP + GROUPING() (q5/q18/q22/q27/q36/q77/q86), INTERSECT/EXCEPT
(q38/q87), STDDEV (via q17's family rewrite), duplicate-table-alias
joins (q25/q29/q50), CTE-to-CTE joins with shared column names (q77),
and single-row cross joins (q28/q61/q88/q90). Still out of grammar:
|| concatenation, multi-table/grouped subquery bodies, non-equality
correlation in EXISTS, uncorrelated scalar subqueries, and join
conditions on arithmetic (the q2/q59 weekly-offset shape).

The catalog generator builds every referenced table with exactly the
columns these queries touch, seeded and sized so each query returns a
non-empty answer (each query's literal predicates — manager ids,
manufacturer ids, price bands, date windows — are guaranteed hits by
construction below).
"""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pyarrow as pa

# Calendar span covering every query's date predicates (1998..2002).
_D0 = datetime.date(1998, 1, 1)
N_DD = 1700

_DAY_NAMES = ["Monday", "Tuesday", "Wednesday", "Thursday", "Friday",
              "Saturday", "Sunday"]


def tables(rng: np.random.Generator) -> Dict[str, pa.Table]:
    n_it, n_cu, n_ca, n_st, n_cd, n_pr, n_hd, n_td, n_wh = \
        60, 120, 80, 6, 40, 12, 15, 200, 4
    n_sm, n_web, n_cc = 5, 4, 3
    n_ss, n_cs, n_inv, n_ws = 1600, 1200, 900, 1000

    dates = [_D0 + datetime.timedelta(days=i) for i in range(N_DD)]
    date_dim = pa.table({
        "d_date_sk": pa.array(np.arange(N_DD, dtype=np.int64)),
        "d_date": pa.array(dates, type=pa.date32()),
        "d_year": pa.array(np.array([d.year for d in dates], np.int64)),
        "d_moy": pa.array(np.array([d.month for d in dates], np.int64)),
        "d_qoy": pa.array(np.array([(d.month - 1) // 3 + 1 for d in dates],
                                   np.int64)),
        "d_day_name": pa.array([_DAY_NAMES[d.weekday()] for d in dates]),
        # TPC-DS month sequence: 2000-01 = 1200 (q62/q99's window).
        "d_month_seq": pa.array(np.array(
            [(d.year - 1998) * 12 + (d.month - 1) + 1176 for d in dates],
            np.int64)),
    })

    # Items: cycle manager/manufacturer ids through every value the query
    # texts name, and force price-band coverage (q21: [0.99,1.49],
    # q37: [68,98], q82: [62,92]).
    managers = np.array([1, 8, 28] + list(range(2, 8)) + [9, 10],
                        dtype=np.int64)
    manufacts = np.array([128, 677, 940, 694, 808, 129, 270, 821, 423, 55],
                         dtype=np.int64)
    prices = np.round(rng.uniform(1, 110, n_it), 2)
    prices[0:6] = [1.10, 1.25, 70.0, 80.0, 65.0, 90.0]
    cats = ["Music", "Books", "Sports", "Home", "Shoes"]
    item = pa.table({
        "i_item_sk": pa.array(np.arange(n_it, dtype=np.int64)),
        "i_item_id": pa.array([f"ITEM{i:08d}" for i in range(n_it)]),
        "i_item_desc": pa.array([f"desc of item {i}" for i in range(n_it)]),
        "i_brand_id": pa.array((np.arange(n_it, dtype=np.int64) % 9) + 1),
        "i_brand": pa.array([f"brand#{(i % 9) + 1}" for i in range(n_it)]),
        "i_manufact_id": pa.array(manufacts[np.arange(n_it) % len(manufacts)]),
        "i_manufact": pa.array(
            [f"manufact{int(m)}" for m in
             manufacts[np.arange(n_it) % len(manufacts)]]),
        "i_category_id": pa.array((np.arange(n_it, dtype=np.int64) % 5) + 1),
        "i_category": pa.array([cats[i % 5] for i in range(n_it)]),
        "i_class": pa.array([f"class{i % 4}" for i in range(n_it)]),
        "i_current_price": pa.array(prices),
        "i_manager_id": pa.array(managers[np.arange(n_it) % len(managers)]),
    })

    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(n_cu, dtype=np.int64)),
        "c_current_addr_sk": pa.array(
            rng.integers(0, n_ca, n_cu).astype(np.int64)),
    })
    zips = ["85669", "86197", "60601", "10001", "94111", "30301", "73301",
            "88274"]
    states = ["CA", "WA", "GA", "TN", "TX", "NY", "OH", "OR", "NM",
              "KY", "VA", "MS", "IN", "WI", "MO"]
    customer_address = pa.table({
        "ca_address_sk": pa.array(np.arange(n_ca, dtype=np.int64)),
        "ca_zip": pa.array([zips[i % len(zips)] + "0000" for i in
                            range(n_ca)]),
        "ca_state": pa.array([states[i % len(states)] for i in range(n_ca)]),
        "ca_country": pa.array(["United States"] * n_ca),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(n_st, dtype=np.int64)),
        "s_store_id": pa.array([f"S{i:04d}" for i in range(n_st)]),
        "s_store_name": pa.array(
            ["ese" if i % 3 == 0 else f"store{i}" for i in range(n_st)]),
        "s_zip": pa.array([zips[(i + 3) % len(zips)] + "0000"
                           for i in range(n_st)]),
        "s_gmt_offset": pa.array(
            np.where(np.arange(n_st) % 2 == 0, -5, -6).astype(np.int64)),
    })
    maritals = ["M", "S", "W", "D", "U"]
    educations = ["Advanced Degree", "College", "2 yr Degree",
                  "4 yr Degree", "Secondary"]
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(np.arange(n_cd, dtype=np.int64)),
        "cd_gender": pa.array(["M" if i % 2 == 0 else "F"
                               for i in range(n_cd)]),
        # Independent small cycles: every (marital, education) pair the
        # query texts name co-occurs within n_cd=40 rows (q7/q26 need
        # (S, College); q13 (M, Advanced Degree), (S, College),
        # (W, 2 yr Degree); q48 (M, 4 yr Degree), (D, 2 yr Degree)).
        "cd_marital_status": pa.array(
            [maritals[i % 5] for i in range(n_cd)]),
        "cd_education_status": pa.array(
            [educations[(i + i // 5) % 5] for i in range(n_cd)]),
    })
    promotion = pa.table({
        "p_promo_sk": pa.array(np.arange(n_pr, dtype=np.int64)),
        "p_channel_email": pa.array(["N" if i % 2 == 0 else "Y"
                                     for i in range(n_pr)]),
        "p_channel_event": pa.array(["N" if i % 3 == 0 else "Y"
                                     for i in range(n_pr)]),
    })
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(np.arange(n_hd, dtype=np.int64)),
        "hd_dep_count": pa.array((np.arange(n_hd, dtype=np.int64) % 10)),
    })
    time_dim = pa.table({
        "t_time_sk": pa.array(np.arange(n_td, dtype=np.int64)),
        "t_hour": pa.array((np.arange(n_td, dtype=np.int64) % 24)),
        "t_minute": pa.array(
            ((np.arange(n_td, dtype=np.int64) * 7) % 60)),
    })
    warehouse = pa.table({
        "w_warehouse_sk": pa.array(np.arange(n_wh, dtype=np.int64)),
        "w_warehouse_name": pa.array([f"Warehouse number {i}"
                                      for i in range(n_wh)]),
    })
    ship_mode = pa.table({
        "sm_ship_mode_sk": pa.array(np.arange(n_sm, dtype=np.int64)),
        "sm_type": pa.array(["EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY",
                             "LIBRARY"][:n_sm]),
    })
    web_site = pa.table({
        "web_site_sk": pa.array(np.arange(n_web, dtype=np.int64)),
        "web_name": pa.array([f"site_{i}" for i in range(n_web)]),
    })
    call_center = pa.table({
        "cc_call_center_sk": pa.array(np.arange(n_cc, dtype=np.int64)),
        "cc_name": pa.array([f"call center {i}" for i in range(n_cc)]),
    })
    rng2 = np.random.default_rng(99)
    ws_sold = rng.integers(0, N_DD - 150, n_ws).astype(np.int64)
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(ws_sold),
        "ws_ship_date_sk": pa.array(
            ws_sold + rng.integers(1, 140, n_ws).astype(np.int64)),
        "ws_warehouse_sk": pa.array(
            rng.integers(0, n_wh, n_ws).astype(np.int64)),
        "ws_ship_mode_sk": pa.array(
            rng.integers(0, n_sm, n_ws).astype(np.int64)),
        "ws_web_site_sk": pa.array(
            rng.integers(0, n_web, n_ws).astype(np.int64)),
    })

    # Constructed hit rows make the q13/q48 compound predicates TRUE by
    # construction, not seed luck: both are scalar aggregates that return
    # one row even with zero matches, so an accidentally-empty match set
    # would never fail the non-empty guard (r4 review finding).
    ss_sold = rng.integers(0, N_DD, n_ss).astype(np.int64)
    ss_cdemo = rng.integers(0, n_cd, n_ss).astype(np.int64)
    ss_hdemo = rng.integers(0, n_hd, n_ss).astype(np.int64)
    ss_price = np.round(rng.uniform(1, 290, n_ss), 2)
    d2001 = (datetime.date(2001, 6, 15) - _D0).days
    for j in range(4):
        ss_sold[j] = d2001 + j
        ss_cdemo[j] = 0       # (M, Advanced Degree) — q13 branch 1
        ss_hdemo[j] = 3       # hd_dep_count == 3
        ss_price[j] = 120.0   # in [100, 150]
    for j in range(4, 8):
        ss_sold[j] = d2001 + j
        ss_cdemo[j] = 1       # i=1: marital S, education College (q48 b3)
        ss_price[j] = 170.0   # in [150, 200]
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(ss_sold),
        "ss_sold_time_sk": pa.array(
            rng.integers(0, n_td, n_ss).astype(np.int64)),
        "ss_item_sk": pa.array(rng.integers(0, n_it, n_ss).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(0, n_cu, n_ss).astype(np.int64)),
        "ss_cdemo_sk": pa.array(ss_cdemo),
        "ss_hdemo_sk": pa.array(ss_hdemo),
        "ss_promo_sk": pa.array(rng.integers(0, n_pr, n_ss).astype(np.int64)),
        "ss_store_sk": pa.array(rng.integers(0, n_st, n_ss).astype(np.int64)),
        "ss_quantity": pa.array(rng.integers(1, 100, n_ss).astype(np.int64)),
        "ss_list_price": pa.array(np.round(rng.uniform(1, 300, n_ss), 2)),
        "ss_coupon_amt": pa.array(np.round(rng.uniform(0, 40, n_ss), 2)),
        "ss_sales_price": pa.array(ss_price),
        "ss_ext_sales_price": pa.array(
            np.round(rng.uniform(5, 4000, n_ss), 2)),
        # q13/q48 columns from a SEPARATE generator: appending draws to
        # the shared one would shift every later table and churn the
        # whole corpus' data.
        "ss_ext_wholesale_cost": pa.array(
            np.round(rng2.uniform(1, 100, n_ss), 2)),
        "ss_addr_sk": pa.array(np.concatenate(
            [np.full(8, 4, np.int64),  # ca 4 = TX, United States
             rng2.integers(0, n_ca, n_ss - 8).astype(np.int64)])),
        "ss_net_profit": pa.array(np.concatenate(
            [np.full(8, 150.0),       # inside every profit band used
             np.round(rng2.uniform(0, 330, n_ss - 8), 2)])),
    })
    cs_sold = rng.integers(0, N_DD - 150, n_cs).astype(np.int64)
    catalog_sales = pa.table({
        "cs_sold_date_sk": pa.array(cs_sold),
        "cs_ship_date_sk": pa.array(
            cs_sold + rng.integers(1, 140, n_cs).astype(np.int64)),
        "cs_warehouse_sk": pa.array(
            rng.integers(0, n_wh, n_cs).astype(np.int64)),
        "cs_ship_mode_sk": pa.array(
            rng.integers(0, n_sm, n_cs).astype(np.int64)),
        "cs_call_center_sk": pa.array(
            rng.integers(0, n_cc, n_cs).astype(np.int64)),
        "cs_item_sk": pa.array(rng.integers(0, n_it, n_cs).astype(np.int64)),
        "cs_bill_customer_sk": pa.array(
            rng.integers(0, n_cu, n_cs).astype(np.int64)),
        "cs_bill_cdemo_sk": pa.array(
            rng.integers(0, n_cd, n_cs).astype(np.int64)),
        "cs_promo_sk": pa.array(rng.integers(0, n_pr, n_cs).astype(np.int64)),
        "cs_quantity": pa.array(rng.integers(1, 100, n_cs).astype(np.int64)),
        "cs_list_price": pa.array(np.round(rng.uniform(1, 300, n_cs), 2)),
        "cs_coupon_amt": pa.array(np.round(rng.uniform(0, 40, n_cs), 2)),
        "cs_sales_price": pa.array(np.round(rng.uniform(1, 600, n_cs), 2)),
        "cs_ext_sales_price": pa.array(
            np.round(rng.uniform(5, 4000, n_cs), 2)),
    })
    # Inventory dates concentrated around the q21/q37/q82 windows so the
    # ±30/60-day BETWEENs keep rows.
    inv_base = (datetime.date(2000, 2, 1) - _D0).days
    inventory = pa.table({
        "inv_item_sk": pa.array(rng.integers(0, n_it, n_inv).astype(np.int64)),
        "inv_warehouse_sk": pa.array(
            rng.integers(0, n_wh, n_inv).astype(np.int64)),
        "inv_date_sk": pa.array(
            (inv_base + rng.integers(0, 160, n_inv)).astype(np.int64)),
        "inv_quantity_on_hand": pa.array(
            rng.integers(0, 600, n_inv).astype(np.int64)),
    })

    out = {
        "date_dim": date_dim, "item": item, "customer": customer,
        "customer_address": customer_address, "store": store,
        "customer_demographics": customer_demographics,
        "promotion": promotion,
        "household_demographics": household_demographics,
        "time_dim": time_dim, "warehouse": warehouse,
        "ship_mode": ship_mode, "web_site": web_site,
        "call_center": call_center, "web_sales": web_sales,
        "store_sales": store_sales, "catalog_sales": catalog_sales,
        "inventory": inventory,
    }
    _extend_catalog(out, dates)
    return out


def _np(t: pa.Table, name: str) -> np.ndarray:
    return t.column(name).to_numpy(zero_copy_only=False).copy()


def _set(t: pa.Table, name: str, arr) -> pa.Table:
    idx = t.schema.get_field_index(name)
    return t.set_column(idx, name, pa.array(arr))


def _add(t: pa.Table, name: str, arr, typ=None) -> pa.Table:
    return t.append_column(name, pa.array(arr, type=typ))


def _extend_catalog(out, dates) -> None:
    """Round-5 corpus extension: the columns, tables, and constructed hit
    rows the CTE/window/cross-join queries need (q1, q12/q20/q98, q25,
    q28/q61/q88/q90, q29, q30, q33/q56/q60, q34/q73, q46/q68/q79, q50,
    q53/q63/q89, q81, q91). Everything here either APPENDS columns (fresh
    generators — appending draws to the shared rng would shift every
    later table and churn the corpus) or overwrites targeted rows far
    from the constructed guarantee rows 0-7."""
    rngx = np.random.default_rng(4242)
    n_dd = len(out["date_dim"])

    # --- date_dim: day-of-month / day-of-week (TPC-DS d_dow: Sunday=0).
    dd = out["date_dim"]
    dd = _add(dd, "d_dom", np.array([d.day for d in dates], np.int64))
    dd = _add(dd, "d_dow",
              np.array([(d.weekday() + 1) % 7 for d in dates], np.int64))
    out["date_dim"] = dd

    # --- item: q53/q63/q89 (category, class, brand) combos on rows 6-19
    # (guarantee rows 0-5 pin prices; manager/manufact cycles untouched),
    # plus Electronics/Jewelry coverage for q33/q61 and i_color for q56.
    it = out["item"]
    n_it = len(it)
    cat = _np(it, "i_category").astype(object)
    cls = _np(it, "i_class").astype(object)
    brd = _np(it, "i_brand").astype(object)
    combos = [
        (6, "Books", "personal", "scholaramalgamalg #14"),
        (7, "Books", "portable", "scholaramalgamalg #7"),
        (8, "Children", "reference", "exportiunivamalg #9"),
        (9, "Electronics", "refernece", "scholaramalgamalg #9"),
        (10, "Women", "accessories", "amalgimporto #1"),
        (11, "Music", "classical", "edu packscholar #1"),
        (12, "Men", "fragrances", "exportiimporto #1"),
        (13, "Women", "pants", "importoamalg #1"),
        (14, "Books", "computers", "scholaramalgamalg #6"),
        (15, "Electronics", "stereo", "importoexporti #2"),
        (16, "Sports", "football", "edu packimporto #2"),
        (17, "Men", "shirts", "importoamalg #2"),
        (18, "Jewelry", "birdal", "amalgedu pack #2"),
        (19, "Women", "dresses", "exportiunivamalg #2"),
        (20, "Jewelry", "estate", "edu packamalg #2"),
        (21, "Electronics", "portable", "scholaramalgamalg #7"),
    ]
    for i, c, k, b in combos:
        cat[i], cls[i], brd[i] = c, k, b
    it = _set(it, "i_category", cat)
    it = _set(it, "i_class", cls)
    it = _set(it, "i_brand", brd)
    colors = ["slate", "blanched", "burnished", "powder", "peru",
              "saddle", "navajo", "spring"]
    it = _add(it, "i_color", [colors[i % len(colors)] for i in range(n_it)])
    out["item"] = it

    # --- store: location/company columns (q1 s_state, q34/q73 s_county,
    # q46/q68/q79 s_city + employees, q50's address block, q89
    # s_company_name).
    st = out["store"]
    st = _add(st, "s_state", ["TN", "SC", "GA", "TN", "OH", "TX"])
    st = _add(st, "s_county",
              ["Williamson County", "Ziebach County", "Williamson County",
               "Daviess County", "Williamson County", "Barrow County"])
    st = _add(st, "s_city", ["Fairview", "Midway", "Fairview", "Oak Grove",
                             "Midway", "Glendale"])
    st = _add(st, "s_company_id", np.array([1, 2, 1, 2, 1, 2], np.int64))
    st = _add(st, "s_company_name",
              ["Unknown", "ese co", "Unknown", "Mid Co", "Unknown", "North"])
    st = _add(st, "s_street_number", [str(100 + 7 * i) for i in range(6)])
    st = _add(st, "s_street_name",
              ["Main", "Oak", "Park", "First", "Cedar", "Elm"])
    st = _add(st, "s_street_type", ["St", "Ave", "Blvd", "Ln", "Ct", "Dr"])
    st = _add(st, "s_suite_number", [f"Suite {i * 10}" for i in range(6)])
    st = _add(st, "s_number_employees",
              np.array([210, 250, 280, 300, 220, 290], np.int64))
    out["store"] = st

    # --- customer demographics: q91 needs (M, Unknown) and
    # (W, Advanced Degree) pairs — overwrite rows 30/31 (the documented
    # guarantee pairs live at rows 0-3, 15, 23).
    cd = out["customer_demographics"]
    mar = _np(cd, "cd_marital_status").astype(object)
    edu = _np(cd, "cd_education_status").astype(object)
    mar[30], edu[30] = "M", "Unknown"
    mar[31], edu[31] = "W", "Advanced Degree"
    cd = _set(cd, "cd_marital_status", mar)
    cd = _set(cd, "cd_education_status", edu)
    out["customer_demographics"] = cd

    # --- household demographics: buying potential + vehicles (q34/q73/
    # q46/q68/q79/q88/q90/q91). Row 6: ('unknown', 1 vehicle, 6 deps) —
    # passes the q34/q73 ratio filters; row 14: dep 4 (q46/q68).
    hd = out["household_demographics"]
    n_hd = len(hd)
    pots = [">10000", "unknown", "Unknown", "501-1000", "1001-5000"]
    hd = _add(hd, "hd_buy_potential",
              [pots[i % 5] for i in range(n_hd)])
    hd = _add(hd, "hd_vehicle_count",
              np.array([i % 5 for i in range(n_hd)], np.int64))
    out["household_demographics"] = hd

    # --- customer: identity/biography columns + demo/addr links.
    cu = out["customer"]
    n_cu = len(cu)
    countries = ["United States", "Canada", "Mexico", "Japan"]
    cu = _add(cu, "c_customer_id", [f"AAAAAAAA{i:05d}" for i in range(n_cu)])
    cu = _add(cu, "c_salutation",
              [["Mr.", "Mrs.", "Ms.", "Dr.", "Sir"][i % 5]
               for i in range(n_cu)])
    cu = _add(cu, "c_first_name", [f"First{i:03d}" for i in range(n_cu)])
    cu = _add(cu, "c_last_name", [f"Last{i:03d}" for i in range(n_cu)])
    cu = _add(cu, "c_preferred_cust_flag",
              ["Y" if i % 2 else "N" for i in range(n_cu)])
    cu = _add(cu, "c_birth_day",
              np.array([(i % 28) + 1 for i in range(n_cu)], np.int64))
    cu = _add(cu, "c_birth_month",
              np.array([(i % 12) + 1 for i in range(n_cu)], np.int64))
    cu = _add(cu, "c_birth_year",
              np.array([1940 + (i % 60) for i in range(n_cu)], np.int64))
    cu = _add(cu, "c_birth_country",
              [countries[i % 4] for i in range(n_cu)])
    cu = _add(cu, "c_login", [f"login{i}" for i in range(n_cu)])
    cu = _add(cu, "c_email_address",
              [f"c{i}@example.com" for i in range(n_cu)])
    cu = _add(cu, "c_last_review_date",
              [str(2450000 + i) for i in range(n_cu)])
    cdemo = rngx.integers(0, 40, n_cu).astype(np.int64)
    hdemo = rngx.integers(0, n_hd, n_cu).astype(np.int64)
    # q91 hits: customers 100-103 carry the (M, Unknown)/(W, Advanced
    # Degree) demographics, an 'Unknown%' buy potential, and a GMT -7
    # address (addr 11 — see ca_gmt_offset below).
    cdemo[100:104] = [30, 31, 30, 31]
    hdemo[100:104] = 2  # pots[2] = 'Unknown'
    cu = _add(cu, "c_current_cdemo_sk", cdemo)
    cu = _add(cu, "c_current_hdemo_sk", hdemo)
    addr = _np(cu, "c_current_addr_sk")
    addr[100:104] = 11   # ca_gmt_offset -7 (q91)
    addr[110:116] = 2    # ca_state 'GA' (q30/q81 outer join)
    cu = _set(cu, "c_current_addr_sk", addr)
    out["customer"] = cu

    # --- customer_address: timezone, city, street block (q33/q56/q60/q61
    # gmt -5, q91 gmt -7, q46/q68 city inequality, q81's address block).
    ca = out["customer_address"]
    n_ca = len(ca)
    gmt = np.full(n_ca, -5, np.int64)
    gmt[np.arange(n_ca) % 16 == 7] = -6
    gmt[np.arange(n_ca) % 16 == 11] = -7
    ca = _add(ca, "ca_gmt_offset", gmt)
    cities = ["Fairview", "Midway", "Oak Grove", "Glendale", "Sunnyside",
              "Five Points", "Pleasant Hill", "Union"]
    ca = _add(ca, "ca_city", [cities[i % 8] for i in range(n_ca)])
    ca = _add(ca, "ca_county",
              [["Williamson County", "Walker County", "Daviess County",
                "Luce County"][i % 4] for i in range(n_ca)])
    ca = _add(ca, "ca_street_number", [str(200 + 3 * i) for i in range(n_ca)])
    ca = _add(ca, "ca_street_name",
              [["Jackson", "Washington", "Lincoln", "Adams"][i % 4]
               for i in range(n_ca)])
    ca = _add(ca, "ca_street_type", [["Ave", "Blvd", "St", "Ln"][i % 4]
                                     for i in range(n_ca)])
    ca = _add(ca, "ca_suite_number", [f"Suite {i % 40}" for i in range(n_ca)])
    ca = _add(ca, "ca_location_type",
              [["apartment", "condo", "single family"][i % 3]
               for i in range(n_ca)])
    out["customer_address"] = ca

    # --- call_center / web_site / promotion / web_page.
    cc = out["call_center"]
    cc = _add(cc, "cc_call_center_id",
              [f"AAAAAAAA{i}CC" for i in range(len(cc))])
    cc = _add(cc, "cc_manager",
              ["Bob Belcher", "Felipe Perkins", "Mark Hightower"])
    cc = _add(cc, "cc_county", ["Williamson County"] * len(cc))
    out["call_center"] = cc
    ws_site = out["web_site"]
    ws_site = _add(ws_site, "web_company_name",
                   ["pri", "allison", "eing", "pri"])
    ws_site = _add(ws_site, "web_site_id",
                   [f"AAAAAAAA{i}WS" for i in range(len(ws_site))])
    out["web_site"] = ws_site
    st5 = out["store"]
    if "s_store_id" not in st5.schema.names:
        st5 = _add(st5, "s_store_id",
                   [f"AAAAAAAA{i}ST" for i in range(len(st5))])
        out["store"] = st5
    pr = out["promotion"]
    n_pr = len(pr)
    pr = _add(pr, "p_channel_dmail",
              ["Y" if i % 2 == 0 else "N" for i in range(n_pr)])
    pr = _add(pr, "p_channel_tv",
              ["Y" if i % 3 == 0 else "N" for i in range(n_pr)])
    out["promotion"] = pr
    out["web_page"] = pa.table({
        "wp_web_page_sk": pa.array(np.arange(4, dtype=np.int64)),
        "wp_char_count": pa.array(
            np.array([5050, 5100, 5150, 4000], np.int64)),
    })

    # --- store_sales: tickets + price extensions + constructed hit rows.
    ss = out["store_sales"]
    n_ss = len(ss)
    ticket = (np.arange(n_ss, dtype=np.int64) // 3)
    sold = _np(ss, "ss_sold_date_sk")
    cust = _np(ss, "ss_customer_sk")
    item_sk = _np(ss, "ss_item_sk")
    hdemo_sk = _np(ss, "ss_hdemo_sk")
    store_sk = _np(ss, "ss_store_sk")
    promo_sk = _np(ss, "ss_promo_sk")
    addr_sk = _np(ss, "ss_addr_sk")

    def day(y, m, d):
        return (datetime.date(y, m, d) - _D0).days

    # q34: two 16-row tickets passing every filter (count in [15, 20]).
    for j in range(32):
        r = 200 + j
        ticket[r] = 900001 + j // 16
        cust[r] = 50 + j // 16
        hdemo_sk[r] = 6
        store_sk[r] = 0
        sold[r] = day(1999, 6, 1)      # d_dom 1, d_year 1999
    # q73: six singleton tickets (count in [1, 5]).
    for j in range(6):
        r = 232 + j
        ticket[r] = 900010 + j
        cust[r] = 52 + (j % 2)
        hdemo_sk[r] = 6
        store_sk[r] = 0
        sold[r] = day(1999, 6, 1)
    # q46: weekend sales, Fairview store, dep-4 household, varied addr.
    for j in range(4):
        r = 240 + j
        ticket[r] = 900020 + j
        cust[r] = 54 + j
        hdemo_sk[r] = 14
        store_sk[r] = 0
        sold[r] = day(1999, 6, 5)      # Saturday: d_dow 6
        addr_sk[r] = j
    # q68: dom 1-2, Midway store, dep-4 household.
    for j in range(4):
        r = 244 + j
        ticket[r] = 900030 + j
        cust[r] = 58 + j
        hdemo_sk[r] = 14
        store_sk[r] = 1
        sold[r] = day(1999, 6, 1)
        addr_sk[r] = 4 + j
    # q79: Monday sales, dep-6 household, store with 200-295 employees.
    for j in range(4):
        r = 248 + j
        ticket[r] = 900040 + j
        cust[r] = 62 + j
        hdemo_sk[r] = 6
        store_sk[r] = 0
        sold[r] = day(1999, 6, 7)      # Monday: d_dow 1
    # q61: Jewelry sales in 1998-11 through a dmail promotion, gmt -5.
    for j in range(8):
        r = 252 + j
        item_sk[r] = 18
        promo_sk[r] = 0
        cust[r] = 64 + j
        store_sk[r] = 0
        sold[r] = day(1998, 11, 10)
    # q25 / q29 / q50 chains (sales whose returns and follow-on catalog
    # purchases are constructed below).
    for j in range(6):
        r = 260 + j
        sold[r] = day(2001, 4, 10) + j
        cust[r] = 80 + j
        item_sk[r] = 30 + j
        ticket[r] = 910000 + j
        store_sk[r] = 2
    for j in range(4):
        r = 266 + j
        sold[r] = day(1999, 9, 10) + j
        cust[r] = 86 + j
        item_sk[r] = 35 + j
        ticket[r] = 910100 + j
        store_sk[r] = 2
    for j in range(4):
        r = 270 + j
        sold[r] = day(2001, 7, 20) + j
        cust[r] = 90 + j
        item_sk[r] = 40 + j
        ticket[r] = 910200 + j
        store_sk[r] = 3
    ss = _set(ss, "ss_sold_date_sk", sold)
    ss = _set(ss, "ss_customer_sk", cust)
    ss = _set(ss, "ss_item_sk", item_sk)
    ss = _set(ss, "ss_hdemo_sk", hdemo_sk)
    ss = _set(ss, "ss_store_sk", store_sk)
    ss = _set(ss, "ss_promo_sk", promo_sk)
    ss = _set(ss, "ss_addr_sk", addr_sk)
    ss = _add(ss, "ss_ticket_number", ticket)
    ss = _add(ss, "ss_ext_list_price",
              np.round(rngx.uniform(10, 500, n_ss), 2))
    ss = _add(ss, "ss_ext_tax", np.round(rngx.uniform(0, 30, n_ss), 2))
    ss = _add(ss, "ss_wholesale_cost",
              np.round(rngx.uniform(1, 100, n_ss), 2))
    out["store_sales"] = ss

    # --- catalog_sales: profit/addr columns + the q25/q29 chain rows.
    cs = out["catalog_sales"]
    n_cs = len(cs)
    cs_cust = _np(cs, "cs_bill_customer_sk")
    cs_item = _np(cs, "cs_item_sk")
    cs_sold = _np(cs, "cs_sold_date_sk")
    for j in range(6):
        r = 200 + j
        cs_cust[r] = 80 + j
        cs_item[r] = 30 + j
        cs_sold[r] = day(2001, 7, 5) + j   # moy 7 in [4, 10]
    for j in range(4):
        r = 206 + j
        cs_cust[r] = 86 + j
        cs_item[r] = 35 + j
        cs_sold[r] = day(2000, 3, 15) + j  # year 2000 in (1999..2001)
    cs = _set(cs, "cs_bill_customer_sk", cs_cust)
    cs = _set(cs, "cs_item_sk", cs_item)
    cs = _set(cs, "cs_sold_date_sk", cs_sold)
    cs = _add(cs, "cs_net_profit", np.round(rngx.uniform(-50, 300, n_cs), 2))
    cs = _add(cs, "cs_bill_addr_sk",
              rngx.integers(0, n_ca, n_cs).astype(np.int64))
    out["catalog_sales"] = cs

    # --- web_sales: item/price/addr/page columns (q12/q33/q56/q60/q90).
    wsl = out["web_sales"]
    n_ws = len(wsl)
    wsl = _add(wsl, "ws_item_sk",
               rngx.integers(0, n_it, n_ws).astype(np.int64))
    wsl = _add(wsl, "ws_ext_sales_price",
               np.round(rngx.uniform(5, 4000, n_ws), 2))
    wsl = _add(wsl, "ws_sales_price",
               np.round(rngx.uniform(1, 600, n_ws), 2))
    wsl = _add(wsl, "ws_bill_addr_sk",
               rngx.integers(0, n_ca, n_ws).astype(np.int64))
    wsl = _add(wsl, "ws_sold_time_sk",
               rngx.integers(0, 200, n_ws).astype(np.int64))
    wsl = _add(wsl, "ws_ship_hdemo_sk",
               rngx.integers(0, n_hd, n_ws).astype(np.int64))
    wsl = _add(wsl, "ws_web_page_sk",
               rngx.integers(0, 4, n_ws).astype(np.int64))
    out["web_sales"] = wsl

    # --- store_returns: background rows sampled from store_sales (so the
    # (customer, item, ticket) joins hit) + the q1/q25/q29/q50 chains.
    n_bg = 380
    bg = rngx.integers(8, n_ss, n_bg)
    sr_item = item_sk[bg].copy()
    sr_cust = cust[bg].copy()
    sr_tick = ticket[bg].copy()
    sr_store = store_sk[bg].copy()
    sr_ret = np.minimum(sold[bg] + rngx.integers(5, 120, n_bg), n_dd - 1)
    sr_amt = np.round(rngx.uniform(10, 200, n_bg), 2)
    sr_loss = np.round(rngx.uniform(5, 150, n_bg), 2)
    sr_qty = rngx.integers(1, 10, n_bg).astype(np.int64)

    def chain(rows, ret_days):
        idx = np.array(rows)
        return (item_sk[idx], cust[idx], ticket[idx], store_sk[idx],
                np.array(ret_days, np.int64))

    extra = []
    # q1: large returns for customers 0-2 at the TN store 0 in 2000.
    for j in range(3):
        extra.append((j, j, 920000 + j, 0, day(2000, 5, 10) + j,
                      9000.0 + j, 100.0, 2))
    # q25 chain: returned 2001-06 (moy in [4, 10]).
    for j in range(6):
        r = 260 + j
        extra.append((item_sk[r], cust[r], ticket[r], store_sk[r],
                      day(2001, 6, 15) + j, 120.0, 80.0 + j, 3))
    # q29 chain: returned 1999-10 (moy in [9, 12]).
    for j in range(4):
        r = 266 + j
        extra.append((item_sk[r], cust[r], ticket[r], store_sk[r],
                      day(1999, 10, 20) + j, 90.0, 60.0, 4))
    # q50 chain: returned 2001-08, within 30 days of the sale.
    for j in range(4):
        r = 270 + j
        extra.append((item_sk[r], cust[r], ticket[r], store_sk[r],
                      day(2001, 8, 5) + j, 70.0, 40.0, 2))
    ex = np.array(extra, dtype=object)
    out["store_returns"] = pa.table({
        "sr_item_sk": pa.array(np.concatenate(
            [sr_item, ex[:, 0].astype(np.int64)])),
        "sr_customer_sk": pa.array(np.concatenate(
            [sr_cust, ex[:, 1].astype(np.int64)])),
        "sr_ticket_number": pa.array(np.concatenate(
            [sr_tick, ex[:, 2].astype(np.int64)])),
        "sr_store_sk": pa.array(np.concatenate(
            [sr_store, ex[:, 3].astype(np.int64)])),
        "sr_returned_date_sk": pa.array(np.concatenate(
            [sr_ret, ex[:, 4].astype(np.int64)])),
        "sr_return_amt": pa.array(np.concatenate(
            [sr_amt, ex[:, 5].astype(np.float64)])),
        "sr_net_loss": pa.array(np.concatenate(
            [sr_loss, ex[:, 6].astype(np.float64)])),
        "sr_return_quantity": pa.array(np.concatenate(
            [sr_qty, ex[:, 7].astype(np.int64)])),
    })

    # --- catalog_returns: background + q91 (1998-11, call centers) and
    # q81 (2000, large amounts, GA customers 110-113).
    n_cr = 300
    cr_cust = rngx.integers(0, n_cu, n_cr).astype(np.int64)
    cr_addr = rngx.integers(0, n_ca, n_cr).astype(np.int64)
    cr_ret = rngx.integers(0, n_dd, n_cr).astype(np.int64)
    cr_amt = np.round(rngx.uniform(5, 100, n_cr), 2)
    cr_cc = rngx.integers(0, 3, n_cr).astype(np.int64)
    cr_loss = np.round(rngx.uniform(5, 200, n_cr), 2)
    cr_cust[0:4] = [100, 101, 102, 103]
    cr_ret[0:4] = [day(1998, 11, 5) + j for j in range(4)]
    cr_loss[0:4] = [500.0 + 10 * j for j in range(4)]
    cr_cust[4:8] = [110, 111, 112, 113]
    cr_addr[4:8] = 2
    cr_ret[4:8] = [day(2000, 3, 10) + j for j in range(4)]
    cr_amt[4:8] = [8000.0 + j for j in range(4)]
    out["catalog_returns"] = pa.table({
        "cr_returning_customer_sk": pa.array(cr_cust),
        "cr_returning_addr_sk": pa.array(cr_addr),
        "cr_returned_date_sk": pa.array(cr_ret),
        "cr_return_amt_inc_tax": pa.array(cr_amt),
        "cr_call_center_sk": pa.array(cr_cc),
        "cr_net_loss": pa.array(cr_loss),
    })

    # --- Round-5 wave 2: the year-over-year / channel-union families
    # (q5/q11/q18/q22/q38/q49/q74/q77/q86/q87).
    cd2 = out["customer_demographics"]
    cd2 = _add(cd2, "cd_dep_count",
               np.array([(i % 7) for i in range(len(cd2))], np.int64))
    out["customer_demographics"] = cd2
    it2 = out["item"]
    it2 = _add(it2, "i_product_name",
               [f"product{i:04d}" for i in range(len(it2))])
    out["item"] = it2
    ss2 = out["store_sales"]
    ss2 = _add(ss2, "ss_net_paid",
               np.round(rngx.uniform(5, 2000, len(ss2)), 2))
    ss2 = _add(ss2, "ss_ext_discount_amt",
               np.round(rngx.uniform(0, 80, len(ss2)), 2))
    out["store_sales"] = ss2
    cs2 = out["catalog_sales"]
    cs2 = _add(cs2, "cs_net_paid",
               np.round(rngx.uniform(5, 2000, len(cs2)), 2))
    cs2 = _add(cs2, "cs_ext_discount_amt",
               np.round(rngx.uniform(0, 80, len(cs2)), 2))
    cs2 = _add(cs2, "cs_catalog_page_sk",
               rngx.integers(0, 6, len(cs2)).astype(np.int64))
    cs2 = _add(cs2, "cs_order_number",
               np.arange(len(cs2), dtype=np.int64) // 2)
    out["catalog_sales"] = cs2
    ws2 = out["web_sales"]
    n_ws2 = len(ws2)
    ws2 = _add(ws2, "ws_order_number",
               np.arange(n_ws2, dtype=np.int64) // 2)
    ws2 = _add(ws2, "ws_quantity",
               rngx.integers(1, 100, n_ws2).astype(np.int64))
    ws2 = _add(ws2, "ws_net_profit",
               np.round(rngx.uniform(-50, 300, n_ws2), 2))
    ws2 = _add(ws2, "ws_net_paid",
               np.round(rngx.uniform(5, 2000, n_ws2), 2))
    ws2 = _add(ws2, "ws_ext_discount_amt",
               np.round(rngx.uniform(0, 80, n_ws2), 2))
    ws2 = _add(ws2, "ws_ext_list_price",
               np.round(rngx.uniform(10, 500, n_ws2), 2))
    ws2 = _add(ws2, "ws_bill_customer_sk",
               rngx.integers(0, n_cu, n_ws2).astype(np.int64))
    out["web_sales"] = ws2
    out["catalog_page"] = pa.table({
        "cp_catalog_page_sk": pa.array(np.arange(6, dtype=np.int64)),
        "cp_catalog_page_id": pa.array(
            [f"AAAAAAAA{i}PC" for i in range(6)]),
    })

    # --- web_returns: background + q30 (2002, large amounts, GA).
    n_wr = 300
    wr_cust = rngx.integers(0, n_cu, n_wr).astype(np.int64)
    wr_addr = rngx.integers(0, n_ca, n_wr).astype(np.int64)
    wr_ret = rngx.integers(0, n_dd, n_wr).astype(np.int64)
    wr_amt = np.round(rngx.uniform(5, 100, n_wr), 2)
    wr_cust[0:4] = [110, 111, 112, 113]
    wr_addr[0:4] = 2
    wr_ret[0:4] = [day(2002, 2, 15) + j for j in range(4)]
    wr_amt[0:4] = [7000.0 + j for j in range(4)]
    out["web_returns"] = pa.table({
        "wr_returning_customer_sk": pa.array(wr_cust),
        "wr_returning_addr_sk": pa.array(wr_addr),
        "wr_returned_date_sk": pa.array(wr_ret),
        "wr_return_amt": pa.array(wr_amt),
        # wave 2: returns keyed to web_sales orders (q5/q49/q77 join
        # wr back to ws on item+order).
        "wr_item_sk": pa.array(
            _np(out["web_sales"], "ws_item_sk")[
                rngx.integers(0, len(out["web_sales"]), n_wr)]),
        "wr_order_number": pa.array(
            rngx.integers(0, max(len(out["web_sales"]) // 2, 1),
                          n_wr).astype(np.int64)),
        "wr_return_quantity": pa.array(
            rngx.integers(1, 10, n_wr).astype(np.int64)),
        "wr_net_loss": pa.array(np.round(rngx.uniform(5, 150, n_wr), 2)),
        "wr_web_page_sk": pa.array(
            rngx.integers(0, 4, n_wr).astype(np.int64)),
    })
    # wave 2: make a slice of web_returns EXACTLY match sales orders so
    # the (item, order) joins hit: rows 10-60 copy ws rows' keys.
    wsn = len(out["web_sales"])
    pick = rngx.integers(0, wsn, 50)
    wr_t = out["web_returns"]
    wr_item = _np(wr_t, "wr_item_sk")
    wr_ord = _np(wr_t, "wr_order_number")
    wr_item[10:60] = _np(out["web_sales"], "ws_item_sk")[pick]
    wr_ord[10:60] = _np(out["web_sales"], "ws_order_number")[pick]
    wr_t = _set(wr_t, "wr_item_sk", wr_item)
    wr_t = _set(wr_t, "wr_order_number", wr_ord)
    out["web_returns"] = wr_t
    # Same for catalog_returns → catalog_sales (q77's cr totals join via
    # call center only, but q5 joins cr to cp pages; give cr the page,
    # order, item, quantity and amount columns).
    cr_t = out["catalog_returns"]
    n_cr2 = len(cr_t)
    csn = len(out["catalog_sales"])
    pick_c = rngx.integers(0, csn, n_cr2)
    cr_t = _add(cr_t, "cr_item_sk",
                _np(out["catalog_sales"], "cs_item_sk")[pick_c])
    cr_t = _add(cr_t, "cr_order_number",
                _np(out["catalog_sales"], "cs_order_number")[pick_c])
    cr_t = _add(cr_t, "cr_return_quantity",
                rngx.integers(1, 10, n_cr2).astype(np.int64))
    cr_t = _add(cr_t, "cr_return_amount",
                np.round(rngx.uniform(5, 150, n_cr2), 2))
    cr_t = _add(cr_t, "cr_catalog_page_sk",
                rngx.integers(0, 6, n_cr2).astype(np.int64))
    out["catalog_returns"] = cr_t


def register_tables(session, root: str) -> None:
    import os

    import pyarrow.parquet as pq

    rng = np.random.default_rng(2024)
    for name, t in tables(rng).items():
        d = os.path.join(root, name)
        os.makedirs(d, exist_ok=True)
        pq.write_table(t, os.path.join(d, "part0.parquet"))
        session.create_temp_view(name, session.read.parquet(d))


def index_configs():
    """Covering indexes matching the corpus's FIRST joins: the join rule
    (like the reference's isPlanLinear check) only rewrites joins whose
    both sides are linear, i.e. the bottom of each left-deep star-join
    tree. FROM-order puts date_dim⋈store_sales at the bottom of the
    q3/q42/q43/q52/q55 family and item⋈inventory under q21/q37/q82, so
    those four tables carry the indexes — both sides of a rewritten join
    need one (JoinIndexRule compatible-pair requirement)."""
    from hyperspace_tpu.api import IndexConfig

    return [
        ("date_dim", IndexConfig(
            "ds_dd_sk", ["d_date_sk"],
            ["d_date", "d_year", "d_moy", "d_qoy", "d_day_name"])),
        ("store_sales", IndexConfig(
            "ds_ss_date", ["ss_sold_date_sk"],
            ["ss_item_sk", "ss_store_sk", "ss_ext_sales_price",
             "ss_sales_price"])),
        ("item", IndexConfig(
            "ds_item_sk", ["i_item_sk"],
            ["i_item_id", "i_item_desc", "i_brand_id", "i_brand",
             "i_manufact_id", "i_manufact", "i_category_id", "i_category",
             "i_class", "i_current_price", "i_manager_id"])),
        ("inventory", IndexConfig(
            "ds_inv_item", ["inv_item_sk"],
            ["inv_date_sk", "inv_warehouse_sk", "inv_quantity_on_hand"])),
    ]


# The verbatim texts (TPC-DS v1.4, reference parameter substitutions).
QUERY_TEXTS: Dict[str, str] = {
    "tpcds_real_q3": """
SELECT
  dt.d_year,
  item.i_brand_id brand_id,
  item.i_brand brand,
  SUM(ss_ext_sales_price) sum_agg
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manufact_id = 128
  AND dt.d_moy = 11
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, sum_agg DESC, brand_id
LIMIT 100
""",
    "tpcds_real_q7": """
SELECT
  i_item_id,
  avg(ss_quantity) agg1,
  avg(ss_list_price) agg2,
  avg(ss_coupon_amt) agg3,
  avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, item, promotion
WHERE ss_sold_date_sk = d_date_sk AND
  ss_item_sk = i_item_sk AND
  ss_cdemo_sk = cd_demo_sk AND
  ss_promo_sk = p_promo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  (p_channel_email = 'N' OR p_channel_event = 'N') AND
  d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q13": """
SELECT
  avg(ss_quantity),
  avg(ss_ext_sales_price),
  avg(ss_ext_wholesale_cost),
  sum(ss_ext_wholesale_cost)
FROM store_sales
  , store
  , customer_demographics
  , household_demographics
  , customer_address
  , date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND ((ss_hdemo_sk = hd_demo_sk
  AND cd_demo_sk = ss_cdemo_sk
  AND cd_marital_status = 'M'
  AND cd_education_status = 'Advanced Degree'
  AND ss_sales_price BETWEEN 100.00 AND 150.00
  AND hd_dep_count = 3
) OR
  (ss_hdemo_sk = hd_demo_sk
    AND cd_demo_sk = ss_cdemo_sk
    AND cd_marital_status = 'S'
    AND cd_education_status = 'College'
    AND ss_sales_price BETWEEN 50.00 AND 100.00
    AND hd_dep_count = 1
  ) OR
  (ss_hdemo_sk = hd_demo_sk
    AND cd_demo_sk = ss_cdemo_sk
    AND cd_marital_status = 'W'
    AND cd_education_status = '2 yr Degree'
    AND ss_sales_price BETWEEN 150.00 AND 200.00
    AND hd_dep_count = 1
  ))
  AND ((ss_addr_sk = ca_address_sk
  AND ca_country = 'United States'
  AND ca_state IN ('TX', 'OH', 'TX')
  AND ss_net_profit BETWEEN 100 AND 200
) OR
  (ss_addr_sk = ca_address_sk
    AND ca_country = 'United States'
    AND ca_state IN ('OR', 'NM', 'KY')
    AND ss_net_profit BETWEEN 150 AND 300
  ) OR
  (ss_addr_sk = ca_address_sk
    AND ca_country = 'United States'
    AND ca_state IN ('VA', 'TX', 'MS')
    AND ss_net_profit BETWEEN 50 AND 250
  ))
""",
    "tpcds_real_q48": """
SELECT sum(ss_quantity)
FROM store_sales, store, customer_demographics, customer_address, date_dim
WHERE s_store_sk = ss_store_sk
  AND ss_sold_date_sk = d_date_sk AND d_year = 2001
  AND
  (
    (
      cd_demo_sk = ss_cdemo_sk
        AND
        cd_marital_status = 'M'
        AND
        cd_education_status = '4 yr Degree'
        AND
        ss_sales_price BETWEEN 100.00 AND 150.00
    )
      OR
      (
        cd_demo_sk = ss_cdemo_sk
          AND
          cd_marital_status = 'D'
          AND
          cd_education_status = '2 yr Degree'
          AND
          ss_sales_price BETWEEN 50.00 AND 100.00
      )
      OR
      (
        cd_demo_sk = ss_cdemo_sk
          AND
          cd_marital_status = 'S'
          AND
          cd_education_status = 'College'
          AND
          ss_sales_price BETWEEN 150.00 AND 200.00
      )
  )
  AND
  (
    (
      ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('CO', 'OH', 'TX')
        AND ss_net_profit BETWEEN 0 AND 2000
    )
      OR
      (ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('OR', 'MN', 'KY')
        AND ss_net_profit BETWEEN 150 AND 3000
      )
      OR
      (ss_addr_sk = ca_address_sk
        AND
        ca_country = 'United States'
        AND
        ca_state IN ('VA', 'CA', 'MS')
        AND ss_net_profit BETWEEN 50 AND 25000
      )
  )
""",
    "tpcds_real_q15": """
SELECT
  ca_zip,
  sum(cs_sales_price)
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ('85669', '86197', '88274', '83405', '86475',
                                '85392', '85460', '80348', '81792')
  OR ca_state IN ('CA', 'WA', 'GA')
  OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk
  AND d_qoy = 2 AND d_year = 2001
GROUP BY ca_zip
ORDER BY ca_zip
LIMIT 100
""",
    "tpcds_real_q21": """
SELECT *
FROM (
       SELECT
         w_warehouse_name,
         i_item_id,
         sum(CASE WHEN (cast(d_date AS DATE) < cast('2000-03-11' AS DATE))
           THEN inv_quantity_on_hand
             ELSE 0 END) AS inv_before,
         sum(CASE WHEN (cast(d_date AS DATE) >= cast('2000-03-11' AS DATE))
           THEN inv_quantity_on_hand
             ELSE 0 END) AS inv_after
       FROM inventory, warehouse, item, date_dim
       WHERE i_current_price BETWEEN 0.99 AND 1.49
         AND i_item_sk = inv_item_sk
         AND inv_warehouse_sk = w_warehouse_sk
         AND inv_date_sk = d_date_sk
         AND d_date BETWEEN (cast('2000-03-11' AS DATE) - INTERVAL 30 days)
       AND (cast('2000-03-11' AS DATE) + INTERVAL 30 days)
       GROUP BY w_warehouse_name, i_item_id) x
WHERE (CASE WHEN inv_before > 0
  THEN inv_after / inv_before
       ELSE NULL
       END) BETWEEN 2.0 / 3.0 AND 3.0 / 2.0
ORDER BY w_warehouse_name, i_item_id
LIMIT 100
""",
    "tpcds_real_q26": """
SELECT
  i_item_id,
  avg(cs_quantity) agg1,
  avg(cs_list_price) agg2,
  avg(cs_coupon_amt) agg3,
  avg(cs_sales_price) agg4
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND
  cs_item_sk = i_item_sk AND
  cs_bill_cdemo_sk = cd_demo_sk AND
  cs_promo_sk = p_promo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  (p_channel_email = 'N' OR p_channel_event = 'N') AND
  d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q37": """
SELECT
  i_item_id,
  i_item_desc,
  i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 68 AND 68 + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN cast('2000-02-01' AS DATE) AND (cast('2000-02-01' AS DATE) + INTERVAL 60 days)
  AND i_manufact_id IN (677, 940, 694, 808)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q42": """
SELECT
  dt.d_year,
  item.i_category_id,
  item.i_category,
  sum(ss_ext_sales_price)
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = 11
  AND dt.d_year = 2000
GROUP BY dt.d_year
  , item.i_category_id
  , item.i_category
ORDER BY sum(ss_ext_sales_price) DESC, dt.d_year
  , item.i_category_id
  , item.i_category
LIMIT 100
""",
    "tpcds_real_q43": """
SELECT
  s_store_name,
  s_store_id,
  sum(CASE WHEN (d_day_name = 'Sunday')
    THEN ss_sales_price
      ELSE NULL END) sun_sales,
  sum(CASE WHEN (d_day_name = 'Monday')
    THEN ss_sales_price
      ELSE NULL END) mon_sales,
  sum(CASE WHEN (d_day_name = 'Tuesday')
    THEN ss_sales_price
      ELSE NULL END) tue_sales,
  sum(CASE WHEN (d_day_name = 'Wednesday')
    THEN ss_sales_price
      ELSE NULL END) wed_sales,
  sum(CASE WHEN (d_day_name = 'Thursday')
    THEN ss_sales_price
      ELSE NULL END) thu_sales,
  sum(CASE WHEN (d_day_name = 'Friday')
    THEN ss_sales_price
      ELSE NULL END) fri_sales,
  sum(CASE WHEN (d_day_name = 'Saturday')
    THEN ss_sales_price
      ELSE NULL END) sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk AND
  s_store_sk = ss_store_sk AND
  s_gmt_offset = -5 AND
  d_year = 2000
GROUP BY s_store_name, s_store_id
ORDER BY s_store_name, s_store_id, sun_sales, mon_sales, tue_sales, wed_sales,
  thu_sales, fri_sales, sat_sales
LIMIT 100
""",
    "tpcds_real_q52": """
SELECT
  dt.d_year,
  item.i_brand_id brand_id,
  item.i_brand brand,
  sum(ss_ext_sales_price) ext_price
FROM date_dim dt, store_sales, item
WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
  AND store_sales.ss_item_sk = item.i_item_sk
  AND item.i_manager_id = 1
  AND dt.d_moy = 11
  AND dt.d_year = 2000
GROUP BY dt.d_year, item.i_brand, item.i_brand_id
ORDER BY dt.d_year, ext_price DESC, brand_id
LIMIT 100
""",
    "tpcds_real_q55": """
SELECT
  i_brand_id brand_id,
  i_brand brand,
  sum(ss_ext_sales_price) ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 28
  AND d_moy = 11
  AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, brand_id
LIMIT 100
""",
    "tpcds_real_q82": """
SELECT
  i_item_id,
  i_item_desc,
  i_current_price
FROM item, inventory, date_dim, store_sales
WHERE i_current_price BETWEEN 62 AND 62 + 30
  AND inv_item_sk = i_item_sk
  AND d_date_sk = inv_date_sk
  AND d_date BETWEEN cast('2000-05-25' AS DATE) AND (cast('2000-05-25' AS DATE) + INTERVAL 60 days)
  AND i_manufact_id IN (129, 270, 821, 423)
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND ss_item_sk = i_item_sk
GROUP BY i_item_id, i_item_desc, i_current_price
ORDER BY i_item_id
LIMIT 100
""",
    "tpcds_real_q62": """
SELECT
  substr(w_warehouse_name, 1, 20),
  sm_type,
  web_name,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 30) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 60) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 90) AND
    (ws_ship_date_sk - ws_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (ws_ship_date_sk - ws_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  web_sales, warehouse, ship_mode, web_site, date_dim
WHERE
  d_month_seq BETWEEN 1200 AND 1200 + 11
    AND ws_ship_date_sk = d_date_sk
    AND ws_warehouse_sk = w_warehouse_sk
    AND ws_ship_mode_sk = sm_ship_mode_sk
    AND ws_web_site_sk = web_site_sk
GROUP BY
  substr(w_warehouse_name, 1, 20), sm_type, web_name
ORDER BY
  substr(w_warehouse_name, 1, 20), sm_type, web_name
LIMIT 100
""",
    "tpcds_real_q99": """
SELECT
  substr(w_warehouse_name, 1, 20),
  sm_type,
  cc_name,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 30) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 60) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 90) AND
    (cs_ship_date_sk - cs_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (cs_ship_date_sk - cs_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  catalog_sales, warehouse, ship_mode, call_center, date_dim
WHERE
  d_month_seq BETWEEN 1200 AND 1200 + 11
    AND cs_ship_date_sk = d_date_sk
    AND cs_warehouse_sk = w_warehouse_sk
    AND cs_ship_mode_sk = sm_ship_mode_sk
    AND cs_call_center_sk = cc_call_center_sk
GROUP BY
  substr(w_warehouse_name, 1, 20), sm_type, cc_name
ORDER BY substr(w_warehouse_name, 1, 20), sm_type, cc_name
LIMIT 100
""",
    "tpcds_real_q96": """
SELECT count(*)
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = time_dim.t_time_sk
  AND ss_hdemo_sk = household_demographics.hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND time_dim.t_hour = 20
  AND time_dim.t_minute >= 30
  AND household_demographics.hd_dep_count = 7
  AND store.s_store_name = 'ese'
ORDER BY count(*)
LIMIT 100
""",
    "tpcds_real_q1": """
WITH customer_total_return AS
( SELECT
    sr_customer_sk AS ctr_customer_sk,
    sr_store_sk AS ctr_store_sk,
    sum(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_id
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return >
  (SELECT avg(ctr_total_return) * 1.2
  FROM customer_total_return ctr2
  WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk
  AND s_state = 'TN'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id
LIMIT 100
""",
    "tpcds_real_q12": """
SELECT
  i_item_desc,
  i_category,
  i_class,
  i_current_price,
  sum(ws_ext_sales_price) AS itemrevenue,
  sum(ws_ext_sales_price) * 100 / sum(sum(ws_ext_sales_price))
  OVER
  (PARTITION BY i_class) AS revenueratio
FROM
  web_sales, item, date_dim
WHERE
  ws_item_sk = i_item_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('1999-02-22' AS DATE)
  AND (cast('1999-02-22' AS DATE) + INTERVAL 30 days)
GROUP BY
  i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY
  i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
""",
    "tpcds_real_q20": """
SELECT
  i_item_desc,
  i_category,
  i_class,
  i_current_price,
  sum(cs_ext_sales_price) AS itemrevenue,
  sum(cs_ext_sales_price) * 100 / sum(sum(cs_ext_sales_price))
  OVER
  (PARTITION BY i_class) AS revenueratio
FROM catalog_sales, item, date_dim
WHERE cs_item_sk = i_item_sk
  AND i_category IN ('Sports', 'Books', 'Home')
  AND cs_sold_date_sk = d_date_sk
  AND d_date BETWEEN cast('1999-02-22' AS DATE)
AND (cast('1999-02-22' AS DATE) + INTERVAL 30 days)
GROUP BY i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY i_category, i_class, i_item_id, i_item_desc, revenueratio
LIMIT 100
""",
    "tpcds_real_q25": """
SELECT
  i_item_id,
  i_item_desc,
  s_store_id,
  s_store_name,
  sum(ss_net_profit) AS store_sales_profit,
  sum(sr_net_loss) AS store_returns_loss,
  sum(cs_net_profit) AS catalog_sales_profit
FROM
  store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2, date_dim d3,
  store, item
WHERE
  d1.d_moy = 4
    AND d1.d_year = 2001
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND ss_customer_sk = sr_customer_sk
    AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND sr_returned_date_sk = d2.d_date_sk
    AND d2.d_moy BETWEEN 4 AND 10
    AND d2.d_year = 2001
    AND sr_customer_sk = cs_bill_customer_sk
    AND sr_item_sk = cs_item_sk
    AND cs_sold_date_sk = d3.d_date_sk
    AND d3.d_moy BETWEEN 4 AND 10
    AND d3.d_year = 2001
GROUP BY
  i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY
  i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
""",
    "tpcds_real_q28": """
SELECT *
FROM (SELECT
  avg(ss_list_price) B1_LP,
  count(ss_list_price) B1_CNT,
  count(DISTINCT ss_list_price) B1_CNTD
FROM store_sales
WHERE ss_quantity BETWEEN 0 AND 5
  AND (ss_list_price BETWEEN 8 AND 8 + 10
  OR ss_coupon_amt BETWEEN 459 AND 459 + 1000
  OR ss_wholesale_cost BETWEEN 57 AND 57 + 20)) B1,
  (SELECT
    avg(ss_list_price) B2_LP,
    count(ss_list_price) B2_CNT,
    count(DISTINCT ss_list_price) B2_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 6 AND 10
    AND (ss_list_price BETWEEN 90 AND 90 + 10
    OR ss_coupon_amt BETWEEN 2323 AND 2323 + 1000
    OR ss_wholesale_cost BETWEEN 31 AND 31 + 20)) B2,
  (SELECT
    avg(ss_list_price) B3_LP,
    count(ss_list_price) B3_CNT,
    count(DISTINCT ss_list_price) B3_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 11 AND 15
    AND (ss_list_price BETWEEN 142 AND 142 + 10
    OR ss_coupon_amt BETWEEN 12214 AND 12214 + 1000
    OR ss_wholesale_cost BETWEEN 79 AND 79 + 20)) B3,
  (SELECT
    avg(ss_list_price) B4_LP,
    count(ss_list_price) B4_CNT,
    count(DISTINCT ss_list_price) B4_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 16 AND 20
    AND (ss_list_price BETWEEN 135 AND 135 + 10
    OR ss_coupon_amt BETWEEN 6071 AND 6071 + 1000
    OR ss_wholesale_cost BETWEEN 38 AND 38 + 20)) B4,
  (SELECT
    avg(ss_list_price) B5_LP,
    count(ss_list_price) B5_CNT,
    count(DISTINCT ss_list_price) B5_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 21 AND 25
    AND (ss_list_price BETWEEN 122 AND 122 + 10
    OR ss_coupon_amt BETWEEN 836 AND 836 + 1000
    OR ss_wholesale_cost BETWEEN 17 AND 17 + 20)) B5,
  (SELECT
    avg(ss_list_price) B6_LP,
    count(ss_list_price) B6_CNT,
    count(DISTINCT ss_list_price) B6_CNTD
  FROM store_sales
  WHERE ss_quantity BETWEEN 26 AND 30
    AND (ss_list_price BETWEEN 154 AND 154 + 10
    OR ss_coupon_amt BETWEEN 7326 AND 7326 + 1000
    OR ss_wholesale_cost BETWEEN 7 AND 7 + 20)) B6
LIMIT 100
""",
    "tpcds_real_q29": """
SELECT
  i_item_id,
  i_item_desc,
  s_store_id,
  s_store_name,
  sum(ss_quantity) AS store_sales_quantity,
  sum(sr_return_quantity) AS store_returns_quantity,
  sum(cs_quantity) AS catalog_sales_quantity
FROM
  store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
  date_dim d3, store, item
WHERE
  d1.d_moy = 9
    AND d1.d_year = 1999
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND ss_customer_sk = sr_customer_sk
    AND ss_item_sk = sr_item_sk
    AND ss_ticket_number = sr_ticket_number
    AND sr_returned_date_sk = d2.d_date_sk
    AND d2.d_moy BETWEEN 9 AND 9 + 3
    AND d2.d_year = 1999
    AND sr_customer_sk = cs_bill_customer_sk
    AND sr_item_sk = cs_item_sk
    AND cs_sold_date_sk = d3.d_date_sk
    AND d3.d_year IN (1999, 1999 + 1, 1999 + 2)
GROUP BY
  i_item_id, i_item_desc, s_store_id, s_store_name
ORDER BY
  i_item_id, i_item_desc, s_store_id, s_store_name
LIMIT 100
""",
    "tpcds_real_q30": """
WITH customer_total_return AS
(SELECT
    wr_returning_customer_sk AS ctr_customer_sk,
    ca_state AS ctr_state,
    sum(wr_return_amt) AS ctr_total_return
  FROM web_returns, date_dim, customer_address
  WHERE wr_returned_date_sk = d_date_sk
    AND d_year = 2002
    AND wr_returning_addr_sk = ca_address_sk
  GROUP BY wr_returning_customer_sk, ca_state)
SELECT
  c_customer_id,
  c_salutation,
  c_first_name,
  c_last_name,
  c_preferred_cust_flag,
  c_birth_day,
  c_birth_month,
  c_birth_year,
  c_birth_country,
  c_login,
  c_email_address,
  c_last_review_date,
  ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
FROM customer_total_return ctr2
WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name, c_preferred_cust_flag
  , c_birth_day, c_birth_month, c_birth_year, c_birth_country, c_login, c_email_address
  , c_last_review_date, ctr_total_return
LIMIT 100
""",
    "tpcds_real_q33": """
WITH ss AS (
  SELECT
    i_manufact_id,
    sum(ss_ext_sales_price) total_sales
  FROM
    store_sales, date_dim, customer_address, item
  WHERE
    i_manufact_id IN (SELECT i_manufact_id
    FROM item
    WHERE i_category IN ('Electronics'))
      AND ss_item_sk = i_item_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year = 1998
      AND d_moy = 5
      AND ss_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_manufact_id), cs AS
(SELECT
    i_manufact_id,
    sum(cs_ext_sales_price) total_sales
  FROM catalog_sales, date_dim, customer_address, item
  WHERE
    i_manufact_id IN (
      SELECT i_manufact_id
      FROM item
      WHERE
        i_category IN ('Electronics'))
      AND cs_item_sk = i_item_sk
      AND cs_sold_date_sk = d_date_sk
      AND d_year = 1998
      AND d_moy = 5
      AND cs_bill_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_manufact_id),
    ws AS (
    SELECT
      i_manufact_id,
      sum(ws_ext_sales_price) total_sales
    FROM
      web_sales, date_dim, customer_address, item
    WHERE
      i_manufact_id IN (SELECT i_manufact_id
      FROM item
      WHERE i_category IN ('Electronics'))
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = 1998
        AND d_moy = 5
        AND ws_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_manufact_id)
SELECT
  i_manufact_id,
  sum(total_sales) total_sales
FROM (SELECT *
      FROM ss
      UNION ALL
      SELECT *
      FROM cs
      UNION ALL
      SELECT *
      FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY total_sales
LIMIT 100
""",
    "tpcds_real_q34": """
SELECT
  c_last_name,
  c_first_name,
  c_salutation,
  c_preferred_cust_flag,
  ss_ticket_number,
  cnt
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    count(*) cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND (date_dim.d_dom BETWEEN 1 AND 3 OR date_dim.d_dom BETWEEN 25 AND 28)
    AND (household_demographics.hd_buy_potential = '>10000' OR
    household_demographics.hd_buy_potential = 'unknown')
    AND household_demographics.hd_vehicle_count > 0
    AND (CASE WHEN household_demographics.hd_vehicle_count > 0
    THEN household_demographics.hd_dep_count / household_demographics.hd_vehicle_count
         ELSE NULL
         END) > 1.2
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_county IN
    ('Williamson County', 'Williamson County', 'Williamson County', 'Williamson County',
     'Williamson County', 'Williamson County', 'Williamson County', 'Williamson County')
  GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 15 AND 20
ORDER BY c_last_name, c_first_name, c_salutation, c_preferred_cust_flag DESC
""",
    "tpcds_real_q46": """
SELECT
  c_last_name,
  c_first_name,
  ca_city,
  bought_city,
  ss_ticket_number,
  amt,
  profit
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    ca_city bought_city,
    sum(ss_coupon_amt) amt,
    sum(ss_net_profit) profit
  FROM store_sales, date_dim, store, household_demographics, customer_address
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND store_sales.ss_addr_sk = customer_address.ca_address_sk
    AND (household_demographics.hd_dep_count = 4 OR
    household_demographics.hd_vehicle_count = 3)
    AND date_dim.d_dow IN (6, 0)
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_city IN ('Fairview', 'Midway', 'Fairview', 'Fairview', 'Fairview')
  GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn, customer,
  customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
LIMIT 100
""",
    "tpcds_real_q50": """
SELECT
  s_store_name,
  s_company_id,
  s_street_number,
  s_street_name,
  s_street_type,
  s_suite_number,
  s_city,
  s_county,
  s_state,
  s_zip,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk <= 30)
    THEN 1
      ELSE 0 END)  AS `30 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 30) AND
    (sr_returned_date_sk - ss_sold_date_sk <= 60)
    THEN 1
      ELSE 0 END)  AS `31 - 60 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 60) AND
    (sr_returned_date_sk - ss_sold_date_sk <= 90)
    THEN 1
      ELSE 0 END)  AS `61 - 90 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 90) AND
    (sr_returned_date_sk - ss_sold_date_sk <= 120)
    THEN 1
      ELSE 0 END)  AS `91 - 120 days `,
  sum(CASE WHEN (sr_returned_date_sk - ss_sold_date_sk > 120)
    THEN 1
      ELSE 0 END)  AS `>120 days `
FROM
  store_sales, store_returns, store, date_dim d1, date_dim d2
WHERE
  d2.d_year = 2001
    AND d2.d_moy = 8
    AND ss_ticket_number = sr_ticket_number
    AND ss_item_sk = sr_item_sk
    AND ss_sold_date_sk = d1.d_date_sk
    AND sr_returned_date_sk = d2.d_date_sk
    AND ss_customer_sk = sr_customer_sk
    AND ss_store_sk = s_store_sk
GROUP BY
  s_store_name, s_company_id, s_street_number, s_street_name, s_street_type,
  s_suite_number, s_city, s_county, s_state, s_zip
ORDER BY
  s_store_name, s_company_id, s_street_number, s_street_name, s_street_type,
  s_suite_number, s_city, s_county, s_state, s_zip
LIMIT 100
""",
    "tpcds_real_q53": """
SELECT *
FROM
  (SELECT
    i_manufact_id,
    sum(ss_sales_price) sum_sales,
    avg(sum(ss_sales_price))
    OVER (PARTITION BY i_manufact_id) avg_quarterly_sales
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND
    ss_sold_date_sk = d_date_sk AND
    ss_store_sk = s_store_sk AND
    d_month_seq IN (1200, 1200 + 1, 1200 + 2, 1200 + 3, 1200 + 4, 1200 + 5, 1200 + 6,
                          1200 + 7, 1200 + 8, 1200 + 9, 1200 + 10, 1200 + 11) AND
    ((i_category IN ('Books', 'Children', 'Electronics') AND
      i_class IN ('personal', 'portable', 'reference', 'self-help') AND
      i_brand IN ('scholaramalgamalg #14', 'scholaramalgamalg #7',
                  'exportiunivamalg #9', 'scholaramalgamalg #9'))
      OR
      (i_category IN ('Women', 'Music', 'Men') AND
        i_class IN ('accessories', 'classical', 'fragrances', 'pants') AND
        i_brand IN ('amalgimporto #1', 'edu packscholar #1', 'exportiimporto #1',
                    'importoamalg #1')))
  GROUP BY i_manufact_id, d_qoy) tmp1
WHERE CASE WHEN avg_quarterly_sales > 0
  THEN abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
      ELSE NULL END > 0.1
ORDER BY avg_quarterly_sales,
  sum_sales,
  i_manufact_id
LIMIT 100
""",
    "tpcds_real_q56": """
WITH ss AS (
  SELECT
    i_item_id,
    sum(ss_ext_sales_price) total_sales
  FROM
    store_sales, date_dim, customer_address, item
  WHERE
    i_item_id IN (SELECT i_item_id
    FROM item
    WHERE i_color IN ('slate', 'blanched', 'burnished'))
      AND ss_item_sk = i_item_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year = 2001
      AND d_moy = 2
      AND ss_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_item_id),
    cs AS (
    SELECT
      i_item_id,
      sum(cs_ext_sales_price) total_sales
    FROM
      catalog_sales, date_dim, customer_address, item
    WHERE
      i_item_id IN (SELECT i_item_id
      FROM item
      WHERE i_color IN ('slate', 'blanched', 'burnished'))
        AND cs_item_sk = i_item_sk
        AND cs_sold_date_sk = d_date_sk
        AND d_year = 2001
        AND d_moy = 2
        AND cs_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_item_id),
    ws AS (
    SELECT
      i_item_id,
      sum(ws_ext_sales_price) total_sales
    FROM
      web_sales, date_dim, customer_address, item
    WHERE
      i_item_id IN (SELECT i_item_id
      FROM item
      WHERE i_color IN ('slate', 'blanched', 'burnished'))
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = 2001
        AND d_moy = 2
        AND ws_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_item_id)
SELECT
  i_item_id,
  sum(total_sales) total_sales
FROM (SELECT *
      FROM ss
      UNION ALL
      SELECT *
      FROM cs
      UNION ALL
      SELECT *
      FROM ws) tmp1
GROUP BY i_item_id
ORDER BY total_sales
LIMIT 100
""",
    "tpcds_real_q60": """
WITH ss AS (
  SELECT
    i_item_id,
    sum(ss_ext_sales_price) total_sales
  FROM store_sales, date_dim, customer_address, item
  WHERE
    i_item_id IN (SELECT i_item_id
    FROM item
    WHERE i_category IN ('Music'))
      AND ss_item_sk = i_item_sk
      AND ss_sold_date_sk = d_date_sk
      AND d_year = 1998
      AND d_moy = 9
      AND ss_addr_sk = ca_address_sk
      AND ca_gmt_offset = -5
  GROUP BY i_item_id),
    cs AS (
    SELECT
      i_item_id,
      sum(cs_ext_sales_price) total_sales
    FROM catalog_sales, date_dim, customer_address, item
    WHERE
      i_item_id IN (SELECT i_item_id
      FROM item
      WHERE i_category IN ('Music'))
        AND cs_item_sk = i_item_sk
        AND cs_sold_date_sk = d_date_sk
        AND d_year = 1998
        AND d_moy = 9
        AND cs_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_item_id),
    ws AS (
    SELECT
      i_item_id,
      sum(ws_ext_sales_price) total_sales
    FROM web_sales, date_dim, customer_address, item
    WHERE
      i_item_id IN (SELECT i_item_id
      FROM item
      WHERE i_category IN ('Music'))
        AND ws_item_sk = i_item_sk
        AND ws_sold_date_sk = d_date_sk
        AND d_year = 1998
        AND d_moy = 9
        AND ws_bill_addr_sk = ca_address_sk
        AND ca_gmt_offset = -5
    GROUP BY i_item_id)
SELECT
  i_item_id,
  sum(total_sales) total_sales
FROM (SELECT *
      FROM ss
      UNION ALL
      SELECT *
      FROM cs
      UNION ALL
      SELECT *
      FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
LIMIT 100
""",
    "tpcds_real_q61": """
SELECT
  promotions,
  total,
  cast(promotions AS DECIMAL(15, 4)) / cast(total AS DECIMAL(15, 4)) * 100
FROM
  (SELECT sum(ss_ext_sales_price) promotions
  FROM store_sales, store, promotion, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_promo_sk = p_promo_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5
    AND i_category = 'Jewelry'
    AND (p_channel_dmail = 'Y' OR p_channel_email = 'Y' OR p_channel_tv = 'Y')
    AND s_gmt_offset = -5
    AND d_year = 1998
    AND d_moy = 11) promotional_sales,
  (SELECT sum(ss_ext_sales_price) total
  FROM store_sales, store, date_dim, customer, customer_address, item
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_store_sk = s_store_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk
    AND ss_item_sk = i_item_sk
    AND ca_gmt_offset = -5
    AND i_category = 'Jewelry'
    AND s_gmt_offset = -5
    AND d_year = 1998
    AND d_moy = 11) all_sales
ORDER BY promotions, total
LIMIT 100
""",
    "tpcds_real_q63": """
SELECT *
FROM (SELECT
  i_manager_id,
  sum(ss_sales_price) sum_sales,
  avg(sum(ss_sales_price))
  OVER (PARTITION BY i_manager_id) avg_monthly_sales
FROM item
  , store_sales
  , date_dim
  , store
WHERE ss_item_sk = i_item_sk
  AND ss_sold_date_sk = d_date_sk
  AND ss_store_sk = s_store_sk
  AND d_month_seq IN (1200, 1200 + 1, 1200 + 2, 1200 + 3, 1200 + 4, 1200 + 5, 1200 + 6, 1200 + 7,
                            1200 + 8, 1200 + 9, 1200 + 10, 1200 + 11)
  AND ((i_category IN ('Books', 'Children', 'Electronics')
  AND i_class IN ('personal', 'portable', 'refernece', 'self-help')
  AND i_brand IN ('scholaramalgamalg #14', 'scholaramalgamalg #7',
                  'exportiunivamalg #9', 'scholaramalgamalg #9'))
  OR (i_category IN ('Women', 'Music', 'Men')
  AND i_class IN ('accessories', 'classical', 'fragrances', 'pants')
  AND i_brand IN ('amalgimporto #1', 'edu packscholar #1', 'exportiimporto #1',
                  'importoamalg #1')))
GROUP BY i_manager_id, d_moy) tmp1
WHERE CASE WHEN avg_monthly_sales > 0
  THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
      ELSE NULL END > 0.1
ORDER BY i_manager_id
  , avg_monthly_sales
  , sum_sales
LIMIT 100
""",
    "tpcds_real_q68": """
SELECT
  c_last_name,
  c_first_name,
  ca_city,
  bought_city,
  ss_ticket_number,
  extended_price,
  extended_tax,
  list_price
FROM (SELECT
  ss_ticket_number,
  ss_customer_sk,
  ca_city bought_city,
  sum(ss_ext_sales_price) extended_price,
  sum(ss_ext_list_price) list_price,
  sum(ss_ext_tax) extended_tax
FROM store_sales, date_dim, store, household_demographics, customer_address
WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
  AND store_sales.ss_store_sk = store.s_store_sk
  AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
  AND store_sales.ss_addr_sk = customer_address.ca_address_sk
  AND date_dim.d_dom BETWEEN 1 AND 2
  AND (household_demographics.hd_dep_count = 4 OR
  household_demographics.hd_vehicle_count = 3)
  AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
  AND store.s_city IN ('Midway', 'Fairview')
GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
  customer,
  customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100
""",
    "tpcds_real_q73": """
SELECT
  c_last_name,
  c_first_name,
  c_salutation,
  c_preferred_cust_flag,
  ss_ticket_number,
  cnt
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    count(*) cnt
  FROM store_sales, date_dim, store, household_demographics
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND date_dim.d_dom BETWEEN 1 AND 2
    AND (household_demographics.hd_buy_potential = '>10000' OR
    household_demographics.hd_buy_potential = 'unknown')
    AND household_demographics.hd_vehicle_count > 0
    AND CASE WHEN household_demographics.hd_vehicle_count > 0
    THEN
      household_demographics.hd_dep_count / household_demographics.hd_vehicle_count
        ELSE NULL END > 1
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_county IN ('Williamson County', 'Franklin Parish', 'Bronx County', 'Orange County')
  GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC
""",
    "tpcds_real_q79": """
SELECT
  c_last_name,
  c_first_name,
  substr(s_city, 1, 30),
  ss_ticket_number,
  amt,
  profit
FROM
  (SELECT
    ss_ticket_number,
    ss_customer_sk,
    store.s_city,
    sum(ss_coupon_amt) amt,
    sum(ss_net_profit) profit
  FROM store_sales, date_dim, store, household_demographics
  WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
    AND store_sales.ss_store_sk = store.s_store_sk
    AND store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
    AND (household_demographics.hd_dep_count = 6 OR
    household_demographics.hd_vehicle_count > 2)
    AND date_dim.d_dow = 1
    AND date_dim.d_year IN (1999, 1999 + 1, 1999 + 2)
    AND store.s_number_employees BETWEEN 200 AND 295
  GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, store.s_city) ms, customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, substr(s_city, 1, 30), profit
LIMIT 100
""",
    "tpcds_real_q81": """
WITH customer_total_return AS
(SELECT
    cr_returning_customer_sk AS ctr_customer_sk,
    ca_state AS ctr_state,
    sum(cr_return_amt_inc_tax) AS ctr_total_return
  FROM catalog_returns, date_dim, customer_address
  WHERE cr_returned_date_sk = d_date_sk
    AND d_year = 2000
    AND cr_returning_addr_sk = ca_address_sk
  GROUP BY cr_returning_customer_sk, ca_state )
SELECT
  c_customer_id,
  c_salutation,
  c_first_name,
  c_last_name,
  ca_street_number,
  ca_street_name,
  ca_street_type,
  ca_suite_number,
  ca_city,
  ca_county,
  ca_state,
  ca_zip,
  ca_country,
  ca_gmt_offset,
  ca_location_type,
  ctr_total_return
FROM customer_total_return ctr1, customer_address, customer
WHERE ctr1.ctr_total_return > (SELECT avg(ctr_total_return) * 1.2
FROM customer_total_return ctr2
WHERE ctr1.ctr_state = ctr2.ctr_state)
  AND ca_address_sk = c_current_addr_sk
  AND ca_state = 'GA'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_id, c_salutation, c_first_name, c_last_name, ca_street_number, ca_street_name
  , ca_street_type, ca_suite_number, ca_city, ca_county, ca_state, ca_zip, ca_country, ca_gmt_offset
  , ca_location_type, ctr_total_return
LIMIT 100
""",
    "tpcds_real_q88": """
SELECT *
FROM
  (SELECT count(*) h8_30_to_9
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 8
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s1,
  (SELECT count(*) h9_to_9_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 9
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s2,
  (SELECT count(*) h9_30_to_10
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 9
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s3,
  (SELECT count(*) h10_to_10_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 10
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s4,
  (SELECT count(*) h10_30_to_11
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 10
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s5,
  (SELECT count(*) h11_to_11_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 11
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s6,
  (SELECT count(*) h11_30_to_12
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 11
    AND time_dim.t_minute >= 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s7,
  (SELECT count(*) h12_to_12_30
  FROM store_sales, household_demographics, time_dim, store
  WHERE ss_sold_time_sk = time_dim.t_time_sk
    AND ss_hdemo_sk = household_demographics.hd_demo_sk
    AND ss_store_sk = s_store_sk
    AND time_dim.t_hour = 12
    AND time_dim.t_minute < 30
    AND (
    (household_demographics.hd_dep_count = 4 AND household_demographics.hd_vehicle_count <= 4 + 2)
      OR
      (household_demographics.hd_dep_count = 2 AND household_demographics.hd_vehicle_count <= 2 + 2)
      OR
      (household_demographics.hd_dep_count = 0 AND
        household_demographics.hd_vehicle_count <= 0 + 2))
    AND store.s_store_name = 'ese') s8
""",
    "tpcds_real_q89": """
SELECT *
FROM (
       SELECT
         i_category,
         i_class,
         i_brand,
         s_store_name,
         s_company_name,
         d_moy,
         sum(ss_sales_price) sum_sales,
         avg(sum(ss_sales_price))
         OVER
         (PARTITION BY i_category, i_brand, s_store_name, s_company_name)
         avg_monthly_sales
       FROM item, store_sales, date_dim, store
       WHERE ss_item_sk = i_item_sk AND
         ss_sold_date_sk = d_date_sk AND
         ss_store_sk = s_store_sk AND
         d_year IN (1999) AND
         ((i_category IN ('Books', 'Electronics', 'Sports') AND
           i_class IN ('computers', 'stereo', 'football'))
           OR (i_category IN ('Men', 'Jewelry', 'Women') AND
           i_class IN ('shirts', 'birdal', 'dresses')))
       GROUP BY i_category, i_class, i_brand,
         s_store_name, s_company_name, d_moy) tmp1
WHERE CASE WHEN (avg_monthly_sales <> 0)
  THEN (abs(sum_sales - avg_monthly_sales) / avg_monthly_sales)
      ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, s_store_name
LIMIT 100
""",
    "tpcds_real_q90": """
SELECT cast(amc AS DECIMAL(15, 4)) / cast(pmc AS DECIMAL(15, 4)) am_pm_ratio
FROM (SELECT count(*) amc
FROM web_sales, household_demographics, time_dim, web_page
WHERE ws_sold_time_sk = time_dim.t_time_sk
  AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
  AND ws_web_page_sk = web_page.wp_web_page_sk
  AND time_dim.t_hour BETWEEN 8 AND 8 + 1
  AND household_demographics.hd_dep_count = 6
  AND web_page.wp_char_count BETWEEN 5000 AND 5200) at,
  (SELECT count(*) pmc
  FROM web_sales, household_demographics, time_dim, web_page
  WHERE ws_sold_time_sk = time_dim.t_time_sk
    AND ws_ship_hdemo_sk = household_demographics.hd_demo_sk
    AND ws_web_page_sk = web_page.wp_web_page_sk
    AND time_dim.t_hour BETWEEN 19 AND 19 + 1
    AND household_demographics.hd_dep_count = 6
    AND web_page.wp_char_count BETWEEN 5000 AND 5200) pt
ORDER BY am_pm_ratio
LIMIT 100
""",
    "tpcds_real_q91": """
SELECT
  cc_call_center_id Call_Center,
  cc_name Call_Center_Name,
  cc_manager Manager,
  sum(cr_net_loss) Returns_Loss
FROM
  call_center, catalog_returns, date_dim, customer, customer_address,
  customer_demographics, household_demographics
WHERE
  cr_call_center_sk = cc_call_center_sk
    AND cr_returned_date_sk = d_date_sk
    AND cr_returning_customer_sk = c_customer_sk
    AND cd_demo_sk = c_current_cdemo_sk
    AND hd_demo_sk = c_current_hdemo_sk
    AND ca_address_sk = c_current_addr_sk
    AND d_year = 1998
    AND d_moy = 11
    AND ((cd_marital_status = 'M' AND cd_education_status = 'Unknown')
    OR (cd_marital_status = 'W' AND cd_education_status = 'Advanced Degree'))
    AND hd_buy_potential LIKE 'Unknown%'
    AND ca_gmt_offset = -7
GROUP BY cc_call_center_id, cc_name, cc_manager, cd_marital_status, cd_education_status
ORDER BY sum(cr_net_loss) DESC
""",
    "tpcds_real_q98": """
SELECT
  i_item_desc,
  i_category,
  i_class,
  i_current_price,
  sum(ss_ext_sales_price) AS itemrevenue,
  sum(ss_ext_sales_price) * 100 / sum(sum(ss_ext_sales_price))
  OVER
  (PARTITION BY i_class) AS revenueratio
FROM
  store_sales, item, date_dim
WHERE
  ss_item_sk = i_item_sk
    AND i_category IN ('Sports', 'Books', 'Home')
    AND ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('1999-02-22' AS DATE)
  AND (cast('1999-02-22' AS DATE) + INTERVAL 30 days)
GROUP BY
  i_item_id, i_item_desc, i_category, i_class, i_current_price
ORDER BY
  i_category, i_class, i_item_id, i_item_desc, revenueratio
""",
    "tpcds_real_q5": """
WITH ssr AS
( SELECT
    s_store_id,
    sum(sales_price) AS sales,
    sum(profit) AS profit,
    sum(return_amt) AS RETURNS,
    sum(net_loss) AS profit_loss
  FROM
    (SELECT
       ss_store_sk AS store_sk,
       ss_sold_date_sk AS date_sk,
       ss_ext_sales_price AS sales_price,
       ss_net_profit AS profit,
       cast(0 AS DECIMAL(7, 2)) AS return_amt,
       cast(0 AS DECIMAL(7, 2)) AS net_loss
     FROM store_sales
     UNION ALL
     SELECT
       sr_store_sk AS store_sk,
       sr_returned_date_sk AS date_sk,
       cast(0 AS DECIMAL(7, 2)) AS sales_price,
       cast(0 AS DECIMAL(7, 2)) AS profit,
       sr_return_amt AS return_amt,
       sr_net_loss AS net_loss
     FROM store_returns)
    salesreturns, date_dim, store
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND ((cast('2000-08-23' AS DATE) + INTERVAL 14 days))
    AND store_sk = s_store_sk
  GROUP BY s_store_id),
    csr AS
  ( SELECT
    cp_catalog_page_id,
    sum(sales_price) AS sales,
    sum(profit) AS profit,
    sum(return_amt) AS RETURNS,
    sum(net_loss) AS profit_loss
  FROM
    (SELECT
       cs_catalog_page_sk AS page_sk,
       cs_sold_date_sk AS date_sk,
       cs_ext_sales_price AS sales_price,
       cs_net_profit AS profit,
       cast(0 AS DECIMAL(7, 2)) AS return_amt,
       cast(0 AS DECIMAL(7, 2)) AS net_loss
     FROM catalog_sales
     UNION ALL
     SELECT
       cr_catalog_page_sk AS page_sk,
       cr_returned_date_sk AS date_sk,
       cast(0 AS DECIMAL(7, 2)) AS sales_price,
       cast(0 AS DECIMAL(7, 2)) AS profit,
       cr_return_amount AS return_amt,
       cr_net_loss AS net_loss
     FROM catalog_returns
    ) salesreturns, date_dim, catalog_page
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND ((cast('2000-08-23' AS DATE) + INTERVAL 14 days))
    AND page_sk = cp_catalog_page_sk
  GROUP BY cp_catalog_page_id)
  ,
    wsr AS
  ( SELECT
    web_site_id,
    sum(sales_price) AS sales,
    sum(profit) AS profit,
    sum(return_amt) AS RETURNS,
    sum(net_loss) AS profit_loss
  FROM
    (SELECT
       ws_web_site_sk AS wsr_web_site_sk,
       ws_sold_date_sk AS date_sk,
       ws_ext_sales_price AS sales_price,
       ws_net_profit AS profit,
       cast(0 AS DECIMAL(7, 2)) AS return_amt,
       cast(0 AS DECIMAL(7, 2)) AS net_loss
     FROM web_sales
     UNION ALL
     SELECT
       ws_web_site_sk AS wsr_web_site_sk,
       wr_returned_date_sk AS date_sk,
       cast(0 AS DECIMAL(7, 2)) AS sales_price,
       cast(0 AS DECIMAL(7, 2)) AS profit,
       wr_return_amt AS return_amt,
       wr_net_loss AS net_loss
     FROM web_returns
       LEFT OUTER JOIN web_sales ON
                                   (wr_item_sk = ws_item_sk
                                     AND wr_order_number = ws_order_number)
    ) salesreturns, date_dim, web_site
  WHERE date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-23' AS DATE)
  AND ((cast('2000-08-23' AS DATE) + INTERVAL 14 days))
    AND wsr_web_site_sk = web_site_sk
  GROUP BY web_site_id)
SELECT
  channel,
  id,
  sum(sales) AS sales,
  sum(returns) AS returns,
  sum(profit) AS profit
FROM
  (SELECT
     'store channel' AS channel,
     concat('store', s_store_id) AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM ssr
   UNION ALL
   SELECT
     'catalog channel' AS channel,
     concat('catalog_page', cp_catalog_page_id) AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM csr
   UNION ALL
   SELECT
     'web channel' AS channel,
     concat('web_site', web_site_id) AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM wsr
  ) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
""",
    "tpcds_real_q11": """
WITH year_total AS (
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    c_preferred_cust_flag customer_preferred_cust_flag,
    c_birth_country customer_birth_country,
    c_login customer_login,
    c_email_address customer_email_address,
    d_year dyear,
    sum(ss_ext_list_price - ss_ext_discount_amt) year_total,
    's' sale_type
  FROM customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND ss_sold_date_sk = d_date_sk
  GROUP BY c_customer_id
    , c_first_name
    , c_last_name
    , d_year
    , c_preferred_cust_flag
    , c_birth_country
    , c_login
    , c_email_address
    , d_year
  UNION ALL
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    c_preferred_cust_flag customer_preferred_cust_flag,
    c_birth_country customer_birth_country,
    c_login customer_login,
    c_email_address customer_email_address,
    d_year dyear,
    sum(ws_ext_list_price - ws_ext_discount_amt) year_total,
    'w' sale_type
  FROM customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
  GROUP BY
    c_customer_id, c_first_name, c_last_name, c_preferred_cust_flag, c_birth_country,
    c_login, c_email_address, d_year)
SELECT t_s_secyear.customer_preferred_cust_flag
FROM year_total t_s_firstyear
  , year_total t_s_secyear
  , year_total t_w_firstyear
  , year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 2001
  AND t_s_secyear.dyear = 2001 + 1
  AND t_w_firstyear.dyear = 2001
  AND t_w_secyear.dyear = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
  THEN t_w_secyear.year_total / t_w_firstyear.year_total
      ELSE NULL END
  > CASE WHEN t_s_firstyear.year_total > 0
  THEN t_s_secyear.year_total / t_s_firstyear.year_total
    ELSE NULL END
ORDER BY t_s_secyear.customer_preferred_cust_flag
LIMIT 100
""",
    "tpcds_real_q18": """
SELECT
  i_item_id,
  ca_country,
  ca_state,
  ca_county,
  avg(cast(cs_quantity AS DECIMAL(12, 2))) agg1,
  avg(cast(cs_list_price AS DECIMAL(12, 2))) agg2,
  avg(cast(cs_coupon_amt AS DECIMAL(12, 2))) agg3,
  avg(cast(cs_sales_price AS DECIMAL(12, 2))) agg4,
  avg(cast(cs_net_profit AS DECIMAL(12, 2))) agg5,
  avg(cast(c_birth_year AS DECIMAL(12, 2))) agg6,
  avg(cast(cd1.cd_dep_count AS DECIMAL(12, 2))) agg7
FROM catalog_sales, customer_demographics cd1,
  customer_demographics cd2, customer, customer_address, date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND
  cs_item_sk = i_item_sk AND
  cs_bill_cdemo_sk = cd1.cd_demo_sk AND
  cs_bill_customer_sk = c_customer_sk AND
  cd1.cd_gender = 'F' AND
  cd1.cd_education_status = 'Unknown' AND
  c_current_cdemo_sk = cd2.cd_demo_sk AND
  c_current_addr_sk = ca_address_sk AND
  c_birth_month IN (1, 6, 8, 9, 12, 2) AND
  d_year = 1998 AND
  ca_state IN ('MS', 'IN', 'ND', 'OK', 'NM', 'VA', 'MS')
GROUP BY ROLLUP (i_item_id, ca_country, ca_state, ca_county)
ORDER BY ca_country, ca_state, ca_county, i_item_id
LIMIT 100
""",
    "tpcds_real_q22": """
SELECT
  i_product_name,
  i_brand,
  i_class,
  i_category,
  avg(inv_quantity_on_hand) qoh
FROM inventory, date_dim, item, warehouse
WHERE inv_date_sk = d_date_sk
  AND inv_item_sk = i_item_sk
  AND inv_warehouse_sk = w_warehouse_sk
  AND d_month_seq BETWEEN 1200 AND 1200 + 11
GROUP BY ROLLUP (i_product_name, i_brand, i_class, i_category)
ORDER BY qoh, i_product_name, i_brand, i_class, i_category
LIMIT 100
""",
    "tpcds_real_q27": """
SELECT
  i_item_id,
  s_state,
  grouping(s_state) g_state,
  avg(ss_quantity) agg1,
  avg(ss_list_price) agg2,
  avg(ss_coupon_amt) agg3,
  avg(ss_sales_price) agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND
  ss_item_sk = i_item_sk AND
  ss_store_sk = s_store_sk AND
  ss_cdemo_sk = cd_demo_sk AND
  cd_gender = 'M' AND
  cd_marital_status = 'S' AND
  cd_education_status = 'College' AND
  d_year = 2002 AND
  s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN')
GROUP BY ROLLUP (i_item_id, s_state)
ORDER BY i_item_id, s_state
LIMIT 100
""",
    "tpcds_real_q31": """
WITH ss AS
(SELECT
    ca_county,
    d_qoy,
    d_year,
    sum(ss_ext_sales_price) AS store_sales
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk
    AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
    ws AS
  (SELECT
    ca_county,
    d_qoy,
    d_year,
    sum(ws_ext_sales_price) AS web_sales
  FROM web_sales, date_dim, customer_address
  WHERE ws_sold_date_sk = d_date_sk
    AND ws_bill_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT
  ss1.ca_county,
  ss1.d_year,
  ws2.web_sales / ws1.web_sales web_q1_q2_increase,
  ss2.store_sales / ss1.store_sales store_q1_q2_increase,
  ws3.web_sales / ws2.web_sales web_q2_q3_increase,
  ss3.store_sales / ss2.store_sales store_q2_q3_increase
FROM
  ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3
WHERE
  ss1.d_qoy = 1
    AND ss1.d_year = 2000
    AND ss1.ca_county = ss2.ca_county
    AND ss2.d_qoy = 2
    AND ss2.d_year = 2000
    AND ss2.ca_county = ss3.ca_county
    AND ss3.d_qoy = 3
    AND ss3.d_year = 2000
    AND ss1.ca_county = ws1.ca_county
    AND ws1.d_qoy = 1
    AND ws1.d_year = 2000
    AND ws1.ca_county = ws2.ca_county
    AND ws2.d_qoy = 2
    AND ws2.d_year = 2000
    AND ws1.ca_county = ws3.ca_county
    AND ws3.d_qoy = 3
    AND ws3.d_year = 2000
    AND CASE WHEN ws1.web_sales > 0
    THEN ws2.web_sales / ws1.web_sales
        ELSE NULL END
    > CASE WHEN ss1.store_sales > 0
    THEN ss2.store_sales / ss1.store_sales
      ELSE NULL END
    AND CASE WHEN ws2.web_sales > 0
    THEN ws3.web_sales / ws2.web_sales
        ELSE NULL END
    > CASE WHEN ss2.store_sales > 0
    THEN ss3.store_sales / ss2.store_sales
      ELSE NULL END
ORDER BY ss1.ca_county
""",
    "tpcds_real_q36": """
SELECT
  sum(ss_net_profit) / sum(ss_ext_sales_price) AS gross_margin,
  i_category,
  i_class,
  grouping(i_category) + grouping(i_class) AS lochierarchy,
  rank()
  OVER (
    PARTITION BY grouping(i_category) + grouping(i_class),
      CASE WHEN grouping(i_class) = 0
        THEN i_category END
    ORDER BY sum(ss_net_profit) / sum(ss_ext_sales_price) ASC) AS rank_within_parent
FROM
  store_sales, date_dim d1, item, store
WHERE
  d1.d_year = 2001
    AND d1.d_date_sk = ss_sold_date_sk
    AND i_item_sk = ss_item_sk
    AND s_store_sk = ss_store_sk
    AND s_state IN ('TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN', 'TN')
GROUP BY ROLLUP (i_category, i_class)
ORDER BY
  lochierarchy DESC
  , CASE WHEN lochierarchy = 0
  THEN i_category END
  , rank_within_parent
LIMIT 100
""",
    "tpcds_real_q38": """
SELECT count(*)
FROM (
       SELECT DISTINCT
         c_last_name,
         c_first_name,
         d_date
       FROM store_sales, date_dim, customer
       WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
         AND store_sales.ss_customer_sk = customer.c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1200 + 11
       INTERSECT
       SELECT DISTINCT
         c_last_name,
         c_first_name,
         d_date
       FROM catalog_sales, date_dim, customer
       WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
         AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1200 + 11
       INTERSECT
       SELECT DISTINCT
         c_last_name,
         c_first_name,
         d_date
       FROM web_sales, date_dim, customer
       WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
         AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
         AND d_month_seq BETWEEN 1200 AND 1200 + 11
     ) hot_cust
LIMIT 100
""",
    "tpcds_real_q47": """
WITH v1 AS (
  SELECT
    i_category,
    i_brand,
    s_store_name,
    s_company_name,
    d_year,
    d_moy,
    sum(ss_sales_price) sum_sales,
    avg(sum(ss_sales_price))
    OVER
    (PARTITION BY i_category, i_brand,
      s_store_name, s_company_name, d_year)
    avg_monthly_sales,
    rank()
    OVER
    (PARTITION BY i_category, i_brand,
      s_store_name, s_company_name
      ORDER BY d_year, d_moy) rn
  FROM item, store_sales, date_dim, store
  WHERE ss_item_sk = i_item_sk AND
    ss_sold_date_sk = d_date_sk AND
    ss_store_sk = s_store_sk AND
    (
      d_year = 1999 OR
        (d_year = 1999 - 1 AND d_moy = 12) OR
        (d_year = 1999 + 1 AND d_moy = 1)
    )
  GROUP BY i_category, i_brand,
    s_store_name, s_company_name,
    d_year, d_moy),
    v2 AS (
    SELECT
      v1.i_category,
      v1.i_brand,
      v1.s_store_name,
      v1.s_company_name,
      v1.d_year,
      v1.d_moy,
      v1.avg_monthly_sales,
      v1.sum_sales,
      v1_lag.sum_sales psum,
      v1_lead.sum_sales nsum
    FROM v1, v1 v1_lag, v1 v1_lead
    WHERE v1.i_category = v1_lag.i_category AND
      v1.i_category = v1_lead.i_category AND
      v1.i_brand = v1_lag.i_brand AND
      v1.i_brand = v1_lead.i_brand AND
      v1.s_store_name = v1_lag.s_store_name AND
      v1.s_store_name = v1_lead.s_store_name AND
      v1.s_company_name = v1_lag.s_company_name AND
      v1.s_company_name = v1_lead.s_company_name AND
      v1.rn = v1_lag.rn + 1 AND
      v1.rn = v1_lead.rn - 1)
SELECT *
FROM v2
WHERE d_year = 1999 AND
  avg_monthly_sales > 0 AND
  CASE WHEN avg_monthly_sales > 0
    THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
  ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, 3
LIMIT 100
""",
    "tpcds_real_q57": """
WITH v1 AS (
  SELECT
    i_category,
    i_brand,
    cc_name,
    d_year,
    d_moy,
    sum(cs_sales_price) sum_sales,
    avg(sum(cs_sales_price))
    OVER
    (PARTITION BY i_category, i_brand, cc_name, d_year)
    avg_monthly_sales,
    rank()
    OVER
    (PARTITION BY i_category, i_brand, cc_name
      ORDER BY d_year, d_moy) rn
  FROM item, catalog_sales, date_dim, call_center
  WHERE cs_item_sk = i_item_sk AND
    cs_sold_date_sk = d_date_sk AND
    cc_call_center_sk = cs_call_center_sk AND
    (
      d_year = 1999 OR
        (d_year = 1999 - 1 AND d_moy = 12) OR
        (d_year = 1999 + 1 AND d_moy = 1)
    )
  GROUP BY i_category, i_brand,
    cc_name, d_year, d_moy),
    v2 AS (
    SELECT
      v1.i_category,
      v1.i_brand,
      v1.cc_name,
      v1.d_year,
      v1.d_moy,
      v1.avg_monthly_sales,
      v1.sum_sales,
      v1_lag.sum_sales psum,
      v1_lead.sum_sales nsum
    FROM v1, v1 v1_lag, v1 v1_lead
    WHERE v1.i_category = v1_lag.i_category AND
      v1.i_category = v1_lead.i_category AND
      v1.i_brand = v1_lag.i_brand AND
      v1.i_brand = v1_lead.i_brand AND
      v1.cc_name = v1_lag.cc_name AND
      v1.cc_name = v1_lead.cc_name AND
      v1.rn = v1_lag.rn + 1 AND
      v1.rn = v1_lead.rn - 1)
SELECT *
FROM v2
WHERE d_year = 1999 AND
  avg_monthly_sales > 0 AND
  CASE WHEN avg_monthly_sales > 0
    THEN abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
  ELSE NULL END > 0.1
ORDER BY sum_sales - avg_monthly_sales, 3
LIMIT 100
""",
    "tpcds_real_q74": """
WITH year_total AS (
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    d_year AS year,
    sum(ss_net_paid) year_total,
    's' sale_type
  FROM
    customer, store_sales, date_dim
  WHERE c_customer_sk = ss_customer_sk
    AND ss_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2001 + 1)
  GROUP BY
    c_customer_id, c_first_name, c_last_name, d_year
  UNION ALL
  SELECT
    c_customer_id customer_id,
    c_first_name customer_first_name,
    c_last_name customer_last_name,
    d_year AS year,
    sum(ws_net_paid) year_total,
    'w' sale_type
  FROM
    customer, web_sales, date_dim
  WHERE c_customer_sk = ws_bill_customer_sk
    AND ws_sold_date_sk = d_date_sk
    AND d_year IN (2001, 2001 + 1)
  GROUP BY
    c_customer_id, c_first_name, c_last_name, d_year)
SELECT
  t_s_secyear.customer_id,
  t_s_secyear.customer_first_name,
  t_s_secyear.customer_last_name
FROM
  year_total t_s_firstyear, year_total t_s_secyear,
  year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_id = t_s_firstyear.customer_id
  AND t_s_firstyear.customer_id = t_w_secyear.customer_id
  AND t_s_firstyear.customer_id = t_w_firstyear.customer_id
  AND t_s_firstyear.sale_type = 's'
  AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's'
  AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.year = 2001
  AND t_s_secyear.year = 2001 + 1
  AND t_w_firstyear.year = 2001
  AND t_w_secyear.year = 2001 + 1
  AND t_s_firstyear.year_total > 0
  AND t_w_firstyear.year_total > 0
  AND CASE WHEN t_w_firstyear.year_total > 0
  THEN t_w_secyear.year_total / t_w_firstyear.year_total
      ELSE NULL END
  > CASE WHEN t_s_firstyear.year_total > 0
  THEN t_s_secyear.year_total / t_s_firstyear.year_total
    ELSE NULL END
ORDER BY 1, 1, 1
LIMIT 100
""",
    "tpcds_real_q77": """
WITH ss AS
(SELECT
    s_store_sk,
    sum(ss_ext_sales_price) AS sales,
    sum(ss_net_profit) AS profit
  FROM store_sales, date_dim, store
  WHERE ss_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND ss_store_sk = s_store_sk
  GROUP BY s_store_sk),
    sr AS
  (SELECT
    s_store_sk,
    sum(sr_return_amt) AS returns,
    sum(sr_net_loss) AS profit_loss
  FROM store_returns, date_dim, store
  WHERE sr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND sr_store_sk = s_store_sk
  GROUP BY s_store_sk),
    cs AS
  (SELECT
    cs_call_center_sk,
    sum(cs_ext_sales_price) AS sales,
    sum(cs_net_profit) AS profit
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
  GROUP BY cs_call_center_sk),
    cr AS
  (SELECT
    sum(cr_return_amount) AS returns,
    sum(cr_net_loss) AS profit_loss
  FROM catalog_returns, date_dim
  WHERE cr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)),
    ws AS
  (SELECT
    wp_web_page_sk,
    sum(ws_ext_sales_price) AS sales,
    sum(ws_net_profit) AS profit
  FROM web_sales, date_dim, web_page
  WHERE ws_sold_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND ws_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk),
    wr AS
  (SELECT
    wp_web_page_sk,
    sum(wr_return_amt) AS returns,
    sum(wr_net_loss) AS profit_loss
  FROM web_returns, date_dim, web_page
  WHERE wr_returned_date_sk = d_date_sk
    AND d_date BETWEEN cast('2000-08-03' AS DATE) AND
  (cast('2000-08-03' AS DATE) + INTERVAL 30 days)
    AND wr_web_page_sk = wp_web_page_sk
  GROUP BY wp_web_page_sk)
SELECT
  channel,
  id,
  sum(sales) AS sales,
  sum(returns) AS returns,
  sum(profit) AS profit
FROM
  (SELECT
     'store channel' AS channel,
     ss.s_store_sk AS id,
     sales,
     coalesce(returns, 0) AS returns,
     (profit - coalesce(profit_loss, 0)) AS profit
   FROM ss
     LEFT JOIN sr
       ON ss.s_store_sk = sr.s_store_sk
   UNION ALL
   SELECT
     'catalog channel' AS channel,
     cs_call_center_sk AS id,
     sales,
     returns,
     (profit - profit_loss) AS profit
   FROM cs, cr
   UNION ALL
   SELECT
     'web channel' AS channel,
     ws.wp_web_page_sk AS id,
     sales,
     coalesce(returns, 0) returns,
     (profit - coalesce(profit_loss, 0)) AS profit
   FROM ws
     LEFT JOIN wr
       ON ws.wp_web_page_sk = wr.wp_web_page_sk
  ) x
GROUP BY ROLLUP (channel, id)
ORDER BY channel, id
LIMIT 100
""",
    "tpcds_real_q86": """
SELECT
  sum(ws_net_paid) AS total_sum,
  i_category,
  i_class,
  grouping(i_category) + grouping(i_class) AS lochierarchy,
  rank()
  OVER (
    PARTITION BY grouping(i_category) + grouping(i_class),
      CASE WHEN grouping(i_class) = 0
        THEN i_category END
    ORDER BY sum(ws_net_paid) DESC) AS rank_within_parent
FROM
  web_sales, date_dim d1, item
WHERE
  d1.d_month_seq BETWEEN 1200 AND 1200 + 11
    AND d1.d_date_sk = ws_sold_date_sk
    AND i_item_sk = ws_item_sk
GROUP BY ROLLUP (i_category, i_class)
ORDER BY
  lochierarchy DESC,
  CASE WHEN lochierarchy = 0
    THEN i_category END,
  rank_within_parent
LIMIT 100
""",
    "tpcds_real_q87": """
SELECT count(*)
FROM ((SELECT DISTINCT
  c_last_name,
  c_first_name,
  d_date
FROM store_sales, date_dim, customer
WHERE store_sales.ss_sold_date_sk = date_dim.d_date_sk
  AND store_sales.ss_customer_sk = customer.c_customer_sk
  AND d_month_seq BETWEEN 1200 AND 1200 + 11)
      EXCEPT
      (SELECT DISTINCT
        c_last_name,
        c_first_name,
        d_date
      FROM catalog_sales, date_dim, customer
      WHERE catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
        AND catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11)
      EXCEPT
      (SELECT DISTINCT
        c_last_name,
        c_first_name,
        d_date
      FROM web_sales, date_dim, customer
      WHERE web_sales.ws_sold_date_sk = date_dim.d_date_sk
        AND web_sales.ws_bill_customer_sk = customer.c_customer_sk
        AND d_month_seq BETWEEN 1200 AND 1200 + 11)
     ) cool_cust
""",
}

QUERY_NAMES = sorted(QUERY_TEXTS)
