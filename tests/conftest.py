"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (the reference validates its
distribution semantics on single-process Spark local[4]; our equivalent is
XLA's host-platform device virtualization — see SURVEY.md §4). The real-TPU
path is exercised by bench.py, not the unit tests.

Env vars must be set before jax is imported anywhere.
"""

import os

# Hard override: the ambient environment pins JAX_PLATFORMS=axon (the real
# TPU tunnel); tests must run CPU-only with 8 virtual devices. jax is
# pre-imported by sitecustomize, so update its config too — env alone is
# captured before conftest runs.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    """XLA:CPU segfaults deterministically once enough distinct programs
    accumulate in one process (observed at test ~412 of the full suite,
    inside backend_compile_and_load, at modest RSS). Dropping compiled
    executables and trace caches per module bounds the accumulation; the
    recompile cost is a few percent of suite time."""
    jax.clear_caches()
    yield


@pytest.fixture()
def tmp_system_path(tmp_path):
    """A fresh hyperspace system path per test."""
    p = tmp_path / "indexes"
    p.mkdir()
    return str(p)


def run_on_mesh(snippet: str, device_count: int = 8,
                timeout: int = 240) -> str:
    """Run a python snippet in a SUBPROCESS pinned to a forced-host CPU
    mesh of ``device_count`` devices (XLA_FLAGS
    --xla_force_host_platform_device_count). Device count is fixed at
    backend init, so in-process tests can never vary it — and an
    externally-set XLA_FLAGS could silently shrink the mesh; the
    subprocess guarantees the topology regardless of the parent
    environment. The snippet's stdout is returned (assert on it);
    non-zero exit raises with stderr attached."""
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={device_count}")
    proc = subprocess.run(
        [sys.executable, "-c", snippet], env=env, capture_output=True,
        text=True, timeout=timeout,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if proc.returncode != 0:
        raise AssertionError(
            f"mesh subprocess (devices={device_count}) failed "
            f"rc={proc.returncode}\nstdout: {proc.stdout[-4000:]}\n"
            f"stderr: {proc.stderr[-4000:]}")
    return proc.stdout


@pytest.fixture()
def mesh_subprocess():
    """Subprocess-isolated forced-host mesh runner (see run_on_mesh):
    ``mesh_subprocess(snippet, device_count=8)`` → stdout."""
    return run_on_mesh


class CaptureLogger:
    """Conf-pluggable telemetry sink collecting every event (the reference
    test pattern: TestUtils.MockEventLogger). Point the conf at
    "tests.conftest.CaptureLogger" and read events via capture_logger()."""

    events = []

    def log_event(self, event):
        CaptureLogger.events.append(event)


def capture_logger():
    """The CaptureLogger class as the ENGINE sees it: get_logger imports
    "tests.conftest" by dotted name, which is a different module object
    from the one pytest executes this file as — events land on that class,
    not on this module's."""
    import importlib
    return importlib.import_module("tests.conftest").CaptureLogger
