"""Parquet row-group pruning translation (execution/pushdown.py).

The translator long handled Col <op> Literal comparisons; this suite
pins the full conjunct surface — IN lists and IS [NOT] NULL included
(the IN-heavy TPC-DS filter shape got no pruning before those landed) —
plus result-correctness of scans whose filters are pushed.

Sessions run with the default distributed tier (partitioned-jit SPMD
over the virtual 8-device CPU mesh).
"""

from __future__ import annotations

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution.pushdown import (filter_constrains,
                                               pushable_filter)
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.schema import INT64, STRING, Field, Schema

SCHEMA = Schema([Field("k", INT64), Field("v", INT64, True),
                 Field("s", STRING)])


class TestTranslation:
    def test_comparison_translates(self):
        assert pushable_filter(col("k") > 5, SCHEMA) is not None

    def test_in_list_translates(self):
        f = pushable_filter(col("k").isin([1, 2, 3]), SCHEMA)
        assert f is not None
        assert "is_in" in str(f)

    def test_in_with_non_literal_option_does_not(self):
        from hyperspace_tpu.plan import expr as E
        e = E.In(col("k"), [E.Lit(1), col("v")])
        assert pushable_filter(e, SCHEMA) is None

    def test_is_null_translates(self):
        f = pushable_filter(col("v").is_null(), SCHEMA)
        assert f is not None
        assert "is_null" in str(f)

    def test_is_not_null_translates(self):
        f = pushable_filter(col("v").is_not_null(), SCHEMA)
        assert f is not None
        assert "invert" in str(f) or "is_null" in str(f)

    def test_partial_conjunction_pushes_sound_subset(self):
        # LIKE cannot push; the IN and NOT NULL conjuncts still do.
        cond = (col("s").like("a%") & col("k").isin([1, 2])
                & col("v").is_not_null())
        f = pushable_filter(cond, SCHEMA)
        assert f is not None
        assert "is_in" in str(f)

    def test_filter_constrains_sees_null_guard(self):
        assert filter_constrains(col("k").is_not_null(), SCHEMA, "k")
        assert not filter_constrains(col("k").is_not_null(), SCHEMA, "v")


class TestEndToEnd:
    @pytest.fixture()
    def env(self, tmp_path):
        rng = np.random.default_rng(11)
        n = 4000
        v = rng.integers(0, 50, n).astype(np.float64)
        t = pa.table({
            "k": pa.array(np.sort(rng.integers(0, 1000, n))
                          .astype(np.int64)),
            "v": pa.array(v, mask=rng.random(n) < 0.3),
        })
        d = tmp_path / "data"
        d.mkdir()
        # Many small row groups so pruning has something to skip.
        pq.write_table(t, d / "p0.parquet", row_group_size=256)
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        return session, str(d), t.to_pandas()

    def _check(self, session, path, expected):
        got = session.read.parquet(path) \
            .filter(self.cond).to_pandas()
        got = got.sort_values(list(got.columns)).reset_index(drop=True)
        expected = expected.sort_values(
            list(expected.columns)).reset_index(drop=True)
        pd.testing.assert_frame_equal(got, expected, check_dtype=False)

    def test_in_filter_results(self, env):
        session, path, frame = env
        self.cond = col("k").isin([5, 500, 995])
        self._check(session, path, frame[frame.k.isin([5, 500, 995])])

    def test_not_null_filter_results(self, env):
        session, path, frame = env
        self.cond = col("v").is_not_null() & (col("k") < 200)
        self._check(session, path,
                    frame[frame.v.notna() & (frame.k < 200)])

    def test_is_null_filter_results(self, env):
        session, path, frame = env
        self.cond = col("v").is_null() & (col("k") < 200)
        self._check(session, path,
                    frame[frame.v.isna() & (frame.k < 200)])
