"""Cost-based join reordering + the statistics layer beneath it
(optimizer/stats.py, optimizer/cardinality.py, optimizer/join_order.py).

Covers: lazy/cached/invalidated statistics harvesting, the selectivity
and join-output estimators, chain extraction + reorder semantics
(identical results modulo row order, asserted by a randomized
star-schema property test), the explain/telemetry observability, and
the advisor's selectivity-discounted costing.

Sessions run with the default distributed tier (partitioned-jit SPMD
over the virtual 8-device CPU mesh; the r12 port retired the old
quarantine).
"""

from __future__ import annotations

import datetime
import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.optimizer import cardinality
from hyperspace_tpu.optimizer.constants import OptimizerConstants
from hyperspace_tpu.optimizer.stats import provider_for
from hyperspace_tpu.plan.expr import col, sum_

from conftest import capture_logger as sink  # noqa: E402


def _session(tmp_path, **conf):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    for k, v in conf.items():
        session.conf.set(k, v)
    return session


def _write(dirpath, table):
    os.makedirs(dirpath, exist_ok=True)
    pq.write_table(table, os.path.join(dirpath, "part0.parquet"))
    return str(dirpath)


@pytest.fixture()
def star(tmp_path):
    """A small star schema: fact(4000) x dim1(50) x dim2(20), with a
    selective category on each dimension."""
    rng = np.random.default_rng(7)
    n_f, n_d1, n_d2 = 4000, 50, 20
    base = datetime.date(1995, 1, 1).toordinal() \
        - datetime.date(1970, 1, 1).toordinal()
    fact = pa.table({
        "f_d1": pa.array(rng.integers(0, n_d1, n_f).astype(np.int64)),
        "f_d2": pa.array(rng.integers(0, n_d2, n_f).astype(np.int64)),
        "f_date": pa.array((rng.integers(0, 1000, n_f) + base)
                           .astype(np.int32), type=pa.int32())
        .cast(pa.date32()),
        "f_val": pa.array(rng.uniform(0, 100, n_f).round(3)),
    })
    dim1 = pa.table({
        "d1_key": pa.array(np.arange(n_d1, dtype=np.int64)),
        "d1_cat": pa.array(rng.choice(["a", "b", "c", "d", "e"], n_d1)),
    })
    dim2 = pa.table({
        "d2_key": pa.array(np.arange(n_d2, dtype=np.int64)),
        "d2_cat": pa.array(rng.choice(["x", "y"], n_d2)),
    })
    paths = {
        "fact": _write(tmp_path / "fact", fact),
        "dim1": _write(tmp_path / "dim1", dim1),
        "dim2": _write(tmp_path / "dim2", dim2),
    }
    session = _session(tmp_path)
    return session, paths


def _three_way(session, paths):
    fact = session.read.parquet(paths["fact"])
    d1 = session.read.parquet(paths["dim1"]).filter(col("d1_cat") == "b")
    d2 = session.read.parquet(paths["dim2"])
    return (fact.join(d2, on=col("f_d2") == col("d2_key"))
            .join(d1, on=col("f_d1") == col("d1_key"))
            .select("d1_cat", "d2_cat", "f_val"))


def _sorted_rows(df):
    out = df.to_pandas()
    return out.sort_values(list(out.columns)).reset_index(drop=True)


REORDER_ON = {OptimizerConstants.JOIN_REORDER_ENABLED: "true"}


# ---------------------------------------------------------------------------
# Statistics provider.
# ---------------------------------------------------------------------------

class TestStatsProvider:
    def test_footer_harvest(self, star):
        session, paths = star
        relation = session.read.parquet(paths["fact"]).plan.relation
        ts = provider_for(session).table_stats(relation)
        assert ts is not None
        assert ts.row_count == 4000
        cs = ts.column("f_d1")
        assert cs.has_minmax and cs.minimum == 0 and cs.maximum == 49
        assert ts.null_fraction("f_d1") == 0.0
        # Integer span bounds NDV at 50.
        assert ts.ndv("f_d1") == 50.0

    def test_null_fraction_from_footers(self, star, tmp_path):
        session, _ = star
        t = pa.table({"k": pa.array([1, None, 3, None], type=pa.int64())})
        d = _write(tmp_path / "nulls", t)
        ts = provider_for(session).table_stats(
            session.read.parquet(d).plan.relation)
        assert ts.null_fraction("k") == 0.5

    def test_string_ndv_from_sample(self, star):
        session, paths = star
        relation = session.read.parquet(paths["dim1"]).plan.relation
        ts = provider_for(session).table_stats(relation)
        # 5 distinct categories over 50 rows: the saturated-sample branch
        # reports the sample's distinct count exactly.
        assert ts.ndv("d1_cat") == 5.0

    def test_cache_hits_and_invalidation(self, star):
        session, paths = star
        provider = provider_for(session)
        relation = session.read.parquet(paths["fact"]).plan.relation
        ts1 = provider.table_stats(relation)
        n = provider.harvest_count
        ts2 = provider.table_stats(relation)
        assert ts2 is ts1 and provider.harvest_count == n
        # In-place source change (append a file): signature flips, the
        # entry re-harvests — the result-cache invalidation contract.
        extra = pa.table({
            "f_d1": pa.array([0], type=pa.int64()),
            "f_d2": pa.array([0], type=pa.int64()),
            "f_date": pa.array([datetime.date(1995, 1, 1)]),
            "f_val": pa.array([1.0]),
        })
        pq.write_table(extra, os.path.join(paths["fact"], "part1.parquet"))
        fresh = session.read.parquet(paths["fact"]).plan.relation
        ts3 = provider.table_stats(fresh)
        assert provider.harvest_count == n + 1
        assert ts3.row_count == 4001

    def test_non_parquet_has_no_stats(self, star, tmp_path):
        session, _ = star
        d = tmp_path / "csvdata"
        d.mkdir()
        pd.DataFrame({"k": [1, 2, 3]}).to_csv(d / "p0.csv", index=False)
        relation = session.read.csv(str(d)).plan.relation
        assert provider_for(session).table_stats(relation) is None

    def test_stats_disabled_conf(self, star):
        session, paths = star
        session.conf.set(OptimizerConstants.STATS_ENABLED, "false")
        relation = session.read.parquet(paths["fact"]).plan.relation
        assert provider_for(session).table_stats(relation) is None

    def test_lazy_no_harvest_below_two_joins(self, star):
        """The laziness acceptance: single-join (and join-free) plans
        with reorder enabled never touch the statistics provider."""
        session, paths = star
        session.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED, "true")
        fact = session.read.parquet(paths["fact"])
        d1 = session.read.parquet(paths["dim1"])
        fact.filter(col("f_d1") < 10).select("f_val").to_pandas()
        fact.join(d1, on=col("f_d1") == col("d1_key")) \
            .select("f_val").to_pandas()
        provider = getattr(session, "_stats_provider", None)
        assert provider is None or provider.harvest_count == 0


# ---------------------------------------------------------------------------
# Cardinality estimators.
# ---------------------------------------------------------------------------

class TestCardinality:
    @pytest.fixture()
    def fact_stats(self, star):
        session, paths = star
        relation = session.read.parquet(paths["fact"]).plan.relation
        return provider_for(session).table_stats(relation)

    def test_equality_is_one_over_ndv(self, fact_stats):
        sel = cardinality.filter_selectivity(
            fact_stats, col("f_d1") == 7)
        assert sel == pytest.approx(1 / 50, rel=1e-6)

    def test_out_of_range_equality_hits_floor(self, fact_stats):
        sel = cardinality.filter_selectivity(
            fact_stats, col("f_d1") == 1000)
        assert sel == cardinality.MIN_SELECTIVITY

    def test_range_fraction(self, fact_stats):
        sel = cardinality.filter_selectivity(
            fact_stats, col("f_d1") < 25)
        assert 0.3 < sel < 0.7

    def test_date_range_fraction(self, fact_stats):
        sel = cardinality.filter_selectivity(
            fact_stats, col("f_date") < datetime.date(1995, 5, 1))
        assert 0.05 < sel < 0.25

    def test_in_list(self, fact_stats):
        sel = cardinality.filter_selectivity(
            fact_stats, col("f_d1").isin([1, 2, 3, 4, 5]))
        assert sel == pytest.approx(5 / 50, rel=1e-6)

    def test_is_not_null(self, fact_stats):
        assert cardinality.filter_selectivity(
            fact_stats, col("f_d1").is_not_null()) == 1.0
        assert cardinality.filter_selectivity(
            fact_stats, col("f_d1").is_null()) \
            == cardinality.MIN_SELECTIVITY

    def test_conjunction_multiplies_or_adds(self, fact_stats):
        a = col("f_d1") == 7
        b = col("f_d2") == 3
        s_and = cardinality.filter_selectivity(fact_stats, a & b)
        s_or = cardinality.filter_selectivity(fact_stats, a | b)
        sa = cardinality.filter_selectivity(fact_stats, a)
        sb = cardinality.filter_selectivity(fact_stats, b)
        assert s_and == pytest.approx(sa * sb, rel=1e-6)
        assert s_or == pytest.approx(sa + sb - sa * sb, rel=1e-6)

    def test_sketch_cap_bounds_from_above(self, fact_stats):
        capped = cardinality.filter_selectivity(
            fact_stats, col("f_d1") < 25, sketch_cap=0.01)
        assert capped == pytest.approx(0.01)

    def test_join_output_containment(self):
        rows = cardinality.join_output_rows(4000, 50, 50, 50)
        assert rows == pytest.approx(4000.0)
        # Missing NDV falls back to the side's row count.
        assert cardinality.join_output_rows(4000, 50, None, None) \
            == pytest.approx(50.0)

    def test_unknown_shape_is_conservative(self, fact_stats):
        sel = cardinality.filter_selectivity(
            fact_stats, col("f_val") * 2 > col("f_d1"))
        assert sel == 1.0


# ---------------------------------------------------------------------------
# The reorder rewrite.
# ---------------------------------------------------------------------------

class TestJoinReorder:
    def test_off_by_default(self, star):
        session, paths = star
        q = _three_way(session, paths)
        session.optimize(q.plan)
        assert session._last_join_order is None

    def test_reorders_selective_dim_first(self, star):
        session, paths = star
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        q = _three_way(session, paths)
        optimized = session.optimize(q.plan)
        records = session._last_join_order
        assert len(records) == 1 and records[0]["reordered"]
        # The filtered dim1 (est ~10 rows) joins before the unfiltered
        # dim2 (20 rows x no selectivity).
        assert records[0]["order"] == ["fact", "dim1", "dim2"]
        assert "[reordered" in optimized.tree_string()

    def test_results_identical_and_columns_preserved(self, star):
        session, paths = star
        q = _three_way(session, paths)
        off = _sorted_rows(q)
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        on = _sorted_rows(q)
        assert list(on.columns) == list(off.columns)
        pd.testing.assert_frame_equal(on, off)

    def test_two_table_chain_untouched(self, star):
        session, paths = star
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        fact = session.read.parquet(paths["fact"])
        d1 = session.read.parquet(paths["dim1"])
        q = fact.join(d1, on=col("f_d1") == col("d1_key"))
        before = session.optimize(q.plan)
        assert session._last_join_order == []
        assert "[reordered" not in before.tree_string()

    def test_missing_stats_keeps_original_order(self, star, tmp_path):
        """A chain member without parquet footers (csv) bails the whole
        chain to its original order — never a half-estimated reorder."""
        session, paths = star
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        d = tmp_path / "d2csv"
        d.mkdir()
        pd.DataFrame({"c_key": np.arange(20, dtype=np.int64)}).to_csv(
            d / "p0.csv", index=False)
        fact = session.read.parquet(paths["fact"])
        d1 = session.read.parquet(paths["dim1"]).filter(
            col("d1_cat") == "b")
        c = session.read.csv(str(d))
        q = (fact.join(c, on=col("f_d2") == col("c_key"))
             .join(d1, on=col("f_d1") == col("d1_key")))
        optimized = session.optimize(q.plan)
        records = session._last_join_order
        assert len(records) == 1 and not records[0]["reordered"]
        assert "statistics" in records[0]["note"]
        assert "[reordered" not in optimized.tree_string()

    def test_outer_join_is_a_barrier(self, star):
        session, paths = star
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        fact = session.read.parquet(paths["fact"])
        d1 = session.read.parquet(paths["dim1"])
        d2 = session.read.parquet(paths["dim2"])
        q = (fact.join(d2, on=col("f_d2") == col("d2_key"), how="left")
             .join(d1, on=col("f_d1") == col("d1_key")))
        session.optimize(q.plan)
        # The left join blocks the chain: only a 2-table inner chain
        # remains above it, so nothing reorders.
        assert all(not r["reordered"]
                   for r in session._last_join_order)

    def test_greedy_path_matches_dp_answer_here(self, star):
        session, paths = star
        session.conf.set(OptimizerConstants.JOIN_REORDER_DP_THRESHOLD, "0")
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        q = _three_way(session, paths)
        session.optimize(q.plan)
        records = session._last_join_order
        assert records[0]["reordered"]
        assert records[0]["order"] == ["fact", "dim1", "dim2"]

    def test_property_random_star_schemas(self, tmp_path):
        """Randomized 3-5 table star joins, random dimension filters and
        FROM orders: reorder on vs off answers are identical under
        sorted-row comparison (the semantics-preservation acceptance)."""
        rng = np.random.default_rng(20260803)
        session = _session(tmp_path)
        n_f = 1500
        n_dims_max = 4
        dim_sizes = [30, 12, 8, 45]
        dim_paths = []
        fact_cols = {"f_val": pa.array(
            rng.uniform(0, 10, n_f).round(3))}
        for d in range(n_dims_max):
            fact_cols[f"f_k{d}"] = pa.array(
                rng.integers(0, dim_sizes[d], n_f).astype(np.int64))
            dim_paths.append(_write(tmp_path / f"dim{d}", pa.table({
                f"k{d}": pa.array(np.arange(dim_sizes[d],
                                            dtype=np.int64)),
                f"c{d}": pa.array(rng.integers(0, 4, dim_sizes[d])
                                  .astype(np.int64)),
            })))
        fact_path = _write(tmp_path / "fact", pa.table(fact_cols))
        for trial in range(6):
            n_dims = int(rng.integers(2, n_dims_max + 1))  # 3-5 tables
            dims = list(rng.permutation(n_dims_max))[:n_dims]
            q = session.read.parquet(fact_path)
            for d in dims:
                dim = session.read.parquet(dim_paths[d])
                if rng.random() < 0.7:
                    dim = dim.filter(
                        col(f"c{d}") == int(rng.integers(0, 4)))
                q = q.join(dim, on=col(f"f_k{d}") == col(f"k{d}"))
            q = q.agg(sum_(col("f_val")).alias("total"))
            session.conf.set(
                OptimizerConstants.JOIN_REORDER_ENABLED, "false")
            off = _sorted_rows(q)
            session.conf.set(
                OptimizerConstants.JOIN_REORDER_ENABLED, "true")
            on = _sorted_rows(q)
            pd.testing.assert_frame_equal(on, off, check_dtype=False)


# ---------------------------------------------------------------------------
# Observability: telemetry events, explain section, q-error inputs.
# ---------------------------------------------------------------------------

class TestObservability:
    @pytest.fixture()
    def wired(self, star):
        session, paths = star
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        sink().events.clear()
        return session, paths

    def test_reorder_emits_events(self, wired):
        session, paths = wired
        _three_way(session, paths).to_pandas()
        names = [type(e).__name__ for e in sink().events]
        assert "JoinReorderEvent" in names
        assert "CardinalityEstimateEvent" in names
        jr = next(e for e in sink().events
                  if type(e).__name__ == "JoinReorderEvent")
        assert jr.tables == ["fact", "dim2", "dim1"]
        assert jr.order == ["fact", "dim1", "dim2"]
        assert len(jr.estimated_rows) == 2

    def test_explain_diagnostic_is_silent(self, wired):
        session, paths = wired
        from hyperspace_tpu.plananalysis.explain import explain_string
        q = _three_way(session, paths)
        text = explain_string(session, q.plan)
        assert "Join order:" in text
        assert "reordered ->" in text
        assert not [e for e in sink().events
                    if type(e).__name__ == "JoinReorderEvent"]

    def test_estimated_vs_actual_qerror(self, wired):
        """The executor records actual inner-join output rows under the
        condition repr the reorder steps carry — every reordered step
        must be pairable, with a sane q-error. Since r13 the SPMD
        program reports per-join output counts too (psum'd ``jrows:``
        outputs), so this runs under the DEFAULT distributed tier;
        minStreamRows is lowered so the 4000-row star actually
        dispatches on the mesh where SPMD is available (single-device
        images exercise the executor path through the same test)."""
        session, paths = wired
        session.conf.set(
            IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "64")
        from hyperspace_tpu.execution import spmd
        dispatches0 = spmd.DISPATCH_COUNT
        _three_way(session, paths).to_pandas()
        if session.hs_conf.distributed_enabled():
            # The point of the un-pin: the actuals below came from the
            # SPMD program, not single-device instrumentation.
            assert spmd.DISPATCH_COUNT > dispatches0
        steps = [s for r in session._last_join_order
                 for s in r["steps"]]
        assert steps
        for s in steps:
            actual = session._join_actuals.get(s["key"])
            assert actual is not None
            est = max(s["est_rows"], 1.0)
            q_err = max(est / max(actual, 1), max(actual, 1) / est)
            assert q_err < 50  # sane, not perfect

    def test_spmd_actuals_match_single_device(self, wired):
        """The SPMD-reported join actuals must be the SAME numbers the
        single-device executor records (results are byte-identical, so
        the observed cardinalities must be too)."""
        session, paths = wired
        session.conf.set(
            IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "64")
        if not session.hs_conf.distributed_enabled():
            import pytest as _pytest
            _pytest.skip("SPMD tier unavailable on this image")
        _three_way(session, paths).to_pandas()
        spmd_actuals = dict(session._join_actuals)
        session._join_actuals.clear()
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        _three_way(session, paths).to_pandas()
        single = dict(session._join_actuals)
        session.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
        assert spmd_actuals
        for key, rows in single.items():
            assert spmd_actuals.get(key) == rows, key

    def test_explain_shows_actuals_after_execution(self, wired):
        # Runs under the default distributed tier (see
        # test_estimated_vs_actual_qerror — the r13 un-pin).
        session, paths = wired
        session.conf.set(
            IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "64")
        from hyperspace_tpu.plananalysis.explain import explain_string
        q = _three_way(session, paths)
        q.to_pandas()
        text = explain_string(session, q.plan)
        section = text.split("Join order:")[-1]
        assert "actual" in section
        assert "actual n/a" not in section


# ---------------------------------------------------------------------------
# Interplay with the hyperspace index rules: reordering runs BEFORE
# rules/, so JoinIndexRule must still rewrite the reordered chain's
# leaf-level joins when a matching index pair exists.
# ---------------------------------------------------------------------------

class TestIndexRuleInterplay:
    def test_join_index_rewrites_reordered_leaf_join(self, star):
        from hyperspace_tpu.api import Hyperspace, IndexConfig
        session, paths = star
        q = _three_way(session, paths)
        plain = _sorted_rows(q)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(paths["fact"]),
                        IndexConfig("fact_d1", ["f_d1"],
                                    ["f_d2", "f_val"]))
        hs.create_index(session.read.parquet(paths["dim1"]),
                        IndexConfig("dim1_key", ["d1_key"], ["d1_cat"]))
        session.enable_hyperspace()
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        tree = session.optimize(q.plan).tree_string()
        # The chain reordered (filtered dim1 first) AND the now-leaf-level
        # fact x dim1 join was rewritten to the index pair: the rules
        # match the reordered tree exactly as they would the original.
        assert "[reordered" in tree
        assert tree.count("IndexScan") == 2
        assert "fact_d1" in tree and "dim1_key" in tree
        pd.testing.assert_frame_equal(_sorted_rows(q), plain)

    def test_reorder_may_trade_away_non_leaf_index_match(self, star):
        """The cost model is deliberately index-unaware: a chain order
        whose cardinality is cheapest wins even if the original text
        order had an index-servable leaf join (measured faster in this
        sandbox — intermediate-row reduction beats the bucketed-join
        byte discount). The traded-away rewrite must degrade to plain
        scans, never to a wrong plan."""
        from hyperspace_tpu.api import Hyperspace, IndexConfig
        session, paths = star
        q = _three_way(session, paths)
        plain = _sorted_rows(q)
        hs = Hyperspace(session)
        # Indexes serve the TEXT-order first join (fact x dim2); the
        # reorderer moves the filtered dim1 ahead of it, so the fact
        # side of this pair stops being leaf-level.
        hs.create_index(session.read.parquet(paths["fact"]),
                        IndexConfig("fact_d2", ["f_d2"],
                                    ["f_d1", "f_val"]))
        hs.create_index(session.read.parquet(paths["dim2"]),
                        IndexConfig("dim2_key", ["d2_key"], ["d2_cat"]))
        session.enable_hyperspace()
        for k, v in REORDER_ON.items():
            session.conf.set(k, v)
        tree = session.optimize(q.plan).tree_string()
        assert "[reordered" in tree
        pd.testing.assert_frame_equal(_sorted_rows(q), plain)


# ---------------------------------------------------------------------------
# Advisor costing rides the same estimates.
# ---------------------------------------------------------------------------

class TestAdvisorSelectivityCost:
    def test_selectivity_discounts_filtered_leaf(self, star):
        from hyperspace_tpu.advisor import cost
        session, paths = star
        d1 = session.read.parquet(paths["dim1"])
        filtered = d1.filter(col("d1_cat") == "b")
        sel_map = cost.filter_selectivity_map(session, filtered.plan)
        assert len(sel_map) == 1
        (sel,) = sel_map.values()
        assert sel == pytest.approx(1 / 5, rel=1e-6)
        full = cost.plan_cost_bytes(d1.plan)
        discounted = cost.plan_cost_bytes(filtered.plan, sel_map)
        assert discounted == pytest.approx(full * sel, rel=0.01)
        # Without the map: the legacy pure size-ratio proxy.
        assert cost.plan_cost_bytes(filtered.plan) == full

    def test_stats_disabled_yields_empty_map(self, star):
        from hyperspace_tpu.advisor import cost
        session, paths = star
        session.conf.set(OptimizerConstants.STATS_ENABLED, "false")
        filtered = session.read.parquet(paths["dim1"]).filter(
            col("d1_cat") == "b")
        assert cost.filter_selectivity_map(session, filtered.plan) == {}
