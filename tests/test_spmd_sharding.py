"""The NamedSharding/jit SPMD tier (parallel/sharding.py + the r12 port).

Covers what the port must guarantee:

- ``device_view`` launcher semantics: per-device bodies with lax
  collectives run under vmap-over-the-mesh-axis with byte-exact
  per-device results (routing, psum/pmax, replication contract).
- Distributed on/off byte-identity for sort, grouped aggregation, and
  the bucketed (exchange) join — in-process on the 8-device mesh and
  subprocess-isolated at device_count {1, 2, 4, 8} (the forced-host fixture,
  so mesh>1 paths run in tier-1 regardless of the parent environment).
- The shuffle-free property, ASSERTED on compiled HLO: the co-bucketed
  sort-merge join-aggregate compiles with ZERO resharding collectives
  (no all-to-all / all-gather / collective-permute / reduce-scatter).
- Warm sharded programs hit the r11 ProgramBank: two sessions running
  the same distributed workload compile ≤ 1.2x one session's count.
- Observability: ShardedExecutionEvent / SpmdExchangeEvent, the explain()
  "Distributed:" section, Hyperspace.spmd_stats(), and the
  distributed.mesh.maxDevices / fileAlignedScan knobs.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.parallel import sharding
from hyperspace_tpu.parallel.mesh import DATA_AXIS, make_mesh, pad_and_shard
from hyperspace_tpu.plan.expr import col, count, sum_

from conftest import capture_logger, run_on_mesh  # noqa: E402


def _write(d, n=4000, seed=7, files=4):
    rng = np.random.default_rng(seed)
    d.mkdir(parents=True, exist_ok=True)
    t = pa.table({
        "k": rng.integers(0, 40, n).astype(np.int64),
        "g": rng.integers(0, 12, n).astype(np.int64),
        "v": rng.integers(1, 100, n).astype(np.int64),
        "w": np.round(rng.uniform(0, 10, n), 3),
    })
    per = -(-n // files)
    for i in range(files):
        pq.write_table(t.slice(i * per, per), str(d / f"p{i}.parquet"))


def _session(tmp_path, capture_events=False, **conf):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    # Gate off for the fixtures here (deliberately small meshes); the
    # gate itself is tested explicitly in TestObservability.
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    if capture_events:
        capture_logger().events = []
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
    for k, v in conf.items():
        session.conf.set(k, v)
    return session


def _run_both(session, make_query):
    before = spmd.DISPATCH_COUNT
    dist = make_query().to_arrow()
    assert spmd.DISPATCH_COUNT > before, "SPMD path was not taken"
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    try:
        single = make_query().to_arrow()
    finally:
        session.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
    return dist, single


class TestDeviceViewLauncher:
    def test_psum_and_routing_semantics(self):
        """The launcher contract in one program: hash-routed all_to_all
        lands every row on its owner device, psum/pmax produce replicated
        scalars, and sharded outputs concatenate in device order."""
        mesh = make_mesh()
        n_dev = mesh.devices.size
        n = 64 * n_dev
        cap = 64

        def per_device(arrays, valid):
            x = arrays["x"]
            dst = (x % n_dev).astype(jnp.int32)
            dst = jnp.where(valid, dst, n_dev)
            perm = jnp.argsort(dst)
            sd = jnp.take(dst, perm)
            starts = jnp.searchsorted(
                sd, jnp.arange(n_dev + 1, dtype=sd.dtype))
            pos = jnp.arange(x.shape[0], dtype=jnp.int32) - jnp.take(
                starts, jnp.minimum(sd, n_dev)).astype(jnp.int32)
            ok = (pos < cap) & (sd < n_dev)
            idx = jnp.where(ok, sd * cap + pos, n_dev * cap)
            buf = jnp.zeros(n_dev * cap + 1, x.dtype) \
                .at[idx].set(jnp.take(x, perm), mode="drop")[:-1]
            recv = jax.lax.all_to_all(
                buf.reshape(n_dev, cap), DATA_AXIS,
                split_axis=0, concat_axis=0).reshape(-1)
            rv = jax.lax.all_to_all(
                (jnp.zeros(n_dev * cap + 1, jnp.bool_)
                 .at[idx].set(ok, mode="drop")[:-1]).reshape(n_dev, cap),
                DATA_AXIS, split_axis=0, concat_axis=0).reshape(-1)
            tot = jax.lax.psum(
                jnp.sum(jnp.where(valid, x, 0)), DATA_AXIS)
            mx = jax.lax.pmax(
                jnp.max(jnp.where(valid, x, -1)), DATA_AXIS)
            return {"recv": recv, "rv": rv, "tot": tot, "mx": mx}

        rows = n - 13
        arrays, valid = pad_and_shard(
            mesh, {"x": jnp.arange(rows, dtype=jnp.int64)}, rows)
        out = sharding.device_view(
            per_device, mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs={"recv": P(DATA_AXIS), "rv": P(DATA_AXIS),
                       "tot": P(), "mx": P()})(arrays, valid)
        assert int(out["tot"]) == rows * (rows - 1) // 2
        assert int(out["mx"]) == rows - 1
        assert out["tot"].shape == ()  # replicated contract: one copy
        recv = np.asarray(out["recv"])
        rv = np.asarray(out["rv"])
        per_dev = n_dev * cap  # each device's receive buffer
        for dev in range(n_dev):
            block = slice(dev * per_dev, (dev + 1) * per_dev)
            got = sorted(recv[block][rv[block]].tolist())
            assert got == [v for v in range(rows) if v % n_dev == dev]

    def test_mesh_program_caches_per_shape(self):
        mesh = make_mesh()

        def body(x):
            return jax.lax.psum(jnp.sum(x), DATA_AXIS)

        def run(x):
            return sharding.device_view(
                body, mesh, in_specs=(P(DATA_AXIS),), out_specs=P())(x)

        prog = sharding.MeshProgram(run, "test")
        a, va = pad_and_shard(mesh, {"x": jnp.arange(64.0)}, 64)
        del va
        assert float(prog(a["x"])) == float(np.arange(64).sum())
        assert prog.programs == 1
        prog(a["x"])
        assert prog.programs == 1  # same shape → cached executable
        b, vb = pad_and_shard(mesh, {"x": jnp.arange(128.0)}, 128)
        del vb
        prog(b["x"])
        assert prog.programs == 2
        counts = prog.collectives(a["x"])
        assert counts["all-reduce"] >= 1 and counts["all-to-all"] == 0


class TestByteIdentity8Devices:
    """Distributed on/off identity through the public API on the
    in-process 8-device mesh (grouped agg, exchange join, sort)."""

    def test_grouped_aggregate_identity(self, tmp_path):
        _write(tmp_path / "d")
        s = _session(tmp_path)
        r = s.read.parquet(str(tmp_path / "d"))
        d, single = _run_both(s, lambda: r.group_by("g").agg(
            sum_(col("v")).alias("sv"), count(None).alias("n")))
        assert d.equals(single)

    def test_exchange_join_identity(self, tmp_path):
        """m:n join — duplicate keys on both sides force the hash-routed
        bucket exchange (broadcast would raise on duplicates)."""
        _write(tmp_path / "a", n=3000, seed=1)
        _write(tmp_path / "b", n=900, seed=2, files=2)
        s = _session(tmp_path)
        ta = s.read.parquet(str(tmp_path / "a"))
        tb = s.read.parquet(str(tmp_path / "b"))
        rb = tb.select(col("k").alias("rk"), col("v").alias("rv"))
        d, single = _run_both(
            s, lambda: ta.join(rb, on=col("k") == col("rk")).agg(
                count(None).alias("pairs"), sum_(col("w")).alias("sw")))
        pd.testing.assert_frame_equal(d.to_pandas(), single.to_pandas())

    def test_distributed_sort_identity(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HST_SPMD_SORT", "on")
        _write(tmp_path / "d")
        s = _session(tmp_path)
        r = s.read.parquet(str(tmp_path / "d"))
        before = spmd.SORT_DISPATCH_COUNT
        d, single = _run_both(
            s, lambda: r.filter(col("v") > 5).select("k", "v").sort("k"))
        assert spmd.SORT_DISPATCH_COUNT > before
        # Sort is defined modulo ties: compare fully-ordered projections.
        pd.testing.assert_frame_equal(
            d.to_pandas().sort_values(["k", "v"]).reset_index(drop=True),
            single.to_pandas().sort_values(["k", "v"])
            .reset_index(drop=True))


@pytest.mark.parametrize("device_count", [1, 2, 4, 8])
def test_mesh_subprocess_byte_identity(tmp_path, device_count):
    """The forced-host subprocess fixture: sort, grouped aggregation, and
    the bucketed join byte-identical to the single-device executor at
    every supported-matrix device count {1, 2, 4, 8}, independent of
    this process's topology. At 1 device the program degenerates to the
    fused single-jit dispatch (singleDevice=on forces it on CPU)."""
    d = tmp_path / "data"
    _write(d, n=1500, seed=3)
    snippet = f"""
import os
os.environ["HST_SPMD_SORT"] = "on"
import pandas as pd
import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_
import jax
assert len(jax.devices()) == {device_count}, jax.devices()
s = hst.Session(system_path=r"{tmp_path}/idx")
s.conf.set(IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE, "on")
s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
r = s.read.parquet(r"{d}")
queries = dict(
    agg=lambda: r.group_by("g").agg(sum_(col("v")).alias("sv"),
                                    count(None).alias("n")),
    join=lambda: r.join(
        r.select(col("k").alias("rk"), col("w").alias("rw")),
        on=col("k") == col("rk")).agg(count(None).alias("pairs")),
    sort=lambda: r.filter(col("v") > 50).select("k", "v").sort("k"),
)
for name, q in queries.items():
    before = spmd.DISPATCH_COUNT
    dist = q().to_arrow().to_pandas()
    assert spmd.DISPATCH_COUNT > before, name
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
    single = q().to_arrow().to_pandas()
    s.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
    key = [c for c in dist.columns]
    pd.testing.assert_frame_equal(
        dist.sort_values(key).reset_index(drop=True),
        single.sort_values(key).reset_index(drop=True))
    print("IDENTICAL", name)
print("MESH", len(jax.devices()))
"""
    out = run_on_mesh(snippet, device_count=device_count, timeout=360)
    assert "IDENTICAL agg" in out
    assert "IDENTICAL join" in out
    assert "IDENTICAL sort" in out
    assert f"MESH {device_count}" in out


class TestShuffleFreeJoinHLO:
    def test_cobucketed_join_zero_resharding(self):
        """THE acceptance assert: the co-bucketed sort-merge join
        aggregate compiles with zero resharding collectives between the
        index sides — only the final psum all-reduces. Sharded end-to-end
        under PartitionSpec(buckets axis), verified on compiled HLO."""
        from hyperspace_tpu.execution.columnar import Table
        from hyperspace_tpu.parallel.distributed_build import \
            distributed_build_sorted_buckets
        from hyperspace_tpu.parallel.distributed_query import (
            distributed_join_agg, join_agg_collectives)
        rng = np.random.default_rng(5)
        n = 2048
        left = Table.from_arrow(pa.table({
            "k": rng.integers(0, 64, n).astype(np.int64),
            "lv": rng.integers(0, 50, n).astype(np.int64)}))
        right = Table.from_arrow(pa.table({
            "k": rng.integers(0, 64, n // 2).astype(np.int64),
            "rv": rng.integers(0, 50, n // 2).astype(np.int64)}))
        mesh = make_mesh()
        lt, lvalid, _ = distributed_build_sorted_buckets(
            left, ["k"], 16, mesh)
        rt, rvalid, _ = distributed_build_sorted_buckets(
            right, ["k"], 16, mesh)
        counts = join_agg_collectives(lt, lvalid, rt, rvalid,
                                      "k", "lv", "rv", mesh)
        assert counts["all-to-all"] == 0, counts
        assert counts["all-gather"] == 0, counts
        assert counts["collective-permute"] == 0, counts
        assert counts["reduce-scatter"] == 0, counts
        assert counts["all-reduce"] >= 1, counts  # the psum merges
        # And the numbers it produces are the oracle join aggregate.
        cnt, lsum, rsum = distributed_join_agg(
            lt, lvalid, rt, rvalid, "k", "lv", "rv", mesh)
        lk = np.asarray(left.column("k").data)
        rk = np.asarray(right.column("k").data)
        lv = np.asarray(left.column("lv").data)
        rv = np.asarray(right.column("rv").data)
        dfl = pd.DataFrame({"k": lk, "lv": lv})
        dfr = pd.DataFrame({"k": rk, "rv": rv})
        joined = dfl.merge(dfr, on="k")
        assert cnt == len(joined)
        assert lsum == joined["lv"].sum()
        assert rsum == joined["rv"].sum()

    def test_build_exchange_collectives_observable(self):
        from hyperspace_tpu.execution.columnar import Table
        from hyperspace_tpu.parallel import distributed_build as db
        rng = np.random.default_rng(6)
        t = Table.from_arrow(pa.table(
            {"k": rng.integers(0, 99, 512).astype(np.int64)}))
        db.distributed_build_sorted_buckets(t, ["k"], 8, make_mesh())
        assert db.last_collectives().get("all-to-all", 0) >= 1


class TestProgramBankIntegration:
    def test_two_sessions_share_warm_spmd_programs(self, tmp_path):
        """Warm sharded programs land in and return from the r11 bank:
        two sessions running the same distributed workload compile ≤1.2x
        one session's count (acceptance), and the bank's hit counter
        moves for the spmd stage keys."""
        from hyperspace_tpu.execution import shapes
        from hyperspace_tpu.serving.program_bank import get_bank
        _write(tmp_path / "d")

        def workload(session):
            r = session.read.parquet(str(tmp_path / "d"))
            out = [r.group_by("g").agg(sum_(col("v")).alias("sv"))
                   .to_arrow()]
            out.append(r.filter(col("k") < 20).agg(
                count(None).alias("n")).to_arrow())
            return out

        sess_a = _session(tmp_path)
        d0 = spmd.DISPATCH_COUNT
        c0 = shapes.compile_count()
        ref = workload(sess_a)
        c_a = shapes.compile_count() - c0
        assert spmd.DISPATCH_COUNT - d0 >= 2  # the workload IS sharded
        h0 = get_bank().stats()["hits"]
        sess_b = _session(tmp_path)
        c1 = shapes.compile_count()
        got = workload(sess_b)
        c_b = shapes.compile_count() - c1
        for x, y in zip(ref, got):
            assert x.equals(y)
        assert c_a + c_b <= 1.2 * c_a + 1, (c_a, c_b)
        assert get_bank().stats()["hits"] > h0

    def test_mesh_signature_distinguishes_meshes(self):
        devs = jax.devices()
        full = make_mesh(devs)
        half = make_mesh(devs[:max(len(devs) // 2, 1)])
        assert sharding.mesh_signature(full) != \
            sharding.mesh_signature(half)


class TestObservability:
    def test_sharding_and_exchange_events(self, tmp_path):
        """ShardedExecutionEvent (mesh shape, specs, HLO collective counts)
        per dispatch; SpmdExchangeEvent per join stage with the strategy
        actually chosen."""
        # files = mesh width: whole-file assignment with no idle device
        # (fewer files than devices trips the skew guard's 2x-padding
        # bound and falls back to the even split — see the guard test).
        _write(tmp_path / "a", n=2000, seed=8, files=8)
        _write(tmp_path / "b", n=600, seed=9, files=2)
        s = _session(tmp_path, capture_events=True)
        ta = s.read.parquet(str(tmp_path / "a"))
        tb = s.read.parquet(str(tmp_path / "b"))
        rb = tb.select(col("k").alias("rk"), col("v").alias("rv"))
        ta.join(rb, on=col("k") == col("rk")).agg(
            count(None).alias("n")).to_arrow()
        events = capture_logger().events
        shard_evs = [e for e in events
                     if e.event_name == "ShardedExecutionEvent"]
        xch_evs = [e for e in events
                   if e.event_name == "SpmdExchangeEvent"]
        assert shard_evs, [e.event_name for e in events]
        ev = shard_evs[-1]
        assert ev.mesh_shape == [len(jax.devices())]
        assert ev.mesh_platform == "cpu"
        assert ev.mode == "global-agg"
        assert ev.collectives and ev.collectives.get("all-to-all", 0) >= 1
        assert "P(d)" in ev.in_specs
        assert ev.file_aligned_scan  # 8-file parquet scan, no pushdown
        assert xch_evs and xch_evs[-1].strategy == "exchange"
        assert xch_evs[-1].join_type == "inner"
        assert xch_evs[-1].capacity > 0

    def test_file_aligned_scan_knob_and_identity(self, tmp_path):
        _write(tmp_path / "d", files=5)
        key = IndexConstants.TPU_DISTRIBUTED_MESH_FILE_ALIGNED_SCAN
        res = {}
        for setting in ("true", "false"):
            s = _session(tmp_path, capture_events=True,
                         **{key: setting})
            r = s.read.parquet(str(tmp_path / "d"))
            res[setting] = r.group_by("g").agg(
                sum_(col("v")).alias("sv")).to_arrow()
            evs = [e for e in capture_logger().events
                   if e.event_name == "ShardedExecutionEvent"]
            assert evs[-1].file_aligned_scan == (setting == "true")
        assert res["true"].equals(res["false"])

    def test_file_aligned_scan_skew_guard(self, tmp_path):
        """A lopsided layout (one file holding ~90% of the rows) must NOT
        shard on file boundaries: every shard pads to the largest block,
        so alignment would hand one device nearly everything at ~n_dev x
        the memory. The guard falls back to the even row split (the
        event says so) and results stay identical."""
        d = tmp_path / "d"
        d.mkdir(parents=True)
        rng = np.random.default_rng(4)
        n = 4000
        t = pa.table({
            "g": rng.integers(0, 12, n).astype(np.int64),
            "v": rng.integers(1, 100, n).astype(np.int64),
        })
        pq.write_table(t.slice(0, 3600), str(d / "big.parquet"))
        for i in range(4):
            pq.write_table(t.slice(3600 + i * 100, 100),
                           str(d / f"small{i}.parquet"))
        s = _session(tmp_path, capture_events=True)
        r = s.read.parquet(str(d))
        dist, single = _run_both(
            s, lambda: r.group_by("g").agg(sum_(col("v")).alias("sv")))
        assert dist.equals(single)
        evs = [e for e in capture_logger().events
               if e.event_name == "ShardedExecutionEvent"]
        assert evs and evs[-1].file_aligned_scan is False

    def test_mesh_max_devices_knob(self, tmp_path):
        _write(tmp_path / "d")
        s = _session(
            tmp_path, capture_events=True,
            **{IndexConstants.TPU_DISTRIBUTED_MESH_MAX_DEVICES: "2"})
        r = s.read.parquet(str(tmp_path / "d"))
        r.agg(count(None).alias("n")).to_arrow()
        evs = [e for e in capture_logger().events
               if e.event_name == "ShardedExecutionEvent"]
        assert evs[-1].mesh_shape == [2]

    def test_explain_spmd_section_and_stats(self, tmp_path):
        _write(tmp_path / "d")
        s = _session(tmp_path)
        r = s.read.parquet(str(tmp_path / "d"))
        df = r.group_by("g").agg(sum_(col("v")).alias("sv"))
        df.to_arrow()
        hs = hst.Hyperspace(s)
        text = hs.explain(df)
        assert "Distributed:" in text
        assert "distributed: on" in text
        assert "mesh devices=8" in text
        stats = hs.spmd_stats()
        assert stats["enabled"] and stats["mesh_devices"] == 8
        assert stats["query_dispatches"] >= 1
        assert stats["mesh_programs_compiled"] >= 1
        assert stats["last_collectives"]

    def test_min_stream_rows_cost_gate(self, tmp_path):
        """The distributed cost gate: a stream whose leaf is smaller
        than distributed.minStreamRows stays single-device (with an
        observable fallback), identical answers either way."""
        _write(tmp_path / "d", n=500, files=1)
        s = hst.Session(system_path=str(tmp_path / "indexes"))
        s.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                   "tests.conftest.CaptureLogger")
        capture_logger().events = []
        s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "4096")
        r = s.read.parquet(str(tmp_path / "d"))
        before = spmd.DISPATCH_COUNT
        gated = r.group_by("g").agg(sum_(col("v")).alias("sv")).to_arrow()
        assert spmd.DISPATCH_COUNT == before  # stayed single-device
        falls = [e for e in capture_logger().events
                 if e.event_name == "DistributedFallbackEvent"]
        assert any("minStreamRows" in e.reason for e in falls)
        s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
        dist = r.group_by("g").agg(sum_(col("v")).alias("sv")).to_arrow()
        assert spmd.DISPATCH_COUNT > before
        assert gated.equals(dist)

    def test_capability_probe_defaults_on(self, tmp_path):
        """distributed.enabled UNSET → the config capability probe (mesh
        API available on this image) decides — and it passes here."""
        from hyperspace_tpu.config import spmd_capable
        assert spmd_capable()
        s = _session(tmp_path)
        assert s.hs_conf.distributed_enabled()
        s.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        assert not s.hs_conf.distributed_enabled()
