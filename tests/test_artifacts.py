"""Compiled-program artifact store acceptance (ISSUE r20).

The cold-start compile storm is the one TPU serving cost no in-process
cache survives: every new process re-traces and re-compiles every
program. The artifact store persists AOT-serialized executables in the
lake (``_hst_artifacts/``) behind the banked interfaces, so a second
process imports instead of compiling. Proven here:

- **off is a no-op**: ``artifacts.enabled=false`` (the default) writes
  nothing, wraps nothing, and answers byte-identically;
- **AOT parity + events**: wrapped dispatch answers exactly like the
  plain jit path while emitting typed ``Artifact*Event``s (persist on
  first compile, hit on import, miss on cold probe);
- **corruption ladder**: a truncated/bit-flipped blob is a MISS —
  quarantine + ``ArtifactMissEvent(reason="corrupt")`` + recompile —
  never an error, never a wrong answer (the r14 spill ladder);
- **stale keys miss silently**: a jax/jaxlib version bump, backend or
  mesh change addresses a blob that does not exist;
- **kill -9 mid-publication** leaves no torn blob (temp + link
  publication), and vacuum (riding ``recover()``/``compact()``) sweeps
  the crashed temp;
- **usage tallies persist** (the r20 bugfix: bank hit tallies used to
  die with the process) and order the boot preload, hottest first,
  within ``preload.maxMs``/``maxBytes`` budgets;
- **byte-budget eviction** deletes coldest-first;
- **cold-boot acceptance**: process A persists, process B's backend
  compile count is <= 5% of an artifacts-off run, with byte-identical
  results.

The ProgramBank is process-wide and wraps stages with the manager
active at REGISTRATION time, so every test here starts from a cleared
bank — otherwise a stage registered by an earlier test (or module)
would carry that test's store root into this one.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.artifacts import manager as artifact_manager
from hyperspace_tpu.artifacts.constants import (ARTIFACT_DIR_NAME,
                                                ArtifactConstants)
from hyperspace_tpu.artifacts.store import (ArtifactStore, key_digest,
                                            key_fields, runtime_env)
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.robustness import faults
from hyperspace_tpu.serving.program_bank import get_bank
from hyperspace_tpu.telemetry import span_names as sn
from hyperspace_tpu.telemetry.constants import TelemetryConstants as TC
from hyperspace_tpu.telemetry.events import (ArtifactEvent,
                                             ArtifactEvictEvent,
                                             ArtifactHitEvent,
                                             ArtifactMissEvent,
                                             ArtifactPersistEvent)

from conftest import capture_logger  # noqa: E402

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_bank():
    """Re-register every bank stage under THIS test's artifact manager
    (the bank outlives sessions; see module docstring)."""
    get_bank().clear()
    yield


# ---------------------------------------------------------------------------
# Workload + session helpers.
# ---------------------------------------------------------------------------

def _write_data(d: str, seed: int = 11, rows: int = 1500) -> None:
    rng = np.random.default_rng(seed)
    os.makedirs(d, exist_ok=True)
    t = pa.table({
        "k": pa.array(rng.integers(0, 50, rows).astype(np.int64)),
        "g": pa.array(rng.integers(0, 7, rows).astype(np.int64)),
        "v": pa.array(rng.integers(0, 1000, rows).astype(np.int64)),
    })
    pq.write_table(t, os.path.join(d, "p0.parquet"))


def _session(tmp_path, **conf):
    """Conf goes through the CONSTRUCTOR: the opt-in boot preload runs
    inside Session.__init__, so post-hoc conf.set would miss it."""
    base = {IndexConstants.INDEX_NUM_BUCKETS: "4"}
    base.update(conf)
    return hst.Session(conf=base,
                       system_path=str(tmp_path / "indexes"))


def _arts_on(session):
    session.conf.set(ArtifactConstants.ENABLED, "true")
    return session


def _query(session, data_dir):
    t = session.read.parquet(data_dir)
    return (t.filter(col("k") > 10)
            .group_by("g").agg(sum_(col("v")).alias("sv"))
            .sort("g"))


def _digest(table: pa.Table) -> str:
    return hashlib.md5(repr(table.to_pydict()).encode()).hexdigest()


def _artifact_root(session) -> str:
    return os.path.join(session.hs_conf.system_path(), ARTIFACT_DIR_NAME)


def _blob_dir(session) -> str:
    return os.path.join(_artifact_root(session), "v1")


def _blobs(session):
    d = _blob_dir(session)
    if not os.path.isdir(d):
        return []
    return sorted(n for n in os.listdir(d) if n.endswith(".hsa"))


def _forget_process_memory(session) -> None:
    """Forget every in-process compiled executable this store fed —
    cleared bank stages, cleared manager caches — so the next dispatch
    goes back to the lake (what a fresh process sees, without paying a
    subprocess)."""
    get_bank().clear()
    mgr = artifact_manager.manager_for(session)
    assert mgr is not None
    with mgr._lock:
        mgr._loaded.clear()
    with mgr._util_lock:
        mgr._util.clear()


def _events():
    return list(capture_logger().events)


def _wire_events(session):
    session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                     "tests.conftest.CaptureLogger")
    capture_logger().events.clear()
    return session


def _tiny_compiled(label: str = "t0"):
    """One real compiled executable to feed store-level tests."""
    fn = jax.jit(lambda x: x + 1)
    args = (np.arange(4, dtype=np.int64),)
    compiled = fn.lower(*args).compile()
    fields = key_fields("bank", f"stage-{label}", f"sig-{label}")
    return compiled, fields, args


# ---------------------------------------------------------------------------
# Off is a hard no-op.
# ---------------------------------------------------------------------------

class TestOffIsNoOp:
    def test_no_store_dir_no_wrapping_no_api_surface(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _session(tmp_path)  # artifacts.enabled defaults off
        hs = Hyperspace(session)
        out = _query(session, data).to_arrow()
        assert out.num_rows > 0
        # Nothing on disk, nothing in the API.
        assert not os.path.exists(_artifact_root(session))
        assert artifact_manager.manager_for(session) is None
        assert hs.artifact_stats() == {"enabled": False}
        assert hs.warmup()["enabled"] is False
        assert hs.recover()["artifacts"]["enabled"] is False
        assert hs.compact()["artifacts"]["enabled"] is False
        assert not os.path.exists(_artifact_root(session))

    def test_on_answers_byte_identical_to_off(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        off = _digest(_query(_session(tmp_path), data).to_arrow())
        # The off run registered unwrapped stages; drop them so the on
        # run re-registers through the artifact seam.
        get_bank().clear()
        on_session = _arts_on(_session(tmp_path / "on"))
        on = _digest(_query(on_session, data).to_arrow())
        assert on == off
        # And the on-run actually persisted something.
        assert _blobs(on_session)


# ---------------------------------------------------------------------------
# AOT parity + typed events (persist / miss / hit).
# ---------------------------------------------------------------------------

class TestAotParityAndEvents:
    def test_persist_then_import_same_answer(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _wire_events(_arts_on(_session(tmp_path)))
        q = _query(session, data)
        first = q.to_arrow()

        persists = [e for e in _events()
                    if isinstance(e, ArtifactPersistEvent)]
        misses = [e for e in _events()
                  if isinstance(e, ArtifactMissEvent)]
        assert persists, "cold run must publish executables"
        assert misses and all(e.reason == "absent" for e in misses)
        for e in persists:
            assert isinstance(e, ArtifactEvent)
            assert e.key_digest and e.nbytes > 0
            assert e.kind in ("bank", "spmd", "util")

        # Forget the in-memory executables: the next run must IMPORT
        # from the lake (ArtifactHitEvent) and answer identically.
        _forget_process_memory(session)
        capture_logger().events.clear()
        second = q.to_arrow()
        assert _digest(second) == _digest(first)
        hits = [e for e in _events() if isinstance(e, ArtifactHitEvent)]
        assert hits
        assert all(e.nbytes > 0 for e in hits)
        stats = Hyperspace(session).artifact_stats()
        assert stats["enabled"] is True
        assert stats["hits"] >= len(hits)
        assert stats["persists"] >= len(persists)

    def test_load_and_export_spans_in_trace(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _arts_on(_session(tmp_path))
        session.conf.set(TC.TRACE_ENABLED, "true")
        hs = Hyperspace(session)
        _query(session, data).to_arrow()
        tr = hs.last_trace()
        assert tr is not None
        names = {s.name for s in tr.spans}
        # Cold run: every probe is an artifact.load miss, every compile
        # an artifact.export.
        assert sn.ARTIFACT_LOAD in names      # "artifact.load"
        assert sn.ARTIFACT_EXPORT in names    # "artifact.export"
        load = [s for s in tr.spans if s.name == sn.ARTIFACT_LOAD][0]
        assert load.attrs.get("hit") in (False, True)

    def test_artifacts_metrics_collector_registered(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _arts_on(_session(tmp_path))
        hs = Hyperspace(session)
        _query(session, data).to_arrow()
        stats = hs.metrics()["collectors"]["artifacts"]
        assert stats["stores"] >= 1
        assert stats["persists"] >= 1


# ---------------------------------------------------------------------------
# Corruption ladder: miss + quarantine + typed event, never a wrong
# answer.
# ---------------------------------------------------------------------------

class TestCorruptionLadder:
    @pytest.mark.parametrize("damage", ["truncate", "flip", "garbage"])
    def test_corrupt_blob_is_miss_plus_quarantine(self, tmp_path,
                                                  damage):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _wire_events(_arts_on(_session(tmp_path)))
        q = _query(session, data)
        baseline = q.to_arrow()
        blob_dir = _blob_dir(session)
        names = _blobs(session)
        assert names
        for name in names:
            path = os.path.join(blob_dir, name)
            with open(path, "rb") as f:
                raw = f.read()
            if damage == "truncate":
                raw = raw[:max(1, len(raw) // 2)]
            elif damage == "flip":
                mid = len(raw) - 8
                raw = raw[:mid] + bytes([raw[mid] ^ 0xFF]) + raw[mid + 1:]
            else:
                raw = b"not a blob at all"
            with open(path, "wb") as f:
                f.write(raw)

        corrupt_before = faults.stats().get("artifact_corruptions", 0)
        _forget_process_memory(session)
        capture_logger().events.clear()
        out = q.to_arrow()
        assert _digest(out) == _digest(baseline)  # NEVER a wrong answer
        corrupt_misses = [e for e in _events()
                          if isinstance(e, ArtifactMissEvent)
                          and e.reason == "corrupt"]
        assert corrupt_misses
        assert faults.stats().get("artifact_corruptions", 0) \
            > corrupt_before
        stats = Hyperspace(session).artifact_stats()
        assert stats["corrupt"] >= len(corrupt_misses)


# ---------------------------------------------------------------------------
# Stale keys: runtime/mesh changes are silent misses.
# ---------------------------------------------------------------------------

class TestStaleKeys:
    def test_runtime_bump_changes_digest_and_misses(self, tmp_path):
        store = ArtifactStore(str(tmp_path / "arts"), 1 << 30)
        compiled, fields, _args = _tiny_compiled()
        assert store.publish(fields, compiled)
        assert store.load(fields) is not None

        env = runtime_env()
        for field, bumped in (("jax", env["jax"] + ".post1"),
                              ("jaxlib", env["jaxlib"] + ".post1"),
                              ("backend", "tpu-imaginary")):
            stale = dict(fields)
            stale[field] = bumped
            assert key_digest(stale) != key_digest(fields)
            assert store.load(stale) is None  # silent miss
        # The real blob is untouched by the misses.
        assert store.load(fields) is not None

    def test_mesh_and_format_changes_miss(self, tmp_path):
        store = ArtifactStore(str(tmp_path / "arts"), 1 << 30)
        compiled, _fields, _args = _tiny_compiled("mesh")
        fields = key_fields("spmd", "stage-m", "sig-m",
                            mesh_repr="mesh(8x1:data)")
        assert store.publish(fields, compiled)
        other_mesh = key_fields("spmd", "stage-m", "sig-m",
                                mesh_repr="mesh(4x2:data)")
        assert store.load(other_mesh) is None
        other_format = dict(fields)
        other_format["format"] = "999"
        assert store.load(other_format) is None
        assert store.load(fields) is not None

    def test_loaded_executable_answers_identically(self, tmp_path):
        store = ArtifactStore(str(tmp_path / "arts"), 1 << 30)
        compiled, fields, args = _tiny_compiled("parity")
        want = np.asarray(compiled(*args))
        assert store.publish(fields, compiled)
        loaded = store.load(fields)
        assert loaded is not None
        np.testing.assert_array_equal(np.asarray(loaded(*args)), want)


# ---------------------------------------------------------------------------
# kill -9 mid-publication: no torn blob, vacuum sweeps the temp.
# ---------------------------------------------------------------------------

_KILL_CHILD = textwrap.dedent("""
    import sys

    data_dir, sys_dir = sys.argv[1:3]
    import hyperspace_tpu as hst
    from hyperspace_tpu.plan.expr import col, sum_

    session = hst.Session(system_path=sys_dir)
    session.conf.set("hyperspace.index.numBuckets", 4)
    session.conf.set("hyperspace.tpu.artifacts.enabled", "true")
    session.conf.set(
        "hyperspace.tpu.robustness.faults.artifacts.write",
        "kill:nth=1")
    t = session.read.parquet(data_dir)
    q = (t.filter(col("k") > 10)
         .group_by("g").agg(sum_(col("v")).alias("sv")).sort("g"))
    q.to_arrow()
    print("CHILD-SURVIVED")  # the kill must fire first
""")


class TestKillMidPublication:
    def test_no_torn_blob_and_vacuum_sweeps_temp(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        script = str(tmp_path / "child.py")
        with open(script, "w") as f:
            f.write(_KILL_CHILD)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, script, data, str(tmp_path / "indexes")],
            env=env, capture_output=True, text=True, timeout=420,
            cwd=ROOT)
        assert proc.returncode == -signal.SIGKILL, \
            f"rc={proc.returncode}\nstdout:{proc.stdout}\n" \
            f"stderr:{proc.stderr}"
        assert "CHILD-SURVIVED" not in proc.stdout

        # The store holds the fsync'd temp and ZERO blobs: the kill sat
        # between the temp write and the link — a torn .hsa is
        # impossible by construction.
        blob_dir = os.path.join(str(tmp_path / "indexes"),
                                ARTIFACT_DIR_NAME, "v1")
        names = os.listdir(blob_dir)
        temps = [n for n in names if n.startswith(".tmp-")]
        blobs = [n for n in names if n.endswith(".hsa")]
        assert temps and not blobs

        # Vacuum rides recover(): the crashed temp is swept.
        session = _arts_on(_session(tmp_path))
        summary = Hyperspace(session).recover()
        assert summary["artifacts"]["enabled"] is True
        assert summary["artifacts"]["tmp_removed"] >= len(temps)
        left = os.listdir(blob_dir)
        assert not [n for n in left if n.startswith(".tmp-")]

        # The survivor lake then serves and persists normally.
        out = _query(session, data).to_arrow()
        get_bank().clear()
        plain = _query(_session(tmp_path / "plain"), data).to_arrow()
        assert _digest(out) == _digest(plain)


# ---------------------------------------------------------------------------
# Usage tallies persist (satellite: the r20 bank-tally bugfix).
# ---------------------------------------------------------------------------

class TestUsagePersistence:
    def test_tallies_survive_the_process(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _arts_on(_session(tmp_path))
        q = _query(session, data)
        q.to_arrow()
        q.to_arrow()  # warm dispatches bump tallies
        artifact_manager.flush_all()
        sidecar = os.path.join(_blob_dir(session), "usage.json")
        assert os.path.exists(sidecar)
        with open(sidecar) as f:
            raw = json.load(f)
        assert raw["version"] == 1
        tallies = raw["tallies"]
        assert tallies
        assert all(c >= 1 for c, _seq in tallies.values())
        # A fresh store over the same root (a new process's view) sees
        # the persisted order.
        fresh = ArtifactStore(_artifact_root(session), 1 << 30)
        order = fresh.usage_order()
        assert order
        assert set(order) <= {n[:-4] for n in _blobs(session)}

    def test_merge_by_max_across_stores(self, tmp_path):
        root = str(tmp_path / "arts")
        # Huge flushMs: flushes happen only when forced, so the two
        # stores' tallies meet on disk in a controlled order.
        a = ArtifactStore(root, 1 << 30, usage_flush_ms=1e9)
        compiled, fields, _args = _tiny_compiled("merge")
        assert a.publish(fields, compiled)
        digest = key_digest(fields)
        for _ in range(5):
            a.record_use(digest)
        # A sibling store (fresh process) counts ONE use and flushes
        # first; a's later flush must keep the max, not add or clobber.
        b = ArtifactStore(root, 1 << 30, usage_flush_ms=1e9)
        b.record_use(digest)
        b.flush_usage(force=True)
        a.flush_usage(force=True)
        c = ArtifactStore(root, 1 << 30)
        with c._lock:
            count = c._usage[digest][0]
        assert count == 5


# ---------------------------------------------------------------------------
# Preload: usage-ordered, budgeted, riding warmup() and session init.
# ---------------------------------------------------------------------------

def _seeded_store(tmp_path, n=3):
    """A lake dir holding ``n`` published kernels with distinct usage
    tallies (kernel i used i+1 times — hottest last)."""
    root = str(tmp_path / "arts")
    store = ArtifactStore(root, 1 << 30)
    digests = []
    for i in range(n):
        compiled, fields, _args = _tiny_compiled(f"warm{i}")
        assert store.publish(fields, compiled)
        d = key_digest(fields)
        for _ in range(i + 1):
            store.record_use(d)
        digests.append(d)
    store.flush_usage(force=True)
    return root, digests


class TestPreload:
    def _warm_session(self, tmp_path, root, **conf):
        conf[ArtifactConstants.ENABLED] = "true"
        conf[ArtifactConstants.DIR] = root
        return _session(tmp_path, **conf)

    def test_warmup_loads_hottest_first(self, tmp_path):
        root, digests = _seeded_store(tmp_path)
        assert ArtifactStore(root, 1 << 30).usage_order() \
            == list(reversed(digests))
        session = self._warm_session(tmp_path, root)
        out = Hyperspace(session).warmup()
        assert out["enabled"] is True
        assert out["loaded"] == len(digests)
        assert out["bytes"] > 0
        stats = Hyperspace(session).artifact_stats()
        assert stats["loaded_in_memory"] >= len(digests)
        assert stats["preloaded"] >= len(digests)

    def test_max_ms_budget_stops_the_pass(self, tmp_path):
        root, _digests = _seeded_store(tmp_path)
        session = self._warm_session(
            tmp_path, root,
            **{ArtifactConstants.PRELOAD_MAX_MS: "0"})
        out = Hyperspace(session).warmup()
        assert out["loaded"] == 0
        assert out["budget_hit"] == "maxMs"

    def test_max_bytes_budget_stops_the_pass(self, tmp_path):
        root, _digests = _seeded_store(tmp_path)
        session = self._warm_session(
            tmp_path, root,
            **{ArtifactConstants.PRELOAD_MAX_BYTES: "1"})
        out = Hyperspace(session).warmup()
        assert out["loaded"] == 1  # the hottest blob, then the budget
        assert out["budget_hit"] == "maxBytes"

    def test_opt_in_session_init_preload(self, tmp_path):
        root, digests = _seeded_store(tmp_path)
        session = self._warm_session(
            tmp_path, root,
            **{ArtifactConstants.PRELOAD_ENABLED: "true"})
        # Session.__init__ already preloaded — no warmup() call.
        stats = Hyperspace(session).artifact_stats()
        assert stats["preloaded"] >= len(digests)

    def test_warmup_span_name_is_frozen(self):
        assert sn.ARTIFACT_WARMUP == "artifact.warmup"


# ---------------------------------------------------------------------------
# Byte-budget eviction (coldest first).
# ---------------------------------------------------------------------------

class TestEviction:
    def test_evicts_coldest_until_budget(self, tmp_path):
        root = str(tmp_path / "arts")
        store = ArtifactStore(root, 1 << 30)
        sizes = {}
        for i in range(3):
            compiled, fields, _args = _tiny_compiled(f"evict{i}")
            assert store.publish(fields, compiled)
            d = key_digest(fields)
            sizes[d] = os.path.getsize(store.blob_path(d))
            for _ in range(i + 1):
                store.record_use(d)
        digests = list(sizes)
        # Budget: exactly the two hottest blobs fit.
        store.max_bytes = sizes[digests[1]] + sizes[digests[2]]
        evicted = store._evict_over_budget()
        assert evicted == [digests[0]]  # the coldest
        assert not os.path.exists(store.blob_path(digests[0]))
        assert os.path.exists(store.blob_path(digests[2]))
        assert store.stats()["evictions"] == 1
        # The sidecar forgot the evicted blob.
        assert digests[0] not in ArtifactStore(root, 1 << 30)\
            .usage_order()

    def test_evict_event_on_query_path(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        session = _wire_events(_arts_on(_session(tmp_path)))
        session.conf.set(ArtifactConstants.MAX_BYTES, "1")
        _query(session, data).to_arrow()
        evicts = [e for e in _events()
                  if isinstance(e, ArtifactEvictEvent)]
        assert evicts  # every publish immediately busts the 1-byte cap
        assert all(e.nbytes > 0 for e in evicts)


# ---------------------------------------------------------------------------
# Vacuum (compact()/recover()): temps, stale blobs, corrupt blobs.
# ---------------------------------------------------------------------------

class TestVacuum:
    def test_compact_sweeps_stale_and_corrupt(self, tmp_path):
        root = str(tmp_path / "arts")
        store = ArtifactStore(root, 1 << 30)
        compiled, fields, _args = _tiny_compiled("vac")
        assert store.publish(fields, compiled)
        vdir = store.version_dir
        # A crashed temp, a stale-runtime blob, a corrupt blob.
        with open(os.path.join(vdir, ".tmp-999-dead"), "wb") as f:
            f.write(b"partial")
        stale_fields = dict(fields)
        stale_fields["jax"] = "0.0.0"
        header = dict(stale_fields)
        header["nbytes"] = 3
        header["md5"] = hashlib.md5(b"xyz").hexdigest()
        with open(os.path.join(
                vdir, key_digest(stale_fields) + ".hsa"), "wb") as f:
            f.write(json.dumps(header).encode() + b"\n" + b"xyz")
        with open(os.path.join(vdir, "f" * 24 + ".hsa"), "wb") as f:
            f.write(b"\x00\x01 not json")

        session = _arts_on(_session(tmp_path))
        session.conf.set(ArtifactConstants.DIR, root)
        summary = Hyperspace(session).compact()
        arts = summary["artifacts"]
        assert arts["enabled"] is True
        assert arts["tmp_removed"] == 1
        assert arts["stale_removed"] == 1
        assert arts["corrupt_removed"] == 1
        left = os.listdir(vdir)
        assert key_digest(fields) + ".hsa" in left
        assert len([n for n in left if n.endswith(".hsa")]) == 1


# ---------------------------------------------------------------------------
# Cold-boot acceptance: second process compiles ~ 0.
# ---------------------------------------------------------------------------

_BOOT_CHILD = textwrap.dedent("""
    import hashlib, sys
    data_dir, sys_dir, arts = sys.argv[1:4]

    import hyperspace_tpu as hst
    from hyperspace_tpu.execution import shapes
    from hyperspace_tpu.plan.expr import col, sum_

    conf = {"hyperspace.index.numBuckets": "4"}
    if arts == "on":
        conf["hyperspace.tpu.artifacts.enabled"] = "true"
        conf["hyperspace.tpu.artifacts.preload.enabled"] = "true"
    session = hst.Session(conf=conf, system_path=sys_dir)
    t = session.read.parquet(data_dir)
    q = (t.filter(col("k") > 10)
         .group_by("g").agg(sum_(col("v")).alias("sv")).sort("g"))
    out = q.to_arrow()
    if arts == "on":
        from hyperspace_tpu.artifacts.manager import flush_all
        flush_all()
    digest = hashlib.md5(repr(out.to_pydict()).encode()).hexdigest()
    print("RESULT", digest, shapes.compile_count())
""")


def _boot_child(tmp_path, data, sys_dir, arts):
    script = str(tmp_path / "boot_child.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(_BOOT_CHILD)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, script, data, sys_dir, arts], env=env,
        capture_output=True, text=True, timeout=420, cwd=ROOT)
    assert proc.returncode == 0, \
        f"rc={proc.returncode}\nstdout:{proc.stdout}\n" \
        f"stderr:{proc.stderr}"
    line = [ln for ln in proc.stdout.splitlines()
            if ln.startswith("RESULT")][0]
    _tag, digest, compiles = line.split()
    return digest, int(compiles)


class TestColdBoot:
    def test_second_process_compiles_near_zero(self, tmp_path):
        data = str(tmp_path / "data")
        _write_data(data)
        off_digest, off_compiles = _boot_child(
            tmp_path, data, str(tmp_path / "off_indexes"), "off")
        assert off_compiles > 0

        arts_sys = str(tmp_path / "indexes")
        a_digest, a_compiles = _boot_child(tmp_path, data, arts_sys,
                                           "on")
        b_digest, b_compiles = _boot_child(tmp_path, data, arts_sys,
                                           "on")
        # Byte-identical across off / persist / import.
        assert a_digest == off_digest
        assert b_digest == off_digest
        # THE acceptance: the second process's compile count is <= 5%
        # of the artifacts-off cold boot (measured 0 on CPU).
        assert b_compiles <= max(0, int(0.05 * off_compiles)), \
            (off_compiles, a_compiles, b_compiles)
