"""Signature provider + source provider tests.

Parity: FileBasedSignatureProviderTest / IndexSignatureProviderTest.
"""

import os
import time

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.index.signatures import (
    FileBasedSignatureProvider, IndexSignatureProvider, LogicalPlanSignatureProvider,
    PlanSignatureProvider)
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.plan.nodes import Filter, Scan
from hyperspace_tpu.sources.default import DefaultFileBasedRelation


@pytest.fixture()
def data_dir(tmp_path):
    df = pd.DataFrame({"a": np.arange(10, dtype=np.int64), "b": list("abcdefghij")})
    d = tmp_path / "t"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "p0.parquet")
    return d


class TestSignatureProviders:
    def test_file_based_stable(self, data_dir):
        plan = Scan(DefaultFileBasedRelation([str(data_dir)]))
        p = FileBasedSignatureProvider()
        s1, s2 = p.signature(plan), p.signature(plan)
        assert s1 == s2 and s1 is not None

    def test_file_based_changes_on_file_change(self, data_dir):
        plan = Scan(DefaultFileBasedRelation([str(data_dir)]))
        s1 = FileBasedSignatureProvider().signature(plan)
        # Append a new file → different signature (fresh relation, re-listed).
        df = pd.DataFrame({"a": [99], "b": ["z"]})
        pq.write_table(pa.Table.from_pandas(df), data_dir / "p1.parquet")
        plan2 = Scan(DefaultFileBasedRelation([str(data_dir)]))
        s2 = FileBasedSignatureProvider().signature(plan2)
        assert s1 != s2

    def test_plan_signature_reflects_structure(self, data_dir):
        scan = Scan(DefaultFileBasedRelation([str(data_dir)]))
        s_scan = PlanSignatureProvider().signature(scan)
        s_filter = PlanSignatureProvider().signature(Filter(col("a") > 3, scan))
        assert s_scan != s_filter

    def test_index_signature_combines(self, data_dir):
        plan = Scan(DefaultFileBasedRelation([str(data_dir)]))
        combined = IndexSignatureProvider().signature(plan)
        fb = FileBasedSignatureProvider().signature(plan)
        assert combined is not None and combined != fb

    def test_create_by_name(self):
        p = LogicalPlanSignatureProvider.create("IndexSignatureProvider")
        assert isinstance(p, IndexSignatureProvider)
        p2 = LogicalPlanSignatureProvider.create(
            "hyperspace_tpu.index.signatures.PlanSignatureProvider")
        assert isinstance(p2, PlanSignatureProvider)
        with pytest.raises(HyperspaceException):
            LogicalPlanSignatureProvider.create("no.such.Provider")


class TestDefaultSource:
    def test_all_files_and_schema(self, data_dir):
        rel = DefaultFileBasedRelation([str(data_dir)])
        files = rel.all_files()
        assert len(files) == 1 and files[0].endswith("p0.parquet")
        assert rel.schema.names == ["a", "b"]

    def test_lineage_pairs(self, data_dir):
        from hyperspace_tpu.index.log_entry import FileIdTracker
        rel = DefaultFileBasedRelation([str(data_dir)])
        tracker = FileIdTracker()
        pairs = rel.lineage_pairs(tracker)
        assert len(pairs) == 1 and pairs[0][1] == 0

    def test_provider_manager_exactly_one(self, data_dir, tmp_system_path):
        session = hst.Session(system_path=tmp_system_path)
        mgr = session.source_provider_manager
        rel = mgr.build_relation([str(data_dir)], "parquet", {})
        assert isinstance(rel, DefaultFileBasedRelation)
        with pytest.raises(HyperspaceException):
            mgr.build_relation([str(data_dir)], "xml", {})
