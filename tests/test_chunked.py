"""Chunked (>HBM) build + scan (VERDICT r2 #2, SURVEY §7 hard-part #1).

The device-footprint budget (hyperspace.tpu.maxChunkRows) bounds how many
rows are ever resident at once: builds stream row-group chunks through
hash→bucket-sort→host-spill→per-bucket merge; filtered scans evaluate the
mask per chunk. Tests pin BOTH correctness (chunked result == in-memory
result, disable-and-compare) AND the footprint cap (max_device_rows).
"""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.execution import executor
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.ops import index_build
from hyperspace_tpu.plan.expr import col, sum_


N_ROWS = 120_000
CHUNK = 20_000


def write_parts(tmp_path, name, df, parts):
    d = tmp_path / name
    d.mkdir(parents=True, exist_ok=True)
    step = max(1, len(df) // parts)
    for i in range(parts):
        chunk = df.iloc[i * step:(i + 1) * step if i < parts - 1 else len(df)]
        pq.write_table(pa.Table.from_pandas(chunk.reset_index(drop=True)),
                       d / f"part{i}.parquet", row_group_size=7_000)
    return str(d)


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(17)
    df = pd.DataFrame({
        "k": rng.integers(0, 5000, N_ROWS).astype(np.int64),
        "v": rng.integers(0, 100, N_ROWS).astype(np.int64),
        "s": rng.choice(["ab", "cd", "ef", "gh"], N_ROWS),
    })
    path = write_parts(tmp_path, "data", df, parts=4)
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
    return dict(session=session, hs=Hyperspace(session), path=path,
                df=df, tmp=tmp_path)


class TestChunkedBuild:
    def test_chunked_build_same_layout_and_bounded(self, env):
        session, hs = env["session"], env["hs"]
        # In-memory reference build.
        hs.create_index(session.read.parquet(env["path"]),
                        IndexConfig("memIdx", ["k"], ["v", "s"]))
        # Chunked build under a small budget.
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        index_build.CHUNK_STATS["max_device_rows"] = 0
        index_build.CHUNK_STATS["chunks"] = 0
        hs.create_index(session.read.parquet(env["path"]),
                        IndexConfig("chunkIdx", ["k"], ["v", "s"]))
        assert index_build.CHUNK_STATS["chunks"] >= N_ROWS // CHUNK
        # Footprint cap: a chunk is never larger than the budget, and no
        # bucket merge exceeded the largest bucket (≲ 2x fair share here).
        assert index_build.CHUNK_STATS["max_device_rows"] <= \
            max(CHUNK, int(N_ROWS / 8 * 2))

        sys_path = str(env["tmp"] / "indexes")
        mem_files = sorted(os.listdir(os.path.join(sys_path, "memIdx", "v__=0")))
        chk_files = sorted(os.listdir(os.path.join(sys_path, "chunkIdx", "v__=0")))
        assert mem_files == chk_files  # same one-file-per-bucket layout

        # Same rows, same within-bucket sort order, per bucket file.
        for f in mem_files:
            a = pq.read_table(os.path.join(sys_path, "memIdx", "v__=0", f))
            b = pq.read_table(os.path.join(sys_path, "chunkIdx", "v__=0", f))
            assert a.num_rows == b.num_rows, f
            ka = a.column("k").to_pylist()
            kb = b.column("k").to_pylist()
            assert ka == kb, f"bucket {f} key order differs"
            assert ka == sorted(ka)
            pa_df = a.to_pandas().sort_values(["k", "v", "s"]).reset_index(drop=True)
            pb_df = b.to_pandas().sort_values(["k", "v", "s"]).reset_index(drop=True)
            pd.testing.assert_frame_equal(pa_df, pb_df)

    def test_chunked_build_with_lineage(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        hs.create_index(session.read.parquet(env["path"]),
                        IndexConfig("linIdx", ["k"], ["v"]))
        sys_path = str(env["tmp"] / "indexes")
        vdir = os.path.join(sys_path, "linIdx", "v__=0")
        t = pq.read_table(vdir + "/" + sorted(os.listdir(vdir))[0])
        assert IndexConstants.DATA_FILE_NAME_ID in t.column_names
        # Lineage ids must map 1:1 to distinct source files.
        all_ids = set()
        for f in os.listdir(vdir):
            all_ids |= set(pq.read_table(os.path.join(vdir, f))
                           .column(IndexConstants.DATA_FILE_NAME_ID).to_pylist())
        assert len(all_ids) == 4  # one id per source part file

        # Index answers match the source under lineage+chunked build.
        session.enable_hyperspace()
        q = (session.read.parquet(env["path"])
             .filter(col("k") < 1000).select("k", "v"))
        got = q.to_pandas()
        session.disable_hyperspace()
        exp = q.to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values(["k", "v"]).reset_index(drop=True),
            exp.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False)


class TestChunkedScan:
    def test_chunked_filter_scan_bounded_and_correct(self, env):
        session = env["session"]
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        executor.CHUNK_SCAN_STATS["max_device_rows"] = 0
        executor.CHUNK_SCAN_STATS["chunks"] = 0
        # Broad filter first: survivors exceed the budget, so the stream
        # must chunk (parquet pushdown can't prune anything here).
        broad = (session.read.parquet(env["path"])
                 .filter(col("k") >= 0).select("k", "v"))
        broad.to_pandas()
        assert executor.CHUNK_SCAN_STATS["chunks"] >= N_ROWS // CHUNK
        assert executor.CHUNK_SCAN_STATS["max_device_rows"] <= CHUNK

        # Selective filter: parquet row-filter pushdown shrinks the stream
        # BEFORE chunking (fewer chunks than the raw row count implies).
        executor.CHUNK_SCAN_STATS["chunks"] = 0
        executor.CHUNK_SCAN_STATS["max_device_rows"] = 0
        q = (session.read.parquet(env["path"])
             .filter((col("k") >= 100) & (col("k") < 900)).select("k", "v"))
        got = q.to_pandas()
        assert 1 <= executor.CHUNK_SCAN_STATS["chunks"] < N_ROWS // CHUNK
        assert executor.CHUNK_SCAN_STATS["max_device_rows"] <= CHUNK
        df = env["df"]
        exp = df[(df.k >= 100) & (df.k < 900)][["k", "v"]]
        pd.testing.assert_frame_equal(
            got.sort_values(["k", "v"]).reset_index(drop=True),
            exp.sort_values(["k", "v"]).reset_index(drop=True),
            check_dtype=False)

    def test_chunked_join_aggregate_q3_shape(self, env):
        """A Q3-shaped query (filter ⋈ filter → group-by → sum) runs with
        chunked leaf scans and matches the in-memory run."""
        session = env["session"]
        rng = np.random.default_rng(3)
        dim = pd.DataFrame({
            "dk": np.arange(5000, dtype=np.int64),
            "grp": rng.integers(0, 40, 5000).astype(np.int64),
        })
        dim_path = write_parts(env["tmp"], "dim", dim, parts=1)
        fact = session.read.parquet(env["path"])
        dimt = session.read.parquet(dim_path)

        def q():
            return (fact.filter(col("k") < 2500)
                    .join(dimt.filter(col("grp") < 30),
                          on=col("k") == col("dk"))
                    .group_by("grp").agg(sum_(col("v")).alias("sv")))

        # Single-device execution (the real-chip shape; the SPMD aggregate
        # path shards the leaf over the mesh instead of chunking it).
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        executor.CHUNK_SCAN_STATS["chunks"] = 0
        got = q().to_pandas()
        assert executor.CHUNK_SCAN_STATS["chunks"] > 0
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, 10_000_000)
        exp = q().to_pandas()
        pd.testing.assert_frame_equal(
            got.sort_values("grp").reset_index(drop=True),
            exp.sort_values("grp").reset_index(drop=True), check_dtype=False)


class TestChunkedIndexScan:
    """Filter-over-IndexScan for indexes larger than the device budget
    (the index-side counterpart of TestChunkedScan; VERDICT r2 #2's
    "chunk scan execution likewise" applies to index reads too)."""

    def _build(self, env, lineage=False):
        session, hs = env["session"], env["hs"]
        if lineage:
            session.conf.set(IndexConstants.INDEX_LINEAGE_ENABLED, "true")
        hs.create_index(session.read.parquet(env["path"]),
                        IndexConfig("chix", ["k"], ["v", "s"]))
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        session.enable_hyperspace()
        return session.read.parquet(env["path"])

    def test_bounded_and_equal_to_in_memory(self, env):
        session = env["session"]
        t = self._build(env)
        q = t.filter((col("k") >= 0) & (col("k") < 4000)).select("k", "v")
        from hyperspace_tpu.plan.nodes import IndexScan
        leaves = q.optimized_plan().collect_leaves()
        assert isinstance(leaves[0], IndexScan)
        executor.CHUNK_SCAN_STATS["max_device_rows"] = 0
        executor.CHUNK_SCAN_STATS["chunks"] = 0
        got = q.to_pandas()
        assert executor.CHUNK_SCAN_STATS["chunks"] >= 2
        assert executor.CHUNK_SCAN_STATS["max_device_rows"] <= CHUNK
        # In-memory oracle (budget lifted).
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, 10**9)
        exp = q.to_pandas()
        key = ["k", "v"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True), check_dtype=False)
        # And the no-index oracle.
        session.disable_hyperspace()
        raw = q.to_pandas()
        pd.testing.assert_frame_equal(
            exp.sort_values(key).reset_index(drop=True),
            raw.sort_values(key).reset_index(drop=True), check_dtype=False)

    def test_hybrid_appends_and_deletes_chunked(self, env, tmp_path):
        """Chunked index scan under hybrid state: appended file merged in,
        deleted file's rows masked per chunk via lineage."""
        session, hs, df = env["session"], env["hs"], env["df"]
        t = self._build(env, lineage=True)
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
        # One of 4 source parts gets deleted (25% of bytes) — lift the
        # default 0.2 deleted-ratio cap so the index stays a candidate.
        session.conf.set(
            IndexConstants.INDEX_HYBRID_SCAN_DELETED_RATIO_THRESHOLD, "0.5")
        data_dir = tmp_path / "data"
        # Append a small file and delete one original part.
        rng = np.random.default_rng(9)
        extra = pd.DataFrame({
            "k": rng.integers(0, 5000, 900).astype(np.int64),
            "v": rng.integers(0, 100, 900).astype(np.int64),
            "s": rng.choice(["ab", "cd"], 900),
        })
        pq.write_table(pa.Table.from_pandas(extra),
                       data_dir / "extra.parquet")
        (data_dir / "part0.parquet").unlink()
        t2 = session.read.parquet(env["path"])
        q = t2.filter(col("k") < 2500).select("k", "v")
        from hyperspace_tpu.plan.nodes import IndexScan
        leaves = q.optimized_plan().collect_leaves()
        assert isinstance(leaves[0], IndexScan)
        assert leaves[0].appended_files and leaves[0].deleted_file_ids
        executor.CHUNK_SCAN_STATS["max_device_rows"] = 0
        got = q.to_pandas()
        assert executor.CHUNK_SCAN_STATS["max_device_rows"] <= CHUNK
        session.disable_hyperspace()
        raw = q.to_pandas()
        key = ["k", "v"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            raw.sort_values(key).reset_index(drop=True), check_dtype=False)


class TestChunkedRefreshOptimize:
    """Refresh and optimize over indexes whose data exceeds the chunk
    budget — the lifecycle actions must ride the same streaming paths."""

    def test_incremental_refresh_under_budget(self, env, tmp_path):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("rIdx", ["k"], ["v", "s"]))
        # Append MORE than one chunk budget of new rows.
        rng = np.random.default_rng(99)
        extra = pd.DataFrame({
            "k": rng.integers(0, 5000, CHUNK + 5000).astype(np.int64),
            "v": rng.integers(0, 100, CHUNK + 5000).astype(np.int64),
            "s": rng.choice(["ab", "cd"], CHUNK + 5000),
        })
        pq.write_table(pa.Table.from_pandas(extra),
                       os.path.join(env["path"], "part9.parquet"),
                       row_group_size=7_000)
        index_build.CHUNK_STATS["max_device_rows"] = 0
        hs.refresh_index("rIdx", "incremental")
        assert index_build.CHUNK_STATS["max_device_rows"] <= \
            max(CHUNK, int((CHUNK + 5000) / 8 * 3))
        # Oracle: indexed answers equal fresh-scan answers post-refresh.
        session.enable_hyperspace()
        q = (session.read.parquet(env["path"])
             .filter(col("k") < 500).group_by("k")
             .agg(sum_(col("v")).alias("sv")).sort("k"))
        with_idx = q.to_pandas()
        session.disable_hyperspace()
        pd.testing.assert_frame_equal(with_idx, q.to_pandas())

    def test_optimize_after_chunked_refresh(self, env):
        session, hs = env["session"], env["hs"]
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, CHUNK)
        df = session.read.parquet(env["path"])
        hs.create_index(df, IndexConfig("oIdx", ["k"], ["v"]))
        rng = np.random.default_rng(7)
        extra = pd.DataFrame({
            "k": rng.integers(0, 5000, 9000).astype(np.int64),
            "v": rng.integers(0, 100, 9000).astype(np.int64),
            "s": rng.choice(["ab", "cd"], 9000),
        })
        pq.write_table(pa.Table.from_pandas(extra),
                       os.path.join(env["path"], "part8.parquet"))
        hs.refresh_index("oIdx", "incremental")
        hs.optimize_index("oIdx", "full")
        sys_path = str(env["tmp"] / "indexes")
        versions = sorted(os.listdir(os.path.join(sys_path, "oIdx")))
        latest = [v for v in versions if v.startswith("v__=")][-1]
        files = os.listdir(os.path.join(sys_path, "oIdx", latest))
        assert len(files) == 8  # one file per bucket after full compaction
        session.enable_hyperspace()
        q = (session.read.parquet(env["path"])
             .filter(col("k") < 300).group_by("k")
             .agg(sum_(col("v")).alias("sv")).sort("k"))
        with_idx = q.to_pandas()
        session.disable_hyperspace()
        pd.testing.assert_frame_equal(with_idx, q.to_pandas())


class TestChunkedSkew:
    def test_one_bucket_dominates(self, tmp_path):
        """90% of rows hash to one key: that bucket alone exceeds the chunk
        budget; the per-bucket merge must still produce a single sorted
        bucket file with every row."""
        rng = np.random.default_rng(5)
        n = 60_000
        k = np.where(rng.random(n) < 0.9, 7, rng.integers(0, 5000, n)) \
            .astype(np.int64)
        df = pd.DataFrame({"k": k,
                           "v": rng.integers(0, 9, n).astype(np.int64)})
        path = write_parts(tmp_path, "skew", df, parts=3)
        session = hst.Session(system_path=str(tmp_path / "idx"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
        session.conf.set(IndexConstants.TPU_MAX_CHUNK_ROWS, 10_000)
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(path),
                        IndexConfig("skewIdx", ["k"], ["v"]))
        sys_path = str(tmp_path / "idx")
        files = os.listdir(os.path.join(sys_path, "skewIdx", "v__=0"))
        total = 0
        for f in files:
            t = pq.read_table(os.path.join(sys_path, "skewIdx", "v__=0", f))
            keys = t.column("k").to_pylist()
            assert keys == sorted(keys), f"bucket {f} unsorted"
            total += t.num_rows
        assert total == n
        # Oracle through the rewrite on the skewed key.
        session.enable_hyperspace()
        q = (session.read.parquet(path).filter(col("k") == 7)
             .group_by("k").agg(sum_(col("v")).alias("sv")))
        with_idx = q.to_pandas()
        session.disable_hyperspace()
        pd.testing.assert_frame_equal(with_idx, q.to_pandas())
