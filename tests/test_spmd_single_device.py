"""One-device dispatch of the fused SPMD query program (VERDICT r3 #8).

On a 1-device mesh the SPMD program degenerates to a single fused jit
program (XLA removes identity collectives). On an accelerator that cuts
the per-operator host↔device round trips the interpreted executor pays —
the measured round-3 on-chip filter bottleneck — so `auto` enables it
there; on CPU `auto` keeps the interpreted path (shared silicon, compile
cost buys nothing). These tests force `on` with the mesh shrunk to one
device and oracle-match every supported plan shape.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.execution import spmd
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col, count, sum_


@pytest.fixture()
def session(tmp_system_path, monkeypatch):
    monkeypatch.setattr(spmd, "_device_count", lambda *a: 1)
    s = hst.Session(system_path=tmp_system_path)
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_MIN_STREAM_ROWS, "0")
    s.conf.set(IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE, "on")
    return s


def write_dir(tmp_path, name, table):
    d = tmp_path / name
    d.mkdir()
    pq.write_table(table, str(d / "part0.parquet"))
    return str(d)


@pytest.fixture()
def dirs(tmp_path):
    rng = np.random.default_rng(70)
    left = write_dir(tmp_path, "l", pa.table({
        "k": rng.integers(0, 50, 2000).astype(np.int64),
        "g": rng.integers(0, 7, 2000).astype(np.int64),
        "v": np.round(rng.uniform(0, 10, 2000), 3),
    }))
    right = write_dir(tmp_path, "r", pa.table({
        "rk": np.arange(50, dtype=np.int64),
        "w": rng.integers(0, 100, 50).astype(np.int64),
    }))
    return left, right


def run_both(session, make_query, sort_by):
    before = spmd.DISPATCH_COUNT
    fused = make_query().to_pandas()
    assert spmd.DISPATCH_COUNT > before, \
        "1-device fused dispatch was not taken"
    session.conf.set(IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE, "off")
    try:
        interp = make_query().to_pandas()
    finally:
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE, "on")
    a = fused.sort_values(sort_by).reset_index(drop=True)
    b = interp.sort_values(sort_by).reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return a


class TestOneDeviceFusedDispatch:
    def test_filtered_grouped_aggregate(self, session, dirs):
        left, _ = dirs
        lf = session.read.parquet(left)
        run_both(
            session,
            lambda: lf.filter(col("k") < 30).group_by("g")
                      .agg(count(None).alias("n"), sum_(col("v")).alias("sv")),
            sort_by=["g"])

    def test_join_then_aggregate(self, session, dirs):
        left, right = dirs
        lf = session.read.parquet(left)
        rf = session.read.parquet(right)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .group_by("g").agg(sum_(col("w")).alias("sw")),
            sort_by=["g"])

    def test_row_returning_stream(self, session, dirs):
        left, _ = dirs
        lf = session.read.parquet(left)
        out = run_both(
            session,
            lambda: lf.filter(col("k") < 10).select("k", "v"),
            sort_by=["k", "v"])
        assert len(out) > 0

    def test_exchange_join_degenerates_cleanly(self, session, dirs,
                                               tmp_path):
        """m:n join on one device: the hash route is an identity
        all_to_all; the local merge does all the work."""
        left, _ = dirs
        rng = np.random.default_rng(71)
        dup = write_dir(tmp_path, "rdup", pa.table({
            "rk": rng.integers(0, 50, 200).astype(np.int64),
            "w": np.arange(200, dtype=np.int64)}))
        lf = session.read.parquet(left)
        rf = session.read.parquet(dup)
        run_both(
            session,
            lambda: lf.join(rf, on=col("k") == col("rk"))
                      .group_by("k").agg(count(None).alias("n")),
            sort_by=["k"])

    def test_auto_stays_off_on_cpu(self, session, dirs):
        """`auto` must not take the fused path on the CPU backend — the
        host and the 'device' share silicon, so there is no round trip
        to save (the analysis BASELINE.md records)."""
        import jax
        if jax.default_backend() != "cpu":
            pytest.skip("auto keys on the backend; this pins the CPU leg")
        left, _ = dirs
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE,
                         "auto")
        lf = session.read.parquet(left)
        before = spmd.DISPATCH_COUNT
        lf.group_by("g").agg(count(None).alias("n")).to_pandas()
        assert spmd.DISPATCH_COUNT == before
