"""DataFrame surface ops: with_column / drop / distinct / union.

distinct() lowers onto grouped aggregation (group by every column), so it
inherits index rewrites and the SPMD path; union() uses the IR's Union
node. Oracles are pandas equivalents.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.expr import col, lit


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(17)
    df = pd.DataFrame({
        "k": rng.integers(0, 12, 4000).astype(np.int64),
        "v": rng.integers(0, 5, 4000).astype(np.int64),
        "s": rng.choice(["p", "q"], 4000),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "p.parquet")
    session = hst.Session(system_path=str(tmp_path / "idx"))
    return dict(session=session, t=session.read.parquet(str(d)), df=df)


class TestWithColumnDrop:
    def test_with_column_adds(self, env):
        got = env["t"].with_column("k2", col("k") * lit(2)).to_pandas()
        assert list(got.columns) == ["k", "v", "s", "k2"]
        assert (got["k2"] == got["k"] * 2).all()

    def test_with_column_replaces_in_place(self, env):
        got = env["t"].with_column("v", col("v") + lit(100)).to_pandas()
        assert list(got.columns) == ["k", "v", "s"]
        assert (got["v"] >= 100).all()

    def test_drop(self, env):
        got = env["t"].drop("s", "v").to_pandas()
        assert list(got.columns) == ["k"]
        with pytest.raises(HyperspaceException, match="every column"):
            env["t"].drop("k", "v", "s")


class TestDistinct:
    def test_matches_pandas(self, env):
        got = env["t"].distinct().to_pandas()
        exp = env["df"].drop_duplicates()
        assert len(got) == len(exp)
        assert list(got.columns) == ["k", "v", "s"]
        key = ["k", "v", "s"]
        pd.testing.assert_frame_equal(
            got.sort_values(key).reset_index(drop=True),
            exp.sort_values(key).reset_index(drop=True))

    def test_after_projection(self, env):
        got = env["t"].select("k", "s").distinct().to_pandas()
        exp = env["df"][["k", "s"]].drop_duplicates()
        assert len(got) == len(exp)


class TestUnion:
    def test_round_trip(self, env):
        t = env["t"]
        a = t.filter(col("k") < 6).select("k", "v")
        b = t.filter(col("k") >= 6).select("k", "v")
        got = a.union(b).to_pandas()
        assert len(got) == len(env["df"])

    def test_column_mismatch_is_loud(self, env):
        t = env["t"]
        with pytest.raises(HyperspaceException, match="column mismatch"):
            t.select("k").union(t.select("v"))

    def test_union_then_aggregate(self, env):
        t = env["t"]
        u = t.select("k", "v").union(t.select("k", "v"))
        from hyperspace_tpu.plan.expr import sum_
        got = u.group_by("k").agg(sum_(col("v")).alias("sv")).to_pandas()
        exp = env["df"].groupby("k", as_index=False)["v"].sum()
        exp["v"] *= 2
        got = got.sort_values("k").reset_index(drop=True)
        exp = exp.sort_values("k").reset_index(drop=True)
        np.testing.assert_array_equal(got["sv"], exp["v"])


class TestReviewRegressions:
    def test_distinct_with_hostile_column_name(self, tmp_path):
        df = pd.DataFrame({"__distinct_cnt": [1, 1, 2],
                           "v": [5, 5, 6]})
        d = tmp_path / "h"
        d.mkdir()
        pq.write_table(pa.Table.from_pandas(df), d / "p.parquet")
        session = hst.Session(system_path=str(tmp_path / "idx"))
        got = session.read.parquet(str(d)).distinct().to_pandas()
        assert len(got) == 2
        assert sorted(got["__distinct_cnt"]) == [1, 2]  # real values kept

    def test_union_dtype_mismatch_is_loud(self, tmp_path):
        a = pd.DataFrame({"k": np.array([1, 2], np.int64)})
        b = pd.DataFrame({"k": np.array(["1", "2"])})
        da, db = tmp_path / "a", tmp_path / "b"
        da.mkdir(), db.mkdir()
        pq.write_table(pa.Table.from_pandas(a), da / "p.parquet")
        pq.write_table(pa.Table.from_pandas(b), db / "p.parquet")
        session = hst.Session(system_path=str(tmp_path / "idx"))
        with pytest.raises(HyperspaceException, match="dtype mismatch"):
            session.read.parquet(str(da)).union(
                session.read.parquet(str(db)))


class TestUnionPruning:
    def test_union_children_with_different_filter_refs(self, env):
        """Each union child materializes its own filter's columns on top of
        the pruned need-set; the union must align on ITS output schema,
        not child 0's superset (property-oracle regression)."""
        from hyperspace_tpu.plan.expr import count
        t, df = env["t"], env["df"]
        q = (t.filter(col("s") == "p")
             .union(t.filter(col("v") > 2))
             .group_by("k").agg(count(None).alias("n")))
        got = q.to_pandas().sort_values("k").reset_index(drop=True)
        part = pd.concat([df[df.s == "p"], df[df.v > 2]])
        exp = part.groupby("k").size().reset_index(name="n")
        np.testing.assert_array_equal(got["n"], exp["n"])

    def test_global_aggregate_over_union(self, env):
        """count(*) over a union references no columns; the union must
        widen its children's need-set for the alignment column
        (review regression — crashed with Unknown column)."""
        from hyperspace_tpu.plan.expr import count
        t, df = env["t"], env["df"]
        q = (t.filter(col("s") == "p")
             .union(t.filter(col("v") > 2))
             .agg(count(None).alias("n")))
        got = int(q.to_pandas()["n"].iloc[0])
        assert got == int((df.s == "p").sum() + (df.v > 2).sum())
