"""Unit tests for kernels/helpers added in round 3: dense_rank,
change_mask, null-aware sort keys, the a2a exchange primitive, multi-key
composite packing, and the hybrid-merge position math."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hyperspace_tpu.execution.columnar import Column, Table
from hyperspace_tpu.ops import kernels


class TestDenseRank:
    def test_matches_numpy_single_key(self):
        rng = np.random.default_rng(1)
        a = rng.integers(-50, 50, 500).astype(np.int64)
        ranks = np.asarray(kernels.dense_rank([jnp.asarray(a)]))
        # Equal values ⇔ equal ranks; order-preserving.
        _, exp = np.unique(a, return_inverse=True)
        assert np.array_equal(ranks - ranks.min(), exp)

    def test_matches_numpy_multi_key(self):
        rng = np.random.default_rng(2)
        a = rng.integers(0, 10, 300).astype(np.int64)
        b = rng.integers(0, 7, 300).astype(np.int64)
        ranks = np.asarray(kernels.dense_rank(
            [jnp.asarray(a), jnp.asarray(b)]))
        tuples = list(zip(a.tolist(), b.tolist()))
        uniq = {t: i for i, t in enumerate(sorted(set(tuples)))}
        exp = np.array([uniq[t] for t in tuples])
        assert np.array_equal(ranks - ranks.min(), exp)

    def test_empty(self):
        assert kernels.dense_rank([jnp.zeros(0, jnp.int64)]).shape == (0,)

    def test_join_on_ranks_equals_join_on_tuples(self):
        rng = np.random.default_rng(3)
        la = rng.integers(0, 6, 100).astype(np.int64)
        lb = rng.integers(0, 4, 100).astype(np.int64)
        ra = rng.integers(0, 6, 40).astype(np.int64)
        rb = rng.integers(0, 4, 40).astype(np.int64)
        keys = [jnp.asarray(np.concatenate([la, ra])),
                jnp.asarray(np.concatenate([lb, rb]))]
        ranks = kernels.dense_rank(keys)
        lk, rk = ranks[:100], ranks[100:]
        order = kernels.lex_sort_indices([rk])
        li, ri = kernels.merge_join_indices(lk, jnp.take(rk, order))
        got = len(li)
        exp = sum((la[i] == ra[j]) and (lb[i] == rb[j])
                  for i in range(100) for j in range(40))
        assert got == exp


class TestChangeMask:
    def test_boundaries(self):
        a = jnp.asarray(np.array([1, 1, 2, 2, 2, 5], np.int64))
        m = np.asarray(kernels.change_mask([a]))
        assert m.tolist() == [False, False, True, False, False, True]

    def test_multi_key_changes(self):
        a = jnp.asarray(np.array([1, 1, 1, 2], np.int64))
        b = jnp.asarray(np.array([7, 8, 8, 8], np.int64))
        m = np.asarray(kernels.change_mask([a, b]))
        assert m.tolist() == [False, True, False, True]


class TestNullAwareKeys:
    def test_null_first_ordering(self):
        from hyperspace_tpu.execution.executor import _null_aware_keys

        data = jnp.asarray(np.array([5, 0, -3, 7], np.int64))
        validity = jnp.asarray(np.array([True, False, True, True]))
        keys = _null_aware_keys(Column("int64", data, validity))
        order = np.asarray(kernels.lex_sort_indices(keys))
        # Null row (index 1) first, then -3, 5, 7.
        assert order.tolist() == [1, 2, 0, 3]

    def test_non_nullable_passthrough(self):
        from hyperspace_tpu.execution.executor import _null_aware_keys

        data = jnp.asarray(np.array([3, 1], np.int64))
        keys = _null_aware_keys(Column("int64", data, None))
        assert len(keys) == 1


class TestPack2:
    def test_negative_second_key_order(self):
        a = jnp.asarray(np.array([0, 0, 0], np.int32))
        b = jnp.asarray(np.array([-5, 0, 5], np.int32))
        packed = np.asarray(kernels.pack2_int32(a, b))
        assert packed.tolist() == sorted(packed.tolist())


class TestA2AExchange:
    def test_rows_land_on_hashed_owner(self):
        """Every valid row must arrive exactly once, on the device its key
        hashes to."""
        from hyperspace_tpu.execution.spmd import _a2a_exchange
        from hyperspace_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                                  pad_and_shard)
        from jax.sharding import PartitionSpec as P

        n_dev = len(jax.devices())
        rng = np.random.default_rng(4)
        n = 512
        keys = rng.integers(0, 1000, n).astype(np.int64)
        payload = np.arange(n, dtype=np.int64)
        mesh = make_mesh()
        arrays, valid = pad_and_shard(
            mesh, {"k": jnp.asarray(keys), "p": jnp.asarray(payload)}, n)
        cap = n  # plenty

        def per_device(arrays, valid):
            dst = (kernels.hash32_values(arrays["k"], "int64")
                   % np.uint32(n_dev)).astype(jnp.int32)
            recv, rvalid, of, _need = _a2a_exchange(
                arrays, valid, dst, n_dev, cap)
            return recv["k"], recv["p"], rvalid, of

        from hyperspace_tpu.parallel.sharding import device_view
        k_r, p_r, v_r, of = device_view(
            per_device, mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P()))(
                arrays, valid)
        assert int(of) == 0
        k_r = np.asarray(k_r)
        p_r = np.asarray(p_r)
        v_r = np.asarray(v_r)
        # Exactly the n valid rows arrived, each payload exactly once.
        assert v_r.sum() == n
        assert sorted(p_r[v_r].tolist()) == payload.tolist()
        # Owner check: the device block a row sits in == hash(key) % n_dev.
        rows_per_dev = len(v_r) // n_dev
        for i in np.nonzero(v_r)[0]:
            dev = i // rows_per_dev
            h = kernels.hash32_value_host(int(k_r[i]), "int64")
            assert h % n_dev == dev

    def test_overflow_flag_on_tiny_cap(self):
        from hyperspace_tpu.execution.spmd import _a2a_exchange
        from hyperspace_tpu.parallel.mesh import (DATA_AXIS, make_mesh,
                                                  pad_and_shard)
        from jax.sharding import PartitionSpec as P

        n_dev = len(jax.devices())
        n = 256
        keys = np.full(n, 7, np.int64)  # all rows to one device
        mesh = make_mesh()
        arrays, valid = pad_and_shard(mesh, {"k": jnp.asarray(keys)}, n)

        def per_device(arrays, valid):
            dst = (kernels.hash32_values(arrays["k"], "int64")
                   % np.uint32(n_dev)).astype(jnp.int32)
            _, _, of, need = _a2a_exchange(arrays, valid, dst, n_dev, 2)
            return (of, need)

        from hyperspace_tpu.parallel.sharding import device_view
        (of, need) = device_view(
            per_device, mesh, in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(P(), P()))(arrays, valid)
        assert int(of) == 1
        # The reported need is the exact worst block: every row of the
        # biggest shard targets one destination.
        rows_per_dev = -(-n // n_dev)
        assert int(need) == rows_per_dev


class TestMultiKeyComposite:
    def test_packed_composite_equality_is_exact(self):
        from hyperspace_tpu.execution.spmd import (_prepare_broadcast,
                                                   _stream_probe_key)

        rng = np.random.default_rng(5)
        ra = rng.integers(10, 20, 30).astype(np.int64)
        rb = rng.integers(-3, 3, 30).astype(np.int64)
        right = Table({
            "ra": Column("int64", jnp.asarray(ra)),
            "rb": Column("int64", jnp.asarray(rb)),
            "val": Column("int64", jnp.asarray(np.arange(30, dtype=np.int64))),
        })
        # Deduplicate (broadcast side must be unique on the key).
        seen = {}
        for i, t in enumerate(zip(ra.tolist(), rb.tolist())):
            seen.setdefault(t, i)
        keep = np.zeros(30, bool)
        keep[list(seen.values())] = True
        right = right.filter(jnp.asarray(keep))

        la = rng.integers(0, 30, 200).astype(np.int64)  # incl. out-of-range
        lb = rng.integers(-6, 6, 200).astype(np.int64)
        tiny = {"la": Column("int64", jnp.asarray(la)),
                "lb": Column("int64", jnp.asarray(lb))}
        side = _prepare_broadcast(right, [("la", "ra"), ("lb", "rb")], tiny)
        probe_table = Table({"la": tiny["la"], "lb": tiny["lb"]})
        lk, valid = _stream_probe_key(
            probe_table, [("la", "ra"), ("lb", "rb")], side.pack)
        idx = jnp.searchsorted(side.keys, lk)
        idx_c = jnp.minimum(idx, side.keys.shape[0] - 1)
        found = np.asarray(jnp.take(side.keys, idx_c) == lk)
        rset = set(zip(np.asarray(side.table.column("ra").data).tolist(),
                       np.asarray(side.table.column("rb").data).tolist()))
        exp = np.array([(x, y) in rset for x, y in zip(la, lb)])
        assert np.array_equal(found, exp)


class TestHybridMergePositions:
    def test_two_way_merge_is_a_permutation(self):
        rng = np.random.default_rng(6)
        a = np.sort(rng.integers(0, 100, 50))
        b = np.sort(rng.integers(0, 100, 20))
        ja, jb = jnp.asarray(a), jnp.asarray(b)
        pos_a = np.arange(50) + np.asarray(
            jnp.searchsorted(jb, ja, side="left"))
        pos_b = np.arange(20) + np.asarray(
            jnp.searchsorted(ja, jb, side="right"))
        allpos = np.concatenate([pos_a, pos_b])
        assert sorted(allpos.tolist()) == list(range(70))
        merged = np.empty(70, np.int64)
        merged[pos_a] = a
        merged[pos_b] = b
        assert np.array_equal(merged, np.sort(np.concatenate([a, b]),
                                              kind="stable"))


class TestMergeJoinProperty:
    def test_random_joins_match_naive(self):
        """Property: merge_join_indices over random multisets equals the
        naive nested-loop pairing, across sizes incl. empty and skew."""
        import numpy as np
        import jax.numpy as jnp
        from hyperspace_tpu.ops import kernels

        for seed in (0, 1, 2, 3):
            rng = np.random.default_rng(seed)
            n_l = int(rng.integers(0, 300))
            n_r = int(rng.integers(0, 300))
            left = rng.integers(-20, 20, n_l).astype(np.int64)
            right = np.sort(rng.integers(-20, 20, n_r).astype(np.int64))
            li, ri = kernels.merge_join_indices(
                jnp.asarray(left), jnp.asarray(right))
            got = sorted(zip(np.asarray(li).tolist(),
                             np.asarray(ri).tolist()))
            naive = sorted((i, j) for i in range(n_l) for j in range(n_r)
                           if left[i] == right[j])
            assert got == naive, f"seed {seed}"
