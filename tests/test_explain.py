"""Explain fidelity (VERDICT r2 #10; parity: PlanAnalyzer.scala:36-120 +
DisplayMode.scala): lockstep diff highlighting changed subtrees, display
modes, used-index listing, operator-count diff."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    rng = np.random.default_rng(70)
    df = pd.DataFrame({
        "k": rng.integers(0, 100, 1000).astype(np.int64),
        "v": rng.integers(0, 10, 1000).astype(np.int64),
        "w": np.round(rng.uniform(0, 1, 1000), 4),
    })
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(pa.Table.from_pandas(df), d / "part0.parquet")
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    hs = Hyperspace(session)
    hs.create_index(session.read.parquet(str(d)),
                    IndexConfig("expIdx", ["k"], ["v"]))
    q = session.read.parquet(str(d)).filter(col("k") == 5).select("k", "v")
    return dict(session=session, hs=hs, q=q)


class TestExplain:
    def test_plaintext_structure(self, env):
        text = env["hs"].explain(env["q"])
        assert "Plan with indexes:" in text
        assert "Plan without indexes:" in text
        assert "Indexes used:" in text
        assert "expIdx" in text
        # Changed-subtree highlighting is absent in plaintext (no tags).
        assert "\033[" not in text and "<b>" not in text

    def test_console_highlights_changed_subtree(self, env):
        text = env["hs"].explain(env["q"], mode="console")
        assert "\033[93m" in text and "\033[0m" in text
        # The changed leaf (IndexScan on one side, Scan on the other) is
        # highlighted; the unchanged Project/Filter headers are not.
        hi_lines = [l for l in text.splitlines() if "\033[93m" in l]
        assert any("IndexScan" in l for l in hi_lines)
        assert any("Scan" in l for l in hi_lines)
        assert not any(l.strip().startswith("\033[93mProject")
                       for l in hi_lines)

    def test_html_mode(self, env):
        text = env["hs"].explain(env["q"], mode="html")
        assert text.startswith("<pre>") and text.endswith("</pre>")
        assert "<br>" in text and "<b>" in text

    def test_verbose_operator_counts(self, env):
        text = env["hs"].explain(env["q"], verbose=True)
        assert "Physical operator stats:" in text
        assert "IndexScan: 0 -> 1" in text
        assert "Scan: 1 -> 0" in text

    def test_no_rewrite_no_highlight(self, env):
        session = env["session"]
        # Query the index can't cover → identical plans, nothing marked.
        q = session.read.parquet(
            env["q"].plan.children[0].children[0].relation.root_paths[0]) \
            .filter(col("w") > 0.5).select("k", "w")
        text = env["hs"].explain(q, mode="console")
        assert "\033[93m" not in text
        assert "<none>" in text

    def test_unknown_mode_raises(self, env):
        with pytest.raises(Exception):
            env["hs"].explain(env["q"], mode="nope")


class TestRedirect:
    def test_redirect_func_receives_full_text(self, env):
        """Parity: the reference's explain(df, redirectFunc) streams the
        rendered output to a caller-supplied sink."""
        captured = []
        out = env["hs"].explain(env["q"], verbose=True,
                                redirect_func=captured.append)
        assert captured and captured[0] == out
