"""BucketUnion node tests (parity: index/BucketUnionTest.scala:1-124 — the
reference asserts child-compatibility rules and that the union preserves the
children's partitioning instead of introducing an exchange).

Here the analogue invariants: schema compatibility is validated at
construction, execution is a pure aligned concatenation (no re-sort, no
collective), and column pruning flows through the node.
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.exceptions import HyperspaceException
from hyperspace_tpu.plan.nodes import BucketUnion, Project, Union
from hyperspace_tpu.plan.expr import col


@pytest.fixture()
def env(tmp_path):
    session = hst.Session(system_path=str(tmp_path / "indexes"))
    dfs = {}
    for name, seed in [("a", 1), ("b", 2)]:
        d = tmp_path / name
        d.mkdir()
        rng = np.random.default_rng(seed)
        pq.write_table(pa.Table.from_pandas(pd.DataFrame({
            "k": rng.integers(0, 50, 200).astype(np.int64),
            "v": rng.integers(0, 9, 200).astype(np.int64),
        })), d / "p0.parquet")
        dfs[name] = session.read.parquet(str(d))
    return session, dfs


class TestConstruction:
    def test_empty_children_raise(self):
        with pytest.raises(HyperspaceException, match="requires children"):
            BucketUnion([], bucket_spec=None)
        with pytest.raises(HyperspaceException, match="requires children"):
            Union([])

    def test_mismatched_schema_raises(self, env):
        _, dfs = env
        renamed = dfs["b"].select(col("k").alias("kk"), col("v"))
        with pytest.raises(HyperspaceException, match="share schema"):
            BucketUnion([dfs["a"].plan, renamed.plan], bucket_spec=None)

    def test_with_children_keeps_bucket_spec(self, env):
        _, dfs = env
        spec = ("k", 8)
        bu = BucketUnion([dfs["a"].plan, dfs["b"].plan], bucket_spec=spec)
        rebuilt = bu.with_children(list(bu.children))
        assert isinstance(rebuilt, BucketUnion)
        assert rebuilt.bucket_spec == spec
        assert rebuilt.schema.names == bu.schema.names

    def test_schema_is_first_childs(self, env):
        _, dfs = env
        bu = BucketUnion([dfs["a"].plan, dfs["b"].plan], bucket_spec=None)
        assert bu.schema.names == ["k", "v"]


class TestExecution:
    def test_union_is_ordered_concat(self, env):
        session, dfs = env
        bu = BucketUnion([dfs["a"].plan, dfs["b"].plan], bucket_spec=None)
        got = session.create_dataframe(bu).to_pandas()
        expect = pd.concat([dfs["a"].to_pandas(), dfs["b"].to_pandas()],
                           ignore_index=True)
        # Pure aligned concatenation: child rows in order, no re-sort.
        pd.testing.assert_frame_equal(got, expect)

    def test_projection_prunes_through_union(self, env):
        session, dfs = env
        bu = BucketUnion([dfs["a"].plan, dfs["b"].plan], bucket_spec=None)
        proj = Project([col("v")], bu)
        got = session.create_dataframe(proj).to_pandas()
        assert list(got.columns) == ["v"]
        assert len(got) == 400

    def test_aggregate_over_union_matches_pandas(self, env):
        session, dfs = env
        from hyperspace_tpu.plan.expr import sum_
        bu = BucketUnion([dfs["a"].plan, dfs["b"].plan], bucket_spec=None)
        got = (session.create_dataframe(bu)
               .group_by("k").agg(sum_(col("v")).alias("s"))
               .sort("k").to_pandas())
        expect = (pd.concat([dfs["a"].to_pandas(), dfs["b"].to_pandas()])
                  .groupby("k", as_index=False)["v"].sum()
                  .rename(columns={"v": "s"}).sort_values("k")
                  .reset_index(drop=True))
        pd.testing.assert_frame_equal(got, expect)

    def test_three_way_union(self, env):
        session, dfs = env
        bu = BucketUnion(
            [dfs["a"].plan, dfs["b"].plan, dfs["a"].plan], bucket_spec=None)
        assert session.create_dataframe(bu).count() == 600
