"""Property-based disable-and-compare: random schemas, random indexes,
random query shapes — indexed answers must equal no-index answers.

The reference's single most valuable oracle is checkAnswer with rules
toggled (E2EHyperspaceRulesTest); hand-written suites cover the named
shapes, while this harness walks the interaction space (nullable ×
dictionary × pushdown × hybrid × group-by × sort) with FIXED seeds so
failures reproduce exactly. Each seed builds a fresh dataset + indexes,
runs a batch of generated queries both ways, and compares.
"""

import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace, IndexConfig
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan import expr as E
from hyperspace_tpu.plan.expr import (avg, col, count,
                                      count_distinct, max_, min_, sum_)

_EPOCH = datetime.date(1970, 1, 1)


def _random_schema(rng):
    """3-6 columns across the full type surface; ~1/3 nullable."""
    cols = {}
    n_cols = int(rng.integers(3, 7))
    makers = [
        ("i64", lambda n: rng.integers(-50, 200, n).astype(np.int64)),
        ("i32", lambda n: rng.integers(0, 90, n).astype(np.int32)),
        ("f64", lambda n: np.round(rng.uniform(-5, 5, n), 4)),
        ("date", lambda n: np.array(
            [_EPOCH + datetime.timedelta(days=int(d))
             for d in rng.integers(18000, 18400, n)], dtype=object)),
        ("str", lambda n: rng.choice(
            ["aa", "bb", "cc", "dd", "é✓", ""], n)),
        ("bool", lambda n: rng.integers(0, 2, n).astype(bool)),
    ]
    picks = rng.choice(len(makers), n_cols, replace=True)
    for i, m in enumerate(picks):
        kind, make = makers[m]
        cols[f"c{i}_{kind}"] = (kind, make, bool(rng.random() < 0.33))
    return cols


def _build_frame(rng, schema, n):
    data = {}
    for name, (kind, make, nullable) in schema.items():
        vals = pd.Series(make(n))
        if kind == "date":
            vals = pd.Series(pd.array(vals, dtype="object"))
        if nullable:
            mask = rng.random(n) < 0.12
            vals = vals.mask(mask, None)
        data[name] = vals
    df = pd.DataFrame(data)
    return df


def _arrow_table(df, schema):
    fields = []
    for name, (kind, _, nullable) in schema.items():
        t = {"i64": pa.int64(), "i32": pa.int32(), "f64": pa.float64(),
             "date": pa.date32(), "str": pa.string(),
             "bool": pa.bool_()}[kind]
        fields.append(pa.field(name, t, nullable=True))
    return pa.Table.from_pandas(df, schema=pa.schema(fields),
                                preserve_index=False)


def _literal_for(rng, kind):
    if kind == "i64":
        return int(rng.integers(-50, 200))
    if kind == "i32":
        return int(rng.integers(0, 90))
    if kind == "f64":
        return float(np.round(rng.uniform(-5, 5), 3))
    if kind == "date":
        return _EPOCH + datetime.timedelta(days=int(rng.integers(18000, 18400)))
    if kind == "str":
        return str(rng.choice(["aa", "bb", "cc", "dd", "é✓"]))
    return bool(rng.integers(0, 2))


def _random_predicate(rng, schema, depth=0):
    name = str(rng.choice(list(schema)))
    kind = schema[name][0]
    lit = _literal_for(rng, kind)
    ops = [lambda c, v: c == v, lambda c, v: c != v] if kind == "bool" else [
        lambda c, v: c == v, lambda c, v: c < v, lambda c, v: c >= v,
        lambda c, v: c != v]
    pred = ops[int(rng.integers(0, len(ops)))](col(name), lit)
    if kind in ("i64", "i32") and rng.random() < 0.3:
        pred = col(name).isin([_literal_for(rng, kind) for _ in range(3)])
    if depth < 2 and rng.random() < 0.4:
        other = _random_predicate(rng, schema, depth + 1)
        pred = (pred & other) if rng.random() < 0.6 else (pred | other)
    if rng.random() < 0.15:
        pred = ~pred
    return pred


def _random_query(rng, t, schema):
    names = list(schema)
    q = t
    for _ in range(int(rng.integers(1, 3))):
        q = q.filter(_random_predicate(rng, schema))
    # Occasionally join back against a DISTINCT aliased projection of the
    # source (inner or outer). Keys restricted to the high-cardinality int
    # columns and the right side always deduplicated — low-cardinality keys
    # against the raw source fan out to ~|left|*n/|key| intermediate rows
    # (measured 3.4x suite slowdown before these bounds).
    joined = False
    if rng.random() < 0.25:
        keys = [n for n in names if schema[n][0] in ("i64", "i32")]
        if keys:
            k = str(rng.choice(keys))
            payload = [n for n in names if n != k and rng.random() < 0.4]
            right = t.select(col(k).alias(f"r_{k}"),
                             *[col(p).alias(f"r_{p}") for p in payload])
            how = str(rng.choice(["inner", "left"]))
            q = q.join(right.distinct(), on=col(k) == col(f"r_{k}"),
                       how=how)
            names = list(q.plan.schema.names)
            joined = True
    # Occasionally union with a differently-filtered copy of the source
    # (only when no join happened — the schemas must match exactly).
    if not joined and rng.random() < 0.2:
        q = q.union(t.filter(_random_predicate(rng, schema)))
    if rng.random() < 0.5:
        keep = [n for n in names if rng.random() < 0.7] or names[:1]
        q = q.select(*keep)
        names = keep
    if rng.random() < 0.2:
        q = q.distinct()
        names = list(q.plan.schema.names)
    if rng.random() < 0.45:
        kind_of = lambda n: schema[n.removeprefix("r_")][0] \
            if n.removeprefix("r_") in schema else None
        group_pool = [n for n in names
                      if kind_of(n) in ("i64", "i32", "str", "bool",
                                        "date")]
        num_pool = [n for n in names if kind_of(n) in ("i64", "i32",
                                                       "f64")]
        if group_pool:
            g = str(rng.choice(group_pool))
            aggs = [count(None).alias("n")]
            if num_pool:
                v = str(rng.choice(num_pool))
                aggs.append(sum_(col(v)).alias("s"))
                if rng.random() < 0.5:
                    aggs.append(avg(col(v)).alias("a"))
                else:
                    aggs.append(min_(col(v)).alias("lo"))
                    aggs.append(max_(col(v)).alias("hi"))
                if rng.random() < 0.3:
                    aggs.append(count_distinct(col(v)).alias("nd"))
            q = q.group_by(g).agg(*aggs)
    if rng.random() < 0.4:
        sch = q.plan.schema
        sortable = list(sch.names)
        if sortable:
            s = str(rng.choice(sortable))
            # Limit needs a TOTAL order over NON-FLOAT keys: float f64
            # aggregates differ ~1 ulp between the indexed and raw paths,
            # so a float tie-break at the cut keeps different rows.
            exact = [n for n in sortable
                     if sch.field(n).dtype not in ("float64", "float32")]
            if rng.random() < 0.5 and exact == sortable:
                keys = [(s, bool(rng.random() < 0.7))] + \
                    [(o, True) for o in sortable if o != s]
                q = q.sort(*keys).limit(int(rng.integers(1, 50)))
            else:
                q = q.sort((s, bool(rng.random() < 0.7)))
    return q


def _compare(a: pa.Table, b: pa.Table, ordered: bool):
    assert a.column_names == b.column_names
    assert a.num_rows == b.num_rows
    if not ordered:
        keys = [(c, "ascending") for c in a.column_names]
        a, b = a.sort_by(keys), b.sort_by(keys)
    for name in a.column_names:
        ca, cb = a.column(name), b.column(name)
        if pa.types.is_floating(ca.type):
            va = ca.to_numpy(zero_copy_only=False)
            vb = cb.to_numpy(zero_copy_only=False)
            np.testing.assert_allclose(va, vb, rtol=1e-9, equal_nan=True)
        else:
            assert ca.equals(cb), f"column {name} differs"


N_QUERIES = 12


@pytest.mark.parametrize("seed", [101, 202, 303, 404, 505, 606, 707, 808])
def test_random_queries_indexed_equals_raw(seed, tmp_path):
    rng = np.random.default_rng(seed)
    schema = _random_schema(rng)
    df = _build_frame(rng, schema, n=int(rng.integers(3000, 9000)))
    at = _arrow_table(df, schema)
    d = tmp_path / "data"
    d.mkdir()
    parts = int(rng.integers(1, 4))
    step = max(1, at.num_rows // parts)
    for i in range(parts):
        pq.write_table(at.slice(i * step, step if i < parts - 1 else None),
                       d / f"p{i}.parquet")

    session = hst.Session(system_path=str(tmp_path / "idx"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS,
                     int(rng.integers(2, 9)))
    hs = Hyperspace(session)
    t = session.read.parquet(str(d))

    # 1-2 random covering indexes (random key, random includes).
    names = list(schema)
    for i in range(int(rng.integers(1, 3))):
        key = str(rng.choice(names))
        includes = [n for n in names if n != key and rng.random() < 0.6]
        try:
            hs.create_index(t, IndexConfig(f"pix{i}", [key], includes))
        except Exception:
            pass  # e.g. duplicate config on same key — irrelevant here

    failures = []
    for qi in range(N_QUERIES):
        q = _random_query(rng, t, schema)
        ordered = False  # compare sorted; Sort+Limit keeps set semantics
        try:
            session.enable_hyperspace()
            with_idx = q.to_arrow()
            session.disable_hyperspace()
            without = q.to_arrow()
            _compare(with_idx, without, ordered)
        except AssertionError as e:
            failures.append(
                f"seed={seed} query#{qi}: {q.plan.tree_string()}\n{e}")
        finally:
            session.disable_hyperspace()
    assert not failures, "\n\n".join(failures)


@pytest.mark.parametrize("seed", [11, 22])
def test_random_queries_under_hybrid_scan(seed, tmp_path):
    """Same oracle with appended source files and hybrid scan enabled."""
    rng = np.random.default_rng(seed)
    schema = _random_schema(rng)
    df = _build_frame(rng, schema, n=4000)
    at = _arrow_table(df, schema)
    d = tmp_path / "data"
    d.mkdir()
    pq.write_table(at.slice(0, 3600), d / "base.parquet")

    session = hst.Session(system_path=str(tmp_path / "idx"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
    hs = Hyperspace(session)
    t = session.read.parquet(str(d))
    key = str(rng.choice(list(schema)))
    hs.create_index(t, IndexConfig("hyb", [key],
                                   [n for n in schema if n != key]))
    # Append AFTER the build: hybrid scan must merge these rows in.
    pq.write_table(at.slice(3600), d / "appended.parquet")
    t2 = session.read.parquet(str(d))

    for qi in range(6):
        q = _random_query(rng, t2, schema)
        session.enable_hyperspace()
        with_idx = q.to_arrow()
        session.disable_hyperspace()
        without = q.to_arrow()
        _compare(with_idx, without, ordered=False)
