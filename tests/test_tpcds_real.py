"""Verbatim TPC-DS plan stability + disable-and-compare oracle.

The reference ships 99 approved-plan golden files from the actual TPC-DS
v1.4 SQL (goldstandard/TPCDSBase.scala:41); this suite runs the subset the
SQL grammar covers today (the texts in goldstandard/tpcds_real.py,
verbatim) through
session.sql, pins the optimized plan in enabled AND disabled golden files,
and checks the answers agree between the two (the disable-and-compare
oracle). Regenerate goldens with GENERATE_GOLDEN_FILES=1.
"""

import os
import re

import pandas as pd
import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.index.constants import IndexConstants

from goldstandard import tpcds_real

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "resources",
                          "golden_plans")
GENERATE = os.environ.get("GENERATE_GOLDEN_FILES") == "1"


def normalize_plan(s: str) -> str:
    s = re.sub(r"(?:/[\w.\-]+)*/(?:data|indexes)/", "<root>/", s)
    s = re.sub(r"LogVersion: \d+", "LogVersion: <v>", s)
    return s.rstrip() + "\n"


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpcds_real")
    session = hst.Session(system_path=str(root / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    tpcds_real.register_tables(session, str(root / "data"))
    hs = Hyperspace(session)
    for table, cfg in tpcds_real.index_configs():
        hs.create_index(session.table(table), cfg)
    return session


def _check(mode: str, name: str, plan_str: str):
    path = os.path.join(GOLDEN_DIR, mode, f"{name}.txt")
    actual = normalize_plan(plan_str)
    if GENERATE:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(actual)
        return
    assert os.path.isfile(path), \
        f"Missing golden file {path}; regenerate with GENERATE_GOLDEN_FILES=1"
    with open(path) as f:
        expected = f.read()
    assert actual == expected, (
        f"Optimized plan for {name} ({mode}) changed.\n--- expected ---\n"
        f"{expected}\n--- actual ---\n{actual}\n"
        "If intentional, regenerate with GENERATE_GOLDEN_FILES=1")


@pytest.mark.parametrize("name", tpcds_real.QUERY_NAMES)
class TestTpcdsRealPlanStability:
    def test_disabled(self, harness, name):
        session = harness
        session.disable_hyperspace()
        df = session.sql(tpcds_real.QUERY_TEXTS[name])
        _check("disabled", name, df.optimized_plan().tree_string())

    def test_enabled(self, harness, name):
        session = harness
        session.enable_hyperspace()
        df = session.sql(tpcds_real.QUERY_TEXTS[name])
        _check("enabled", name, df.optimized_plan().tree_string())

    def test_enabled_equals_disabled_answers(self, harness, name):
        session = harness
        session.enable_hyperspace()
        on = session.sql(tpcds_real.QUERY_TEXTS[name]).to_pandas()
        session.disable_hyperspace()
        off = session.sql(tpcds_real.QUERY_TEXTS[name]).to_pandas()
        assert len(on) > 0, f"{name}: empty answer (catalog mis-sized)"
        # Scalar aggregates return one row even over ZERO matching source
        # rows — an all-null answer means the catalog stopped covering
        # the query's predicates and the oracle degenerated.
        assert not on.isna().all().all(), \
            f"{name}: all-null answer (no source rows matched)"
        pd.testing.assert_frame_equal(
            on.reset_index(drop=True), off.reset_index(drop=True),
            check_exact=False, rtol=1e-9)


def test_some_plans_actually_rewrite(harness):
    """At least the item-keyed star joins must take a covering index when
    enabled — otherwise the enabled goldens pin nothing interesting."""
    session = harness
    session.enable_hyperspace()
    rewritten = []
    for name in tpcds_real.QUERY_NAMES:
        df = session.sql(tpcds_real.QUERY_TEXTS[name])
        if any("IndexScan" in l.simple_string()
               for l in df.optimized_plan().collect_leaves()):
            rewritten.append(name)
    assert len(rewritten) >= 3, (
        f"only {rewritten} rewrote; the index configs miss the corpus")
