"""Multi-host initialization helper (parallel/multihost.py).

Parity: the reference defers cluster wiring to Spark's cluster manager;
here jax.distributed is the runtime, and the helper's contract is pinned
with a mocked `jax.distributed` — actual multi-host hardware is not
available in any CI, which is exactly why the wiring logic needs tests.
"""

from unittest import mock

import pytest

from hyperspace_tpu.parallel.multihost import global_mesh, initialize_multihost


class TestInitializeMultihost:
    def test_single_process_is_noop(self, monkeypatch):
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        with mock.patch("jax.distributed.initialize") as init:
            out = initialize_multihost()
        init.assert_not_called()
        assert out["initialized"] is False
        assert out["process_count"] == 1
        assert out["global_devices"] >= 1

    def test_explicit_args_wire_through(self):
        with mock.patch("jax.distributed.initialize") as init, \
                mock.patch("jax.distributed.is_initialized",
                           return_value=False, create=True):
            out = initialize_multihost("10.0.0.1:8476",
                                       num_processes=4, process_id=2)
        init.assert_called_once_with(
            coordinator_address="10.0.0.1:8476",
            num_processes=4, process_id=2)
        assert out["initialized"] is True

    def test_env_vars_are_the_default_source(self, monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "h0:9999")
        monkeypatch.setenv("JAX_NUM_PROCESSES", "2")
        monkeypatch.setenv("JAX_PROCESS_ID", "1")
        with mock.patch("jax.distributed.initialize") as init, \
                mock.patch("jax.distributed.is_initialized",
                           return_value=False, create=True):
            out = initialize_multihost()
        init.assert_called_once_with(
            coordinator_address="h0:9999", num_processes=2, process_id=1)
        assert out["initialized"] is True

    def test_half_configured_raises(self, monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "h0:9999")
        monkeypatch.delenv("JAX_NUM_PROCESSES", raising=False)
        with pytest.raises(ValueError, match="num_processes"):
            initialize_multihost()

    def test_idempotent_when_already_initialized(self):
        with mock.patch("jax.distributed.initialize") as init, \
                mock.patch("jax.distributed.is_initialized",
                           return_value=True, create=True):
            out = initialize_multihost("h0:9999", num_processes=2,
                                       process_id=0)
        init.assert_not_called()  # second Session in-process: no re-init
        assert out["initialized"] is True

    def test_second_initialize_race_swallowed(self):
        with mock.patch("jax.distributed.initialize",
                        side_effect=RuntimeError(
                            "backend already initialized")), \
                mock.patch("jax.distributed.is_initialized",
                           return_value=False, create=True):
            out = initialize_multihost("h0:9999", num_processes=2,
                                       process_id=0)
        assert out["initialized"] is True

    def test_other_runtime_errors_propagate(self):
        with mock.patch("jax.distributed.initialize",
                        side_effect=RuntimeError("connection refused")), \
                mock.patch("jax.distributed.is_initialized",
                           return_value=False, create=True):
            with pytest.raises(RuntimeError, match="connection refused"):
                initialize_multihost("h0:9999", num_processes=2,
                                     process_id=0)


class TestGlobalMesh:
    def test_mesh_spans_all_devices(self):
        import numpy as np
        mesh = global_mesh()
        import jax
        assert int(np.prod(mesh.devices.shape)) == len(jax.devices())


class TestRealTwoProcessCluster:
    """The wiring above, un-mocked: 2 OS processes × 2 virtual CPU devices
    form ONE jax.distributed cluster (gloo collectives standing in for
    DCN) and run the REAL distributed index build across the process
    boundary (SURVEY §5 comm-backend DCN row; VERDICT r3 #10)."""

    def test_distributed_build_crosses_the_process_boundary(self):
        import os
        import sys
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        import __graft_entry__ as g

        # Verified inside the dryrun: worker init through
        # initialize_multihost, row conservation across processes,
        # device-computed bucket ids equal the host hash, every bucket
        # owned by exactly one (process, device), contiguous per-device
        # ranges, and an UNEVEN source split (the worldwide shard pad).
        g.dryrun_multihost(n_processes=2, local_devices=2)
