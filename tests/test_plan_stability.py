"""Golden-file plan-stability tests.

Parity with the reference's goldstandard/PlanStabilitySuite.scala:84: run a
fixed TPC-H/TPC-DS-shaped query set, normalize the optimized plan (strip
temp paths and other run-dependent tokens), and diff against approved golden
files — once with hyperspace disabled, once with indexes created + enabled.

Regenerate after an intentional plan change with:

    GENERATE_GOLDEN_FILES=1 python -m pytest tests/test_plan_stability.py
"""

import os
import re

import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.index.constants import IndexConstants

from goldstandard import tpc

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "resources",
                          "golden_plans")
GENERATE = os.environ.get("GENERATE_GOLDEN_FILES") == "1"


def normalize_plan(s: str) -> str:
    """Strip run-dependent tokens: absolute temp paths and log versions
    (parity: the reference strips expr ids and locations)."""
    s = re.sub(r"(?:/[\w.\-]+)*/(?:data|indexes)/", "<root>/", s)
    s = re.sub(r"LogVersion: \d+", "LogVersion: <v>", s)
    return s.rstrip() + "\n"


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpc")
    session = hst.Session(system_path=str(root / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    dfs = tpc.register_tables(session, str(root / "data"))
    hs = Hyperspace(session)
    for cfg in tpc.index_configs():
        hs.create_index(dfs[tpc.INDEXED_TABLES[cfg.index_name]], cfg)
    return session, tpc.queries(dfs)


def _check(mode: str, name: str, plan_str: str):
    path = os.path.join(GOLDEN_DIR, mode, f"{name}.txt")
    actual = normalize_plan(plan_str)
    if GENERATE:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(actual)
        return
    assert os.path.isfile(path), \
        f"Missing golden file {path}; regenerate with GENERATE_GOLDEN_FILES=1"
    with open(path) as f:
        expected = f.read()
    assert actual == expected, (
        f"Optimized plan for {name} ({mode}) changed.\n--- expected ---\n"
        f"{expected}\n--- actual ---\n{actual}\n"
        "If intentional, regenerate with GENERATE_GOLDEN_FILES=1")


@pytest.mark.parametrize("name", tpc.QUERY_NAMES)
class TestPlanStability:
    def test_disabled(self, harness, name):
        session, queries = harness
        session.disable_hyperspace()
        _check("disabled", name, queries[name].optimized_plan().tree_string())

    def test_enabled(self, harness, name):
        session, queries = harness
        session.enable_hyperspace()
        _check("enabled", name, queries[name].optimized_plan().tree_string())

    def test_enabled_equals_disabled_answers(self, harness, name):
        """The disable-and-compare oracle over the whole golden query set.
        Float columns compare with tolerance: the index path sums rows in
        bucket-sorted order, so f64 aggregates differ by ~1 ulp (the
        reference's checkAnswer tolerates doubles the same way)."""
        import numpy as np
        import pyarrow as pa

        session, queries = harness
        q = queries[name]
        session.enable_hyperspace()
        with_idx = q.to_arrow()
        session.disable_hyperspace()
        without = q.to_arrow()
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        a, b = key(with_idx), key(without)
        assert a.column_names == b.column_names and a.num_rows == b.num_rows
        for col_name in a.column_names:
            ca, cb = a.column(col_name), b.column(col_name)
            if pa.types.is_floating(ca.type):
                np.testing.assert_allclose(
                    ca.to_numpy(zero_copy_only=False),
                    cb.to_numpy(zero_copy_only=False), rtol=1e-9)
            else:
                assert ca.equals(cb), f"column {col_name} differs"


class TestExpectedRewrites:
    """Pin which queries must (not) be rewritten — a reviewable summary of
    the rewrite surface, independent of the golden text."""

    EXPECT = {"tpch_q1": False, "tpch_q3": True, "tpch_q6": True,
              "tpch_q12": False, "tpch_q14": False,
              "tpch_q17": True,  # group-by index on l_partkey (avg subquery)
              "tpch_q18": False, "tpch_q19": False,
              "tpcds_q1_like": False, "tpcds_q3_like": False,
              "groupby_index": True, "multi_key_join": False,
              "self_join": True,
              # Pushdown surface: the sunk filter hits li_ship_idx.
              "pushdown_select_where": True, "pushdown_alias": True,
              # Coverage misses (o_orderpriority / l_orderkey not included;
              # no index keyed on the filtered/grouped columns).
              "tpch_q5_like": False, "filter_topk_rows": False,
              "tpcds_q7_like": False, "join_on_aggregate": False,
              "tpch_q10_like": True,
              "having_over_groupby": True,  # groupby index; HAVING stays up
              "in_list_indexed": True,
              # or_of_ranges: both disjuncts constrain li_ship_idx's key
              # and all referenced columns are covered.
              "or_of_ranges": True,
              # The rest miss coverage (group keys / filter columns not in
              # any index) or have no filter/aggregate to rewrite.
              "minmax_aggregates": False, "multi_dir_sort": False,
              "string_range_scan": False, "count_distinct_groups": False,
              "join_chain_filters": False, "not_in_exclusion": False,
              "proj_arith_groupby": False,
              # New surface: distinct/union/outer shapes (no coverage or
              # rule deliberately inner-only → no rewrites expected).
              "distinct_flags": False, "union_of_ranges": False,
              "left_outer_orders": False}

    def test_rewrite_expectations(self, harness):
        session, queries = harness
        session.enable_hyperspace()
        got = {name: "IndexScan" in q.optimized_plan().tree_string()
               for name, q in queries.items()}
        assert got == self.EXPECT
