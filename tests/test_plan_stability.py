"""Golden-file plan-stability tests.

Parity with the reference's goldstandard/PlanStabilitySuite.scala:84: run a
fixed TPC-H/TPC-DS-shaped query set, normalize the optimized plan (strip
temp paths and other run-dependent tokens), and diff against approved golden
files — once with hyperspace disabled, once with indexes created + enabled.

Regenerate after an intentional plan change with:

    GENERATE_GOLDEN_FILES=1 python -m pytest tests/test_plan_stability.py
"""

import os
import re

import pytest

import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.index.constants import IndexConstants

from goldstandard import tpc

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "resources",
                          "golden_plans")
GENERATE = os.environ.get("GENERATE_GOLDEN_FILES") == "1"


def normalize_plan(s: str) -> str:
    """Strip run-dependent tokens: absolute temp paths and log versions
    (parity: the reference strips expr ids and locations)."""
    s = re.sub(r"(?:/[\w.\-]+)*/(?:data|indexes)/", "<root>/", s)
    s = re.sub(r"LogVersion: \d+", "LogVersion: <v>", s)
    return s.rstrip() + "\n"


@pytest.fixture(scope="module")
def harness(tmp_path_factory):
    root = tmp_path_factory.mktemp("tpc")
    session = hst.Session(system_path=str(root / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    dfs = tpc.register_tables(session, str(root / "data"))
    hs = Hyperspace(session)
    for cfg in tpc.index_configs():
        hs.create_index(dfs[tpc.INDEXED_TABLES[cfg.index_name]], cfg)
    return session, tpc.queries(dfs)


def _check(mode: str, name: str, plan_str: str):
    path = os.path.join(GOLDEN_DIR, mode, f"{name}.txt")
    actual = normalize_plan(plan_str)
    if GENERATE:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(actual)
        return
    assert os.path.isfile(path), \
        f"Missing golden file {path}; regenerate with GENERATE_GOLDEN_FILES=1"
    with open(path) as f:
        expected = f.read()
    assert actual == expected, (
        f"Optimized plan for {name} ({mode}) changed.\n--- expected ---\n"
        f"{expected}\n--- actual ---\n{actual}\n"
        "If intentional, regenerate with GENERATE_GOLDEN_FILES=1")


@pytest.mark.parametrize("name", tpc.QUERY_NAMES)
class TestPlanStability:
    def test_disabled(self, harness, name):
        session, queries = harness
        session.disable_hyperspace()
        _check("disabled", name, queries[name].optimized_plan().tree_string())

    def test_enabled(self, harness, name):
        session, queries = harness
        session.enable_hyperspace()
        _check("enabled", name, queries[name].optimized_plan().tree_string())

    def test_enabled_equals_disabled_answers(self, harness, name):
        """The disable-and-compare oracle over the whole golden query set.
        Float columns compare with tolerance: the index path sums rows in
        bucket-sorted order, so f64 aggregates differ by ~1 ulp (the
        reference's checkAnswer tolerates doubles the same way)."""
        import numpy as np
        import pyarrow as pa

        session, queries = harness
        q = queries[name]
        session.enable_hyperspace()
        with_idx = q.to_arrow()
        session.disable_hyperspace()
        without = q.to_arrow()
        key = lambda t: t.sort_by([(c, "ascending") for c in t.column_names])
        a, b = key(with_idx), key(without)
        assert a.column_names == b.column_names and a.num_rows == b.num_rows
        for col_name in a.column_names:
            ca, cb = a.column(col_name), b.column(col_name)
            if pa.types.is_floating(ca.type):
                np.testing.assert_allclose(
                    ca.to_numpy(zero_copy_only=False),
                    cb.to_numpy(zero_copy_only=False), rtol=1e-9)
            else:
                assert ca.equals(cb), f"column {col_name} differs"


class TestExplainGolden:
    """Pin the full rendered explain output for representative queries
    (parity: the reference's ExplainTest diffs rendered output against
    expected files under src/test/resources/expected/)."""

    # One rewritten filter query, the headline join query, the group-by
    # index shape, and one deliberately-unrewritten query.
    CASES = ["tpch_q6", "tpch_q3", "groupby_index", "tpch_q1"]

    @pytest.mark.parametrize("name", CASES)
    @pytest.mark.parametrize("mode", ["plaintext", "console", "html"])
    def test_rendered_explain(self, harness, name, mode):
        from hyperspace_tpu.plananalysis.explain import explain_string

        session, queries = harness
        # explain_string enables hyperspace itself and restores prior
        # state. diagnostics=False: the golden pins the PLAN rendering;
        # the runtime sections (compilation/io/spmd) read process-wide
        # counters earlier tests in this process already moved.
        out = explain_string(session, queries[name].plan, verbose=True,
                             mode=mode, diagnostics=False)
        _check(os.path.join("explain", mode), name, out)


class TestExpectedRewrites:
    """Pin which queries must (not) be rewritten — a reviewable summary of
    the rewrite surface, independent of the golden text."""

    EXPECT = {"tpch_q1": False, "tpch_q3": True, "tpch_q6": True,
              "tpch_q12": False, "tpch_q14": False,
              "tpch_q17": True,  # group-by index on l_partkey (avg subquery)
              "tpch_q18": False, "tpch_q19": False,
              "tpcds_q1_like": False, "tpcds_q3_like": False,
              "groupby_index": True, "multi_key_join": False,
              "self_join": True,
              # Pushdown surface: the sunk filter hits li_ship_idx.
              "pushdown_select_where": True, "pushdown_alias": True,
              # Coverage misses (o_orderpriority / l_orderkey not included;
              # no index keyed on the filtered/grouped columns).
              "tpch_q5_like": False, "filter_topk_rows": False,
              "tpcds_q7_like": False, "join_on_aggregate": False,
              "tpch_q10_like": True,
              "having_over_groupby": True,  # groupby index; HAVING stays up
              "in_list_indexed": True,
              # or_of_ranges: both disjuncts constrain li_ship_idx's key
              # and all referenced columns are covered.
              "or_of_ranges": True,
              # The rest miss coverage (group keys / filter columns not in
              # any index) or have no filter/aggregate to rewrite.
              "minmax_aggregates": False, "multi_dir_sort": False,
              "string_range_scan": False, "count_distinct_groups": False,
              "join_chain_filters": False, "not_in_exclusion": False,
              "proj_arith_groupby": False,
              # New surface: distinct/union/outer shapes (no coverage or
              # rule deliberately inner-only → no rewrites expected).
              "distinct_flags": False, "union_of_ranges": False,
              "left_outer_orders": False,
              # Round-3 additions. q55 is the direct ss⋈item pair (both
              # sides indexed on the join key); q42/q52 interpose the
              # date_dim join so the item join's left side is no longer a
              # scan — correctly not rewritten.
              "tpcds_q42_like": False, "tpcds_q52_like": False,
              "tpcds_q55_like": True,
              "store_channel_mix": False,  # store unindexed
              "returns_vs_sales": True,    # sr_cust_idx groupby side
              "with_column_charge": False,
              "drop_columns_scan": True,   # survivors covered by li_ship_idx
              # Outer joins: the JOIN rule is deliberately inner-only, but
              # the FILTER rule still rewrites an outer join's input — the
              # ss_item_sk<10 filter hits ss_item_idx inside the right
              # outer.
              "right_outer_items": True, "full_outer_store_keys": False,
              "tpch_q4_like": True,        # od_ok_idx ⋈ li_ok_idx
              "tpch_q13_like": False,      # left outer
              "tpch_q15_like": True,       # li_ok_idx group-by, covered filter
              "tpch_q16_like": False,      # part unindexed
              "tpch_q20_like": True,       # li_pk_idx group-by
              "tpch_q22_like": False,      # left outer
              "tpch_q2_like": False,       # l_extendedprice not in li_pk_idx
              "tpch_q11_like": False,      # same coverage miss
              "in_list_strings": False, "float_between_discount": False,
              "second_level_agg": False, "union_sales_returns": False,
              "distinct_join": True,       # ss_item_idx ⋈ it_sk_idx
              "cross_fact_join": False,    # ss side not keyed on customer
              # Data skipping narrows the Scan in place (no IndexScan
              # node); the golden pins the [k/4 files] annotation instead.
              "skipping_date_window": False,
              "skipping_unprunable_amount": False,
              # Nested leaves index like flat columns; rewrites reach
              # through temp views to the underlying scan.
              "nested_filter_rewrite": True, "nested_group_rollup": True,
              "view_filter_pushdown": True, "view_join_orders": True,
              # COUNT DISTINCT over l_orderkey: not covered by any index.
              "tpch_q16_distinct": False,
              # Edge shapes: only the literal-true filter is covered
              # (li_ship_idx; the always-true conjunct is harmless).
              "union_three_way": False, "limit_zero": False,
              "literal_true_filter": True,
              "count_distinct_two_level": False,
              # Wrong-case spellings resolve to the schema's names and the
              # covering rewrite fires as if spelled exactly.
              "case_insensitive_cols": True}

    def test_rewrite_expectations(self, harness):
        session, queries = harness
        session.enable_hyperspace()
        got = {name: "IndexScan" in q.optimized_plan().tree_string()
               for name, q in queries.items()}
        assert got == self.EXPECT


class TestSqlParity:
    """SQL text versions of golden queries produce byte-identical optimized
    plans to their DataFrame counterparts — the front-end adds no plan
    divergence, so every golden file covers both surfaces."""

    def test_sql_matches_dataframe_plans(self, harness):
        session, queries = harness
        # Views over the same scans the DataFrame queries use.
        for name in ("lineitem", "orders"):
            session.create_temp_view(
                name, session.create_dataframe(_scan_for(queries, name)),
                replace=True)
        session.enable_hyperspace()
        cases = {
            "tpch_q6": (
                "SELECT SUM(l_extendedprice * l_discount) AS revenue "
                "FROM lineitem WHERE l_shipdate BETWEEN DATE '1994-01-01' "
                "AND DATE '1994-12-31' AND l_discount BETWEEN 0.05 AND 0.07 "
                "AND l_quantity < 24"),
            "groupby_index": (
                "SELECT l_partkey, AVG(l_quantity) AS aq, COUNT(*) AS n "
                "FROM lineitem GROUP BY l_partkey "
                "ORDER BY l_partkey LIMIT 15"),
            # The headline join, written the natural way: the
            # filter-through-join pushdown sinks each WHERE conjunct to
            # its side, so this optimizes to the SAME plan as the
            # DataFrame version that filters below the join.
            "tpch_q3": (
                "SELECT l_orderkey, o_orderdate, o_shippriority, "
                "SUM(l_extendedprice * (1 - l_discount)) AS revenue "
                "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
                "WHERE l_shipdate > DATE '1995-03-15' "
                "AND o_orderdate < DATE '1995-03-15' "
                "GROUP BY l_orderkey, o_orderdate, o_shippriority "
                "ORDER BY revenue DESC, o_orderdate LIMIT 10"),
        }
        for name, text in cases.items():
            sql_plan = session.sql(text).optimized_plan().tree_string()
            df_plan = queries[name].optimized_plan().tree_string()
            assert sql_plan == df_plan, (
                f"{name}: SQL and DataFrame plans diverge\n--- sql ---\n"
                f"{sql_plan}\n--- df ---\n{df_plan}")


def _scan_for(queries, table):
    """The Scan leaf of the golden query set for a base table."""
    from hyperspace_tpu.plan.nodes import Scan
    probe = {"lineitem": "tpch_q1", "orders": "tpch_q18"}[table]
    for leaf in queries[probe].plan.collect_leaves():
        if isinstance(leaf, Scan) and \
                f"/{table}" in leaf.relation.describe():
            return leaf
    raise AssertionError(f"no scan for {table}")
