"""Tiered columnar buffer pool (execution/buffer_pool.py).

The cache layer UNDER the result cache: decoded, shape-class-padded
column buffers shared across queries and sessions, keyed by file
signature + column set + pruning selection. The acceptance surface:

- warm path: a literal-variant repeat of TPC-H q3 (result-cache miss by
  construction) executes with ZERO parquet reads and ZERO host→device
  scan transfers — counter-asserted, not timed;
- pool-on vs pool-off byte-identical across TPC-H + sampled TPC-DS;
- eviction ladders device→host→drop, padding preserved through the
  round trip;
- the "buffer.load" fault point degrades to a silent miss + re-read
  (never a wrong answer) and fails loud with degrade disabled;
- bufferPool.* conf keys stay OUT of the result-cache config hash;
- kill -9 proves the pool is purely process-local (no recovery
  surface, nothing on disk);
- telemetry: BufferPoolEvent family (BufferPoolHitEvent /
  BufferPoolMissEvent / BufferPoolEvictEvent), the "buffer_pool"
  metrics collector, Hyperspace.buffer_pool_stats(), and explain's
  I/O section line.
"""

import datetime
import os
import signal
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

import hyperspace_tpu as hst
from conftest import capture_logger as sink
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.execution import buffer_pool
from hyperspace_tpu.execution.buffer_pool import (BufferPool, PoolKey,
                                                  scan_key, table_nbytes)
from hyperspace_tpu.execution.columnar import (Column, Table,
                                               iter_dataset_chunks,
                                               read_parquet)
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.parallel import io as pio
from hyperspace_tpu.plan.expr import col, sum_
from hyperspace_tpu.robustness.constants import RobustnessConstants
from hyperspace_tpu.robustness.faults import (FaultRegistry,
                                              InjectedFaultError, scope)
from hyperspace_tpu.telemetry.events import (BufferPoolEvent,
                                             BufferPoolEvictEvent,
                                             BufferPoolHitEvent,
                                             BufferPoolMissEvent)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def fresh_pool():
    # Entries AND budgets reset around every test: the pool is a process
    # singleton and conf-driven budget refreshes outlive their session.
    pool = buffer_pool.get_pool()
    pool.clear()
    pool.set_budgets(4 << 30, 4 << 30)
    yield
    pool.clear()
    pool.set_budgets(4 << 30, 4 << 30)


def _table(n, valid_rows=None):
    return Table({"x": Column("int64", jnp.arange(n)),
                  "y": Column("float64", jnp.linspace(0.0, 1.0, n))},
                 valid_rows=valid_rows)


def _pk(i, nb=0):
    return PoolKey("scan", ("unit", i), nb)


def _write(d, n=300, seed=5):
    rng = np.random.default_rng(seed)
    os.makedirs(d, exist_ok=True)
    f = os.path.join(str(d), "p0.parquet")
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 40, n).astype(np.int64)),
        "v": pa.array(rng.uniform(0, 1, n))}), f)
    return f


class TestLadder:
    def test_demote_promote_drop_preserves_padding(self):
        t = _table(256, valid_rows=200)
        nb = table_nbytes(t)
        pool = BufferPool(device_bytes=2 * nb, host_bytes=2 * nb)
        pool.put(_pk(1), t)
        pool.put(_pk(2), _table(256))
        pool.put(_pk(3), _table(256))  # demotes LRU pk1 to host
        s = pool.stats()
        assert s["demotions"] == 1
        assert s["device_nbytes"] <= pool.device_bytes
        got = pool.get(_pk(1))  # host hit → promoted back into HBM
        assert got is not None
        s = pool.stats()
        assert s["host_hits"] == 1 and s["promotions"] == 1
        assert s["transfers"] == s["loads"] + 1
        # The demote/promote round trip kept the padded physical length
        # AND the logical row count (Table.to_host would have trimmed).
        assert got.column("x").data.shape[0] == 256
        assert got.valid_rows == 200
        np.testing.assert_array_equal(np.asarray(got.column("x").data),
                                      np.arange(256))
        # Overflow both tiers: the ladder ends in drops.
        for i in range(4, 10):
            pool.put(_pk(i), _table(256))
        s = pool.stats()
        assert s["evictions"] >= 1
        assert s["device_nbytes"] <= pool.device_bytes
        assert s["host_nbytes"] <= pool.host_bytes

    def test_oversize_rejected(self):
        t = _table(256)
        pool = BufferPool(device_bytes=table_nbytes(t) - 1, host_bytes=0)
        pool.put(_pk(1), t)
        s = pool.stats()
        assert s["rejections"] == 1 and s["admissions"] == 0
        assert pool.get(_pk(1)) is None

    def test_device_only_entries_drop_instead_of_demoting(self):
        t = _table(256)
        nb = table_nbytes(t)
        pool = BufferPool(device_bytes=nb, host_bytes=10 * nb)
        pool.put(_pk(1), t, nbytes=nb, device_only=True)
        pool.put(_pk(2), t, nbytes=nb, device_only=True)
        s = pool.stats()
        assert s["host_entries"] == 0 and s["demotions"] == 0
        assert s["evictions"] == 1
        assert pool.get(_pk(1)) is None and pool.get(_pk(2)) is not None


class TestInvalidation:
    def test_file_signature_flips_key_and_serves_new_bytes(self, tmp_path):
        f = _write(tmp_path / "d", n=300, seed=5)
        k1 = scan_key([f], ("k",), None)
        t1 = read_parquet([f], ["k"], pad_to_class=True)
        assert read_parquet([f], ["k"], pad_to_class=True) is t1
        # In-place rewrite (different row count ⇒ different size): the
        # signature embedded in the key changes, the stale entry is
        # simply unreachable — no explicit invalidation call anywhere.
        _write(tmp_path / "d", n=500, seed=6)
        k2 = scan_key([f], ("k",), None)
        assert k1 != k2
        t2 = read_parquet([f], ["k"], pad_to_class=True)
        assert t2 is not t1
        assert (t2.valid_rows or t2.num_rows) == 500

    def test_unpadded_and_optout_reads_bypass_the_pool(self, tmp_path):
        f = _write(tmp_path / "d")
        before = buffer_pool.pool_stats()
        read_parquet([f], ["k"])                         # exact read
        read_parquet([f], ["k"], pad_to_class=True, pool=False)
        after = buffer_pool.pool_stats()
        assert after["hits"] == before["hits"]
        assert after["misses"] == before["misses"]
        assert after["admissions"] == before["admissions"]


class TestStreamReplay:
    def test_chunk_for_chunk_byte_identical_replay(self, tmp_path):
        files = []
        for i in range(3):
            d = tmp_path / f"f{i}"
            files.append(_write(d, n=120, seed=i))
        first = list(iter_dataset_chunks(files, ["k", "v"], 100))
        ns0 = buffer_pool.get_pool().ns_counts("stream")
        second = list(iter_dataset_chunks(files, ["k", "v"], 100))
        assert buffer_pool.get_pool().ns_counts("stream")[0] == ns0[0] + 1
        assert len(second) == len(first) and len(first) >= 3
        for a, b in zip(first, second):
            assert a.to_arrow().equals(b.to_arrow())
        # An abandoned COLD iteration (fresh key: different chunk size)
        # must never poison the pool with a truncated sequence: later
        # full passes see the complete stream, and they match each
        # other chunk-for-chunk.
        it = iter_dataset_chunks(files, ["k", "v"], 50)
        next(it)
        it.close()
        third = list(iter_dataset_chunks(files, ["k", "v"], 50))
        fourth = list(iter_dataset_chunks(files, ["k", "v"], 50))
        assert sum(c.num_rows for c in third) == 360
        assert len(fourth) == len(third)
        for a, b in zip(third, fourth):
            assert a.to_arrow().equals(b.to_arrow())


class TestDegrade:
    def test_buffer_load_fault_is_a_silent_miss(self, tmp_path):
        f = _write(tmp_path / "d")
        t1 = read_parquet([f], ["k"], pad_to_class=True)
        before = buffer_pool.pool_stats()
        reg = FaultRegistry.from_conf_specs({"buffer.load": "error"},
                                            seed=7)
        with scope(reg):
            t2 = read_parquet([f], ["k"], pad_to_class=True)
        # Degrade contract (default on): the injected load failure
        # dropped the entry and reported a miss; the caller re-read.
        # Same bytes, never a wrong answer.
        assert t2 is not t1
        assert t2.to_arrow().equals(t1.to_arrow())
        after = buffer_pool.pool_stats()
        assert after["degraded_loads"] > before["degraded_loads"]
        assert after["invalidations"] > before["invalidations"]

    def test_fail_loud_with_degrade_disabled(self, tmp_path):
        f = _write(tmp_path / "d")
        read_parquet([f], ["k"], pad_to_class=True)
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        session.conf.set(RobustnessConstants.DEGRADE_ENABLED, "false")
        reg = FaultRegistry.from_conf_specs({"buffer.load": "error"},
                                            seed=9)
        with pio.use_session(session), scope(reg):
            with pytest.raises(InjectedFaultError):
                buffer_pool.get_pool().get(scan_key([f], ("k",), None))


class TestConfigHash:
    def test_result_cache_hit_survives_buffer_pool_toggle(self, tmp_path):
        from hyperspace_tpu.serving.constants import ServingConstants
        from hyperspace_tpu.serving.fingerprint import config_hash
        _write(tmp_path / "d")
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
        session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS,
                         "0")
        df = session.read.parquet(str(tmp_path / "d"))
        q = df.group_by("k").agg(sum_(col("v")).alias("sv"))
        h0 = config_hash(session)
        r1 = q.to_arrow()
        cache = session.result_cache
        s0 = cache.stats()
        # Flipping ANY bufferPool.* key is residency tuning, not result
        # identity: the config hash — and therefore the result-cache
        # entry — must survive the toggle.
        session.conf.set(IndexConstants.TPU_BUFFER_POOL_ENABLED, "false")
        session.conf.set(IndexConstants.TPU_BUFFER_POOL_DEVICE_BYTES,
                         str(1 << 20))
        assert config_hash(session) == h0
        assert session.result_cache is cache
        r2 = q.to_arrow()
        s1 = cache.stats()
        assert s1["hits"] == s0["hits"] + 1
        assert s1["misses"] == s0["misses"]
        assert r1.equals(r2)


@pytest.fixture(scope="module")
def tpc_env(tmp_path_factory):
    from goldstandard import tpc
    base = tmp_path_factory.mktemp("bp_tpc")
    session = hst.Session(system_path=str(base / "indexes"))
    session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
    root = str(base / "tpc")
    dfs = tpc.register_tables(session, root)
    return dict(session=session, dfs=dfs, root=root)


class TestWarmPath:
    def test_literal_variant_q3_repeat_zero_reads_zero_transfers(
            self, tpc_env, monkeypatch):
        """THE acceptance: q3, then a literal-variant q3 (different
        aggregate literal → result-cache fingerprint differs, scans
        identical). The second execution must do ZERO parquet reads and
        ZERO host→device scan transfers — every scan served from the
        device tier."""
        from goldstandard import tpc
        from hyperspace_tpu.execution import columnar
        dfs = tpc_env["dfs"]
        decodes = {"n": 0}
        real_read, real_pf = pq.read_table, pq.ParquetFile

        def counting_read(*a, **kw):
            decodes["n"] += 1
            return real_read(*a, **kw)

        def counting_pf(*a, **kw):
            decodes["n"] += 1
            return real_pf(*a, **kw)

        monkeypatch.setattr(columnar.pq, "read_table", counting_read)
        monkeypatch.setattr(columnar.pq, "ParquetFile", counting_pf)

        r1 = tpc.queries(dfs)["tpch_q3"].to_arrow()
        assert decodes["n"] > 0  # the cold run really decoded parquet

        li, od = dfs["lineitem"], dfs["orders"]
        cutoff = datetime.date(1995, 3, 15)
        variant = (
            li.filter(col("l_shipdate") > cutoff)
            .join(od.filter(col("o_orderdate") < cutoff),
                  on=col("l_orderkey") == col("o_orderkey"))
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(sum_(col("l_extendedprice") * (0.9 - col("l_discount")))
                 .alias("revenue"))
            .sort(("revenue", False), "o_orderdate").limit(10))
        before = buffer_pool.pool_stats()
        decodes["n"] = 0
        r2 = variant.to_arrow()
        after = buffer_pool.pool_stats()
        assert decodes["n"] == 0                       # 0 parquet reads
        assert after["transfers"] == before["transfers"]  # 0 h→d transfers
        assert after["misses"] == before["misses"]
        assert after["hits"] > before["hits"]
        assert after["decode_bytes_saved"] > before["decode_bytes_saved"]
        assert r1.num_rows > 0 and r2.num_rows > 0


class TestParity:
    def test_pool_on_vs_pool_off_byte_identical(self, tpc_env):
        """Full TPC-H set + sampled TPC-DS: a pool-off session (fresh
        plans, pool disabled by conf) must produce byte-identical
        results to the pool-on session's WARM executions — and must
        never touch the pool."""
        from goldstandard import tpc
        names = ["tpch_q1", "tpch_q3", "tpch_q6", "tpch_q12", "tpch_q14",
                 "tpch_q17", "self_join", "tpcds_q1_like",
                 "tpcds_q42_like"]
        qs_on = tpc.queries(tpc_env["dfs"])
        warm = {}
        for name in names:
            qs_on[name].to_arrow()          # cold: admit
            warm[name] = qs_on[name].to_arrow()   # warm: pool-served

        off = hst.Session(system_path=tpc_env["root"] + "_off_idx")
        off.conf.set(IndexConstants.TPU_BUFFER_POOL_ENABLED, "false")
        qs_off = tpc.queries(tpc.register_tables(off, tpc_env["root"]))
        probes0 = buffer_pool.pool_stats()
        for name in names:
            assert qs_off[name].to_arrow().equals(warm[name]), name
        probes1 = buffer_pool.pool_stats()
        assert probes1["hits"] == probes0["hits"]
        assert probes1["misses"] == probes0["misses"]


class TestObservability:
    def test_events_metrics_stats_and_explain(self, tmp_path):
        f1 = _write(tmp_path / "d1", seed=1)
        _write(tmp_path / "d2", seed=2)
        session = hst.Session(system_path=str(tmp_path / "indexes"))
        session.conf.set(IndexConstants.EVENT_LOGGER_CLASS,
                         "tests.conftest.CaptureLogger")
        hs = Hyperspace(session)
        nb = table_nbytes(read_parquet([f1], None, pad_to_class=True,
                                       pool=False))
        buffer_pool.get_pool().clear()
        # Budget fits one scan + slack but not two: the second admit
        # demotes the first — miss, hit, and demotion events in one run.
        session.conf.set(IndexConstants.TPU_BUFFER_POOL_DEVICE_BYTES,
                         str(int(1.5 * nb)))
        session.conf.set(IndexConstants.TPU_BUFFER_POOL_HOST_BYTES,
                         str(4 * nb))
        mark = len(sink().events)
        with pio.use_session(session):
            read_parquet([f1], None, pad_to_class=True)   # miss + admit
            read_parquet([f1], None, pad_to_class=True)   # device hit
            read_parquet([str(tmp_path / "d2" / "p0.parquet")], None,
                         pad_to_class=True)               # evicts f1
        evs = [e for e in sink().events[mark:]
               if isinstance(e, BufferPoolEvent)]
        kinds = [type(e).__name__ for e in evs]
        assert "BufferPoolMissEvent" in kinds
        assert "BufferPoolHitEvent" in kinds
        assert "BufferPoolEvictEvent" in kinds
        hit = next(e for e in evs if isinstance(e, BufferPoolHitEvent))
        assert hit.namespace == "scan" and hit.tier == "device"
        assert hit.nbytes > 0
        evict = next(e for e in evs
                     if isinstance(e, BufferPoolEvictEvent))
        assert evict.demoted  # host tier had room: demotion, not drop
        assert not any(isinstance(e, BufferPoolMissEvent) and e.reason
                       for e in evs)  # no fault-degraded probes here

        stats = hs.buffer_pool_stats()
        assert stats["hits"] >= 1 and stats["transfers"] >= 2
        # The collector every worker's OpenMetrics scrape carries
        # fleet-wide (no cross-process byte shipping — stats only).
        assert "buffer_pool" in hs.metrics()["collectors"]

        # A prefetch stream makes explain's I/O section render
        # deterministically (it gates on the process-wide io counters).
        with pio.use_session(session):
            list(iter_dataset_chunks([f1], ["k"], 100))
        df = session.read.parquet(str(tmp_path / "d1"))
        df.filter(col("k") >= 0).select("k", "v").to_pandas()
        text = hs.explain(df.filter(col("k") >= 0).select("k", "v"))
        assert "buffer pool: hits=" in text
        assert "decode_bytes_saved=" in text


_CHILD_WARM = """\
import os, signal, sys
from hyperspace_tpu.execution import buffer_pool
from hyperspace_tpu.execution.columnar import read_parquet
f = sys.argv[1]
t1 = read_parquet([f], None, pad_to_class=True)
t2 = read_parquet([f], None, pad_to_class=True)
assert t2 is t1
s = buffer_pool.pool_stats()
assert s["hits"] == 1 and s["admissions"] == 1, s
print("WARM", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""

_CHILD_COLD = """\
import sys
from hyperspace_tpu.execution import buffer_pool
from hyperspace_tpu.execution.buffer_pool import scan_key
f = sys.argv[1]
s = buffer_pool.pool_stats()
assert s["hits"] == 0 and s["admissions"] == 0, s
assert buffer_pool.get_pool().get(scan_key([f], None, None)) is None
print("COLD-MISS", flush=True)
"""


class TestProcessLocal:
    def test_kill9_leaves_nothing_behind_and_next_process_starts_cold(
            self, tmp_path):
        """kill -9 a process with a warm pool: nothing to recover,
        nothing recovered. The pool has NO disk presence — the data
        directory is untouched and a fresh process probes cold."""
        f = _write(tmp_path / "d")
        listing0 = sorted(os.listdir(tmp_path / "d"))

        def run(body):
            script = str(tmp_path / "child.py")
            with open(script, "w") as fh:
                fh.write(body)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            env["PYTHONPATH"] = ROOT + os.pathsep + env.get(
                "PYTHONPATH", "")
            return subprocess.run([sys.executable, script, f], env=env,
                                  capture_output=True, text=True,
                                  timeout=300, cwd=ROOT)

        warm = run(_CHILD_WARM)
        assert warm.returncode == -signal.SIGKILL, warm.stderr
        assert "WARM" in warm.stdout
        assert sorted(os.listdir(tmp_path / "d")) == listing0
        cold = run(_CHILD_COLD)
        assert cold.returncode == 0, cold.stderr
        assert "COLD-MISS" in cold.stdout
