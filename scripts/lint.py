#!/usr/bin/env python
"""Self-contained lint gate (no third-party linters in the image).

The reference gates compile+test behind scalastyle (build.sbt:96-101);
this is the equivalent style gate for CI here: every source must compile,
carry no tabs/trailing whitespace, respect the line-length cap, and not
import modules it never uses (package code only). Exit code 1 on any
violation; run as `python scripts/lint.py`.
"""

from __future__ import annotations

import ast
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MAX_LINE = 100
PACKAGE_DIRS = ("hyperspace_tpu",)
ALL_DIRS = ("hyperspace_tpu", "tests", "scripts")
TOP_FILES = ("bench.py", "__graft_entry__.py")


def iter_sources():
    for d in ALL_DIRS:
        for r, _dirs, files in os.walk(os.path.join(ROOT, d)):
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(r, f)
    for f in TOP_FILES:
        yield os.path.join(ROOT, f)


def unused_imports(tree: ast.AST) -> list:
    imported = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and len(node.value) < 200:
            # Forward-reference annotations ('"HyperspaceConf"') count.
            import re
            used.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
    # Strings can reference names (docstrings citing symbols don't count,
    # but __all__ / annotations-as-strings do); be conservative.
    return sorted((line, name) for name, line in imported.items()
                  if name not in used and not name.startswith("_"))


def main() -> int:
    problems = []
    for path in iter_sources():
        rel = os.path.relpath(path, ROOT)
        with open(path, encoding="utf-8") as f:
            text = f.read()
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        for i, line in enumerate(text.splitlines(), 1):
            if "\t" in line:
                problems.append(f"{rel}:{i}: tab character")
            if line != line.rstrip():
                problems.append(f"{rel}:{i}: trailing whitespace")
            if len(line) > MAX_LINE:
                problems.append(f"{rel}:{i}: line longer than {MAX_LINE}")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and os.path.basename(path) != "__init__.py":  # re-exports
            for line, name in unused_imports(tree):
                problems.append(f"{rel}:{line}: unused import '{name}'")
    for p in problems:
        print(p)
    print(f"lint: {len(problems)} problem(s) across "
          f"{sum(1 for _ in iter_sources())} files")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
