#!/usr/bin/env python
"""Single lint entrypoint — a thin shim over scripts/analysis/.

`python scripts/lint.py` behaves exactly as it always has (one line per
problem, `lint: N problem(s) across M files`, exit 1 on problems), but
the work happens in the multi-pass framework under scripts/analysis/:
the ported monolith gates plus the HS3xx dataflow passes (lock
discipline, jit host-sync accounting, thread handoff), suppressions,
baseline, and `--json` output. See docs/static_analysis.md for the
pass catalog and `python scripts/lint.py --help` for flags.

For existing tests that import gate helpers from this file, the
monolith's pure functions and frozen allowlists are re-exported from
their verbatim home, scripts/analysis/legacy_reference.py.
"""

from __future__ import annotations

import os
import sys

_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS_DIR not in sys.path:
    sys.path.insert(0, _SCRIPTS_DIR)

from analysis.legacy_reference import (  # noqa: F401,E402  (re-exports)
    ALL_DIRS,
    CONFIG_DOC,
    CONFIG_KEY_PATTERN,
    ENV_READ_ALLOWLIST,
    EVENTS_FILE,
    EXCEPT_SWALLOW_ALLOWLIST,
    FAULT_NAMES_FILE,
    FUSION_BOUNDARIES_FILE,
    JIT_SITE_ALLOWLIST,
    MAX_LINE,
    METRIC_NAMES_FILE,
    MUTABLE_STATE_ALLOWLIST,
    PACKAGE_DIRS,
    SPAN_NAMES_FILE,
    SPMD_BANNED_NAMES,
    SPMD_JIT_SHARDING_MODULES,
    THREAD_SITE_ALLOWLIST,
    TOP_FILES,
    config_key_literals,
    env_reads,
    event_class_names,
    except_swallow_sites,
    fault_site_violations,
    fusion_boundary_violations,
    iter_sources,
    jit_sharding_violations,
    jit_sites,
    metric_site_violations,
    mutable_state_sites,
    span_name_constants,
    span_site_violations,
    spmd_banned_sites,
    thread_sites,
    unused_imports,
)
from analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
