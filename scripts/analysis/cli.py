"""Command line for the static-analysis framework.

``python scripts/lint.py`` (the thin shim over this module) keeps the
monolith's contract: print one line per problem, a trailing
``lint: N problem(s) across M files`` summary, exit 1 on any active
problem. Flags:

- ``--json``            machine-readable findings (codes, anchors,
                        related sites, suppression/baseline state);
- ``--no-cache``        ignore and do not write the findings cache;
- ``--ported-only``     run only the ported monolith gates (the parity
                        surface the tests compare against
                        legacy_reference);
- ``--exemptions``      print every frozen-allowlist entry with its
                        one-line justification, then exit 0;
- ``--write-baseline``  grandfather all current findings into
                        scripts/analysis/baseline.json;
- ``--baseline PATH``   use a different baseline file.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from . import engine


def _print_exemptions() -> None:
    from . import (handoff_pass, hostsync_pass, lock_pass,
                   serialization_pass)
    lines = (lock_pass.describe_exemptions()
             + hostsync_pass.describe_exemptions()
             + handoff_pass.describe_exemptions()
             + serialization_pass.describe_exemptions())
    print("frozen exemptions (each carries its justification; unused "
          "entries fail lint as HS004):")
    for ln in lines:
        print("  " + ln)


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(
        prog="lint.py",
        description="hyperspace_tpu static analysis "
                    "(docs/static_analysis.md)")
    p.add_argument("--json", action="store_true", dest="as_json")
    p.add_argument("--no-cache", action="store_true")
    p.add_argument("--ported-only", action="store_true")
    p.add_argument("--exemptions", action="store_true")
    p.add_argument("--write-baseline", action="store_true")
    p.add_argument("--baseline", default=None)
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    args = p.parse_args(argv)

    if args.exemptions:
        _print_exemptions()
        return 0
    if args.write_baseline:
        path = engine.write_baseline(args.root, args.baseline)
        print(f"baseline written: {path}")
        return 0

    result = engine.run(args.root, ported_only=args.ported_only,
                        use_cache=not args.no_cache,
                        baseline_path=args.baseline)
    if args.as_json:
        print(json.dumps(result.to_json(), indent=2))
    else:
        print(result.render_text())
    return 1 if result.active() else 0


if __name__ == "__main__":
    sys.exit(main())
