"""HS321 — thread-handoff checker (the r14 worker-fault bug class).

Pool workers and raw threads never inherit the submitter's contextvars:
a callable that reads ambient per-query state inside the worker — the
armed fault registry, the active QueryContext, the io session scope,
the trace — silently gets defaults there. The r14 fix pattern is
explicit: either snapshot ``contextvars.copy_context()`` and run the
callable inside it, or capture the state consumer-side and pass it as
an explicit argument (``fault_point(name, reg=...)``).

This pass checks every handoff site in package code:

- ``threading.Thread(target=...)`` construction,
- ``submit_serving(fn, ...)`` (the sanctioned serving-pool entry),
- ``<executor>.submit(fn, ...)`` where the first argument resolves to a
  local function/method (a non-callable first argument — e.g. a
  DataFrame handed to ``ServingFrontend.submit`` — is not a thread
  handoff and is skipped).

A handoff is clean when the callable is a ``Context.run`` bound from
``contextvars.copy_context()``, or when its transitive local body
(module-level functions, ``self`` methods, nested defs; depth-bounded)
performs no ambient context read. Ambient reads: ``active_context`` /
``active_params`` / ``active_session`` / ``armed`` /
``check_deadline`` / ``deadline_remaining_s`` calls,
``<ContextVar>.get()`` on a module-level ContextVar,
``fault_point(name)`` WITHOUT an explicit ``reg=``, and
``trace.span``/``trace.add_span``. Reads delegated through an explicit
``<ctx>.run(...)`` (the r14 idiom inside the serving drain loop) do not
count — the context is handed over, which is the point.

Deliberate exceptions go in :data:`HANDOFF_ALLOWLIST` with a one-line
justification (printed by ``--exemptions``); unused entries are HS004.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from . import dataflow as df
from .diagnostics import Diagnostic, Related

CONTEXT_READERS = frozenset({
    "active_context", "active_params", "active_session", "armed",
    "check_deadline", "deadline_remaining_s",
})
_TRACE_RECEIVERS = ("trace", "_trace", "_tr")
_MAX_DEPTH = 5

# (slash rel, qualname of the function containing the handoff site)
# -> justification.
HANDOFF_ALLOWLIST: dict = {
    # (empty: the tree is clean — r14 fixed the last of this class.
    #  Entries added here must explain how the callable gets its
    #  context state without the ambient contextvars.)
}


def exemption_ids() -> dict:
    return {f"{rel}#handoff:{fn}": why
            for (rel, fn), why in HANDOFF_ALLOWLIST.items()}


def describe_exemptions() -> List[str]:
    return [f"handoff[{rel}::{fn}]: {why}"
            for (rel, fn), why in sorted(HANDOFF_ALLOWLIST.items())]


def _contextvar_names(src) -> Set[str]:
    out: Set[str] = set()
    for node in src.tree.body:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call):
            name = df.dotted_name(node.value.func)
            if name.split(".")[-1] == "ContextVar":
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.value, ast.Call):
            name = df.dotted_name(node.value.func)
            if name.split(".")[-1] == "ContextVar" \
                    and isinstance(node.target, ast.Name):
                out.add(node.target.id)
    return out


def _ambient_reads(fn_node, cv_names: Set[str]) -> list:
    """(node, what) ambient context reads performed directly in this
    function's own body."""
    out = []
    for node in df.walk_own(fn_node):
        if not isinstance(node, ast.Call):
            continue
        name = df.dotted_name(node.func)
        leaf = name.split(".")[-1] if name else ""
        if leaf in CONTEXT_READERS:
            out.append((node, f"{leaf}()"))
        elif leaf == "fault_point":
            kws = {k.arg for k in node.keywords}
            if "reg" not in kws and len(node.args) < 2:
                out.append((node, "fault_point() without explicit reg="))
        elif leaf == "get" and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in cv_names:
            out.append((node, f"{node.func.value.id}.get()"))
        elif leaf in ("span", "add_span") \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in _TRACE_RECEIVERS:
            out.append((node, f"trace.{leaf}()"))
    return out


def _local_calls(fn_node) -> list:
    """(kind, name) of calls resolvable locally: ('name', f) for bare
    names, ('self', m) for self.m(...)."""
    out = []
    for node in df.walk_own(fn_node):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name):
            out.append(("name", f.id))
        elif isinstance(f, ast.Attribute) \
                and isinstance(f.value, ast.Name) and f.value.id == "self":
            out.append(("self", f.attr))
    return out


def _is_copied_context_run(expr, site_fn, funcs) -> bool:
    """``ctx.run`` where ``ctx`` was bound from
    ``contextvars.copy_context()`` in an enclosing function."""
    if not (isinstance(expr, ast.Attribute) and expr.attr == "run"
            and isinstance(expr.value, ast.Name)):
        return False
    var = expr.value.id
    fn = site_fn
    while fn is not None:
        for node in df.walk_own(fn.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and df.dotted_name(node.value.func).split(".")[-1] \
                    == "copy_context":
                if any(isinstance(t, ast.Name) and t.id == var
                       for t in node.targets):
                    return True
        fn = fn.parent
    return False


def _resolve_target(expr, site_fn, funcs, cls_of_site: Optional[str]):
    """FuncInfo for the submitted callable, or None when opaque."""
    if isinstance(expr, ast.Lambda):
        return df.FuncInfo(expr, "<lambda>", site_fn, None)
    if isinstance(expr, ast.Name):
        return df.resolve_callable(expr.id, site_fn, funcs)
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self" and cls_of_site:
        return df.resolve_method(cls_of_site, expr.attr, funcs)
    return None


def _scan_transitive(start, funcs, cv_names, cls: Optional[str]):
    """First ambient read reachable from ``start`` through local calls
    (depth-bounded, cycle-safe), or None."""
    seen: Set[int] = set()
    frontier = [(start, 0)]
    while frontier:
        info, depth = frontier.pop(0)
        if id(info.node) in seen or depth > _MAX_DEPTH:
            continue
        seen.add(id(info.node))
        reads = _ambient_reads(info.node, cv_names)
        if reads:
            return reads[0]
        for kind, name in _local_calls(info.node):
            nxt = None
            if kind == "name":
                nxt = df.resolve_callable(name, info, funcs)
            elif kind == "self":
                c = info.cls if info.cls else cls
                if c:
                    nxt = df.resolve_method(c, name, funcs)
            if nxt is not None:
                frontier.append((nxt, depth + 1))
    return None


def _handoff_sites(src) -> list:
    """(call node, callable expr) for every thread-handoff site."""
    out = []
    for node in src.index.of(ast.Call):
        name = df.dotted_name(node.func)
        leaf = name.split(".")[-1] if name else ""
        if leaf == "Thread" and (name == "threading.Thread"
                                 or name == "Thread"):
            target = None
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
            if target is None and len(node.args) >= 2:
                target = node.args[1]
            if target is not None:
                out.append((node, target))
        elif leaf == "submit_serving" and node.args:
            out.append((node, node.args[0]))
        elif leaf == "submit" and isinstance(node.func, ast.Attribute) \
                and node.args:
            # Non-callable first args (ServingFrontend.submit takes a
            # DataFrame/plan) fail resolution below and are skipped.
            out.append((node, node.args[0]))
    return out


def check_file(src, ctx) -> List[Diagnostic]:
    if not src.is_package:
        return []
    sites = _handoff_sites(src)
    if not sites:
        return []
    out: List[Diagnostic] = []
    rel = src.rel
    funcs = df.function_map(src.tree)
    cv_names = _contextvar_names(src)

    # Which function each site sits in (for resolution scope).
    def enclosing(node) -> Optional[df.FuncInfo]:
        best = None
        for info in funcs.values():
            f = info.node
            if f.lineno <= node.lineno <= max(
                    getattr(f, "end_lineno", f.lineno), f.lineno):
                if best is None or f.lineno > best.node.lineno:
                    best = info
        return best

    for call, target in sites:
        site_fn = enclosing(call)
        cls = site_fn.cls if site_fn is not None else None
        if cls is None and site_fn is not None:
            p = site_fn
            while p is not None and cls is None:
                cls = p.cls
                p = p.parent
        if _is_copied_context_run(target, site_fn, funcs):
            continue
        resolved = _resolve_target(target, site_fn, funcs, cls)
        if resolved is None:
            # Opaque callable: a parameter-passed fn (submit_serving's
            # own body) or a bound method of another object. The
            # CALLER's handoff site is where the check applies.
            continue
        qual = site_fn.qualname if site_fn is not None else "<module>"
        read = _scan_transitive(resolved, funcs, cv_names, cls)
        if read is None:
            continue
        entry = HANDOFF_ALLOWLIST.get((src.slash_rel, qual))
        if entry is not None:
            ctx.note_exemption(f"{src.slash_rel}#handoff:{qual}")
            continue
        rnode, what = read
        out.append(Diagnostic(
            "HS321", rel, call.lineno,
            f"callable '{resolved.qualname}' handed to a worker thread "
            f"in {qual} reads ambient context ({what} at line "
            f"{rnode.lineno}) that pool threads never inherit; wrap "
            "the submission in contextvars.copy_context().run or pass "
            "the state as an explicit argument (r14 contract)",
            col=call.col_offset,
            related=Related(rel, rnode.lineno, what)))
    return out
