"""Typed diagnostic model of the static-analysis framework.

Every finding any pass emits is one :class:`Diagnostic` — a stable
``HS###`` code, a ``file:line:col`` anchor, a human message, and an
optional *related* site (the second location a dataflow finding points
at: the lock that should have been held, the contextvar read a thread
handoff loses, the jit entry a traced sync sits under).

Code space (frozen; docs/static_analysis.md carries the same table and
the HS003 drift pass keeps the two in lockstep):

- ``HS0xx`` — the framework itself (syntax, suppressions, baselines,
  registry hygiene);
- ``HS1xx`` — style gates ported from the retired monolith;
- ``HS2xx`` — discipline gates ported from the retired monolith;
- ``HS3xx`` — the dataflow passes (lock discipline, host-sync
  accounting, thread handoff).

Ported gates keep their pre-framework message text byte-identical (the
parity contract with ``legacy_reference.collect``), so their rendered
line omits the code; ``--json`` carries codes for every finding.

Suppression: a source line may carry ``# hst: disable=HS###`` (comma-
separated for several codes) to silence findings anchored on that line.
A directive that silences nothing is itself a finding (``HS002``).
"""

from __future__ import annotations

from typing import Optional

# code -> one-line title. Keys are unique by construction (dict); the
# uniqueness TEST (tests/test_static_analysis.py) guards against a
# duplicate literal silently overwriting an entry, mirroring the
# span/fault-names frozen-registry precedent.
CODES = {
    "HS001": "syntax error",
    "HS002": "unused suppression directive",
    "HS003": "HS-code documentation drift",
    "HS004": "unused frozen-registry exemption",
    "HS005": "stale baseline entry",
    "HS101": "tab character",
    "HS102": "trailing whitespace",
    "HS103": "line longer than the cap",
    "HS104": "unused import",
    "HS201": "ad-hoc environment read",
    "HS202": "undocumented config key",
    "HS203": "jax.jit outside the instrumented modules",
    "HS204": "shard_map/pmap is banned repo-wide",
    "HS205": "unstated sharding on a distributed jit",
    "HS206": "module-level mutable state",
    "HS207": "free-form span name",
    "HS208": "free-form fault-point name",
    "HS209": "free-form fusion-boundary kind",
    "HS210": "exception swallowing",
    "HS211": "thread construction outside parallel/io.py",
    "HS212": "event class never observed by tests",
    "HS213": "span name never observed by tests",
    "HS214": "fault point never injected by tests",
    "HS215": "fusion boundary never exercised by tests",
    "HS216": "free-form metric name",
    "HS217": "metric name never observed by tests",
    "HS301": "unguarded shared-state mutation",
    "HS302": "unguarded read-modify-write",
    "HS311": "host sync inside traced code",
    "HS312": "unallowlisted host sync at a jit-adjacent site",
    "HS321": "raw thread handoff of context-dependent work",
    "HS331": "executable serialization outside the artifact store",
    "HS341": "socket creation outside the sanctioned modules",
    "HS342": "parquet decode or device transfer outside the buffer-pool "
             "modules",
}

# Raw source text of a suppression directive (engine.py owns parsing).
SUPPRESS_DIRECTIVE = "hst: disable="


class Related:
    """The second site a two-point finding references."""

    __slots__ = ("path", "line", "note")

    def __init__(self, path: str, line: int, note: str = ""):
        self.path = path
        self.line = line
        self.note = note

    def to_json(self) -> dict:
        out = {"path": self.path, "line": self.line}
        if self.note:
            out["note"] = self.note
        return out


class Diagnostic:
    __slots__ = ("code", "path", "line", "col", "message", "related",
                 "legacy_text", "suppressed", "baselined")

    def __init__(self, code: str, path: str, line: int, message: str,
                 col: int = 0, related: Optional[Related] = None,
                 legacy_text: Optional[str] = None):
        if code not in CODES:
            raise ValueError(f"unknown diagnostic code {code!r}")
        self.code = code
        self.path = path
        self.line = line
        self.col = col
        self.message = message
        self.related = related
        # Ported gates carry the monolith's exact output line here; the
        # text renderer prints it verbatim (the parity contract).
        self.legacy_text = legacy_text
        self.suppressed = False
        self.baselined = False

    def text(self) -> str:
        if self.legacy_text is not None:
            return self.legacy_text
        out = f"{self.path}:{self.line}:{self.col}: {self.code}: " \
              f"{self.message}"
        if self.related is not None:
            out += f" (related: {self.related.path}:{self.related.line}"
            if self.related.note:
                out += f" — {self.related.note}"
            out += ")"
        return out

    def to_json(self) -> dict:
        out = {
            "code": self.code,
            "title": CODES[self.code],
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
            "suppressed": self.suppressed,
            "baselined": self.baselined,
        }
        if self.related is not None:
            out["related"] = self.related.to_json()
        return out

    @classmethod
    def from_json(cls, d: dict) -> "Diagnostic":
        rel = d.get("related")
        out = cls(d["code"], d["path"], d["line"], d["message"],
                  col=d.get("col", 0),
                  related=Related(rel["path"], rel["line"],
                                  rel.get("note", ""))
                  if rel else None,
                  legacy_text=d.get("legacy_text"))
        return out

    def to_cache(self) -> dict:
        """Cache serialization: like to_json plus the verbatim legacy
        line (suppressed/baselined are re-derived per run)."""
        out = self.to_json()
        del out["suppressed"], out["baselined"], out["title"]
        if self.legacy_text is not None:
            out["legacy_text"] = self.legacy_text
        return out
