"""Pass manager: parse every source exactly once, share one AST walk.

The retired monolith (legacy_reference.py) ran ~a dozen independent
``ast.walk`` traversals per file per run — one per gate — plus three
extra parses of the frozen-name registry files. The manager here:

- loads the file list once (the monolith's own ``iter_sources`` order,
  so finding order is byte-identical);
- parses each file exactly ONCE (``Result.parse_count`` asserts it);
- builds ONE :class:`NodeIndex` per tree (a single ``ast.walk``) that
  every pass consumes — a ported gate that used to re-walk the whole
  tree now iterates just its node types;
- runs the ported gates in the monolith's exact order (per file, then
  the four coverage finalizers), then the dataflow passes, then the
  framework's own hygiene checks;
- applies ``# hst: disable=HS###`` line suppressions (flagging unused
  directives, HS002) and the optional checked-in baseline
  (``scripts/analysis/baseline.json``; stale entries are HS005);
- memoizes per-file findings in a content-hash cache
  (``scripts/analysis/.lint_cache.json``, git-ignored) keyed by the
  file's sha AND an environment fingerprint covering the analyzer's own
  sources, the docs the doc-drift gates read, and the frozen-name
  registries — so a warm run re-analyzes only what changed.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from typing import Dict, List, Optional

from . import legacy_reference as legacy
from .diagnostics import CODES, Diagnostic

DEFAULT_ROOT = legacy.ROOT
BASELINE_REL = os.path.join("scripts", "analysis", "baseline.json")
CACHE_REL = os.path.join("scripts", "analysis", ".lint_cache.json")
STATIC_ANALYSIS_DOC = os.path.join("docs", "static_analysis.md")
_CACHE_VERSION = 1

_SUPPRESS_RE = re.compile(
    r"#\s*hst:\s*disable=([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*)")


class NodeIndex:
    """All nodes of a tree grouped by type, from ONE ``ast.walk``.

    ``ast.walk`` is breadth-first; each per-type list preserves that
    order, so a gate iterating ``index.of(ast.Call)`` sees call nodes in
    exactly the order its ``ast.walk`` loop used to — the property the
    byte-identical-output parity contract rides on.
    """

    def __init__(self, tree: ast.AST):
        by_type: Dict[type, list] = {}
        order: Dict[int, int] = {}
        for i, node in enumerate(ast.walk(tree)):
            by_type.setdefault(type(node), []).append(node)
            order[id(node)] = i
        self._by_type = by_type
        self._order = order

    def of(self, *types) -> list:
        if len(types) == 1:
            return self._by_type.get(types[0], [])
        out: list = []
        for t in types:
            out.extend(self._by_type.get(t, []))
        # Multi-type queries re-merge into walk order, so gates that
        # fold several node types into one stateful scan (e.g. the
        # unused-import dict, where a later import shadows an earlier
        # one) behave exactly like their ast.walk originals.
        out.sort(key=lambda n: self._order[id(n)])
        return out


class SourceFile:
    """One loaded source: text always; tree/index only when analyzed
    this run (a cache hit never parses)."""

    def __init__(self, root: str, path: str):
        self.path = path
        self.rel = os.path.relpath(path, root)
        self.slash_rel = self.rel.replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.sha = hashlib.sha256(self.text.encode("utf-8")).hexdigest()
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.syntax_error: Optional[SyntaxError] = None
        self._index: Optional[NodeIndex] = None
        self.parsed = False

    def parse(self) -> None:
        if self.parsed:
            return
        self.parsed = True
        try:
            self.tree = ast.parse(self.text, filename=self.rel)
        except SyntaxError as e:
            self.syntax_error = e

    @property
    def index(self) -> NodeIndex:
        if self._index is None:
            if self.tree is None:
                raise RuntimeError(f"{self.rel}: no tree to index")
            self._index = NodeIndex(self.tree)
        return self._index

    def in_dirs(self, dirs) -> bool:
        return any(self.rel.startswith(d + os.sep) for d in dirs)

    @property
    def is_package(self) -> bool:
        return self.in_dirs(legacy.PACKAGE_DIRS)

    @property
    def is_test(self) -> bool:
        return self.rel.startswith("tests" + os.sep)

    def suppressions(self) -> Dict[int, set]:
        """line number -> set of codes a directive on that line names.
        Only real COMMENT tokens count — a directive spelled inside a
        string literal (fixture snippets, docs) is not a directive.
        The tokenize pass runs only for files whose raw text mentions
        the marker at all, so the common case stays one substring
        check."""
        if "hst: disable=" not in self.text:
            return {}
        import io
        import tokenize
        out: Dict[int, set] = {}
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _SUPPRESS_RE.search(tok.string)
                if m:
                    out.setdefault(tok.start[0], set()).update(
                        c.strip() for c in m.group(1).split(","))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # Unparsable file: fall back to the line scan (the syntax
            # gate already owns the real failure).
            for i, line in enumerate(self.lines, 1):
                m = _SUPPRESS_RE.search(line)
                if m:
                    out[i] = {c.strip() for c in m.group(1).split(",")}
        return out


class Context:
    """Shared run state every pass reads (built once per run)."""

    def __init__(self, root: str, sources: List[SourceFile]):
        self.root = root
        self.sources = sources
        self.by_rel = {s.slash_rel: s for s in sources}
        with open(os.path.join(root, legacy.CONFIG_DOC),
                  encoding="utf-8") as f:
            self.config_doc_text = f.read()
        self.span_names = self._registry(legacy.SPAN_NAMES_FILE)
        self.fault_names = self._registry(legacy.FAULT_NAMES_FILE)
        self.fusion_kinds = self._registry(legacy.FUSION_BOUNDARIES_FILE)
        self.metric_names = self._registry(legacy.METRIC_NAMES_FILE)
        # Facts the finalizers consume; per-file passes (or the cache)
        # fill them in file order.
        self.event_classes: list = []
        self.registry_hits: Dict[str, set] = {
            "span": set(), "fault": set(), "fusion": set(),
            "metric": set(), "event": set()}
        self.used_exemptions: set = set()
        # Exemption ids the CURRENT file's dataflow passes consumed —
        # drained into the per-file cache entry by the engine.
        self._file_exemptions: set = set()

    def note_exemption(self, eid: str) -> None:
        self._file_exemptions.add(eid)

    def pop_file_exemptions(self) -> set:
        out = self._file_exemptions
        self._file_exemptions = set()
        return out

    def _registry(self, rel: str) -> dict:
        with open(os.path.join(self.root, rel), encoding="utf-8") as f:
            return legacy.span_name_constants(ast.parse(f.read()))

    def note_test_text(self, src: SourceFile) -> dict:
        """Which registered names this test file's text mentions — the
        coverage gates' substring-containment check, made per-file so it
        caches. The events file precedes tests/ in source order
        (hyperspace_tpu walks first), so ``event_classes`` is always
        populated by the time a test file lands here; a change to the
        events file invalidates the whole cache via the env
        fingerprint."""
        return {
            "span": [v for v in self.span_names.values()
                     if v in src.text],
            "fault": [v for v in self.fault_names.values()
                      if v in src.text],
            "fusion": [v for v in self.fusion_kinds.values()
                       if v in src.text],
            "metric": [v for v in self.metric_names.values()
                       if v in src.text],
            "event": [n for n in self.event_classes if n in src.text],
        }

    def absorb_test_hits(self, hits: dict) -> None:
        for k in ("span", "fault", "fusion", "metric", "event"):
            self.registry_hits[k].update(hits.get(k, []))


class Result:
    def __init__(self, problems: List[Diagnostic], file_count: int,
                 parse_count: int):
        self.problems = problems
        self.file_count = file_count
        self.parse_count = parse_count

    def active(self) -> List[Diagnostic]:
        return [d for d in self.problems
                if not d.suppressed and not d.baselined]

    def render_text(self) -> str:
        lines = [d.text() for d in self.active()]
        # Exactly the monolith's summary wording.
        lines.append(f"lint: {len(self.active())} problem(s) across "
                     f"{self.file_count} files")
        return "\n".join(lines)

    def to_json(self) -> dict:
        return {
            "version": 1,
            "files": self.file_count,
            "problems": [d.to_json() for d in self.problems],
            "count": len(self.active()),
        }


# ---------------------------------------------------------------------------
# Environment fingerprint + cache.
# ---------------------------------------------------------------------------

def _env_fingerprint(root: str) -> str:
    """sha over everything that can change a finding besides the file
    itself: the analyzer's own sources, the doc files the drift gates
    compare against, the frozen-name registries, the events taxonomy,
    and the baseline."""
    h = hashlib.sha256()
    here = os.path.dirname(os.path.abspath(__file__))
    for name in sorted(os.listdir(here)):
        if name.endswith(".py"):
            with open(os.path.join(here, name), "rb") as f:
                h.update(name.encode())
                h.update(f.read())
    for rel in (legacy.CONFIG_DOC, STATIC_ANALYSIS_DOC,
                legacy.SPAN_NAMES_FILE, legacy.FAULT_NAMES_FILE,
                legacy.FUSION_BOUNDARIES_FILE, legacy.METRIC_NAMES_FILE,
                legacy.EVENTS_FILE, BASELINE_REL):
        p = os.path.join(root, rel)
        h.update(rel.encode())
        if os.path.exists(p):
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


def _load_cache(root: str, env: str) -> dict:
    try:
        with open(os.path.join(root, CACHE_REL), encoding="utf-8") as f:
            cache = json.load(f)
        if cache.get("version") == _CACHE_VERSION \
                and cache.get("env") == env:
            return cache.get("files", {})
    except Exception:
        pass
    return {}


def _save_cache(root: str, env: str, files: dict) -> None:
    try:
        path = os.path.join(root, CACHE_REL)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": _CACHE_VERSION, "env": env,
                       "files": files}, f)
        os.replace(tmp, path)
    except Exception:
        pass  # the cache is an optimization, never a failure


# ---------------------------------------------------------------------------
# The run.
# ---------------------------------------------------------------------------

def run(root: Optional[str] = None, *, ported_only: bool = False,
        use_cache: bool = True,
        baseline_path: Optional[str] = None) -> Result:
    from . import (handoff_pass, hostsync_pass, lock_pass, ported,
                   serialization_pass)
    root = DEFAULT_ROOT if root is None else root
    env = _env_fingerprint(root)
    cache = _load_cache(root, env) if use_cache else {}
    new_cache: dict = {}

    sources = [SourceFile(root, p) for p in legacy.iter_sources(root)]
    ctx = Context(root, sources)

    parse_count = 0
    per_file_ported: List[List[Diagnostic]] = []
    per_file_dataflow: List[List[Diagnostic]] = []
    for src in sources:
        entry = cache.get(src.slash_rel)
        if entry is not None and entry.get("sha") == src.sha:
            ported_d = [_diag_from_cache(d) for d in entry["ported"]]
            dataflow_d = [_diag_from_cache(d) for d in entry["dataflow"]]
            facts = entry.get("facts", {})
            new_cache[src.slash_rel] = entry
        else:
            src.parse()
            parse_count += 1
            ported_d = ported.check_file(src, ctx)
            facts = {}
            if src.slash_rel == legacy.EVENTS_FILE \
                    and src.tree is not None:
                facts["event_classes"] = \
                    legacy.event_class_names(src.tree)
            if src.is_test:
                facts["test_hits"] = ctx.note_test_text(src)
            dataflow_d = []
            if src.syntax_error is None:
                dataflow_d += lock_pass.check_file(src, ctx)
                dataflow_d += hostsync_pass.check_file(src, ctx)
                dataflow_d += handoff_pass.check_file(src, ctx)
                dataflow_d += serialization_pass.check_file(src, ctx)
            facts["used_exemptions"] = sorted(ctx.pop_file_exemptions())
            new_cache[src.slash_rel] = {
                "sha": src.sha,
                "ported": [d.to_cache() for d in ported_d],
                "dataflow": [d.to_cache() for d in dataflow_d],
                "facts": facts,
            }
        # Re-absorb facts (cached or fresh) into the run context.
        if "event_classes" in facts:
            ctx.event_classes = facts["event_classes"]
        if "test_hits" in facts:
            ctx.absorb_test_hits(facts["test_hits"])
        ctx.used_exemptions.update(facts.get("used_exemptions", []))
        per_file_ported.append(ported_d)
        per_file_dataflow.append(dataflow_d)

    problems: List[Diagnostic] = []
    for d in per_file_ported:
        problems.extend(d)
    problems.extend(ported.finalize(ctx))
    if not ported_only:
        for d in per_file_dataflow:
            problems.extend(d)
        problems.extend(_unused_exemptions(ctx))
        problems.extend(_doc_drift(ctx))

    _apply_suppressions(sources, problems, ported_only)
    _apply_baseline(root, problems, baseline_path)

    if use_cache:
        _save_cache(root, env, new_cache)
    return Result(problems, len(sources), parse_count)


def _diag_from_cache(d: dict) -> Diagnostic:
    out = Diagnostic.from_json(d)
    return out


def _unused_exemptions(ctx: Context) -> List[Diagnostic]:
    from . import (handoff_pass, hostsync_pass, lock_pass,
                   serialization_pass)
    out = []
    registered = {}
    registered.update(lock_pass.exemption_ids())
    registered.update(hostsync_pass.exemption_ids())
    registered.update(handoff_pass.exemption_ids())
    registered.update(serialization_pass.exemption_ids())
    for eid in sorted(registered):
        if eid not in ctx.used_exemptions:
            out.append(Diagnostic(
                "HS004", eid.split("#", 1)[0], 1,
                f"frozen-allowlist entry '{eid}' matches no site; drop "
                f"it (justification was: {registered[eid]})"))
    return out


def _doc_drift(ctx: Context) -> List[Diagnostic]:
    """HS003: every diagnostic code must appear in the
    docs/static_analysis.md table, and every HS### the table lists must
    exist in the analyzer — the configuration.md-keys pattern."""
    out = []
    path = os.path.join(ctx.root, STATIC_ANALYSIS_DOC)
    doc_rel = STATIC_ANALYSIS_DOC.replace(os.sep, "/")
    if not os.path.exists(path):
        out.append(Diagnostic(
            "HS003", doc_rel, 1,
            "docs/static_analysis.md is missing; it must carry the "
            "HS### code table"))
        return out
    with open(path, encoding="utf-8") as f:
        doc = f.read()
    documented = set(re.findall(r"\bHS\d{3}\b", doc))
    for code in sorted(CODES):
        if code not in documented:
            out.append(Diagnostic(
                "HS003", doc_rel, 1,
                f"diagnostic code {code} ({CODES[code]}) is not "
                f"documented in {doc_rel}"))
    for code in sorted(documented - set(CODES)):
        out.append(Diagnostic(
            "HS003", doc_rel, 1,
            f"{doc_rel} documents {code}, which no pass emits; "
            "drop it from the table"))
    return out


def _apply_suppressions(sources: List[SourceFile],
                        problems: List[Diagnostic],
                        ported_only: bool) -> None:
    by_rel = {}
    for src in sources:
        sups = src.suppressions()
        if sups:
            by_rel[src.rel] = (src, sups)
    if not by_rel:
        return
    used = set()  # (rel, line, code) triples a directive consumed
    for d in problems:
        entry = by_rel.get(d.path)
        if entry is None:
            continue
        codes = entry[1].get(d.line)
        if codes and d.code in codes:
            d.suppressed = True
            used.add((d.path, d.line, d.code))
    if ported_only:
        return  # parity runs must not append framework findings
    for rel, (src, sups) in sorted(by_rel.items()):
        for line, codes in sorted(sups.items()):
            for code in sorted(codes):
                if (rel, line, code) not in used:
                    problems.append(Diagnostic(
                        "HS002", rel, line,
                        f"suppression of {code} matches no finding on "
                        "this line; remove the directive"))


def _apply_baseline(root: str, problems: List[Diagnostic],
                    baseline_path: Optional[str]) -> None:
    path = baseline_path or os.path.join(root, BASELINE_REL)
    if not os.path.exists(path):
        return
    try:
        with open(path, encoding="utf-8") as f:
            entries = json.load(f).get("findings", [])
    except Exception:
        problems.append(Diagnostic(
            "HS005", os.path.relpath(path, root), 1,
            "baseline file is unreadable; regenerate it with "
            "--write-baseline"))
        return
    keys = {(e.get("code"), e.get("path"), e.get("message"))
            for e in entries}
    matched = set()
    for d in problems:
        k = (d.code, d.path, d.message)
        if k in keys:
            d.baselined = True
            matched.add(k)
    for code, p, message in sorted(k for k in keys if k not in matched):
        problems.append(Diagnostic(
            "HS005", os.path.relpath(path, root), 1,
            f"stale baseline entry ({code} {p}: {message!r}) matches "
            "no current finding; regenerate the baseline"))


def write_baseline(root: Optional[str] = None,
                   path: Optional[str] = None) -> str:
    """Grandfather every current active finding into the baseline."""
    root = DEFAULT_ROOT if root is None else root
    result = run(root, use_cache=False)
    out = {"findings": [
        {"code": d.code, "path": d.path, "message": d.message}
        for d in result.problems if not d.suppressed
        and d.code not in ("HS005",)]}
    path = path or os.path.join(root, BASELINE_REL)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=2, sort_keys=True)
        f.write("\n")
    return path
