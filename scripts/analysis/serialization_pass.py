"""HS331 — executable serialization pinned to the artifact store.

The artifact store's correctness story leans on ONE fact: every
serialized compiled executable in the lake was written by store.py's
codec, under store.py's key discipline (format version, stage/sig
digests, mesh signature, jax/jaxlib/backend) and its checksum header.
A second serialization site would mint blobs the corrupt/stale ladders
have never seen — so, exactly like the jit-site gate (HS203) pins
``jax.jit`` to the instrumented kernel modules, this pass pins the
serialization machinery to :data:`SERIALIZATION_ALLOWLIST`:

- any import of ``jax.experimental.serialize_executable`` or
  ``jax.export`` (the two executable-serialization entry points this
  jax ships) outside the allowlist is a finding;
- so is a dotted use of either without an import (defense in depth);
- so is a ``pickle``/``cloudpickle`` dump/load whose payload expression
  names a compiled executable (``compiled``/``executable``/``lowered``
  identifiers) — the raw-pickle side door around the codec.

The allowlist is FROZEN the way every other registry here is: entries
carry a justification (printed by ``scripts/lint.py --exemptions``) and
an entry that stops matching any site surfaces as HS004.
"""

from __future__ import annotations

import ast
from typing import List

from . import dataflow as df
from .diagnostics import Diagnostic

# slash rel -> justification. The ONE sanctioned serialization module.
SERIALIZATION_ALLOWLIST = {
    "hyperspace_tpu/artifacts/store.py":
        "THE serialization boundary: the blob codec with the full-key "
        "header, checksum, and corrupt/stale miss ladders lives here",
}

_SERIALIZE_MODULES = ("jax.experimental.serialize_executable",
                      "jax.export")
_PICKLE_ROOTS = ("pickle", "cloudpickle")
_PICKLE_CALLS = ("dumps", "dump", "loads", "load")
_EXECUTABLE_MARKERS = ("compiled", "executable", "lowered")


def exemption_ids() -> dict:
    return {f"{rel}#serialization": why
            for rel, why in SERIALIZATION_ALLOWLIST.items()}


def describe_exemptions() -> List[str]:
    return [f"serialization[{rel}]: {why}"
            for rel, why in sorted(SERIALIZATION_ALLOWLIST.items())]


def _imported_serializer(node) -> str:
    """The serialization module an import node pulls in, or ''."""
    if isinstance(node, ast.Import):
        for alias in node.names:
            for mod in _SERIALIZE_MODULES:
                if alias.name == mod or alias.name.startswith(mod + "."):
                    return mod
    elif isinstance(node, ast.ImportFrom):
        mod = node.module or ""
        for target in _SERIALIZE_MODULES:
            if mod == target or mod.startswith(target + "."):
                return target
            # ``from jax.experimental import serialize_executable`` /
            # ``from jax import export``.
            parent, _, leaf = target.rpartition(".")
            if mod == parent and any(a.name == leaf
                                     for a in node.names):
                return target
    return ""


def _names_executable(expr) -> bool:
    for sub in ast.walk(expr):
        ident = ""
        if isinstance(sub, ast.Name):
            ident = sub.id
        elif isinstance(sub, ast.Attribute):
            ident = sub.attr
        ident = ident.lower()
        if any(m in ident for m in _EXECUTABLE_MARKERS):
            return True
    return False


def check_file(src, ctx) -> List[Diagnostic]:
    if not (src.is_package or src.rel.startswith("scripts")):
        return []
    out: List[Diagnostic] = []
    allowed = src.slash_rel in SERIALIZATION_ALLOWLIST
    used_exemption = False
    rel = src.rel
    idx = src.index

    for node in idx.of(ast.Import, ast.ImportFrom):
        mod = _imported_serializer(node)
        if not mod:
            continue
        if allowed:
            used_exemption = True
            continue
        out.append(Diagnostic(
            "HS331", rel, node.lineno,
            f"import of {mod} outside the artifact store; executable "
            "serialization is pinned to artifacts/store.py (its codec "
            "owns the key header, checksum, and corrupt ladders)",
            col=node.col_offset))

    for call in idx.of(ast.Call):
        name = df.dotted_name(call.func)
        if any(name == mod or name.startswith(mod + ".")
               for mod in _SERIALIZE_MODULES):
            if allowed:
                used_exemption = True
                continue
            out.append(Diagnostic(
                "HS331", rel, call.lineno,
                f"call through {name} outside the artifact store; "
                "executable serialization is pinned to "
                "artifacts/store.py",
                col=call.col_offset))
            continue
        root, _, leaf = name.rpartition(".")
        if root in _PICKLE_ROOTS and leaf in _PICKLE_CALLS \
                and call.args and _names_executable(call.args[0]):
            if allowed:
                used_exemption = True
                continue
            out.append(Diagnostic(
                "HS331", rel, call.lineno,
                f"{name} of a compiled-executable value outside the "
                "artifact store; raw pickle skips the store's key "
                "header and checksum — route it through "
                "artifacts/store.py",
                col=call.col_offset))

    if used_exemption:
        ctx.note_exemption(f"{src.slash_rel}#serialization")
    return out
