#!/usr/bin/env python
"""The pre-framework monolithic lint gate, kept verbatim as a REFERENCE.

This module is the single-pass implementation `scripts/lint.py` shipped
before the `scripts/analysis` framework replaced it. It exists for two
jobs only:

- **parity**: tests/test_static_analysis.py runs :func:`collect` beside
  the framework's ported passes and asserts a byte-identical finding
  set (every gate, every ordering quirk);
- **perf baseline**: the same tests time it — each gate here re-walks
  the full AST (~a dozen `ast.walk` traversals per file per run), the
  inefficiency the framework's shared one-walk node index removes.

It also remains the home of the frozen allowlists and the pure helper
functions (`mutable_state_sites`, `fault_site_violations`, ...) that
existing tests import via `scripts/lint.py` (which re-exports them).
Do not "optimize" this module — its cost IS the baseline.
"""

from __future__ import annotations

import ast
import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
MAX_LINE = 100
PACKAGE_DIRS = ("hyperspace_tpu",)
ALL_DIRS = ("hyperspace_tpu", "tests", "scripts")
TOP_FILES = ("bench.py", "__graft_entry__.py")

# Config/env-knob discipline: package code reads knobs through config.py
# accessors, never ad-hoc os.environ — otherwise knobs are undocumented,
# unhashable into cache keys, and invisible to the conf system. This list
# is FROZEN: config.py is the sanctioned reader, the rest are pre-gate
# legacy (executor-side switches documented in their module docstrings).
# New modules (e.g. serving/) must not be added here.
ENV_READ_ALLOWLIST = frozenset({
    "hyperspace_tpu/config.py",
    "hyperspace_tpu/execution/__init__.py",
    "hyperspace_tpu/execution/index_cache.py",
    "hyperspace_tpu/execution/spmd.py",
    "hyperspace_tpu/native/__init__.py",
    "hyperspace_tpu/ops/pallas_kernels.py",
    "hyperspace_tpu/parallel/multihost.py",
})

# Compile-observability discipline: every jax.jit stays inside the
# instrumented kernel modules, where the shape-class layer
# (execution/shapes.py) can see and count its compiles. A jit in an
# arbitrary module is invisible to the compile counter's attribution and
# bypasses the padding contract. This list is FROZEN — new jitted stages
# go into ops/kernels.py (or pallas_kernels.py for Mosaic), not new
# files. (The r12 SPMD port removed the distributed modules' direct jits:
# they launch through parallel/sharding.py, the one sanctioned mesh-jit
# site.)
JIT_SITE_ALLOWLIST = frozenset({
    "hyperspace_tpu/ops/kernels.py",
    "hyperspace_tpu/ops/pallas_kernels.py",
    "hyperspace_tpu/execution/shapes.py",
    "hyperspace_tpu/parallel/sharding.py",
})

# SPMD-idiom ratchet (the r12 port must be total and stay total):
# 1. shard_map / pmap are forbidden REPO-WIDE, no allowlist — the
#    distributed tier is built on NamedSharding + jit (GSPMD), the idiom
#    that works on this image AND scales to multi-process pods. A
#    per-device mapping primitive creeping back in would silently fork
#    the two worlds again.
# 2. In the distributed modules, every jax.jit must either pass explicit
#    in_shardings/out_shardings or carry a documented sharding marker
#    (a "# shardings:" or "# replicated" comment on the call line or the
#    two lines above) — partitioning must be stated, never implied.
SPMD_BANNED_NAMES = ("shard_map", "pmap")
SPMD_JIT_SHARDING_MODULES = frozenset({
    "hyperspace_tpu/parallel/sharding.py",
    "hyperspace_tpu/parallel/mesh.py",
    "hyperspace_tpu/parallel/multihost.py",
    "hyperspace_tpu/parallel/distributed_build.py",
    "hyperspace_tpu/parallel/distributed_query.py",
    "hyperspace_tpu/execution/spmd.py",
})


def spmd_banned_sites(tree: ast.AST) -> list:
    """(line, name) of shard_map/pmap references: attribute access
    (jax.shard_map / jax.pmap), bare names, and imports. AST-based, so
    prose in docstrings/comments never trips it."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and node.attr in SPMD_BANNED_NAMES:
            out.append((node.lineno, node.attr))
        elif isinstance(node, ast.Name) and node.id in SPMD_BANNED_NAMES:
            out.append((node.lineno, node.id))
        elif isinstance(node, ast.ImportFrom) and node.module and any(
                part in SPMD_BANNED_NAMES
                for part in node.module.split(".")):
            out.append((node.lineno, node.module))
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            for a in node.names:
                if a.name and any(part in SPMD_BANNED_NAMES
                                  for part in a.name.split(".")):
                    out.append((node.lineno, a.name))
    return sorted(set(out))


def jit_sharding_violations(tree: ast.AST, lines: list) -> list:
    """Lines of jax.jit/pjit CALLS in the distributed modules that
    neither pass in_shardings/out_shardings nor carry a sharding marker
    comment nearby."""
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("jit", "pjit")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "jax"):
            continue
        kw = {k.arg for k in node.keywords}
        if {"in_shardings", "out_shardings"} & kw:
            continue
        lo = max(node.lineno - 5, 0)
        nearby = "\n".join(lines[lo:node.lineno])
        if "# shardings:" in nearby or "# replicated" in nearby:
            continue
        out.append(node.lineno)
    return sorted(set(out))


def iter_sources(root=None):
    root = ROOT if root is None else root
    for d in ALL_DIRS:
        for r, _dirs, files in os.walk(os.path.join(root, d)):
            for f in sorted(files):
                if f.endswith(".py"):
                    yield os.path.join(r, f)
    for f in TOP_FILES:
        yield os.path.join(root, f)


def unused_imports(tree: ast.AST) -> list:
    imported = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            n = node
            while isinstance(n, ast.Attribute):
                n = n.value
            if isinstance(n, ast.Name):
                used.add(n.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and len(node.value) < 200:
            # Forward-reference annotations ('"HyperspaceConf"') count.
            import re
            used.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
    # Strings can reference names (docstrings citing symbols don't count,
    # but __all__ / annotations-as-strings do); be conservative.
    return sorted((line, name) for name, line in imported.items()
                  if name not in used and not name.startswith("_"))


def jit_sites(tree: ast.AST) -> list:
    """Line numbers of jax.jit / jax.pjit references (attribute access
    covers bare calls, partial(jax.jit, ...) and decorators alike)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and node.attr in ("jit", "pjit") \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "jax":
            out.append(node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[0] == "jax":
            if any(a.name in ("jit", "pjit") for a in node.names):
                out.append(node.lineno)
    return sorted(set(out))


# I/O-parallelism discipline: every thread/pool construction stays inside
# parallel/io.py, whose shared reader pool enforces the ordered-gather
# determinism contract and the hyperspace.tpu.io.maxInflightBytes budget.
# An ad-hoc ThreadPoolExecutor/threading.Thread elsewhere would read
# outside the byte budget and invisibly to the pool stats. This list is
# FROZEN — new parallel stages go through parallel/io.py primitives
# (map_ordered / prefetch_iter), not new pools.
THREAD_SITE_ALLOWLIST = frozenset({
    "hyperspace_tpu/parallel/io.py",
})


# Communication discipline (the cluster tier's ratchet): socket
# creation/bind stays inside cluster/transport.py — the one owned
# backend carrying framing, deadlines, and r14 retry semantics — plus
# telemetry/exposition.py's localhost HTTP exporter (a listener that
# predates the transport and stays read-only). An ad-hoc socket
# elsewhere would invent a second wire protocol outside the deadline/
# retry contract and invisibly to the cluster counters. This list is
# FROZEN — new communication rides cluster/transport.py.
SOCKET_SITE_ALLOWLIST = frozenset({
    "hyperspace_tpu/cluster/transport.py",
    "hyperspace_tpu/telemetry/exposition.py",
})


def socket_sites(tree: ast.AST) -> list:
    """Line numbers of socket/socketserver imports, ``socket.*``
    construction helpers, and HTTP-server construction references (the
    listener classes wrap a bind)."""
    out = []
    server_names = ("HTTPServer", "ThreadingHTTPServer", "TCPServer",
                    "UDPServer")
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            if any(a.name.split(".")[0] in ("socket", "socketserver")
                   for a in node.names):
                out.append(node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module:
            root = node.module.split(".")[0]
            if root in ("socket", "socketserver"):
                out.append(node.lineno)
            elif root == "http" and any(a.name in server_names
                                        for a in node.names):
                out.append(node.lineno)
        elif isinstance(node, ast.Attribute) \
                and node.attr in ("socket", "create_connection",
                                  "create_server") \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "socket":
            out.append(node.lineno)
        elif isinstance(node, ast.Name) and node.id in server_names:
            out.append(node.lineno)
    return sorted(set(out))


# Decode/transfer discipline (the buffer-pool ratchet): parquet decode
# (pq.read_table / pq.ParquetFile) and host→device transfer
# (jax.device_put) call sites stay inside the routed scan paths —
# buffer_pool.py + columnar.py — plus the frozen legacy list below
# (ingest/maintenance writers reading their own staged files, metadata-
# only footer readers, and the pre-pool device-residency seams). A new
# decode or transfer elsewhere would bypass the pool: re-paying decode
# + transfer invisibly to the hit/transfer counters and outside the
# file-signature invalidation contract. This list is FROZEN — new scan
# paths route through execution/buffer_pool.py or columnar.py.
DECODE_SITE_ALLOWLIST = frozenset({
    "hyperspace_tpu/actions/create_skipping.py",
    "hyperspace_tpu/execution/buffer_pool.py",
    "hyperspace_tpu/execution/columnar.py",
    "hyperspace_tpu/execution/executor.py",
    "hyperspace_tpu/execution/fusion.py",
    "hyperspace_tpu/optimizer/stats.py",
    "hyperspace_tpu/parallel/mesh.py",
    "hyperspace_tpu/rules/data_skipping_rule.py",
    "hyperspace_tpu/serving/result_cache.py",
    "hyperspace_tpu/streaming/ingest.py",
    "hyperspace_tpu/streaming/sources.py",
})


def decode_sites(tree: ast.AST) -> list:
    """Line numbers of parquet decode (``pq.read_table`` /
    ``pq.ParquetFile`` attribute references, any ``pq``-style alias) and
    host→device transfer (``jax.device_put``) call sites, plus direct
    imports of those names (which would dodge the attribute pattern)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name):
            if node.attr in ("read_table", "ParquetFile") \
                    and node.value.id.lstrip("_") in ("pq", "parquet"):
                out.append(node.lineno)
            elif node.attr == "device_put" and node.value.id == "jax":
                out.append(node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module:
            root = node.module.split(".")[0]
            if root == "jax" and any(a.name == "device_put"
                                     for a in node.names):
                out.append(node.lineno)
            elif root == "pyarrow" and node.module.endswith("parquet") \
                    and any(a.name in ("read_table", "ParquetFile")
                            for a in node.names):
                out.append(node.lineno)
    return sorted(set(out))


def thread_sites(tree: ast.AST) -> list:
    """Line numbers of ThreadPoolExecutor / threading.Thread construction
    references (attribute access covers bare calls and aliases; plain
    Lock/Condition/local stay allowed everywhere)."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and node.attr == "Thread" \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "threading":
            out.append(node.lineno)
        elif isinstance(node, ast.Attribute) \
                and node.attr == "ThreadPoolExecutor":
            out.append(node.lineno)
        elif isinstance(node, ast.Name) and node.id == "ThreadPoolExecutor":
            out.append(node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.module.split(".")[0] in ("threading",
                                                  "concurrent"):
            if any(a.name in ("Thread", "ThreadPoolExecutor")
                   for a in node.names):
                out.append(node.lineno)
    return sorted(set(out))


# Shared-state discipline (the serving refactor's ratchet): module-level
# MUTABLE containers (dict/list/set literals or constructor calls) are
# process-global shared state — invisible to the per-query accounting,
# unguarded against the multi-threaded serving path, and unclearable by
# construction. New cross-query state must live in QueryContext
# (serving/context.py) or one of the sanctioned frontend registries
# (program bank, frontend queue, io pools). This list is FROZEN: it
# names the files that already held module-level mutable state when the
# gate landed (pre-serving legacy caches and the sanctioned registries);
# nothing gets added.
MUTABLE_STATE_ALLOWLIST = frozenset({
    "hyperspace_tpu/execution/executor.py",       # CHUNK_SCAN_STATS
    "hyperspace_tpu/execution/shapes.py",         # compile counters
    "hyperspace_tpu/index/data_store.py",         # scheme registry+cache
    "hyperspace_tpu/index/log_store.py",          # scheme registry
    "hyperspace_tpu/ops/index_build.py",          # CHUNK_STATS
    "hyperspace_tpu/parallel/io.py",              # pool stats (sanctioned)
    "hyperspace_tpu/rules/data_skipping_rule.py",  # sketch-table cache
    "hyperspace_tpu/serving/program_bank.py",     # THE program registry
    "hyperspace_tpu/sources/default.py",          # format-suffix registry
    "hyperspace_tpu/telemetry/logging.py",        # logger instance memo
})

_MUTABLE_CALLS = {"dict", "list", "set", "OrderedDict", "defaultdict",
                  "deque", "Counter"}
_MUTATOR_METHODS = {"append", "appendleft", "add", "update", "setdefault",
                    "pop", "popitem", "clear", "extend", "insert",
                    "remove", "discard", "move_to_end"}


def _mutated_names(tree: ast.AST) -> set:
    """Names the module writes THROUGH (``x[k] = ...``, ``x.append(...)``,
    ``del x[k]``, ``x += ...``) — the signature of a container used as
    state rather than as a constant lookup table."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name):
                    out.add(t.value.id)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) \
                        and isinstance(t.value, ast.Name):
                    out.add(t.value.id)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATOR_METHODS \
                and isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
    return out


def mutable_state_sites(tree: ast.AST) -> list:
    """(line, name) of module-level mutable containers the module also
    MUTATES — process-global shared state. Constant lookup tables
    (dicts/sets never written through) and ContextVar/Lock plumbing stay
    allowed everywhere."""
    mutated = _mutated_names(tree)
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names or names == ["__all__"]:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
        if not mutable and isinstance(value, ast.Call):
            f = value.func
            callee = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            mutable = callee in _MUTABLE_CALLS
        if mutable and any(n in mutated for n in names):
            out.append((node.lineno, names[0]))
    return out


# Span-naming discipline (the r13 tracing layer's ratchet): every
# trace.span(...) / trace.add_span(...) site in package code must name
# its span via a constant from the frozen telemetry/span_names.py
# registry (or a string literal registered there) — free-form strings
# would fragment the vocabulary dashboards and the Chrome exporter key
# on. And like the event-taxonomy gate below, every REGISTERED span
# name must be referenced under tests/: an unobserved span is
# unverified observability.
SPAN_NAMES_FILE = "hyperspace_tpu/telemetry/span_names.py"
SPAN_MODULE_ALIASES = ("span_names", "SN", "_sn")


def span_name_constants(tree: ast.AST) -> dict:
    """Module-level UPPERCASE string constants of span_names.py:
    constant name -> span name string."""
    out = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id.isupper():
                out[t.id] = node.value.value
    return out


def span_site_violations(tree: ast.AST, names: dict) -> list:
    """(line, detail) of trace.span()/trace.add_span() calls whose name
    argument is neither a span_names constant nor a registered literal."""
    values = set(names.values())
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("span", "add_span")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in ("trace", "_trace", "_tr")):
            continue
        if not node.args:
            out.append((node.lineno, "no span name argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in SPAN_MODULE_ALIASES \
                and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno,
                    "span name must come from telemetry/span_names.py"))
    return out


# Fault-point discipline (the robustness layer's ratchet, mirroring the
# span gate): every ``faults.fault_point(...)`` site in package code
# must name its point via a constant from the frozen
# robustness/fault_names.py registry (or a string literal registered
# there), AND every registered name must be referenced under tests/ —
# an uninjected fault point is unverified robustness.
FAULT_NAMES_FILE = "hyperspace_tpu/robustness/fault_names.py"
FAULT_MODULE_ALIASES = ("faults", "_faults")
FAULT_NAME_ALIASES = ("fault_names", "_fn", "_fltn", "FN")


def fault_site_violations(tree: ast.AST, names: dict) -> list:
    """(line, detail) of fault_point() calls whose name argument is
    neither a fault_names constant nor a registered literal."""
    values = set(names.values())
    out = []
    for node in ast.walk(tree):
        is_attr_call = (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "fault_point"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in FAULT_MODULE_ALIASES)
        is_name_call = (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == "fault_point")
        if not (is_attr_call or is_name_call):
            continue
        if not node.args:
            out.append((node.lineno, "no fault-point name argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in FAULT_NAME_ALIASES \
                and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno, "fault-point name must come from "
                    "robustness/fault_names.py"))
    return out


# Fusion-boundary discipline (the whole-plan-fusion layer's ratchet,
# mirroring the span/fault gates): every region boundary or fallback the
# fusion planner/executor draws — ``note_boundary(...)`` sites and
# ``_FuseFallback(...)`` raises in execution/fusion.py — must name its
# kind via a constant from the frozen execution/fusion_boundaries.py
# registry (or a string literal registered there), AND every registered
# kind must be referenced under tests/ — an unexercised boundary is an
# unverified fallback path. The fused programs themselves compile ONLY
# through the ProgramBank (ops/kernels.run_fused_region): fusion.py is
# deliberately NOT in JIT_SITE_ALLOWLIST, so a direct jax.jit there
# trips the jit-site gate above.
FUSION_BOUNDARIES_FILE = "hyperspace_tpu/execution/fusion_boundaries.py"
FUSION_BOUNDARY_ALIASES = ("fusion_boundaries", "FB", "_fb")
FUSION_BOUNDARY_CALLS = ("note_boundary", "_FuseFallback", "FuseFallback")


def fusion_boundary_violations(tree: ast.AST, names: dict) -> list:
    """(line, detail) of note_boundary()/_FuseFallback() call sites whose
    kind argument is neither a fusion_boundaries constant nor a
    registered literal."""
    values = set(names.values())
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        callee = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if callee not in FUSION_BOUNDARY_CALLS:
            continue
        if not node.args:
            out.append((node.lineno, "no boundary-kind argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in FUSION_BOUNDARY_ALIASES \
                and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno, "boundary kind must come from "
                    "execution/fusion_boundaries.py"))
    return out


# Metric-naming discipline (the observability round's ratchet,
# mirroring the span/fault/fusion gates): every push-side instrument ask
# (``counter_add`` / ``gauge_set`` / ``histogram``) and every
# ``register_collector`` site in package code must name its metric via a
# constant from the frozen telemetry/metric_names.py registry (or a
# string literal registered there), AND every registered name must be
# referenced under tests/ — an unobserved metric is unverified
# observability, and free-form names would fragment the OpenMetrics
# exposition external scrapers key on.
METRIC_NAMES_FILE = "hyperspace_tpu/telemetry/metric_names.py"
METRIC_NAME_ALIASES = ("metric_names", "MN", "_mn")
METRIC_CALLS = ("counter_add", "gauge_set", "histogram",
                "register_collector")


def metric_site_violations(tree: ast.AST, names: dict) -> list:
    """(line, detail) of instrument/collector call sites whose name
    argument is neither a metric_names constant nor a registered
    literal. Method-attribute calls only — the registry object is
    reached many ways (``get_registry().counter_add``, a local ``reg``),
    so the callee NAME is the signature, like the fusion gate."""
    values = set(names.values())
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in METRIC_CALLS):
            continue
        if not node.args:
            out.append((node.lineno, "no metric name argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in METRIC_NAME_ALIASES \
                and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno, "metric name must come from "
                    "telemetry/metric_names.py"))
    return out


# Exception-swallowing discipline (robustness ratchet): a bare
# ``except:`` anywhere, or an ``except BaseException: pass`` that
# swallows silently, hides crashes the robustness layer exists to
# surface (cancellation, injected faults, worker death). The allowlist
# is FROZEN and EMPTY — the tree was clean when the gate landed;
# narrow the handler or handle the error instead.
EXCEPT_SWALLOW_ALLOWLIST = frozenset()


def _names_in_except_type(node) -> set:
    if node is None:
        return set()
    types = node.elts if isinstance(node, ast.Tuple) else [node]
    out = set()
    for t in types:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, ast.Attribute):
            out.add(t.attr)
    return out


def except_swallow_sites(tree: ast.AST) -> list:
    """(line, detail) of forbidden handlers: bare ``except:`` (any
    body), and ``except BaseException`` whose body is only ``pass``."""
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append((node.lineno,
                        "bare 'except:'; name the exception classes"))
            continue
        body_is_pass = all(isinstance(s, ast.Pass) for s in node.body)
        if body_is_pass and "BaseException" in _names_in_except_type(
                node.type):
            out.append((node.lineno,
                        "'except BaseException: pass' swallows "
                        "cancellation and crashes silently"))
    return out


# Telemetry-coverage discipline: every event class defined in
# telemetry/events.py must be referenced somewhere under tests/ — an
# event no test ever observes is unverified observability (the
# IndexTableCache counters were counted-but-unreported for three rounds
# before r06 made them visible; this gate would have caught it).
EVENTS_FILE = "hyperspace_tpu/telemetry/events.py"


def event_class_names(tree: ast.AST) -> list:
    return sorted(node.name for node in ast.walk(tree)
                  if isinstance(node, ast.ClassDef))


# Doc-drift discipline: every `hyperspace.tpu.*` config key the package
# defines must be documented in docs/configuration.md — a key literal
# that exists only in code is an undocumented knob. Full-string match
# only, so prose mentioning the prefix never trips it.
CONFIG_KEY_PATTERN = re.compile(
    r"^hyperspace\.tpu(\.[A-Za-z][A-Za-z0-9_]*)+$")
CONFIG_DOC = "docs/configuration.md"


def config_key_literals(tree: ast.AST) -> list:
    """(line, key) for every full-string hyperspace.tpu.* literal."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and CONFIG_KEY_PATTERN.match(node.value):
            out.append((node.lineno, node.value))
    return out


def env_reads(tree: ast.AST) -> list:
    """Line numbers of os.environ / os.getenv style env accesses."""
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "os" \
                and node.attr in ("environ", "getenv"):
            out.append(node.lineno)
        elif isinstance(node, ast.ImportFrom) and node.module == "os":
            if any(a.name in ("environ", "getenv") for a in node.names):
                out.append(node.lineno)
    return sorted(set(out))


def collect(root=None) -> tuple:
    """(problems, file count) over ``root`` — the verbatim body of the
    retired monolith's ``main()``, parameterized for the parity tests."""
    root = ROOT if root is None else root
    problems = []
    with open(os.path.join(root, CONFIG_DOC), encoding="utf-8") as f:
        config_doc_text = f.read()
    with open(os.path.join(root, SPAN_NAMES_FILE), encoding="utf-8") as f:
        span_names = span_name_constants(ast.parse(f.read()))
    with open(os.path.join(root, FAULT_NAMES_FILE), encoding="utf-8") as f:
        fault_names = span_name_constants(ast.parse(f.read()))
    with open(os.path.join(root, FUSION_BOUNDARIES_FILE),
              encoding="utf-8") as f:
        fusion_kinds = span_name_constants(ast.parse(f.read()))
    with open(os.path.join(root, METRIC_NAMES_FILE),
              encoding="utf-8") as f:
        metric_names = span_name_constants(ast.parse(f.read()))
    event_classes: list = []
    tests_text_parts: list = []
    for path in iter_sources(root):
        rel = os.path.relpath(path, root)
        with open(path, encoding="utf-8") as f:
            text = f.read()
        if rel.startswith("tests" + os.sep):
            tests_text_parts.append(text)
        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            problems.append(f"{rel}:{e.lineno}: syntax error: {e.msg}")
            continue
        if rel.replace(os.sep, "/") == EVENTS_FILE:
            event_classes = event_class_names(tree)
        for i, line in enumerate(text.splitlines(), 1):
            if "\t" in line:
                problems.append(f"{rel}:{i}: tab character")
            if line != line.rstrip():
                problems.append(f"{rel}:{i}: trailing whitespace")
            if len(line) > MAX_LINE:
                problems.append(f"{rel}:{i}: line longer than {MAX_LINE}")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and os.path.basename(path) != "__init__.py":  # re-exports
            for line, name in unused_imports(tree):
                problems.append(f"{rel}:{line}: unused import '{name}'")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in ENV_READ_ALLOWLIST:
            for line in env_reads(tree):
                problems.append(
                    f"{rel}:{line}: ad-hoc env read (os.environ/getenv); "
                    "knobs must go through config.py accessors")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS):
            for line, key in config_key_literals(tree):
                if key not in config_doc_text:
                    problems.append(
                        f"{rel}:{line}: config key '{key}' is not "
                        f"documented in {CONFIG_DOC}")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in JIT_SITE_ALLOWLIST:
            for line in jit_sites(tree):
                problems.append(
                    f"{rel}:{line}: jax.jit outside the instrumented "
                    "kernel modules; add the jitted stage to ops/kernels.py "
                    "so the compile counter sees it")
        for line, name in spmd_banned_sites(tree):
            problems.append(
                f"{rel}:{line}: '{name}' is forbidden repo-wide; the SPMD "
                "tier is NamedSharding+jit only (parallel/sharding.py)")
        if rel.replace(os.sep, "/") in SPMD_JIT_SHARDING_MODULES:
            for line in jit_sharding_violations(tree, text.splitlines()):
                problems.append(
                    f"{rel}:{line}: jax.jit in a distributed module must "
                    "pass explicit in_shardings/out_shardings or carry a "
                    "'# shardings:'/'# replicated' marker comment")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in MUTABLE_STATE_ALLOWLIST:
            for line, name in mutable_state_sites(tree):
                problems.append(
                    f"{rel}:{line}: module-level mutable state '{name}'; "
                    "cross-query state belongs in QueryContext "
                    "(serving/context.py) or a sanctioned frontend "
                    "registry (see MUTABLE_STATE_ALLOWLIST)")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS):
            for line, detail in span_site_violations(tree, span_names):
                problems.append(
                    f"{rel}:{line}: {detail} (frozen registry; free-form "
                    "span strings are forbidden)")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS):
            for line, detail in fault_site_violations(tree, fault_names):
                problems.append(
                    f"{rel}:{line}: {detail} (frozen registry; free-form "
                    "fault-point strings are forbidden)")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS):
            for line, detail in fusion_boundary_violations(tree,
                                                           fusion_kinds):
                problems.append(
                    f"{rel}:{line}: {detail} (frozen registry; free-form "
                    "fusion-boundary kinds are forbidden)")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS):
            for line, detail in metric_site_violations(tree,
                                                       metric_names):
                problems.append(
                    f"{rel}:{line}: {detail} (frozen registry; free-form "
                    "metric names are forbidden)")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in \
                EXCEPT_SWALLOW_ALLOWLIST:
            for line, detail in except_swallow_sites(tree):
                problems.append(f"{rel}:{line}: {detail}")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in THREAD_SITE_ALLOWLIST:
            for line in thread_sites(tree):
                problems.append(
                    f"{rel}:{line}: thread/pool construction outside "
                    "parallel/io.py; route the work through its "
                    "map_ordered/prefetch_iter so the in-flight byte "
                    "budget and ordered-gather contract hold")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in SOCKET_SITE_ALLOWLIST:
            for line in socket_sites(tree):
                problems.append(
                    f"{rel}:{line}: socket creation outside "
                    "cluster/transport.py; ride the cluster transport "
                    "so framing, deadlines, and retry semantics hold "
                    "(telemetry/exposition.py's HTTP exporter is the "
                    "one other sanctioned listener)")
        if any(rel.startswith(d + os.sep) for d in PACKAGE_DIRS) \
                and rel.replace(os.sep, "/") not in DECODE_SITE_ALLOWLIST:
            for line in decode_sites(tree):
                problems.append(
                    f"{rel}:{line}: parquet decode or device transfer "
                    "outside the buffer-pool modules; route the read "
                    "through execution/buffer_pool.py or columnar.py so "
                    "the tiered pool's hit/transfer counters and "
                    "file-signature invalidation contract hold")
    tests_text = "\n".join(tests_text_parts)
    for name in event_classes:
        if name not in tests_text:
            problems.append(
                f"{EVENTS_FILE}: event class '{name}' is never referenced "
                "under tests/; add a test observing (or at least naming) it")
    for const, value in sorted(span_names.items()):
        if const == "SPAN_NAMES":
            continue
        if value not in tests_text:
            problems.append(
                f"{SPAN_NAMES_FILE}: span name '{value}' ({const}) is "
                "never referenced under tests/; add a test observing it")
    for const, value in sorted(fault_names.items()):
        if const == "FAULT_NAMES":
            continue
        if value not in tests_text:
            problems.append(
                f"{FAULT_NAMES_FILE}: fault point '{value}' ({const}) is "
                "never referenced under tests/; add a test injecting it")
    for const, value in sorted(fusion_kinds.items()):
        if const == "BOUNDARY_KINDS":
            continue
        if value not in tests_text:
            problems.append(
                f"{FUSION_BOUNDARIES_FILE}: boundary kind '{value}' "
                f"({const}) is never referenced under tests/; add a test "
                "exercising it")
    for const, value in sorted(metric_names.items()):
        if const == "METRIC_NAMES":
            continue
        if value not in tests_text:
            problems.append(
                f"{METRIC_NAMES_FILE}: metric name '{value}' ({const}) "
                "is never referenced under tests/; add a test "
                "observing it")
    return problems, sum(1 for _ in iter_sources(root))


def main(root=None) -> int:
    problems, file_count = collect(root)
    for p in problems:
        print(p)
    print(f"lint: {len(problems)} problem(s) across {file_count} files")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
