"""HS311/HS312 — device→host sync detector for jit-adjacent code.

Scope: the modules the jit-site gate (HS203) sanctions for ``jax.jit``
plus the whole-plan fusion module whose region builders compile through
the ProgramBank — the code that defines every traced program body in
the tree.

Two regions, two codes:

- **traced code** (HS311): bodies of jitted functions (``@jax.jit`` /
  ``partial(jax.jit, ...)`` decorators, functions passed to
  ``jax.jit``/``jax.vmap``/``device_view``/``MeshProgram``, the
  registered extra roots — fusion's builder — and the TRUE branch of
  ``if shapes._is_tracer(x):`` guards, the repo's own "this code runs
  under tracing" idiom). A ``.item()``/``.tolist()``/
  ``jax.device_get``/``int()/float()/bool()/np.asarray`` on a traced
  value here is at best a ConcretizationTypeError at trace time and at
  worst a purity break — there is NO allowlist for it.
- **host dispatch code** (HS312): the wrappers around program dispatch
  may sync — that is the r15 contract: exactly the declared scalars per
  site. Every sync on a device-derived value must match a frozen
  :data:`HOST_SYNC_ALLOWLIST` entry ((module, function) → allowed sync
  count + justification); extra or unlisted syncs are findings, and
  entries that stop matching surface as HS004.

Static arguments (``static_argnames``) are host values and never seed
taint; ``.shape``/``.ndim``/``.dtype``/``len()`` launder it
(``int(x.shape[0])`` is host arithmetic). :data:`TAINTED_PARAMS` names
host functions whose parameters carry device values in from a caller
(fusion's ``out`` program-output dicts) so their contract syncs are
counted too.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from . import dataflow as df
from . import legacy_reference as legacy
from .diagnostics import Diagnostic, Related

SCOPE_MODULES = frozenset(legacy.JIT_SITE_ALLOWLIST) | frozenset({
    "hyperspace_tpu/execution/fusion.py",
})

# (slash rel, function qualname) -> (max allowed syncs, justification).
HOST_SYNC_ALLOWLIST = {
    ("hyperspace_tpu/ops/kernels.py", "mask_count_nonzero"): (
        2, "fused filter front-end: ONE survivor-count scalar per call "
           "(two exclusive branches, one sync each)"),
    ("hyperspace_tpu/ops/kernels.py", "merge_join_indices"): (
        1, "join output length is data-dependent: ONE total-matches "
           "scalar per join"),
    ("hyperspace_tpu/ops/kernels.py", "group_ids_from_sorted"): (
        1, "group count is data-dependent: ONE last-group-id scalar "
           "per aggregate"),
    # (_prepare_side's key-uniqueness check is ONE bool-scalar sync per
    #  side build, but it flows through kernels.has_adjacent_duplicates
    #  — an r20 banked kernel — which intraprocedural taint cannot see;
    #  the call site carries a HOST SYNC comment instead.)
    ("hyperspace_tpu/execution/fusion.py", "_record_actuals"): (
        1, "per-join observed-rows scalar feeding the q-error loop "
           "(one per join stage, after the region program returned)"),
    ("hyperspace_tpu/execution/fusion.py", "_finish_chain"): (
        1, "THE one-scalar-per-region sync: the survivor count that "
           "sizes the compaction gather"),
    ("hyperspace_tpu/execution/fusion.py", "_finish_grouped"): (
        1, "THE one-scalar-per-region sync: the group count that sizes "
           "the output class"),
    # pallas self_check is a diagnostic harness: it compares whole
    # kernel outputs against jnp references host-side, by design. It
    # never runs on a query path (Hyperspace.pallas_self_check only).
    ("hyperspace_tpu/ops/pallas_kernels.py",
     "self_check.chk_range_mask"): (
        1, "self-check harness: full-array comparison vs reference"),
    ("hyperspace_tpu/ops/pallas_kernels.py",
     "self_check.chk_compare_mask"): (
        1, "self-check harness: full-array comparison vs reference"),
    ("hyperspace_tpu/ops/pallas_kernels.py", "self_check.chk_minmax"): (
        4, "self-check harness: four scalar comparisons vs reference"),
    ("hyperspace_tpu/ops/pallas_kernels.py",
     "self_check.chk_histogram"): (
        1, "self-check harness: full-array comparison vs reference"),
}

# Host functions whose listed PARAMETERS are device values handed in by
# a caller (intraprocedural taint cannot see across the call).
TAINTED_PARAMS = {
    ("hyperspace_tpu/execution/fusion.py", "_record_actuals"): {"out"},
    ("hyperspace_tpu/execution/fusion.py", "_finish_chain"): {"out"},
    ("hyperspace_tpu/execution/fusion.py", "_finish_grouped"): {"out"},
    ("hyperspace_tpu/execution/fusion.py", "_finish_global"): {"out"},
}

# Traced roots syntactic detection misses: functions compiled through a
# factory indirection (fusion's builder) or called only from traced
# bodies.
EXTRA_TRACED_ROOTS = {
    # (_pred_eval is imported from execution/evaluator.py — out of this
    #  pass's module scope; the expression builders there are a known
    #  coverage gap, see docs/static_analysis.md.)
    "hyperspace_tpu/execution/fusion.py": frozenset({
        "_make_builder", "_traced_agg", "_null_aware", "_sentinel"}),
    "hyperspace_tpu/parallel/sharding.py": frozenset({
        "device_view.run"}),
}

_SYNC_RECEIVER_CALLS = ("item", "tolist")
_SYNC_FUNCS = ("int", "float", "bool")
_SYNC_NP = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")


def exemption_ids() -> dict:
    out = {}
    for (rel, fn), (_n, why) in HOST_SYNC_ALLOWLIST.items():
        out[f"{rel}#hostsync:{fn}"] = why
    return out


def describe_exemptions() -> List[str]:
    out = []
    for (rel, fn), (n, why) in sorted(HOST_SYNC_ALLOWLIST.items()):
        out.append(f"hostsync[{rel}::{fn} <= {n} sync(s)]: {why}")
    return out


def _static_argnames(dec: ast.Call) -> Set[str]:
    for kw in dec.keywords:
        if kw.arg == "static_argnames":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                return {v.value}
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)}
    return set()


def _jit_decorated(func) -> "tuple":
    """(is_jitted, static names) from the decorator list."""
    for dec in func.decorator_list:
        name = df.dotted_name(dec if not isinstance(dec, ast.Call)
                              else dec.func)
        if name in ("jax.jit", "jit", "jax.pjit", "pjit"):
            return True, (_static_argnames(dec)
                          if isinstance(dec, ast.Call) else set())
        if isinstance(dec, ast.Call) and name in ("partial",
                                                  "functools.partial"):
            if dec.args and df.dotted_name(dec.args[0]) in (
                    "jax.jit", "jax.pjit"):
                return True, _static_argnames(dec)
    return False, set()


def _collect_traced(src, funcs):
    """(id(FunctionDef) -> static param names for every traced root,
    registered extra roots that resolved to nothing)."""
    traced: Dict[int, Set[str]] = {}
    by_qual = {i.qualname: i for i in funcs.values()}
    by_name: Dict[str, list] = {}
    for i in funcs.values():
        by_name.setdefault(i.node.name, []).append(i)

    def mark(name: str, static: Set[str]) -> bool:
        info = by_qual.get(name)
        if info is None:
            cands = by_name.get(name.split(".")[-1], [])
            info = cands[0] if len(cands) == 1 else None
        if info is None:
            return False
        traced.setdefault(id(info.node), set()).update(static)
        return True

    for info in funcs.values():
        jitted, static = _jit_decorated(info.node)
        if jitted:
            traced.setdefault(id(info.node), set()).update(static)
    for call in src.index.of(ast.Call):
        name = df.dotted_name(call.func)
        if name in ("jax.jit", "jax.pjit", "jax.vmap", "device_view",
                    "MeshProgram", "sharding.MeshProgram"):
            if call.args:
                inner = call.args[0]
                # jax.jit(jax.vmap(builder, ...)) and friends: mark any
                # bare Name inside the first argument expression.
                for sub in ast.walk(inner):
                    if isinstance(sub, ast.Name):
                        mark(sub.id, _static_argnames(call))
    unresolved = [qual for qual in
                  sorted(EXTRA_TRACED_ROOTS.get(src.slash_rel, ()))
                  if not mark(qual, set())]
    return traced, unresolved


def _tracer_branches(func) -> List[ast.If]:
    out = []
    for node in ast.walk(func):
        if isinstance(node, ast.If):
            for sub in ast.walk(node.test):
                if isinstance(sub, ast.Call) and df.dotted_name(
                        sub.func).split(".")[-1] == "_is_tracer":
                    out.append(node)
                    break
    return out


def _sync_calls(scope_nodes, taint: df.Taint) -> list:
    """(node, kind) for device→host syncs among ``scope_nodes``."""
    out = []
    for node in scope_nodes:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) \
                and f.attr in _SYNC_RECEIVER_CALLS:
            if taint.expr_tainted(f.value):
                out.append((node, f".{f.attr}()"))
            continue
        name = df.dotted_name(f)
        if name in ("jax.device_get",):
            out.append((node, "jax.device_get"))
        elif name in _SYNC_NP and node.args \
                and taint.expr_tainted(node.args[0]):
            out.append((node, name))
        elif name in _SYNC_FUNCS and node.args \
                and taint.expr_tainted(node.args[0]):
            out.append((node, f"{name}()"))
    return out


def check_file(src, ctx) -> List[Diagnostic]:
    if src.slash_rel not in SCOPE_MODULES:
        return []
    out: List[Diagnostic] = []
    rel = src.rel
    funcs = df.function_map(src.tree)
    traced, unresolved_roots = _collect_traced(src, funcs)
    jitted_names = {i.node.name for i in funcs.values()
                    if id(i.node) in traced}
    for qual in unresolved_roots:
        # A stale EXTRA_TRACED_ROOTS entry silently dropping HS311
        # coverage would be the one frozen registry that rots without
        # a signal — surface it like every other unused entry.
        out.append(Diagnostic(
            "HS004", rel, 1,
            f"EXTRA_TRACED_ROOTS entry '{qual}' matches no function in "
            f"{src.slash_rel}; the traced body it should cover is no "
            "longer checked — fix or drop the entry"))

    for info in funcs.values():
        fn = info.node
        in_traced = id(fn) in traced
        if not in_traced and info.parent is not None \
                and id(info.parent.node) in traced:
            continue  # nested def inside a traced root: covered there
        static = traced.get(id(fn), set())
        params = {a.arg for a in fn.args.args + fn.args.kwonlyargs
                  + fn.args.posonlyargs}
        if in_traced:
            # Nested defs run under the same trace: their params are
            # traced values too (closures over the root's tracers).
            for sub in ast.walk(fn):
                if isinstance(sub, df.FUNC_TYPES) and sub is not fn:
                    params |= {a.arg for a in sub.args.args
                               + sub.args.kwonlyargs
                               + sub.args.posonlyargs}
            seed = params - static - {"self"}
        else:
            seed = set(TAINTED_PARAMS.get((src.slash_rel, info.qualname),
                                          set())) & params
        taint = df.Taint(fn, seed, jitted_names)
        # _is_tracer(x) guards: x (and anything derived) is a tracer in
        # the TRUE branch; the branch itself is traced region.
        branches = [] if in_traced else _tracer_branches(fn)
        branch_ids: Set[int] = set()
        branch_taint = df.Taint(fn, seed | _branch_args(branches),
                                jitted_names)
        for br in branches:
            for stmt in br.body:
                for sub in ast.walk(stmt):
                    branch_ids.add(id(sub))
                branch_ids.add(id(stmt))

        if in_traced:
            syncs = _sync_calls(list(ast.walk(fn)), taint)
            for node, kind in syncs:
                out.append(Diagnostic(
                    "HS311", rel, node.lineno,
                    f"{kind} inside the traced body of "
                    f"{info.qualname}: a device→host sync under "
                    "tracing breaks the jit purity contract "
                    "(ConcretizationTypeError at best)",
                    col=node.col_offset,
                    related=Related(rel, fn.lineno, "traced root")))
            continue
        # Host function: split syncs into traced-branch (HS311) and
        # host-contract (HS312) sites.
        own = list(df.walk_own(fn))
        branch_syncs = _sync_calls(
            [n for n in own if id(n) in branch_ids], branch_taint)
        for node, kind in branch_syncs:
            out.append(Diagnostic(
                "HS311", rel, node.lineno,
                f"{kind} inside the _is_tracer branch of "
                f"{info.qualname}: this branch runs under tracing, "
                "where a data-dependent sync cannot work",
                col=node.col_offset,
                related=Related(rel, fn.lineno, "tracer-guard branch")))
        host_syncs = _sync_calls(
            [n for n in own if id(n) not in branch_ids], taint)
        if not host_syncs:
            continue
        entry = HOST_SYNC_ALLOWLIST.get((src.slash_rel, info.qualname))
        if entry is not None:
            ctx.note_exemption(
                f"{src.slash_rel}#hostsync:{info.qualname}")
            allowed, why = entry
            if len(host_syncs) <= allowed:
                continue
            for node, kind in host_syncs[allowed:]:
                out.append(Diagnostic(
                    "HS312", rel, node.lineno,
                    f"{kind} in {info.qualname} exceeds its frozen "
                    f"sync budget ({allowed} allowed: {why})",
                    col=node.col_offset,
                    related=Related(rel, fn.lineno,
                                    "HOST_SYNC_ALLOWLIST entry")))
            continue
        for node, kind in host_syncs:
            out.append(Diagnostic(
                "HS312", rel, node.lineno,
                f"{kind} on a device value in {info.qualname}, which "
                "has no HOST_SYNC_ALLOWLIST entry; every sanctioned "
                "sync site is frozen with a justification "
                "(one-scalar-per-region contract, r15)",
                col=node.col_offset))
    return out


def _branch_args(branches) -> Set[str]:
    out: Set[str] = set()
    for br in branches:
        for sub in ast.walk(br.test):
            if isinstance(sub, ast.Call) and df.dotted_name(
                    sub.func).split(".")[-1] == "_is_tracer":
                for a in sub.args:
                    for n in ast.walk(a):
                        if isinstance(n, ast.Name):
                            out.add(n.id)
    return out
