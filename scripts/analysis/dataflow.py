"""Shared intraprocedural-dataflow machinery for the HS3xx passes.

Deliberately modest scope — everything here is *intra*procedural and
syntax-directed:

- function/method maps with qualnames and lexical parent chains
  (:func:`function_map`), so passes resolve a called name to its local
  definition (nested defs shadow module-level ones, like the runtime);
- lexical ``with``-guard sets (:func:`guarded_node_ids`): the node ids
  inside any ``with`` statement whose items include a given lock
  expression — the lock-discipline pass's "lexically inside
  ``with self._lock``" check;
- a conservative taint lattice (:class:`Taint`): names derived from
  device computations (``jnp.*``/``jax.*`` calls, known jitted
  callables, declared device parameters) are tainted; shape/dtype/len
  accesses launder the taint. No fixpoint — statements are scanned
  twice in order, which converges for the straight-line + simple-loop
  bodies kernel code actually has. False NEGATIVES are possible by
  design (a device value smuggled through an unregistered helper);
  false positives should be treated as pass bugs.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

FUNC_TYPES = (ast.FunctionDef, ast.AsyncFunctionDef)

MUTATOR_METHODS = {"append", "appendleft", "add", "update", "setdefault",
                   "pop", "popitem", "popleft", "clear", "extend",
                   "insert", "remove", "discard", "move_to_end"}


class FuncInfo:
    __slots__ = ("node", "qualname", "parent", "cls")

    def __init__(self, node, qualname: str, parent, cls: Optional[str]):
        self.node = node
        self.qualname = qualname
        self.parent = parent  # enclosing FuncInfo or None
        self.cls = cls        # name of the enclosing class, if a method


class FuncMap(dict):
    """id(FunctionDef) -> FuncInfo, plus resolution indexes built ONCE
    per file (the transitive handoff scan resolves one call per edge —
    rebuilding the indexes per call would be quadratic)."""

    def __init__(self, items):
        super().__init__(items)
        self.by_parent: Dict[Optional[int], Dict[str, FuncInfo]] = {}
        self.by_method: Dict[Tuple[str, str], FuncInfo] = {}
        for info in self.values():
            key = id(info.parent) if info.parent is not None else None
            self.by_parent.setdefault(key, {})[info.node.name] = info
            if info.cls is not None:
                self.by_method[(info.cls, info.node.name)] = info


def function_map(tree: ast.AST) -> FuncMap:
    """FuncMap for every def in the module, with dotted qualnames
    (``outer.inner``, ``Class.method``)."""
    out: Dict[int, FuncInfo] = {}

    def visit(node, prefix: str, parent, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FUNC_TYPES):
                q = f"{prefix}{child.name}"
                info = FuncInfo(child, q, parent, cls)
                out[id(child)] = info
                visit(child, q + ".", info, None)
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", parent, child.name)
            else:
                visit(child, prefix, parent, cls)

    visit(tree, "", None, None)
    return FuncMap(out)


def resolve_callable(name: str, site_fn: Optional[FuncInfo],
                     funcs: FuncMap) -> Optional[FuncInfo]:
    """The FuncInfo a bare name refers to from inside ``site_fn``:
    nested defs of the enclosing chain first, then module level."""
    fn = site_fn
    while fn is not None:
        hit = funcs.by_parent.get(id(fn), {}).get(name)
        if hit is not None:
            return hit
        fn = fn.parent
    info = funcs.by_parent.get(None, {}).get(name)
    if info is not None and info.cls is None:
        return info
    return None


def resolve_method(cls_name: str, meth: str,
                   funcs: FuncMap) -> Optional[FuncInfo]:
    return funcs.by_method.get((cls_name, meth))


def dotted_name(node) -> str:
    """'a.b.c' for Name/Attribute chains, '' otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _lock_item_matches(expr, spec: str) -> bool:
    """``spec`` forms: "self._lock" / "_LOCK_NAME" / "_STATE.lock"."""
    return dotted_name(expr) == spec


def guarded_node_ids(scope: ast.AST, lock_specs) -> Set[int]:
    """ids of every node lexically inside a ``with`` whose items include
    one of ``lock_specs`` (dotted-name strings), searched under
    ``scope``."""
    specs = tuple(lock_specs)
    out: Set[int] = set()
    for node in ast.walk(scope):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_lock_item_matches(item.context_expr, s)
                   for item in node.items for s in specs):
            continue
        for stmt in node.body:
            for sub in ast.walk(stmt):
                out.add(id(sub))
            out.add(id(stmt))
    return out


def self_attr_of_target(t) -> Optional[str]:
    """The base ``self.<attr>`` an assignment target mutates, digging
    through subscripts (``self._stats[k]`` mutates ``_stats``)."""
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
            and t.value.id == "self":
        return t.attr
    return None


def global_name_of_target(t) -> Optional[str]:
    while isinstance(t, ast.Subscript):
        t = t.value
    if isinstance(t, ast.Name):
        return t.id
    return None


def reads_attr(expr, attr: str) -> bool:
    """Does ``expr`` read ``self.<attr>`` anywhere? (RMW detection.)"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Attribute) and node.attr == attr \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self":
            return True
    return False


def reads_name(expr, name: str) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and node.id == name:
            return True
    return False


# ---------------------------------------------------------------------------
# Taint.
# ---------------------------------------------------------------------------

_DEVICE_PREFIXES = ("jnp.", "jax.", "lax.")
# Cross-module calls whose results are device values wherever they are
# used (the ProgramBank dispatch helpers).
DEVICE_PRODUCER_CALLS = frozenset({
    "run_fused_region", "run_fused_predicate",
    "run_fused_predicate_sweep",
})
# Attribute accesses that LAUNDER taint: static metadata of an array,
# not its payload (``int(x.shape[0])`` is host arithmetic).
_STATIC_ATTRS = ("shape", "ndim", "dtype", "size")
_HOST_CALLS = ("int", "float", "bool", "len", "str", "repr", "range",
               "max", "min", "isinstance")


class Taint:
    """Conservative device-value taint over one function body."""

    def __init__(self, func: ast.AST, seed_params: Set[str],
                 jitted_names: Set[str]):
        self.jitted = jitted_names
        self.tainted: Set[str] = set(seed_params)
        body = getattr(func, "body", [])
        for _ in range(2):  # simple loops converge on the second scan
            for stmt in body:
                self._scan(stmt)

    def _scan(self, stmt) -> None:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Assign):
                if self.expr_tainted(node.value):
                    for t in node.targets:
                        self._taint_target(t)
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                if self.expr_tainted(node.value):
                    self._taint_target(node.target)
            elif isinstance(node, ast.AugAssign):
                if self.expr_tainted(node.value):
                    self._taint_target(node.target)
            elif isinstance(node, (ast.For, ast.comprehension)):
                if self.expr_tainted(node.iter):
                    self._taint_target(node.target)

    def _taint_target(self, t) -> None:
        if isinstance(t, ast.Name):
            self.tainted.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._taint_target(e)
        elif isinstance(t, ast.Starred):
            self._taint_target(t.value)

    def call_produces_device(self, node: ast.Call) -> bool:
        name = dotted_name(node.func)
        if not name:
            return False
        leaf = name.split(".")[-1]
        if name.startswith(_DEVICE_PREFIXES) and leaf not in (
                "issubdtype", "iinfo", "finfo", "promote_types",
                "monitoring", "dtype"):
            return True
        if leaf in DEVICE_PRODUCER_CALLS or name in self.jitted \
                or leaf in self.jitted:
            return True
        return False

    def expr_tainted(self, e) -> bool:
        if e is None:
            return False
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self.expr_tainted(e.value)
        if isinstance(e, ast.Subscript):
            return self.expr_tainted(e.value)
        if isinstance(e, ast.Call):
            name = dotted_name(e.func)
            if name in _HOST_CALLS:
                return False
            if self.call_produces_device(e):
                return True
            # A method on a tainted receiver stays tainted
            # (``codes.astype(...)``, ``mask.sum()``).
            if isinstance(e.func, ast.Attribute) \
                    and self.expr_tainted(e.func.value):
                return True
            return False
        if isinstance(e, (ast.BinOp,)):
            return self.expr_tainted(e.left) or self.expr_tainted(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.expr_tainted(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self.expr_tainted(v) for v in e.values)
        if isinstance(e, ast.Compare):
            return self.expr_tainted(e.left) \
                or any(self.expr_tainted(c) for c in e.comparators)
        if isinstance(e, ast.IfExp):
            return self.expr_tainted(e.body) or self.expr_tainted(e.orelse)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.expr_tainted(v) for v in e.elts)
        if isinstance(e, ast.Starred):
            return self.expr_tainted(e.value)
        return False


def call_args_of(node: ast.Call) -> Tuple[list, dict]:
    return node.args, {k.arg: k.value for k in node.keywords}


def walk_own(func: ast.AST):
    """Walk a function's own statements WITHOUT descending into nested
    function definitions (those are visited through their own FuncInfo;
    and a def lexically under a ``with`` does not RUN under it).
    Breadth-first like ``ast.walk``, so site ordering is stable."""
    queue = list(ast.iter_child_nodes(func))
    i = 0
    while i < len(queue):
        node = queue[i]
        i += 1
        yield node
        if not isinstance(node, FUNC_TYPES + (ast.Lambda,)):
            queue.extend(ast.iter_child_nodes(node))
