"""The monolith's ~12 gates, ported onto the shared AST pipeline.

Every gate here is a line-for-line port of a `legacy_reference.py`
function with its ``ast.walk`` traversals replaced by lookups in the
file's one shared :class:`~.engine.NodeIndex` — same logic, same
message text, same ordering, ONE tree walk per file instead of one per
gate. tests/test_static_analysis.py asserts the output is byte-
identical to the monolith's on the live tree and on seeded fixture
trees; treat any behavior drift here as a bug even when the new
behavior looks "more correct".

The frozen allowlists stay in legacy_reference.py (their historical
home, still imported by existing tests through the scripts/lint.py
shim); this module reads them from there.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List

from . import legacy_reference as legacy
from .diagnostics import Diagnostic

# Gate order is the monolith's main() order; codes are the framework's
# stable ids for suppression/--json (rendered text stays legacy).
_pkg = legacy.PACKAGE_DIRS


def _legacy_diag(code: str, rel: str, line, text: str) -> Diagnostic:
    try:
        anchor = int(line)
    except (TypeError, ValueError):
        anchor = 1
    return Diagnostic(code, rel, anchor, text, legacy_text=text)


# ---------------------------------------------------------------------------
# Index-driven ports of the per-file gate helpers.
# ---------------------------------------------------------------------------

def unused_imports(idx) -> list:
    imported = {}
    for node in idx.of(ast.Import, ast.ImportFrom):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                imported[name] = node.lineno
        else:
            if node.module == "__future__":
                continue
            for a in node.names:
                if a.name == "*":
                    continue
                imported[a.asname or a.name] = node.lineno
    used = set()
    for node in idx.of(ast.Name):
        used.add(node.id)
    for node in idx.of(ast.Attribute):
        n = node
        while isinstance(n, ast.Attribute):
            n = n.value
        if isinstance(n, ast.Name):
            used.add(n.id)
    for node in idx.of(ast.Constant):
        if isinstance(node.value, str) and len(node.value) < 200:
            used.update(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", node.value))
    return sorted((line, name) for name, line in imported.items()
                  if name not in used and not name.startswith("_"))


def env_reads(idx) -> list:
    out = []
    for node in idx.of(ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "os" \
                and node.attr in ("environ", "getenv"):
            out.append(node.lineno)
    for node in idx.of(ast.ImportFrom):
        if node.module == "os" and any(
                a.name in ("environ", "getenv") for a in node.names):
            out.append(node.lineno)
    return sorted(set(out))


def config_key_literals(idx) -> list:
    out = []
    for node in idx.of(ast.Constant):
        if isinstance(node.value, str) \
                and legacy.CONFIG_KEY_PATTERN.match(node.value):
            out.append((node.lineno, node.value))
    return out


def jit_sites(idx) -> list:
    out = []
    for node in idx.of(ast.Attribute):
        if node.attr in ("jit", "pjit") \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "jax":
            out.append(node.lineno)
    for node in idx.of(ast.ImportFrom):
        if node.module and node.module.split(".")[0] == "jax" \
                and any(a.name in ("jit", "pjit") for a in node.names):
            out.append(node.lineno)
    return sorted(set(out))


def spmd_banned_sites(idx) -> list:
    out = []
    for node in idx.of(ast.Attribute):
        if node.attr in legacy.SPMD_BANNED_NAMES:
            out.append((node.lineno, node.attr))
    for node in idx.of(ast.Name):
        if node.id in legacy.SPMD_BANNED_NAMES:
            out.append((node.lineno, node.id))
    for node in idx.of(ast.ImportFrom):
        if node.module and any(part in legacy.SPMD_BANNED_NAMES
                               for part in node.module.split(".")):
            out.append((node.lineno, node.module))
    for node in idx.of(ast.Import, ast.ImportFrom):
        for a in node.names:
            if a.name and any(part in legacy.SPMD_BANNED_NAMES
                              for part in a.name.split(".")):
                out.append((node.lineno, a.name))
    return sorted(set(out))


def jit_sharding_violations(idx, lines: list) -> list:
    out = []
    for node in idx.of(ast.Call):
        if not (isinstance(node.func, ast.Attribute)
                and node.func.attr in ("jit", "pjit")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "jax"):
            continue
        kw = {k.arg for k in node.keywords}
        if {"in_shardings", "out_shardings"} & kw:
            continue
        lo = max(node.lineno - 5, 0)
        nearby = "\n".join(lines[lo:node.lineno])
        if "# shardings:" in nearby or "# replicated" in nearby:
            continue
        out.append(node.lineno)
    return sorted(set(out))


def thread_sites(idx) -> list:
    out = []
    for node in idx.of(ast.Attribute):
        if node.attr == "Thread" and isinstance(node.value, ast.Name) \
                and node.value.id == "threading":
            out.append(node.lineno)
        elif node.attr == "ThreadPoolExecutor":
            out.append(node.lineno)
    for node in idx.of(ast.Name):
        if node.id == "ThreadPoolExecutor":
            out.append(node.lineno)
    for node in idx.of(ast.ImportFrom):
        if node.module and node.module.split(".")[0] in (
                "threading", "concurrent") and any(
                a.name in ("Thread", "ThreadPoolExecutor")
                for a in node.names):
            out.append(node.lineno)
    return sorted(set(out))


def socket_sites(idx) -> list:
    out = []
    server_names = ("HTTPServer", "ThreadingHTTPServer", "TCPServer",
                    "UDPServer")
    for node in idx.of(ast.Import):
        if any(a.name.split(".")[0] in ("socket", "socketserver")
               for a in node.names):
            out.append(node.lineno)
    for node in idx.of(ast.ImportFrom):
        if not node.module:
            continue
        root = node.module.split(".")[0]
        if root in ("socket", "socketserver"):
            out.append(node.lineno)
        elif root == "http" and any(a.name in server_names
                                    for a in node.names):
            out.append(node.lineno)
    for node in idx.of(ast.Attribute):
        if node.attr in ("socket", "create_connection",
                         "create_server") \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "socket":
            out.append(node.lineno)
    for node in idx.of(ast.Name):
        if node.id in server_names:
            out.append(node.lineno)
    return sorted(set(out))


def decode_sites(idx) -> list:
    out = []
    for node in idx.of(ast.Attribute):
        if not isinstance(node.value, ast.Name):
            continue
        if node.attr in ("read_table", "ParquetFile") \
                and node.value.id.lstrip("_") in ("pq", "parquet"):
            out.append(node.lineno)
        elif node.attr == "device_put" and node.value.id == "jax":
            out.append(node.lineno)
    for node in idx.of(ast.ImportFrom):
        if not node.module:
            continue
        root = node.module.split(".")[0]
        if root == "jax" and any(a.name == "device_put"
                                 for a in node.names):
            out.append(node.lineno)
        elif root == "pyarrow" and node.module.endswith("parquet") \
                and any(a.name in ("read_table", "ParquetFile")
                        for a in node.names):
            out.append(node.lineno)
    return sorted(set(out))


def _mutated_names(idx) -> set:
    out = set()
    for node in idx.of(ast.Assign, ast.AugAssign):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name):
                out.add(t.value.id)
    for node in idx.of(ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript) \
                    and isinstance(t.value, ast.Name):
                out.add(t.value.id)
    for node in idx.of(ast.Call):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in legacy._MUTATOR_METHODS \
                and isinstance(node.func.value, ast.Name):
            out.add(node.func.value.id)
    return out


def mutable_state_sites(tree: ast.AST, idx) -> list:
    mutated = _mutated_names(idx)
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        else:
            continue
        names = [t.id for t in targets if isinstance(t, ast.Name)]
        if not names or names == ["__all__"]:
            continue
        mutable = isinstance(value, (ast.List, ast.Dict, ast.Set,
                                     ast.ListComp, ast.DictComp,
                                     ast.SetComp))
        if not mutable and isinstance(value, ast.Call):
            f = value.func
            callee = f.id if isinstance(f, ast.Name) else (
                f.attr if isinstance(f, ast.Attribute) else "")
            mutable = callee in legacy._MUTABLE_CALLS
        if mutable and any(n in mutated for n in names):
            out.append((node.lineno, names[0]))
    return out


def _registry_site_violations(idx, names: dict, *, call_attrs,
                              recv_names, const_aliases,
                              missing_msg: str, bad_msg: str,
                              name_calls=()) -> list:
    """Shared body of the span/fault/fusion site gates: call sites whose
    first argument is neither an aliased registry constant nor a
    registered literal."""
    values = set(names.values())
    out = []
    for node in idx.of(ast.Call):
        f = node.func
        is_attr_call = (isinstance(f, ast.Attribute)
                        and f.attr in call_attrs
                        and isinstance(f.value, ast.Name)
                        and f.value.id in recv_names)
        is_name_call = (isinstance(f, ast.Name) and f.id in name_calls)
        if not (is_attr_call or is_name_call):
            continue
        if not node.args:
            out.append((node.lineno, missing_msg))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in const_aliases and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno, bad_msg))
    return out


def span_site_violations(idx, names: dict) -> list:
    return _registry_site_violations(
        idx, names, call_attrs=("span", "add_span"),
        recv_names=("trace", "_trace", "_tr"),
        const_aliases=legacy.SPAN_MODULE_ALIASES,
        missing_msg="no span name argument",
        bad_msg="span name must come from telemetry/span_names.py")


def fault_site_violations(idx, names: dict) -> list:
    return _registry_site_violations(
        idx, names, call_attrs=("fault_point",),
        recv_names=legacy.FAULT_MODULE_ALIASES,
        const_aliases=legacy.FAULT_NAME_ALIASES,
        missing_msg="no fault-point name argument",
        bad_msg="fault-point name must come from "
                "robustness/fault_names.py",
        name_calls=("fault_point",))


def fusion_boundary_violations(idx, names: dict) -> list:
    values = set(names.values())
    out = []
    for node in idx.of(ast.Call):
        f = node.func
        callee = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if callee not in legacy.FUSION_BOUNDARY_CALLS:
            continue
        if not node.args:
            out.append((node.lineno, "no boundary-kind argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in legacy.FUSION_BOUNDARY_ALIASES \
                and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno, "boundary kind must come from "
                    "execution/fusion_boundaries.py"))
    return out


def metric_site_violations(idx, names: dict) -> list:
    values = set(names.values())
    out = []
    for node in idx.of(ast.Call):
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and f.attr in legacy.METRIC_CALLS):
            continue
        if not node.args:
            out.append((node.lineno, "no metric name argument"))
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Attribute) \
                and isinstance(arg.value, ast.Name) \
                and arg.value.id in legacy.METRIC_NAME_ALIASES \
                and arg.attr in names:
            continue
        if isinstance(arg, ast.Constant) and arg.value in values:
            continue
        out.append((node.lineno, "metric name must come from "
                    "telemetry/metric_names.py"))
    return out


def except_swallow_sites(idx) -> list:
    out = []
    for node in idx.of(ast.ExceptHandler):
        if node.type is None:
            out.append((node.lineno,
                        "bare 'except:'; name the exception classes"))
            continue
        body_is_pass = all(isinstance(s, ast.Pass) for s in node.body)
        if body_is_pass and "BaseException" in \
                legacy._names_in_except_type(node.type):
            out.append((node.lineno,
                        "'except BaseException: pass' swallows "
                        "cancellation and crashes silently"))
    return out


# ---------------------------------------------------------------------------
# The per-file runner (the monolith's main-loop body, gate by gate).
# ---------------------------------------------------------------------------

def check_file(src, ctx) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    rel = src.rel
    if src.syntax_error is not None:
        e = src.syntax_error
        out.append(_legacy_diag(
            "HS001", rel, e.lineno,
            f"{rel}:{e.lineno}: syntax error: {e.msg}"))
        return out
    idx = src.index
    slash = src.slash_rel
    in_pkg = src.is_package
    for i, line in enumerate(src.lines, 1):
        if "\t" in line:
            out.append(_legacy_diag("HS101", rel, i,
                                    f"{rel}:{i}: tab character"))
        if line != line.rstrip():
            out.append(_legacy_diag("HS102", rel, i,
                                    f"{rel}:{i}: trailing whitespace"))
        if len(line) > legacy.MAX_LINE:
            out.append(_legacy_diag(
                "HS103", rel, i,
                f"{rel}:{i}: line longer than {legacy.MAX_LINE}"))
    if in_pkg and os.path.basename(src.path) != "__init__.py":
        for line, name in unused_imports(idx):
            out.append(_legacy_diag(
                "HS104", rel, line,
                f"{rel}:{line}: unused import '{name}'"))
    if in_pkg and slash not in legacy.ENV_READ_ALLOWLIST:
        for line in env_reads(idx):
            out.append(_legacy_diag(
                "HS201", rel, line,
                f"{rel}:{line}: ad-hoc env read (os.environ/getenv); "
                "knobs must go through config.py accessors"))
    if in_pkg:
        for line, key in config_key_literals(idx):
            if key not in ctx.config_doc_text:
                out.append(_legacy_diag(
                    "HS202", rel, line,
                    f"{rel}:{line}: config key '{key}' is not "
                    f"documented in {legacy.CONFIG_DOC}"))
    if in_pkg and slash not in legacy.JIT_SITE_ALLOWLIST:
        for line in jit_sites(idx):
            out.append(_legacy_diag(
                "HS203", rel, line,
                f"{rel}:{line}: jax.jit outside the instrumented "
                "kernel modules; add the jitted stage to ops/kernels.py "
                "so the compile counter sees it"))
    for line, name in spmd_banned_sites(idx):
        out.append(_legacy_diag(
            "HS204", rel, line,
            f"{rel}:{line}: '{name}' is forbidden repo-wide; the SPMD "
            "tier is NamedSharding+jit only (parallel/sharding.py)"))
    if slash in legacy.SPMD_JIT_SHARDING_MODULES:
        for line in jit_sharding_violations(idx, src.lines):
            out.append(_legacy_diag(
                "HS205", rel, line,
                f"{rel}:{line}: jax.jit in a distributed module must "
                "pass explicit in_shardings/out_shardings or carry a "
                "'# shardings:'/'# replicated' marker comment"))
    if in_pkg and slash not in legacy.MUTABLE_STATE_ALLOWLIST:
        for line, name in mutable_state_sites(src.tree, idx):
            out.append(_legacy_diag(
                "HS206", rel, line,
                f"{rel}:{line}: module-level mutable state '{name}'; "
                "cross-query state belongs in QueryContext "
                "(serving/context.py) or a sanctioned frontend "
                "registry (see MUTABLE_STATE_ALLOWLIST)"))
    if in_pkg:
        for line, detail in span_site_violations(idx, ctx.span_names):
            out.append(_legacy_diag(
                "HS207", rel, line,
                f"{rel}:{line}: {detail} (frozen registry; free-form "
                "span strings are forbidden)"))
        for line, detail in fault_site_violations(idx, ctx.fault_names):
            out.append(_legacy_diag(
                "HS208", rel, line,
                f"{rel}:{line}: {detail} (frozen registry; free-form "
                "fault-point strings are forbidden)"))
        for line, detail in fusion_boundary_violations(idx,
                                                       ctx.fusion_kinds):
            out.append(_legacy_diag(
                "HS209", rel, line,
                f"{rel}:{line}: {detail} (frozen registry; free-form "
                "fusion-boundary kinds are forbidden)"))
        for line, detail in metric_site_violations(idx,
                                                   ctx.metric_names):
            out.append(_legacy_diag(
                "HS216", rel, line,
                f"{rel}:{line}: {detail} (frozen registry; free-form "
                "metric names are forbidden)"))
    if in_pkg and slash not in legacy.EXCEPT_SWALLOW_ALLOWLIST:
        for line, detail in except_swallow_sites(idx):
            out.append(_legacy_diag("HS210", rel, line,
                                    f"{rel}:{line}: {detail}"))
    if in_pkg and slash not in legacy.THREAD_SITE_ALLOWLIST:
        for line in thread_sites(idx):
            out.append(_legacy_diag(
                "HS211", rel, line,
                f"{rel}:{line}: thread/pool construction outside "
                "parallel/io.py; route the work through its "
                "map_ordered/prefetch_iter so the in-flight byte "
                "budget and ordered-gather contract hold"))
    if in_pkg and slash not in legacy.SOCKET_SITE_ALLOWLIST:
        for line in socket_sites(idx):
            out.append(_legacy_diag(
                "HS341", rel, line,
                f"{rel}:{line}: socket creation outside "
                "cluster/transport.py; ride the cluster transport "
                "so framing, deadlines, and retry semantics hold "
                "(telemetry/exposition.py's HTTP exporter is the "
                "one other sanctioned listener)"))
    if in_pkg and slash not in legacy.DECODE_SITE_ALLOWLIST:
        for line in decode_sites(idx):
            out.append(_legacy_diag(
                "HS342", rel, line,
                f"{rel}:{line}: parquet decode or device transfer "
                "outside the buffer-pool modules; route the read "
                "through execution/buffer_pool.py or columnar.py so "
                "the tiered pool's hit/transfer counters and "
                "file-signature invalidation contract hold"))
    return out


def finalize(ctx) -> List[Diagnostic]:
    """The monolith's five trailing coverage checks, in its order."""
    out: List[Diagnostic] = []
    for name in ctx.event_classes:
        if name not in ctx.registry_hits["event"]:
            out.append(_legacy_diag(
                "HS212", legacy.EVENTS_FILE, 1,
                f"{legacy.EVENTS_FILE}: event class '{name}' is never "
                "referenced under tests/; add a test observing (or at "
                "least naming) it"))
    for const, value in sorted(ctx.span_names.items()):
        if const == "SPAN_NAMES":
            continue
        if value not in ctx.registry_hits["span"]:
            out.append(_legacy_diag(
                "HS213", legacy.SPAN_NAMES_FILE, 1,
                f"{legacy.SPAN_NAMES_FILE}: span name '{value}' "
                f"({const}) is never referenced under tests/; add a "
                "test observing it"))
    for const, value in sorted(ctx.fault_names.items()):
        if const == "FAULT_NAMES":
            continue
        if value not in ctx.registry_hits["fault"]:
            out.append(_legacy_diag(
                "HS214", legacy.FAULT_NAMES_FILE, 1,
                f"{legacy.FAULT_NAMES_FILE}: fault point '{value}' "
                f"({const}) is never referenced under tests/; add a "
                "test injecting it"))
    for const, value in sorted(ctx.fusion_kinds.items()):
        if const == "BOUNDARY_KINDS":
            continue
        if value not in ctx.registry_hits["fusion"]:
            out.append(_legacy_diag(
                "HS215", legacy.FUSION_BOUNDARIES_FILE, 1,
                f"{legacy.FUSION_BOUNDARIES_FILE}: boundary kind "
                f"'{value}' ({const}) is never referenced under tests/; "
                "add a test exercising it"))
    for const, value in sorted(ctx.metric_names.items()):
        if const == "METRIC_NAMES":
            continue
        if value not in ctx.registry_hits["metric"]:
            out.append(_legacy_diag(
                "HS217", legacy.METRIC_NAMES_FILE, 1,
                f"{legacy.METRIC_NAMES_FILE}: metric name '{value}' "
                f"({const}) is never referenced under tests/; add a "
                "test observing it"))
    return out
