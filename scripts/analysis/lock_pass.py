"""HS301/HS302 — lock-discipline race detector.

A frozen registry names the process-shared mutable state the 8-thread
serving path can hit concurrently, in two shapes:

- **classes** (:data:`LOCK_CLASSES`): instance attributes that must only
  be mutated lexically inside ``with self.<lock>`` (``__init__`` is
  construction and exempt; *delegating methods* — helpers documented to
  run with the lock already held by every caller — are registered
  per-class and count as frozen exemptions with a printed
  justification);
- **module-global groups** (:data:`LOCK_GLOBALS`): module-level
  counters/registries that must only be mutated inside ``with <lock>``
  (their module-top initialization is exempt).

Findings: a plain unguarded mutation is **HS301**; an unguarded
compound read-modify-write (``x += 1``, ``self.n = self.n + d`` — the
shape that LOSES updates under contention, r11's audit class) is
**HS302**. Both carry the registered lock as the related site.

The registry is FROZEN the same way the span/fault-name registries are:
additions need a justification string (printed by
``scripts/lint.py --exemptions``) and a test; entries that stop
matching real code surface as HS004 (unused exemption).
"""

from __future__ import annotations

import ast
from typing import List

from . import dataflow as df
from .diagnostics import Diagnostic, Related

# (slash rel, class name) -> rule. ``locks`` maps a lock attribute to
# the attribute names it guards (None = every instance attribute).
# ``delegates`` are methods whose callers all hold the lock already.
LOCK_CLASSES = {
    ("hyperspace_tpu/serving/program_bank.py", "ProgramBank"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "THE cross-session compiled-program registry; every "
               "serving worker's lookup mutates its LRU + counters",
    },
    ("hyperspace_tpu/serving/result_cache.py", "ResultCache"): {
        "locks": {"_lock": None},
        "delegates": frozenset({"_drop", "_pop_device_victims",
                                "_pop_host_victims"}),
        "why": "three-tier result cache shared by every query thread; "
               "the delegates are eviction helpers every caller invokes "
               "under the lock (their docstrings say 'Under the lock')",
    },
    ("hyperspace_tpu/telemetry/metrics.py", "MetricsRegistry"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "process-wide metrics registry; push-side feeds come "
               "from arbitrary threads",
    },
    ("hyperspace_tpu/telemetry/metrics.py", "SlidingHistogram"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "serving latency histogram; record() runs per completed "
               "query on worker threads",
    },
    ("hyperspace_tpu/serving/frontend.py", "ServingFrontend"): {
        "locks": {"_lock": None},
        "delegates": frozenset({"_collect_batch"}),
        "why": "admission queue + stats shared by submitters and the "
               "drain workers; _collect_batch documents 'Under the "
               "lock' and is only called with it held",
    },
    ("hyperspace_tpu/serving/context.py", "QueryContext"): {
        "locks": {"_io_lock": {"_io", "_cancel_emitted"}},
        "delegates": frozenset(),
        "why": "per-query io counters are written by prefetch producers "
               "on other threads (copied contexts)",
    },
    ("hyperspace_tpu/cluster/worker.py", "ClusterNode"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "forward/broadcast stats are bumped by the submit path, "
               "the server's connection threads, and the heartbeat — "
               "three thread families over one counter dict",
    },
    ("hyperspace_tpu/cluster/gather.py", "_GatherHub"): {
        "locks": {"_cond": None},
        "delegates": frozenset(),
        "why": "rendezvous slots are filled by one connection thread "
               "per rank; the condition is both the mutex and the "
               "all-parts-arrived wakeup",
    },
    ("hyperspace_tpu/robustness/faults.py", "FaultRegistry"): {
        "locks": {"_lock": {"_hits", "_fired"}},
        "delegates": frozenset(),
        "why": "one armed registry is shared across a submission wave; "
               "nth/times counters must not tear",
    },
    ("hyperspace_tpu/robustness/faults.py", "_Stats"): {
        "locks": {"_lock": {"_counts"}},
        "delegates": frozenset(),
        "why": "process-lifetime robustness counters, bumped from "
               "workers and degradation ladders",
    },
    ("hyperspace_tpu/parallel/sharding.py", "MeshProgram"): {
        "locks": {"_lock": {"_compiled"}},
        "delegates": frozenset(),
        "why": "AOT program map; two sessions can race the same stage's "
               "first compile",
    },
    ("hyperspace_tpu/streaming/ingest.py", "CommitQueue"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "process-wide staged-batch registry of the ingestion "
               "tier; appends/commits race from serving workers",
    },
    ("hyperspace_tpu/streaming/ingest.py", "CommitCoordinator"): {
        "locks": {"_cv": None},
        "delegates": frozenset(),
        "why": "group-commit wave ledger; concurrent committers elect "
               "a leader and park as riders on the one condition",
    },
    ("hyperspace_tpu/streaming/sources.py", "ContinuousSource"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "tailer daemon mutates pending/stats while stop()/"
               "stats() read from caller threads",
    },
    ("hyperspace_tpu/streaming/sources.py", "DirectoryTailSource"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "consumed-name set shared between the poll loop and "
               "discovery",
    },
    ("hyperspace_tpu/streaming/sources.py", "LogTailSource"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "consumed byte offset advances on the daemon while "
               "stats() reads",
    },
    ("hyperspace_tpu/streaming/subscriptions.py", "SubscriptionRegistry"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "standing-query table; subscribes race commit-time fires",
    },
    ("hyperspace_tpu/streaming/subscriptions.py", "Subscription"): {
        "locks": {"_cv": None},
        "delegates": frozenset(),
        "why": "deliveries append from serving worker completion "
               "callbacks while consumers poll",
    },
    ("hyperspace_tpu/telemetry/flight_recorder.py", "FlightRecorder"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "process-wide anomaly rings fed by every event "
               "construction and trace retention across worker threads",
    },
    ("hyperspace_tpu/telemetry/slo.py", "SloMonitor"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "sliding SLO window fed per completed query from "
               "serving workers; breach edge state must not tear",
    },
    ("hyperspace_tpu/adaptive/feedback.py", "CorrectionStore"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "process-wide cardinality correction store; executors "
               "observe() from serving workers while reorders read",
    },
    ("hyperspace_tpu/adaptive/admission.py", "AdmissionController"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "process-wide overload verdict + tallies; submits race "
               "from client threads against the rate-limited refresh",
    },
    ("hyperspace_tpu/adaptive/builder.py", "BuilderLedger"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "builder accounting shared by the daemon loop, explicit "
               "run_once callers, and stats readers",
    },
    ("hyperspace_tpu/artifacts/store.py", "ArtifactStore"): {
        "locks": {"_lock": None},
        "delegates": frozenset({"_load_usage_locked"}),
        "why": "one store per lake root shared by every session over "
               "it; hit/miss/persist counters and the usage tallies are "
               "bumped from concurrent serving workers; "
               "_load_usage_locked runs at construction, before the "
               "store escapes __init__",
    },
    ("hyperspace_tpu/artifacts/manager.py", "ArtifactManager"): {
        "locks": {"_lock": {"_loaded", "warm_hits", "preloaded",
                            "preload_ms", "preload_bytes"},
                  "_util_lock": {"_util"}},
        "delegates": frozenset(),
        "why": "per-root executable cache probed by every dispatch "
               "seam while the boot preloader populates it; the "
               "utility-kernel map has its own lock (ordering: "
               "_util_lock -> _lock, never reversed)",
    },
    ("hyperspace_tpu/artifacts/manager.py", "AotStage"): {
        "locks": {"_lock": {"_compiled"}},
        "delegates": frozenset(),
        "why": "bank stages are process-shared; two serving workers "
               "can race one signature's first AOT acquire",
    },
    ("hyperspace_tpu/artifacts/manager.py", "_ManagerRegistry"): {
        "locks": {"_lock": {"_by_root"}},
        "delegates": frozenset(),
        "why": "double-checked per-root manager construction",
    },
    ("hyperspace_tpu/execution/buffer_pool.py", "BufferPool"): {
        "locks": {"_lock": None},
        "delegates": frozenset({"_bump_ns", "_drop",
                                "_pop_device_victims",
                                "_pop_host_victims"}),
        "why": "THE process-wide tiered scan-buffer cache; every query "
               "thread's probe mutates two LRU tiers + counters, and "
               "the delegates are under-lock helpers (their docstrings "
               "say 'Under the lock') whose demote/promote conversions "
               "the callers run outside it",
    },
    ("hyperspace_tpu/index/log_manager.py", "LogLookupCache"): {
        "locks": {"_lock": None},
        "delegates": frozenset(),
        "why": "process-wide op-log lookup memo probed per query per "
               "index on the serving hot path",
    },
    ("hyperspace_tpu/session.py", "Session"): {
        "locks": {"_views_lock": {"_temp_views", "_temp_views_version"},
                  "_join_actuals_lock": {"_join_actuals"},
                  "_sql_plan_lock": {"_sql_plan_cache",
                                     "_sql_plan_stats"},
                  "_usage_counts_lock": {"_index_usage_counts"}},
        "delegates": frozenset(),
        "why": "sessions are shared by serving workers; these four "
               "stores are the documented multi-thread surfaces (r11 "
               "thread-safety audit)",
    },
}

# slash rel -> [{lock, names, why}]: module globals that serving-path
# code mutates. The lock spec is a dotted name as written at the with
# site ("_COUNT_LOCK", "_STATE.lock").
LOCK_GLOBALS = {
    "hyperspace_tpu/parallel/io.py": [
        {"lock": "_pool_lock", "names": {"_pool", "_pool_size"},
         "why": "reader-pool grow-only replacement races submits"},
        {"lock": "_serving_lock",
         "names": {"_serving_pool", "_serving_pool_size"},
         "why": "serving-pool grow-only replacement races submits"},
        {"lock": "_stats_lock", "names": {"_STATS"},
         "why": "process io counters are bumped per pooled read"},
    ],
    "hyperspace_tpu/serving/frontend.py": [
        {"lock": "_DEFAULT_LOCK", "names": {"_DEFAULT"},
         "why": "first-constructed frontend becomes the process "
                "default exactly once"},
    ],
    "hyperspace_tpu/serving/program_bank.py": [
        {"lock": "_BANK_LOCK", "names": {"_BANK"},
         "why": "double-checked singleton construction"},
    ],
    "hyperspace_tpu/execution/buffer_pool.py": [
        {"lock": "_POOL_LOCK", "names": {"_POOL"},
         "why": "double-checked singleton construction"},
    ],
    "hyperspace_tpu/streaming/ingest.py": [
        {"lock": "_QUEUE_LOCK", "names": {"_QUEUE"},
         "why": "double-checked singleton construction"},
        {"lock": "_COORD_LOCK", "names": {"_COORD"},
         "why": "double-checked singleton construction"},
    ],
    "hyperspace_tpu/telemetry/metrics.py": [
        {"lock": "_REGISTRY_LOCK", "names": {"_REGISTRY"},
         "why": "double-checked singleton construction"},
    ],
    "hyperspace_tpu/artifacts/manager.py": [
        {"lock": "_REGISTRY_LOCK", "names": {"_REGISTRY"},
         "why": "double-checked singleton construction"},
    ],
    "hyperspace_tpu/cluster/worker.py": [
        {"lock": "_NODE_LOCK", "names": {"_NODE"},
         "why": "double-checked singleton construction"},
    ],
    "hyperspace_tpu/cluster/gather.py": [
        {"lock": "_HUB_LOCK",
         "names": {"_HUB", "_SEQ", "_NATIVE_OK", "_FORCED"},
         "why": "rank-0 hub construction, the gather sequence counter, "
                "and the cached native-collectives verdict are all "
                "touched from concurrent gather callers"},
    ],
    "hyperspace_tpu/parallel/sharding.py": [
        {"lock": "_COUNT_LOCK",
         "names": {"COMPILE_COUNT", "DISPATCH_COUNT"},
         "why": "mesh compile/dispatch tallies are asserted exact by "
                "tests and bumped from concurrent serving workers"},
    ],
    "hyperspace_tpu/execution/spmd.py": [
        {"lock": "_COUNT_LOCK",
         "names": {"DISPATCH_COUNT", "SORT_DISPATCH_COUNT",
                   "LAST_CAP_ATTEMPTS"},
         "why": "SPMD dispatch tallies (explain/bench read them; "
                "serving workers bump them concurrently)"},
    ],
    "hyperspace_tpu/parallel/distributed_build.py": [
        {"lock": "_COUNT_LOCK", "names": {"DISPATCH_COUNT"},
         "why": "distributed-build dispatch tally"},
    ],
    "hyperspace_tpu/execution/fusion.py": [
        {"lock": "_STATE.lock", "names": {"DISPATCH_COUNT"},
         "why": "fused-execution tally lives beside the _FusionState "
                "counters its stats() reports it with"},
    ],
    "hyperspace_tpu/execution/executor.py": [
        {"lock": "_CHUNK_STATS_LOCK", "names": {"CHUNK_SCAN_STATS"},
         "why": "chunked-scan watermark counters; serving workers "
                "stream chunks concurrently"},
    ],
    "hyperspace_tpu/ops/index_build.py": [
        {"lock": "_CHUNK_STATS_LOCK", "names": {"CHUNK_STATS"},
         "why": "chunked-build watermark counters; concurrent actions "
                "build indexes in parallel"},
    ],
    "hyperspace_tpu/execution/shapes.py": [
        {"lock": "_counter_lock",
         "names": {"_compile_total", "_compile_seconds", "_scope_counts",
                   "_listener_installed"},
         "why": "the backend-compile counter fires from any thread "
                "that triggers an XLA compile"},
    ],
}


def exemption_ids() -> dict:
    """Delegate-method exemptions, for the HS004 unused-entry check."""
    out = {}
    for (rel, cls), rule in LOCK_CLASSES.items():
        for meth in rule["delegates"]:
            out[f"{rel}#lock-delegate:{cls}.{meth}"] = rule["why"]
    return out


def describe_exemptions() -> List[str]:
    out = []
    for (rel, cls), rule in sorted(LOCK_CLASSES.items()):
        locks = ", ".join(sorted(rule["locks"]))
        out.append(f"lock[{rel} {cls} via {locks}]: {rule['why']}")
        for meth in sorted(rule["delegates"]):
            out.append(f"  delegate {cls}.{meth}: callers hold the lock")
    for rel, groups in sorted(LOCK_GLOBALS.items()):
        for g in groups:
            names = ", ".join(sorted(g["names"]))
            out.append(f"lock[{rel} globals {names} via {g['lock']}]: "
                       f"{g['why']}")
    return out


def _is_rmw(node, attr_or_name: str, self_attr: bool) -> bool:
    if isinstance(node, ast.AugAssign):
        return True
    if isinstance(node, ast.Assign):
        reads = df.reads_attr if self_attr else df.reads_name
        return reads(node.value, attr_or_name)
    return False


def _mutations_in(func_node, own_only: bool = False):
    """(node, attr-or-None, global-name-or-None, is_call) mutation sites
    in a function body. ``own_only`` skips nested defs (the module-
    global scan visits those through their own FuncInfo)."""
    out = []
    nodes = df.walk_own(func_node) if own_only else ast.walk(func_node)
    for node in nodes:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                a = df.self_attr_of_target(t)
                if a is not None:
                    out.append((node, a, None, False))
                    continue
                g = None
                # Plain `x = ...` rebinding a local is not a global
                # mutation; `x[k] = ...` through a registered global is.
                if isinstance(t, ast.Subscript):
                    g = df.global_name_of_target(t)
                elif isinstance(t, ast.Name):
                    g = t.id
                if g is not None:
                    out.append((node, None, g, False))
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                a = df.self_attr_of_target(t)
                if a is not None:
                    out.append((node, a, None, False))
                else:
                    g = df.global_name_of_target(t)
                    if g is not None:
                        out.append((node, None, g, False))
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in df.MUTATOR_METHODS:
            recv = node.func.value
            a = df.self_attr_of_target(recv)
            if a is not None:
                out.append((node, a, None, True))
            else:
                g = df.global_name_of_target(recv)
                if g is not None:
                    out.append((node, None, g, True))
    return out


def _globals_declared(func_node) -> set:
    out = set()
    for node in ast.walk(func_node):
        if isinstance(node, ast.Global):
            out.update(node.names)
    return out


def check_file(src, ctx) -> List[Diagnostic]:
    out: List[Diagnostic] = []
    rel = src.rel
    slash = src.slash_rel
    class_rules = {cls: rule for (r, cls), rule in LOCK_CLASSES.items()
                   if r == slash}
    global_groups = LOCK_GLOBALS.get(slash, [])
    if not class_rules and not global_groups:
        return out
    idx = src.index

    # -- registered classes -------------------------------------------
    for cls_node in idx.of(ast.ClassDef):
        rule = class_rules.get(cls_node.name)
        if rule is None:
            continue
        lock_specs = ["self." + lk for lk in rule["locks"]]
        attr_to_lock = {}
        catch_all = None
        for lk, attrs in rule["locks"].items():
            if attrs is None:
                catch_all = lk
            else:
                for a in attrs:
                    attr_to_lock[a] = lk
        for meth in cls_node.body:
            if not isinstance(meth, df.FUNC_TYPES):
                continue
            if meth.name == "__init__":
                continue
            if meth.name in rule["delegates"]:
                ctx.note_exemption(
                    f"{slash}#lock-delegate:{cls_node.name}.{meth.name}")
                continue
            # A nested def/lambda lexically under the with-lock does
            # NOT run under it (it's a deferred callable) — so each
            # function body gets its OWN guard set and own-statements
            # scan, exactly like the module-global pass.
            for fn_node, guarded in _method_scopes(meth, lock_specs):
                _check_method_scope(out, rel, cls_node, rule, meth,
                                    attr_to_lock, catch_all, fn_node,
                                    guarded)
    _check_global_groups(out, src, rel, global_groups)
    return out


def _method_scopes(meth, lock_specs):
    """(function node, guard set) for a method and every nested
    def/lambda inside it. Each scope is guard-computed from its own
    subtree and mutation-scanned own-statements-only, so a with-lock in
    an ENCLOSING scope never guards a deferred callable's body (the
    callable runs later, unlocked) — the module-global pass's
    walk_own contract, applied to classes."""
    scopes = [meth]
    for node in ast.walk(meth):
        if isinstance(node, df.FUNC_TYPES + (ast.Lambda,)) \
                and node is not meth:
            scopes.append(node)
    return [(fn, df.guarded_node_ids(fn, lock_specs)) for fn in scopes]


def _check_method_scope(out, rel, cls_node, rule, meth, attr_to_lock,
                        catch_all, fn_node, guarded) -> None:
    for node, attr, _g, is_call in _mutations_in(fn_node,
                                                 own_only=True):
        if attr is None:
            continue
        lock = attr_to_lock.get(attr, catch_all)
        if lock is None:
            continue  # attribute outside every guarded group
        if id(node) in guarded:
            continue
        rmw = not is_call and _is_rmw(node, attr, True)
        kind = "read-modify-write loses updates" if rmw \
            else "unguarded shared-state mutation"
        out.append(Diagnostic(
            "HS302" if rmw else "HS301", rel, node.lineno,
            f"{cls_node.name}.{meth.name} mutates "
            f"self.{attr} outside 'with self.{lock}' "
            f"({kind}; registered shared-state class)",
            col=node.col_offset,
            related=Related(rel, cls_node.lineno,
                            f"register: {rule['why']}")))


def _check_global_groups(out, src, rel, global_groups) -> None:
    # -- registered module-global groups ------------------------------
    if global_groups:
        funcs = df.function_map(src.tree)
        name_to_group = {}
        for g in global_groups:
            for n in g["names"]:
                name_to_group[n] = g
        for info in funcs.values():
            declared = _globals_declared(info.node)
            guard_cache = {}
            for node, _attr, gname, is_call in _mutations_in(
                    info.node, own_only=True):
                if gname is None or gname not in name_to_group:
                    continue
                grp = name_to_group[gname]
                # A bare `x = ...` in a function only mutates the global
                # when declared global; subscript/mutator writes always
                # reach the module object.
                if not is_call and isinstance(node, (ast.Assign,
                                                     ast.AnnAssign,
                                                     ast.AugAssign)):
                    plain_name = any(
                        isinstance(t, ast.Name)
                        for t in (node.targets if isinstance(
                            node, ast.Assign) else [node.target]))
                    if plain_name and gname not in declared:
                        continue
                lock = grp["lock"]
                if lock not in guard_cache:
                    guard_cache[lock] = df.guarded_node_ids(
                        info.node, [lock])
                if id(node) in guard_cache[lock]:
                    continue
                rmw = not is_call and _is_rmw(node, gname, False)
                kind = "read-modify-write loses updates" if rmw \
                    else "unguarded shared-state mutation"
                out.append(Diagnostic(
                    "HS302" if rmw else "HS301", rel, node.lineno,
                    f"{info.qualname} mutates module global "
                    f"'{gname}' outside 'with {lock}' "
                    f"({kind}; registered shared-state group)",
                    col=node.col_offset,
                    related=Related(rel, node.lineno, grp["why"])))
