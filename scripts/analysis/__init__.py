"""Multi-pass static-analysis framework behind ``scripts/lint.py``.

One shared pipeline (engine.py: parse once, one AST walk per file)
feeding three layers of passes:

- ported.py — the retired monolith's ~12 gates, byte-identical output;
- lock_pass.py / hostsync_pass.py / handoff_pass.py — the HS3xx
  dataflow passes (lock discipline, jit host-sync accounting, thread
  handoff);
- engine-level hygiene — suppressions (``# hst: disable=HS###``),
  baseline, HS-code doc drift, unused frozen-registry entries.

``python scripts/lint.py`` is the single entrypoint (see cli.py for
flags); docs/static_analysis.md is the user-facing catalog.
"""
