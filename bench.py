"""Benchmark: TPC-H-shaped covering-index build + Q3 wall-clock, indexed vs
full scan, on whatever accelerator JAX provides (the real TPU under the
driver; CPU if forced).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

``vs_baseline`` is the Q3 speedup of the index-rewritten query over the
non-indexed scan on the same engine/hardware — the honest analogue of the
reference's value proposition (plan rewrite vs no rewrite), since the repo
publishes no absolute numbers to compare against (BASELINE.md).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


OD_PARTS = 16  # orders part files (skipping granularity).

# Mutable result dict: every phase writes what it measured as soon as it has
# it, so a later-phase failure still yields a meaningful partial JSON line
# (VERDICT r1 #1: BENCH_r01 died rc=1 with zero output).
RESULT: dict = {
    "metric": "tpch_filter_wallclock_speedup_indexed_vs_scan",
    "value": 0.0,
    "unit": "x",
    "vs_baseline": 0.0,
    "errors": [],
}


# The driver captures a bounded tail of stdout; round 4's artifact lost its
# HEAD fields (backend, filter speedup, build rate) because the per-program
# compile_log_* arrays flooded the final line past the capture window
# (BENCH_r04 `parsed: null`). The final line therefore carries only bounded
# values — unbounded debug arrays go to a sidecar file whose path is
# recorded in the line itself.
_FINAL_LINE_MAX = 16384


def _sanitize_nonfinite(v):
    """Make a value strict-JSON-safe, recursively: inf/nan (json.dumps
    would emit non-standard Infinity/NaN tokens a strict driver parser
    rejects) become None; numpy scalars unwrap via item(); anything else
    non-plain becomes its repr rather than a TypeError at emission."""
    if hasattr(v, "item") and not isinstance(v, (str, bytes)):
        try:
            v = v.item()  # numpy / jax scalar
        except Exception:
            pass
    if isinstance(v, float) and (v != v or v in (float("inf"),
                                                 float("-inf"))):
        return None
    if isinstance(v, dict):
        return {str(k): _sanitize_nonfinite(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_sanitize_nonfinite(x) for x in v]
    if v is None or isinstance(v, (str, int, float, bool)):
        return v
    return repr(v)[:300]


def _final_line(result: dict) -> str:
    """Serialize ``result`` to the ONE driver-facing JSON line: strip
    list-valued debug banks into a sidecar, cap error text, enforce a hard
    size bound, and self-check that the line round-trips through json.
    Never raises: emission is the last act of the bench — a failure here
    must still produce a parseable line."""
    try:
        return _final_line_inner(result)
    except Exception as e:  # pragma: no cover - defense in depth
        fallback = {"metric": str(result.get("metric", "?"))[:500],
                    "value": 0.0, "unit": "x", "vs_baseline": 0.0,
                    "errors": [f"final-line emission failed: "
                               f"{type(e).__name__}: {e}"[:500]]}
        # Salvage the measured scalars — a broken debug key must not
        # zero out a real benchmark number.
        for k in ("value", "vs_baseline", "backend", "device", "scale",
                  "index_build_s", "build_rows_per_s"):
            v = _sanitize_nonfinite(result.get(k))
            if isinstance(v, str):
                v = v[:500]
            if isinstance(v, (int, float, str)):
                fallback[k] = v
        return json.dumps(fallback, default=str)


def _final_line_inner(result: dict) -> str:
    slim: dict = {}
    sidecar: dict = {}
    compile_counts: dict = {}
    for k, v in result.items():
        if k.startswith("compile_log_"):
            sidecar[k] = v
            compile_counts[k[len("compile_log_"):]] = \
                len(v) if hasattr(v, "__len__") else 0
        else:
            v = _sanitize_nonfinite(v)
            if isinstance(v, str) and len(v) > 2000:
                v = v[:2000]  # no single string may threaten the bound
            slim[k] = v
    if compile_counts:
        slim["compile_counts"] = compile_counts
    errs_raw = slim.get("errors") or []
    if any(len(str(e)) > 500 for e in errs_raw) or len(errs_raw) > 8:
        sidecar["errors_full"] = [str(e) for e in errs_raw]
        errs = [str(e)[:500] for e in errs_raw]
        # First errors carry the root cause of a cascade; keep both ends.
        slim["errors"] = errs if len(errs) <= 8 else errs[:3] + errs[-5:]

    # Headroom for the debug_file pointer (path created lazily below) and
    # a possible debug_write_error marker appended after the size checks.
    budget = _FINAL_LINE_MAX - 400

    if len(json.dumps(slim)) > budget:
        # Over budget: move the largest non-essential compound/long-string
        # values to the sidecar until the line fits.
        essential = {"metric", "value", "unit", "vs_baseline", "errors",
                     "backend", "device", "scale"}
        movable = sorted(
            (k for k, v in slim.items()
             if k not in essential
             and (isinstance(v, (list, dict))
                  or (isinstance(v, str) and len(v) > 256))),
            key=lambda k: -len(json.dumps(slim[k])))
        for k in movable:
            sidecar[k] = slim.pop(k)
            if len(json.dumps(slim)) <= budget:
                break
        if len(json.dumps(slim)) > budget:
            # Scalar-heavy overflow (should not happen): keep the essential
            # fields, spill the rest, rather than emit a broken line.
            for k in list(slim):
                if k not in essential:
                    sidecar[k] = slim.pop(k)
            slim["truncated"] = True

    if sidecar:
        try:
            debug_path = os.environ.get("BENCH_DEBUG_PATH")
            if debug_path:
                f = open(debug_path, "w")
            else:
                import tempfile as _tf
                fd, debug_path = _tf.mkstemp(prefix="hs_bench_debug_",
                                             suffix=".json")
                f = os.fdopen(fd, "w")
            with f:
                json.dump(sidecar, f, default=str)
            slim["debug_file"] = debug_path
        except OSError as e:
            slim["debug_write_error"] = str(e)[:200]

    line = json.dumps(slim)
    json.loads(line)  # self-check: the emitted artifact must parse
    assert "\n" not in line and len(line) <= _FINAL_LINE_MAX
    return line


def _emit_and_exit(code: int = 0) -> None:
    print(_final_line(RESULT))
    sys.stdout.flush()
    sys.exit(code)


# Staged backend probe (VERDICT r2 #1: two rounds of probe timeouts with the
# evidence thrown away). Each stage prints a sentinel as it completes, so a
# hang is attributable to the *first stage whose sentinel is missing*; on
# timeout the killed child's partial stdout/stderr are recorded, not dropped.
_PROBE_SCRIPT = r"""
import sys, time
t0 = time.time()
def stage(name, extra=""):
    print(f"STAGE {name} ok +{time.time()-t0:.1f}s {extra}", flush=True)
import jax
stage("import", f"jax={jax.__version__}")
try:
    import jaxlib
    stage("jaxlib", f"jaxlib={jaxlib.__version__}")
except Exception as e:  # version info is best-effort
    print(f"jaxlib version unavailable: {e}", flush=True)
d = jax.devices()
stage("devices", f"{d}")
import jax.numpy as jnp
jnp.arange(8).sum().block_until_ready()
stage("tiny_op")
a = jnp.ones((256, 256), jnp.bfloat16)
(a @ a).block_until_ready()
stage("matmul", f"platform={d[0].platform}")
print(f"PROBE_OK {d[0]}", flush=True)
"""


def _tail(text: Optional[str], n: int = 12) -> List[str]:
    return (text or "").strip().splitlines()[-n:]


def _tunnel_definitely_dead() -> bool:
    """True only when every axon relay service port actively REFUSES a
    TCP connect — the signature of the relay process being gone. Any
    accepted or timed-out connect (or a non-axon environment where the
    ports are simply unused but something else may serve the backend)
    keeps the full probe path. Conservative by design: a false negative
    costs a slow probe; a false positive would skip a live chip."""
    import socket

    if "axon" not in os.environ.get("PYTHONPATH", "") and \
            not os.environ.get("JAX_PLATFORMS", "").startswith("axon"):
        # Can't attribute the ports to the axon relay: don't guess.
        probe_anyway = os.environ.get("BENCH_TUNNEL_PORTS")
        if not probe_anyway:
            return False
    raw = os.environ.get("BENCH_TUNNEL_PORTS", "8082,8083")
    ports = [int(p) for p in raw.split(",") if p.strip().isdigit()]
    if not ports:
        return False  # malformed override: don't guess, probe for real
    for port in ports:
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.settimeout(2.0)
        try:
            s.connect(("127.0.0.1", port))
            return False  # something is listening: probe for real
        except ConnectionRefusedError:
            continue
        except OSError:
            return False  # timeout/other: inconclusive, probe for real
        finally:
            s.close()
    return True


def _ensure_backend(timeout_s: float) -> bool:
    """Probe the ambient JAX backend in a subprocess (it can hang or die at
    init — BENCH_r01's failure mode: rc=1 UNAVAILABLE; in other sandboxes it
    hangs indefinitely). Returns True if the ambient backend works, False if
    the caller must fall back to CPU. Retries once: TPU runtime attach
    through the tunnel has been observed to fail transiently.

    NOTE the fallback mechanism: setting JAX_PLATFORMS=cpu in the env is NOT
    honored once the axon plugin site is on PYTHONPATH — only an in-process
    ``jax.config.update("jax_platforms", "cpu")`` takes effect (verified
    empirically; tests/conftest.py relies on the same)."""
    platform = os.environ.get("JAX_PLATFORMS", "")
    if platform.strip().lower() == "cpu":
        # CPU explicitly requested: no point probing the ambient backend
        # (and the env var alone would not even be honored — see below).
        RESULT["backend_fallback"] = "cpu"
        return False
    if _tunnel_definitely_dead():
        # The axon relay's service ports all REFUSE connections: the probe
        # child would hang inside the runtime's connect-retry loop until
        # the timeout, twice (observed: the relay process dying takes the
        # chip away for the rest of the session). Record why and fall back
        # immediately instead of burning 2 x timeout_s.
        RESULT["errors"].append(
            "backend probe skipped: axon relay ports refuse connections "
            "(relay down); falling back to CPU")
        RESULT["backend_fallback"] = "cpu"
        return False
    for attempt in range(2):
        t0 = time.perf_counter()
        try:
            out = subprocess.run(
                [sys.executable, "-c", _PROBE_SCRIPT], capture_output=True,
                text=True, timeout=timeout_s)
        except subprocess.TimeoutExpired as e:
            # The killed child's partial output IS the diagnosis: the last
            # STAGE line printed tells which init step hung.
            so = e.stdout.decode() if isinstance(e.stdout, bytes) else e.stdout
            se = e.stderr.decode() if isinstance(e.stderr, bytes) else e.stderr
            RESULT["errors"].append(
                f"backend probe attempt {attempt + 1} "
                f"(JAX_PLATFORMS={platform!r}) timed out after "
                f"{timeout_s:.0f}s; stdout tail={_tail(so)}; "
                f"stderr tail={_tail(se)}")
            continue
        stages = [l for l in out.stdout.splitlines()
                  if l.startswith("STAGE ")]
        if out.returncode == 0 and "PROBE_OK" in out.stdout:
            RESULT["backend_probe"] = out.stdout.strip().splitlines()[-1]
            RESULT["backend_probe_stages"] = stages
            RESULT["backend_probe_s"] = round(time.perf_counter() - t0, 1)
            return True
        RESULT["errors"].append(
            f"backend probe attempt {attempt + 1} "
            f"(JAX_PLATFORMS={platform!r}) rc={out.returncode}; "
            f"stages={stages}; stderr tail={_tail(out.stderr)}")
    RESULT["backend_fallback"] = "cpu"
    return False


def make_tpch_like(root: str, scale: float, seed: int = 0):
    """Deterministic TPC-H-shaped lineitem + orders parquet datasets."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_li = max(int(6_000_000 * scale), 10_000)
    n_od = max(n_li // 4, 2_500)
    n_pt = max(n_li // 30, 200)

    # Days since unix epoch (date32 semantics).
    base = (datetime.date(1992, 1, 1) - datetime.date(1970, 1, 1)).days
    od_dir = os.path.join(root, "orders")
    li_dir = os.path.join(root, "lineitem")
    pt_dir = os.path.join(root, "part")
    os.makedirs(od_dir)
    os.makedirs(li_dir)
    os.makedirs(pt_dir)

    # Orders arrive time-ordered (sorted by o_orderdate before splitting):
    # each part file covers a date range, which is what makes per-file
    # MinMax sketches prunable — the data-skipping benchmark shape.
    o_orderdate = np.sort(rng.integers(0, 2400, n_od) + base).astype(np.int32)
    orders = pa.table({
        "o_orderkey": pa.array(np.arange(n_od, dtype=np.int64)),
        "o_custkey": pa.array(rng.integers(0, max(n_od // 10, 1), n_od).astype(np.int64)),
        "o_orderdate": pa.array(o_orderdate, type=pa.int32()).cast(pa.date32()),
        "o_shippriority": pa.array(np.zeros(n_od, dtype=np.int32)),
        # Deliberately NOT carried by od_idx: bloom-skipping queries that
        # select it cannot be answered by the covering index, so the
        # DataSkippingIndexRule (not the covering rewrite) is what fires.
        "o_totalprice": pa.array(np.round(rng.uniform(1000, 400000, n_od), 2)),
    })
    n_parts = 4
    step = n_od // OD_PARTS
    for i in range(OD_PARTS):
        lo, hi = i * step, (i + 1) * step if i < OD_PARTS - 1 else n_od
        pq.write_table(orders.slice(lo, hi - lo),
                       os.path.join(od_dir, f"part{i:02d}.parquet"))

    l_orderkey = rng.integers(0, n_od, n_li).astype(np.int64)
    l_shipdate = (rng.integers(0, 2520, n_li) + base).astype(np.int32)
    lineitem = pa.table({
        "l_orderkey": pa.array(l_orderkey),
        "l_partkey": pa.array(rng.integers(0, n_pt, n_li).astype(np.int64)),
        "l_quantity": pa.array(rng.integers(1, 51, n_li).astype(np.int64)),
        "l_extendedprice": pa.array(np.round(rng.uniform(900, 105000, n_li), 2)),
        "l_discount": pa.array(np.round(rng.uniform(0, 0.1, n_li), 2)),
        "l_shipdate": pa.array(l_shipdate, type=pa.int32()).cast(pa.date32()),
    })
    step = n_li // n_parts
    for i in range(n_parts):
        lo, hi = i * step, (i + 1) * step if i < n_parts - 1 else n_li
        pq.write_table(lineitem.slice(lo, hi - lo),
                       os.path.join(li_dir, f"part{i}.parquet"))

    part = pa.table({
        "p_partkey": pa.array(np.arange(n_pt, dtype=np.int64)),
        "p_brand": pa.array(rng.choice(
            ["Brand#11", "Brand#23", "Brand#34", "Brand#45", "Brand#52"], n_pt)),
        "p_container": pa.array(rng.choice(
            ["SM BOX", "MED BOX", "LG BOX", "SM CASE", "MED CASE",
             "LG CASE", "JUMBO PKG"], n_pt)),
    })
    pq.write_table(part, os.path.join(pt_dir, "part0.parquet"))
    return li_dir, od_dir, pt_dir, n_li, n_od


def build_filter_query(session, li_dir: str):
    """BASELINE config #1: l_shipdate range scan over a covering index whose
    within-bucket sort order makes parquet row-group pruning sharp."""
    from hyperspace_tpu.plan.expr import col

    li = session.read.parquet(li_dir)
    return li.filter(col("l_shipdate").between(
        datetime.date(1995, 3, 1), datetime.date(1995, 3, 31))) \
        .select("l_orderkey", "l_extendedprice")


def build_q3(session, li_dir: str, od_dir: str):
    from hyperspace_tpu.plan.expr import col, sum_

    li = session.read.parquet(li_dir)
    od = session.read.parquet(od_dir)
    cutoff = datetime.date(1995, 3, 15)
    return (li.filter(col("l_shipdate") > cutoff)
            .join(od.filter(col("o_orderdate") < cutoff),
                  on=col("l_orderkey") == col("o_orderkey"))
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                 .alias("revenue"))
            .sort(("revenue", False), "o_orderdate")
            .limit(10))


def build_q3_variant(session, li_dir: str, od_dir: str, shift_days: int):
    """Literal variant of q3 (cutoff shifted by ``shift_days``): the
    serving phase's batch-collapse input — same canonical template, only
    the Filter literals differ."""
    from hyperspace_tpu.plan.expr import col, sum_

    li = session.read.parquet(li_dir)
    od = session.read.parquet(od_dir)
    cutoff = datetime.date(1995, 3, 15) + datetime.timedelta(
        days=shift_days)
    return (li.filter(col("l_shipdate") > cutoff)
            .join(od.filter(col("o_orderdate") < cutoff),
                  on=col("l_orderkey") == col("o_orderkey"))
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                 .alias("revenue"))
            .sort(("revenue", False), "o_orderdate")
            .limit(10))


def build_q17(session, li_dir: str, pt_dir: str):
    """TPC-H Q17 shape (small-quantity-order revenue): the correlated avg
    subquery becomes a group-by + rejoin in the DataFrame IR."""
    from hyperspace_tpu.plan.expr import avg, col, sum_

    li = session.read.parquet(li_dir)
    pt = session.read.parquet(pt_dir)
    thr = (li.group_by("l_partkey")
           .agg(avg(col("l_quantity")).alias("avg_qty"))
           .select(col("l_partkey").alias("t_partkey"),
                   (col("avg_qty") * 0.2).alias("qty_thr")))
    return (li.join(pt.filter((col("p_brand") == "Brand#23")
                              & (col("p_container") == "MED BOX")),
                    on=col("l_partkey") == col("p_partkey"))
            .join(thr, on=col("l_partkey") == col("t_partkey"))
            .filter(col("l_quantity") < col("qty_thr"))
            .agg(sum_(col("l_extendedprice")).alias("price_sum"))
            .select((col("price_sum") / 7.0).alias("avg_yearly")))


def build_reorder_query(session, li_dir: str, od_dir: str, pt_dir: str):
    """A multi-join TPC-H shape (Q3's customer role played by the
    filtered part table) written in the PESSIMAL text order: lineitem
    joins the barely-selective orders first (~60% of orders survive the
    date filter), and the 1/35-selective part filter — the join that
    should run first — comes last. Cost-based reordering flips them."""
    import datetime as _dt

    from hyperspace_tpu.plan.expr import col, sum_

    li = session.read.parquet(li_dir)
    od = session.read.parquet(od_dir)
    pt = session.read.parquet(pt_dir)
    return (li.join(od.filter(col("o_orderdate") < _dt.date(1996, 1, 1)),
                    on=col("l_orderkey") == col("o_orderkey"))
            .join(pt.filter((col("p_brand") == "Brand#23")
                            & (col("p_container") == "MED BOX")),
                  on=col("l_partkey") == col("p_partkey"))
            .group_by("p_brand", "o_shippriority")
            .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                 .alias("revenue")))


def build_skipping_query(session, od_dir: str):
    """Month-range scan over the time-ordered orders files: per-file MinMax
    sketches prune most of the 16 parts."""
    from hyperspace_tpu.plan.expr import col

    od = session.read.parquet(od_dir)
    return od.filter(col("o_orderdate").between(
        datetime.date(1994, 6, 1), datetime.date(1994, 7, 31))) \
        .select("o_orderkey", "o_custkey")


def build_bloom_query(session, od_dir: str, n_od: int):
    """BASELINE config #4: point lookups on the high-cardinality
    o_orderkey — the Bloom sketch refutes the files that cannot contain
    each key (orders are written key-contiguous, so ~1 of 16 survives)."""
    from hyperspace_tpu.plan.expr import col

    od = session.read.parquet(od_dir)
    return od.filter(col("o_orderkey").isin(
        [n_od // 5, n_od // 2, (4 * n_od) // 5])) \
        .select("o_orderkey", "o_totalprice")


def append_lineitem_files(li_dir: str, n_li: int, seed: int = 99) -> int:
    """BASELINE config #5 prep: append ~5% new rows as fresh part files
    (inside the 0.3 Hybrid Scan appended-bytes ratio)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_new = max(n_li // 20, 1000)
    base = (datetime.date(1992, 1, 1) - datetime.date(1970, 1, 1)).days
    t = pa.table({
        "l_orderkey": pa.array(rng.integers(0, max(n_li // 4, 1), n_new)
                               .astype("int64")),
        "l_partkey": pa.array(rng.integers(0, max(n_li // 30, 200), n_new)
                              .astype("int64")),
        "l_quantity": pa.array(rng.integers(1, 51, n_new).astype("int64")),
        "l_extendedprice": pa.array(
            (rng.uniform(900, 105000, n_new)).round(2)),
        "l_discount": pa.array((rng.uniform(0, 0.1, n_new)).round(2)),
        "l_shipdate": pa.array((rng.integers(0, 2520, n_new) + base)
                               .astype("int32"), type=pa.int32())
        .cast(pa.date32()),
    })
    pq.write_table(t, os.path.join(li_dir, "part-appended.parquet"))
    return n_new


class _CompileLogBank:
    """Context manager capturing jax's per-program compile log into RESULT
    and spilling the partial file around every compile, so a hang inside the
    tunnel's remote-compile service (the round-3 killer: it dies during
    Q3's compile burst and the process blocks forever in an uninterruptible
    recv) leaves the NAME of the exact in-flight program in the spill the
    watchdog recovers. jax_log_compiles emits at WARNING, so no logger
    level changes are needed."""

    def __init__(self, name: str):
        self._key = f"compile_log_{name}"
        self._loggers = []
        self._handler = None
        self._prev = None

    def __enter__(self):
        import logging

        import jax

        bank = self

        class _H(logging.Handler):
            def emit(self, record):
                try:
                    msg = record.getMessage()
                except Exception:
                    return
                if "ompil" not in msg:  # Compiling / compiled / compilation
                    return
                RESULT.setdefault(bank._key, []).append(msg[:300])
                RESULT["compile_in_flight"] = msg[:300]
                _spill_partial()

        self._handler = _H(level=logging.DEBUG)
        self._prev = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        for mod in ("jax._src.dispatch", "jax._src.interpreters.pxla",
                    "jax._src.compiler"):
            lg = logging.getLogger(mod)
            lg.addHandler(self._handler)
            self._loggers.append(lg)
        return self

    def __exit__(self, et, ev, tb):
        import jax
        jax.config.update("jax_log_compiles", self._prev)
        for lg in self._loggers:
            lg.removeHandler(self._handler)
        if et is None:
            # Clean exit: nothing is in flight any more. On an exception or
            # a hang the last compile line stays behind as the attribution.
            RESULT.pop("compile_in_flight", None)
            _spill_partial()
        return False


def timed_best(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


# Path of the partial-result spill file (watchdog mode): the child rewrites
# it after every phase, so a hard device hang still leaves an attributable
# JSON trail for the parent to emit.
_PARTIAL_PATH: Optional[str] = None


def _spill_partial() -> None:
    if _PARTIAL_PATH:
        try:
            with open(_PARTIAL_PATH, "w") as f:
                json.dump(RESULT, f)
        except OSError:
            pass


# Signatures of a dead device/compile service (observed on the real-TPU
# runs: the tunnel's remote-compile endpoint dies mid-run with Connection
# refused, after which ANY device op blocks forever in a C-level recv that
# no Python signal can interrupt). Once seen, every later device phase must
# be skipped outright — "try the next query anyway" converts a clean partial
# result into a 55-minute watchdog wedge.
_DEAD_BACKEND_MARKERS = ("UNAVAILABLE", "Connection refused",
                         "Connection Failed", "remote_compile",
                         "DEADLINE_EXCEEDED", "failed to connect")
_BACKEND_DEAD = False


class _SkipToMesh(Exception):
    """Control flow: abandon the single-device phases (dead backend /
    failed build) but still run the CPU-subprocess mesh phase."""


def _backend_dead() -> bool:
    return _BACKEND_DEAD


def _compile_counter() -> int:
    """Process-level XLA compile counter (execution/shapes.py, hooked on
    jax.monitoring) — the per-phase tally the shape-bucketing acceptance
    tracks; 0 before hyperspace_tpu is importable."""
    try:
        from hyperspace_tpu.execution import shapes
        return shapes.compile_count()
    except Exception:
        return 0


def _phase(name: str):
    """Decorator-less phase guard: returns True if fn ran clean. Failures
    are recorded in RESULT["errors"] and the bench continues. Each phase
    also records its XLA compile delta from the process-level counter."""
    class _Ctx:
        def __enter__(self):
            RESULT["phase_current"] = name
            self._compiles0 = _compile_counter()
            _spill_partial()
            return self

        def _record_compiles(self):
            delta = _compile_counter() - self._compiles0
            RESULT.setdefault("phase_compiles", {})[name] = delta

        def __exit__(self, et, ev, tb):
            self._record_compiles()
            if et is not None and issubclass(et, Exception):
                import traceback
                # Record the *last frames*, not just the message: JAX wraps
                # device errors in a traceback-filtering notice whose final
                # line says nothing (observed on the first real-TPU run).
                lines = [l.rstrip() for l in
                         traceback.format_exception(et, ev, tb)]
                text = " | ".join(lines[-8:])[-2000:]
                RESULT["errors"].append(f"phase {name}: " + text)
                if any(m in text for m in _DEAD_BACKEND_MARKERS):
                    global _BACKEND_DEAD
                    _BACKEND_DEAD = True
                    RESULT["backend_dead_after_phase"] = name
                _spill_partial()
                return True  # swallow; later phases still run
            RESULT.pop("phase_current", None)
            _spill_partial()
            return False  # KeyboardInterrupt/SystemExit propagate
    return _Ctx()


def _run_with_watchdog(argv: List[str], total_timeout: float) -> int:
    """Re-run this script as a supervised child. A TPU runtime hang cannot
    be interrupted from Python (the blocked C call never returns to the
    signal handler), so the ONE-JSON-line contract is enforced from outside:
    on child timeout the parent emits the child's last spilled partial
    RESULT, annotated with the phase it hung in."""
    import tempfile as _tf
    fd, partial = _tf.mkstemp(prefix="hs_bench_partial_", suffix=".json")
    os.close(fd)
    env = dict(os.environ)
    env["BENCH_CHILD_PARTIAL"] = partial
    try:
        # Popen + SIGTERM-with-grace, never a straight SIGKILL: round 3
        # showed a SIGKILLed child (holding the tunnel's device claim)
        # wedges jax.devices() for every later client until the claim
        # leases out. SIGTERM's default disposition kills the process at
        # the OS level even when it is blocked in an uninterruptible recv,
        # and lets the kernel close the claim socket in the normal path.
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + argv,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True)
        try:
            stdout, stderr = proc.communicate(timeout=total_timeout)
            timed_out = False
        except subprocess.TimeoutExpired:
            timed_out = True
            proc.terminate()
            try:
                stdout, stderr = proc.communicate(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()  # last resort only, after the SIGTERM grace
                try:
                    stdout, stderr = proc.communicate(timeout=10)
                except subprocess.TimeoutExpired:
                    # A child wedged in an uninterruptible (D-state) recv
                    # defers even SIGKILL; blocking on it forever would
                    # wedge the WATCHDOG. Abandon the pipes — the partial
                    # spill below is the recovery path.
                    stdout, stderr = "", "child unkillable (D-state?)"
        last = (stdout or "").strip().splitlines()
        if not timed_out and proc.returncode == 0 and last:
            print(last[-1])
            return 0
        # Child died without printing: recover its spilled partial state.
        try:
            with open(partial) as f:
                RESULT.update(json.load(f))
        except (OSError, ValueError):
            pass
        if timed_out:
            RESULT["errors"].append(
                f"bench child timed out after {total_timeout:.0f}s in phase "
                f"{RESULT.get('phase_current', '?')!r} "
                f"(in-flight compile: {RESULT.get('compile_in_flight')}); "
                f"stdout tail={_tail(stdout)}; stderr tail={_tail(stderr)}")
        else:
            RESULT["errors"].append(
                f"bench child rc={proc.returncode}; "
                f"stderr tail={_tail(stderr)}")
    finally:
        try:
            os.unlink(partial)
        except OSError:
            pass
    print(_final_line(RESULT))
    return 0


def mesh_main(args) -> None:
    """Multi-device phase (VERDICT r2 #7): distributed build throughput and
    SPMD Q3 vs single-device, on a virtual CPU mesh (the real chip is one
    device; ICI-scale numbers need real multi-chip hardware — this measures
    that the distributed paths run and what the collective overhead costs).
    Runs in its own process: the host-platform device count must be fixed
    before jax initializes. Prints ONE JSON line."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.execution import spmd
    from hyperspace_tpu.index.constants import IndexConstants
    from hyperspace_tpu.parallel import distributed_build

    out = {"n_devices": len(jax.devices()), "mesh_backend": "cpu",
           "scale": args.scale}
    root = tempfile.mkdtemp(prefix="hs_mesh_")
    try:
        li_dir, od_dir, _pt, n_li, _n_od = make_tpch_like(root, args.scale)
        session = hst.Session(system_path=os.path.join(root, "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)
        hs = Hyperspace(session)
        li = session.read.parquet(li_dir)

        # Distributed build throughput (mesh path asserted via counter).
        before = distributed_build.DISPATCH_COUNT
        hs.create_index(li, IndexConfig(
            "mesh_li", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        if distributed_build.DISPATCH_COUNT == before:
            out["errors"] = ["distributed build path was not taken"]
        hs.delete_index("mesh_li")
        hs.vacuum_index("mesh_li")
        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig(
            "mesh_li", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        build_s = time.perf_counter() - t0
        out["dist_build_s"] = round(build_s, 3)
        out["dist_build_rows_per_s"] = round(n_li / build_s, 1)

        # SPMD Q3 vs single-device on the same mesh (no indexes in play —
        # this isolates the execution engine, not the rewrite).
        q3 = build_q3(session, li_dir, od_dir)
        before = spmd.DISPATCH_COUNT
        q3.to_arrow()  # warm + compile
        out["spmd_q3_dispatched"] = spmd.DISPATCH_COUNT > before
        spmd_s = timed_best(lambda: q3.to_arrow(), args.repeats)
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        q3.to_arrow()  # warm single-device path
        single_s = timed_best(lambda: q3.to_arrow(), args.repeats)
        out["spmd_q3_s"] = round(spmd_s, 4)
        out["single_q3_s"] = round(single_s, 4)
        out["spmd_q3_speedup"] = round(single_s / spmd_s, 3) if spmd_s else 0.0
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out))


def spmd_main(args) -> None:
    """SPMD phase child (one per device count): q3/q17 and the index
    build, distributed on vs off on THIS process's forced-host mesh,
    with byte-identity asserted and the compiled programs' HLO
    collective counts reported. Prints ONE JSON line.

    Like the r09 io phase, the speedup numbers are ENVIRONMENT-BOUND in
    this sandbox: the N virtual devices time-share ~one physical core,
    so the N-way partitioned program does the same total work plus
    collective overhead — parity (~1.0x) is the healthy reading here,
    and the real signal is byte-identity + dispatch + the collective
    counts (all-to-all present exactly where the exchange was asked
    for, zero resharding in the co-bucketed join). Real speedups need
    real multi-chip ICI."""
    import jax
    jax.config.update("jax_platforms", "cpu")

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.execution import spmd
    from hyperspace_tpu.index.constants import IndexConstants
    from hyperspace_tpu.parallel import distributed_build, sharding

    out = {"n_devices": len(jax.devices()), "scale": args.scale}
    root = tempfile.mkdtemp(prefix="hs_spmd_")
    try:
        li_dir, od_dir, pt_dir, n_li, _n_od = make_tpch_like(
            root, args.scale)
        session = hst.Session(system_path=os.path.join(root, "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 16)
        # One device: the fused single-jit dispatch IS the distributed
        # path there; force it on (CPU "auto" would skip it).
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_SINGLE_DEVICE,
                         "on")
        hs = Hyperspace(session)
        li = session.read.parquet(li_dir)

        # ---- index build, distributed vs off ----
        before = distributed_build.DISPATCH_COUNT
        hs.create_index(li, IndexConfig(
            "spmd_li", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        out["build_dispatched"] = (
            distributed_build.DISPATCH_COUNT > before
            or len(jax.devices()) == 1)  # 1-dev build is single-device
        out["build_exchange_collectives"] = \
            distributed_build.last_collectives()
        hs.delete_index("spmd_li")
        hs.vacuum_index("spmd_li")
        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig(
            "spmd_li", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        out["build_dist_s"] = round(time.perf_counter() - t0, 3)
        hs.delete_index("spmd_li")
        hs.vacuum_index("spmd_li")
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        hs.create_index(li, IndexConfig(
            "spmd_li", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        hs.delete_index("spmd_li")
        hs.vacuum_index("spmd_li")
        t0 = time.perf_counter()
        hs.create_index(li, IndexConfig(
            "spmd_li", ["l_orderkey"], ["l_extendedprice", "l_discount"]))
        out["build_single_s"] = round(time.perf_counter() - t0, 3)
        session.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
        out["build_speedup"] = round(
            out["build_single_s"] / out["build_dist_s"], 3) \
            if out["build_dist_s"] else 0.0
        out["build_rows_per_s_dist"] = round(n_li / out["build_dist_s"], 1)

        # ---- q3 / q17, distributed on vs off, identity ----
        # Non-float columns (group keys, counts, int sums) compare EXACT;
        # float64 aggregates compare at rtol 1e-9 — psum merges partial
        # sums in mesh order, and float addition is not associative, so
        # last-ulp drift is inherent to ANY distributed sum (the SPMD
        # test suite codifies the same tolerance).
        def _tables_identical(a, b):
            import numpy as _np
            import pyarrow as _pa
            if a.column_names != b.column_names or a.num_rows != b.num_rows:
                return False
            for cn in a.column_names:
                ca, cb = a.column(cn), b.column(cn)
                if _pa.types.is_floating(ca.type):
                    if not _np.allclose(
                            ca.to_numpy(zero_copy_only=False),
                            cb.to_numpy(zero_copy_only=False),
                            rtol=1e-9, equal_nan=True):
                        return False
                elif not ca.equals(cb):
                    return False
            return True

        for name, q in (("q3", build_q3(session, li_dir, od_dir)),
                        ("q17", build_q17(session, li_dir, pt_dir))):
            before = spmd.DISPATCH_COUNT
            dist_tbl = q.to_arrow()  # warm + compile
            out[f"{name}_dispatched"] = spmd.DISPATCH_COUNT > before
            out[f"{name}_collectives"] = spmd.last_collectives()
            dist_s = timed_best(lambda: q.to_arrow(), args.repeats)
            session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED,
                             "false")
            single_tbl = q.to_arrow()  # warm single-device path
            single_s = timed_best(lambda: q.to_arrow(), args.repeats)
            session.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
            out[f"{name}_identical"] = _tables_identical(dist_tbl,
                                                         single_tbl)
            out[f"{name}_dist_s"] = round(dist_s, 4)
            out[f"{name}_single_s"] = round(single_s, 4)
            out[f"{name}_speedup"] = round(single_s / dist_s, 3) \
                if dist_s else 0.0
        # ---- sort / group micro-probes (the MULTICHIP artifact rows) ----
        # Distributed ORDER BY is cost-gated OFF on CPU meshes (the host
        # sort wins there — see spmd._use_spmd_sort); force it on so the
        # sample-sort path is what gets timed. Key-only projection: rows
        # tied on the full sort key are interchangeable, so identity
        # compares the multiset the order actually constrains.
        from hyperspace_tpu.plan.expr import col, count, sum_
        cutoff = datetime.date(1995, 6, 1)
        os.environ["HST_SPMD_SORT"] = "on"
        try:
            sq = (li.filter(col("l_shipdate") > cutoff)
                  .select("l_orderkey", "l_extendedprice")
                  .sort("l_orderkey", ("l_extendedprice", False)))
            before = spmd.SORT_DISPATCH_COUNT
            sort_dist = sq.to_arrow()
            out["sort_dispatched"] = (spmd.SORT_DISPATCH_COUNT > before)
            sort_dist_s = timed_best(lambda: sq.to_arrow(), args.repeats)
        finally:
            os.environ.pop("HST_SPMD_SORT", None)
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        sq.to_arrow()
        sort_single_s = timed_best(lambda: sq.to_arrow(), args.repeats)
        session.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
        out["sort_identical"] = _tables_identical(sort_dist, sq.to_arrow())
        out["sort_dist_s"] = round(sort_dist_s, 4)
        out["sort_single_s"] = round(sort_single_s, 4)
        out["sort_speedup"] = round(sort_single_s / sort_dist_s, 3) \
            if sort_dist_s else 0.0

        gq = (li.group_by("l_orderkey")
              .agg(sum_(col("l_quantity")).alias("sq"),
                   count(None).alias("n")))
        before = spmd.DISPATCH_COUNT
        group_dist = gq.to_arrow()
        out["group_dispatched"] = spmd.DISPATCH_COUNT > before
        group_dist_s = timed_best(lambda: gq.to_arrow(), args.repeats)
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        gq.to_arrow()
        group_single_s = timed_best(lambda: gq.to_arrow(), args.repeats)
        session.conf.unset(IndexConstants.TPU_DISTRIBUTED_ENABLED)
        out["group_identical"] = _tables_identical(group_dist, gq.to_arrow())
        out["group_dist_s"] = round(group_dist_s, 4)
        out["group_single_s"] = round(group_single_s, 4)
        out["group_speedup"] = round(group_single_s / group_dist_s, 3) \
            if group_dist_s else 0.0

        out["mesh_programs_compiled"] = sharding.COMPILE_COUNT
    finally:
        shutil.rmtree(root, ignore_errors=True)
    print(json.dumps(out))


def multichip_main(args) -> None:
    """Write the round's MULTICHIP artifact: one spmd child per forced-
    host device count in {1, 2, 4} (the count must be pinned before each
    child's jax init, hence subprocesses), folding every child's
    sort/group/join(q3)/q17/build timings, speedups vs single-device,
    identity flags, and compiled-HLO collective counts into ONE json
    file. r01–r05 artifacts came from a different jax (shard_map-era)
    and are not comparable — this is the NamedSharding/jit tier's
    baseline. ~1.0x is the healthy speedup reading on this 1-core
    sandbox (see spmd_main); identity + collective shape are the signal."""
    import jax

    artifact = {"round": "r06",
                "idiom": "NamedSharding+jit (parallel/sharding.py)",
                "jax_version": jax.__version__,
                "scale": args.scale,
                "device_counts": {},
                "ok": True, "errors": []}
    for n_dev in (1, 2, 4):
        env = dict(os.environ)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n_dev}"])
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("BENCH_CHILD_PARTIAL", None)
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--spmd-devices",
             str(n_dev), "--scale", str(args.scale),
             "--repeats", str(args.repeats)],
            env=env, capture_output=True, text=True, timeout=1800)
        last = (proc.stdout or "").strip().splitlines()
        if proc.returncode == 0 and last:
            child = json.loads(last[-1])
            artifact["device_counts"][str(n_dev)] = child
            for probe in ("sort", "group", "q3", "q17"):
                if child.get(f"{probe}_identical") is False:
                    artifact["ok"] = False
                    artifact["errors"].append(
                        f"d{n_dev}: {probe} distributed != single-device")
        else:
            artifact["ok"] = False
            artifact["errors"].append(
                f"d{n_dev}: rc={proc.returncode} "
                f"stderr tail={(proc.stderr or '')[-800:]}")
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "MULTICHIP_r06.json")
    with open(path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    print(json.dumps({"multichip_artifact": path, "ok": artifact["ok"],
                      "errors": artifact["errors"]}))


def _run_spmd_phase(scale: float, timeout_s: float) -> None:
    """Spawn one SPMD child per device count {1, 8} (forced-host CPU —
    the count must be pinned before the child's jax init) and fold the
    results into RESULT under spmd_d1_* / spmd_d8_*, plus the headline
    spmd_speedup / spmd_exchange_collectives / byte-identity flags from
    the 8-device side. See spmd_main for why ~1.0x is the healthy
    reading on this 1-core sandbox."""
    for n_dev in (1, 8):
        env = dict(os.environ)
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        env["XLA_FLAGS"] = " ".join(
            flags + [f"--xla_force_host_platform_device_count={n_dev}"])
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("BENCH_CHILD_PARTIAL", None)
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--spmd-devices",
             str(n_dev), "--scale", str(scale)],
            env=env, capture_output=True, text=True, timeout=timeout_s)
        last = (out.stdout or "").strip().splitlines()
        if out.returncode == 0 and last:
            child = json.loads(last[-1])
            RESULT[f"spmd_d{n_dev}"] = child
            for e in child.get("errors", []):
                RESULT["errors"].append(f"spmd phase d{n_dev}: {e}")
        else:
            RESULT["errors"].append(
                f"spmd phase d{n_dev} rc={out.returncode}; "
                f"stderr tail={_tail(out.stderr)}")
    d8 = RESULT.get("spmd_d8", {})
    if d8:
        RESULT["spmd_speedup"] = d8.get("q3_speedup", 0.0)
        RESULT["spmd_q17_speedup"] = d8.get("q17_speedup", 0.0)
        RESULT["spmd_build_speedup"] = d8.get("build_speedup", 0.0)
        RESULT["spmd_exchange_collectives"] = d8.get("q3_collectives")
        for name in ("q3", "q17"):
            RESULT[f"spmd_{name}_identical"] = d8.get(f"{name}_identical")
            if not d8.get(f"{name}_identical"):
                RESULT["errors"].append(
                    f"spmd phase: {name} distributed/single results differ")
            if not d8.get(f"{name}_dispatched"):
                RESULT["errors"].append(
                    f"spmd phase: {name} SPMD path was not taken")


def _run_mesh_phase(scale: float, timeout_s: float) -> None:
    """Spawn the mesh phase with a virtual 8-device CPU platform (env must
    be set before the child's jax import)."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    env.pop("BENCH_CHILD_PARTIAL", None)
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--mesh",
         "--scale", str(scale)],
        env=env, capture_output=True, text=True, timeout=timeout_s)
    last = (out.stdout or "").strip().splitlines()
    if out.returncode == 0 and last:
        mesh = json.loads(last[-1])
        RESULT["mesh"] = mesh
        for k in ("n_devices", "dist_build_rows_per_s", "spmd_q3_speedup"):
            if k in mesh:
                RESULT[k] = mesh[k]
        # Bubble child-phase errors up to the bench's own error channel —
        # a clean-looking run must not hide "mesh path not taken".
        for e in mesh.get("errors", []):
            RESULT["errors"].append(f"mesh phase: {e}")
    else:
        RESULT["errors"].append(
            f"mesh phase rc={out.returncode}; stderr tail={_tail(out.stderr)}")


def _single_device_phases(args, root):
    """Datagen + index build + the four timed query pairs on the
    ambient (single-device) backend. Raises _SkipToMesh when the
    backend dies or the build fails — the caller still runs the
    CPU-subprocess mesh phase either way (it spawns its own CPU
    subprocess and needs no device)."""
    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.index.constants import IndexConstants

    if _backend_dead():
        # pallas_self_check (the only device phase so far) killed the
        # backend: skip every single-device phase outright.
        RESULT["errors"].append(
            "index_build and query phases skipped: backend dead")
        raise _SkipToMesh()

    session = None
    with _phase("datagen"):
        li_dir, od_dir, pt_dir, n_li, n_od = make_tpch_like(
            root, args.scale)
        RESULT["lineitem_rows"] = n_li
        session = hst.Session(system_path=os.path.join(root, "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)
        hs = Hyperspace(session)
        li = session.read.parquet(li_dir)
        od = session.read.parquet(od_dir)
    if session is None:
        RESULT["errors"].append("query phases skipped: datagen failed")
        raise _SkipToMesh()

    # ---- index build (the BASELINE "index build time" metric) ----
    with _phase("index_build"):
        row_group = max(4096, int(n_li / 32 / 8))
        session.conf.set(IndexConstants.INDEX_ROW_GROUP_SIZE, row_group)

        def build_all():
            hs.create_index(li, IndexConfig(
                "li_idx", ["l_orderkey"],
                ["l_extendedprice", "l_discount", "l_shipdate"]))
            hs.create_index(od, IndexConfig(
                "od_idx", ["o_orderkey"],
                ["o_custkey", "o_orderdate", "o_shippriority"]))
            # Filter index: fewer, larger buckets → more prunable groups.
            session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
            hs.create_index(li, IndexConfig(
                "li_ship_idx", ["l_shipdate"],
                ["l_orderkey", "l_extendedprice"]))
            session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)

        # Cold pass compiles the build programs; timed pass measures
        # steady-state build throughput (comparable to the JVM
        # baseline's warmed executors).
        t0 = time.perf_counter()
        with _CompileLogBank("build"):
            build_all()
        cold_build_s = time.perf_counter() - t0
        RESULT["index_build_cold_s"] = round(cold_build_s, 3)
        for name in ("li_idx", "od_idx", "li_ship_idx"):
            hs.delete_index(name)
            hs.vacuum_index(name)
        t0 = time.perf_counter()
        build_all()
        build_s = time.perf_counter() - t0
        RESULT["index_build_s"] = round(build_s, 3)
        RESULT["index_build_scope"] = (
            "warm rebuild of all 3 indexes (cold pass incl. compiles "
            "reported separately)")
        RESULT["build_rows_per_s"] = round(n_li / build_s, 1)

    if "index_build_s" not in RESULT or _backend_dead():
        # Build failed or killed the backend: no query numbers are
        # possible, but the CPU-mesh phase still is.
        RESULT["errors"].append("query phases skipped: " + (
            "backend dead" if _backend_dead() else "index build failed"))
        raise _SkipToMesh()

    with _phase("aux_indexes"):
        # Q17 covering indexes + the data-skipping indexes on orders
        # (BASELINE configs #3-#4: MinMax on the time-ordered o_orderdate,
        # Bloom on the high-cardinality o_orderkey).
        from hyperspace_tpu.api import (BloomFilterSketch,
                                        DataSkippingIndexConfig,
                                        MinMaxSketch)
        pt = session.read.parquet(pt_dir)
        hs.create_index(pt, IndexConfig(
            "pt_idx", ["p_partkey"], ["p_brand", "p_container"]))
        hs.create_index(li, IndexConfig(
            "li_pk_idx", ["l_partkey"], ["l_quantity", "l_extendedprice"]))
        hs.create_index(od, DataSkippingIndexConfig(
            "od_skip", [MinMaxSketch("o_orderdate")]))
        # Bloom sized to the per-file key count: the 100k default
        # saturates above scale ~0.5 (scale 20 = 1.9M keys/file) and a
        # saturated bitset prunes nothing.
        hs.create_index(od, DataSkippingIndexConfig(
            "od_bloom", [BloomFilterSketch(
                "o_orderkey",
                expected_items=max(n_od // OD_PARTS, 100_000))]))

    queries = {}
    with _phase("plan_queries"):
        queries["filter"] = build_filter_query(session, li_dir)
        queries["q3"] = build_q3(session, li_dir, od_dir)
        queries["q17"] = build_q17(session, li_dir, pt_dir)
        queries["skipping"] = build_skipping_query(session, od_dir)
        queries["bloom"] = build_bloom_query(session, od_dir, n_od)

    rewrite_ok = {}
    with _phase("rewrite_checks"):
        session.enable_hyperspace()
        for name in ("filter", "q3", "q17"):
            q = queries.get(name)
            if q is None:
                continue
            rewrite_ok[name] = any(
                "IndexScan" in l.simple_string()
                for l in q.optimized_plan().collect_leaves())
            if not rewrite_ok[name]:
                RESULT["errors"].append(
                    f"{name} was not rewritten to use an index")
        for name, label in (("skipping", "data-skipping"),
                            ("bloom", "bloom-skipping")):
            sq = queries.get(name)
            if sq is None:
                continue
            skip_leaves = [l for l in sq.optimized_plan().collect_leaves()
                           if hasattr(l, "relation")]
            if not skip_leaves:
                RESULT["errors"].append(
                    f"{label} query was covering-rewritten, not skipped")
                rewrite_ok[name] = False
                continue
            skip_kept = min(
                len(l.relation.all_files()) for l in skip_leaves)
            RESULT[f"{name}_files_kept"] = skip_kept
            RESULT[f"{name}_files_total"] = OD_PARTS
            rewrite_ok[name] = skip_kept < OD_PARTS
            if not rewrite_ok[name]:
                RESULT["errors"].append(f"{label} pruned nothing")
        session.disable_hyperspace()

    # ---- timed runs (per query: warm both paths, then time both) ----
    # Safest first: q3/q17 compile join programs (searchsorted /
    # match-expansion / multi-operand sorts) that have twice crashed the
    # tunnel's remote-compile service; running filter+skipping first
    # banks those numbers before the risky compiles start.
    timing_order = ["filter", "skipping", "bloom", "q17", "q3"]
    for name in timing_order + [n for n in queries if n not in timing_order]:
        q = queries.get(name)
        if q is None or not rewrite_ok.get(name, False):
            continue  # no rewrite → enabled/disabled runs are the same
            # plan; timing them would report a fake ~1.0x with rc=0.
        if _backend_dead():
            RESULT["errors"].append(
                f"time_{name} skipped: backend dead")
            continue
        with _phase(f"time_{name}"), _CompileLogBank(name):
            session.enable_hyperspace()
            c0 = _compile_counter()
            q.to_arrow()  # warm indexed path (compiles bank per-program)
            RESULT[f"{name}_compiles_first_run"] = _compile_counter() - c0
            c0 = _compile_counter()
            q.to_arrow()
            RESULT[f"{name}_compiles_second_run"] = _compile_counter() - c0
            session.disable_hyperspace()
            q.to_arrow()  # warm scan path
            scan_s = timed_best(lambda: q.to_arrow(), args.repeats)
            session.enable_hyperspace()
            idx_s = timed_best(lambda: q.to_arrow(), args.repeats)
            session.disable_hyperspace()
            sp = scan_s / idx_s if idx_s > 0 else float("inf")
            RESULT[f"{name}_scan_s"] = round(scan_s, 4)
            RESULT[f"{name}_indexed_s"] = round(idx_s, 4)
            if name == "filter":
                # Headline metric lands the moment it's measured — a
                # later phase hanging (observed: tunnel compile service
                # dying mid-q3) must not zero the whole run.
                RESULT["value"] = round(sp, 3)
                RESULT["vs_baseline"] = round(sp, 3)
            else:
                RESULT[f"{name}_speedup"] = round(sp, 3)

    # ---- serving result cache: repeated-query latency pair ----
    # The serving-layer metric (BENCH_r06+): the same query re-issued
    # with the cache off vs on. Runs BEFORE the hybrid appends so the
    # source signatures (cache-key component) stay stable mid-phase.
    if not _backend_dead():
        with _phase("result_cache"):
            from hyperspace_tpu.serving.constants import ServingConstants
            rq = queries.get("q3") or queries.get("filter")
            if rq is None:
                RESULT["errors"].append(
                    "result_cache phase skipped: no planned query")
            else:
                session.disable_hyperspace()
                rq.to_arrow()  # warm the compiled programs
                off_s = timed_best(lambda: rq.to_arrow(), args.repeats)
                session.conf.set(
                    ServingConstants.RESULT_CACHE_ENABLED, "true")
                session.conf.set(
                    ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
                rq.to_arrow()  # miss + admission
                on_s = timed_best(lambda: rq.to_arrow(), args.repeats)
                stats = session.result_cache.stats() \
                    if session.result_cache is not None else {}
                session.conf.set(
                    ServingConstants.RESULT_CACHE_ENABLED, "false")
                RESULT["result_cache_off_s"] = round(off_s, 4)
                RESULT["result_cache_on_s"] = round(on_s, 4)
                RESULT["result_cache_speedup"] = round(
                    off_s / on_s if on_s > 0 else float("inf"), 3)
                RESULT["result_cache_hits"] = stats.get("hits", 0)

    # ---- cost-based join reordering: reorder-off/on A/B ----
    # Alternating best-of-two on a multi-join TPC-H query written in the
    # pessimal text order (hyperspace disabled: this measures the pure
    # reorder effect, not index rewrites). Also asserts result identity
    # modulo row order and reports the estimation q-error of the
    # reordered joins (estimate vs executor-recorded actual output rows).
    if not _backend_dead():
        with _phase("join_reorder"):
            from hyperspace_tpu.optimizer.constants import \
                OptimizerConstants as _OC
            session.disable_hyperspace()
            rq = build_reorder_query(session, li_dir, od_dir, pt_dir)

            def _reorder(on: bool):
                session.conf.set(_OC.JOIN_REORDER_ENABLED,
                                 "true" if on else "false")

            _reorder(False)
            off_plan = rq.optimized_plan().tree_string()
            off_frame = rq.to_pandas()  # warm the off-path programs
            _reorder(True)
            on_plan = rq.optimized_plan().tree_string()
            RESULT["join_reorder_plan_changed"] = on_plan != off_plan
            on_frame = rq.to_pandas()  # warm the on-path programs
            # Estimation q-error: the reorder records carry per-step
            # estimates keyed by condition repr; the executor recorded
            # the actual inner-join output rows under the same keys.
            qerrs = []
            for rec in (session._last_join_order or []):
                for s in rec["steps"]:
                    actual = session._join_actuals.get(s["key"])
                    if actual is None:
                        continue
                    est = max(s["est_rows"], 1.0)
                    act = max(actual, 1)
                    qerrs.append(max(est / act, act / est))
            if qerrs:
                RESULT["join_reorder_qerror_max"] = round(max(qerrs), 3)
                RESULT["join_reorder_qerror_mean"] = round(
                    sum(qerrs) / len(qerrs), 3)
            cols = list(off_frame.columns)
            ident = on_frame.sort_values(cols).reset_index(drop=True) \
                .round(6).equals(
                    off_frame.sort_values(cols).reset_index(drop=True)
                    .round(6))
            RESULT["join_reorder_identical"] = bool(ident)
            if not ident:
                RESULT["errors"].append(
                    "join_reorder: reorder-on answer differs from "
                    "reorder-off")
            off_best = on_best = float("inf")
            for _ in range(2):  # alternating A/B, best-of-two
                _reorder(False)
                off_best = min(off_best,
                               timed_best(lambda: rq.to_arrow(), 1))
                _reorder(True)
                on_best = min(on_best,
                              timed_best(lambda: rq.to_arrow(), 1))
            _reorder(False)
            RESULT["join_reorder_off_s"] = round(off_best, 4)
            RESULT["join_reorder_on_s"] = round(on_best, 4)
            RESULT["join_reorder_speedup"] = round(
                off_best / on_best if on_best > 0 else float("inf"), 3)

    # ---- advisor: capture workload -> recommend -> build top reco ----
    # A FRESH session over its own (empty) system path: recommendations
    # are for indexes that do not exist yet, and the capture must see the
    # unrewritten scans. Runs BEFORE the hybrid appends so the advisor's
    # what-if signatures and the timed pairs see identical sources.
    if not _backend_dead():
        with _phase("advisor"):
            from hyperspace_tpu.advisor.constants import AdvisorConstants
            adv_session = hst.Session(
                system_path=os.path.join(root, "advisor_indexes"))
            adv_session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)
            adv_session.enable_hyperspace()
            adv_hs = Hyperspace(adv_session)
            adv_qs = [("q3", build_q3(adv_session, li_dir, od_dir)),
                      ("q17", build_q17(adv_session, li_dir, pt_dir))]
            adv_session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "true")
            for _qn, q in adv_qs:
                q.to_arrow()  # one captured record per query
            adv_session.conf.set(AdvisorConstants.CAPTURE_ENABLED, "false")
            report = adv_hs.recommend(top_k=5)
            RESULT["advisor_recommended"] = [
                {"names": list(r.names), "kind": r.kind,
                 "predicted_benefit_s": round(r.predicted_benefit_s, 4),
                 "predicted_speedup": round(r.predicted_speedup, 3)}
                for r in report.recommendations]
            if report.recommendations:
                top = report.recommendations[0]
                base_s = {qn: timed_best(lambda q=q: q.to_arrow(),
                                         args.repeats)
                          for qn, q in adv_qs}
                t0 = time.perf_counter()
                adv_hs.build_recommendation(top)
                RESULT["advisor_top_reco_build_s"] = round(
                    time.perf_counter() - t0, 3)
                matched = [adv_qs[i] for i in top.record_indices
                           if i < len(adv_qs)] or adv_qs
                for _qn, q in matched:
                    q.to_arrow()  # warm the rewritten path
                after_s = {qn: timed_best(lambda q=q: q.to_arrow(),
                                          args.repeats)
                           for qn, q in matched}
                tb = sum(base_s[qn] for qn, _ in matched)
                ta = sum(after_s.values())
                RESULT["advisor_top_reco_speedup"] = round(
                    tb / ta if ta > 0 else 0.0, 3)
                RESULT["advisor_top_reco_speedup_predicted"] = round(
                    top.predicted_speedup, 3)
            else:
                RESULT["errors"].append(
                    "advisor produced no recommendations from the "
                    "captured workload")

    # ---- serving: multi-session frontend under a mixed client mix ----
    # Sustained QPS + p50/p99 latency for a mixed TPC-H workload issued
    # by TWO independent sessions — serving frontend (shared program
    # bank / concurrent workers) vs the same queries run in session
    # isolation — plus the literal-batch collapse (N q3 literal variants
    # -> 1 batched invocation). Runs BEFORE the hybrid appends so the
    # batch templates and any cache keys see stable sources.
    if not _backend_dead():
        with _phase("serving"):
            from hyperspace_tpu.serving.constants import \
                ServingConstants as _SC
            from hyperspace_tpu.serving.frontend import ServingFrontend

            def _client_session():
                s = hst.Session(system_path=os.path.join(root, "indexes"))
                s.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)
                return s

            mix_sessions = [_client_session() for _ in range(2)]

            def _build_mix(s):
                return [build_filter_query(s, li_dir),
                        build_q3(s, li_dir, od_dir),
                        build_skipping_query(s, od_dir)]

            mixes = [_build_mix(s) for s in mix_sessions]
            rounds = max(args.repeats, 2)
            for q in mixes[0]:
                q.to_arrow()  # warm the shared compiled programs once

            # Baseline: sessions in isolation, strictly serial.
            lat_iso = []
            t0 = time.perf_counter()
            for _ in range(rounds):
                for mix in mixes:
                    for q in mix:
                        tq = time.perf_counter()
                        q.to_arrow()
                        lat_iso.append(time.perf_counter() - tq)
            iso_s = time.perf_counter() - t0

            def _pct(lats, frac):
                lats = sorted(lats)
                return lats[min(int(len(lats) * frac), len(lats) - 1)]

            RESULT["serving_isolation_qps"] = round(len(lat_iso) / iso_s, 2)
            RESULT["serving_isolation_p50_ms"] = round(
                _pct(lat_iso, 0.5) * 1000, 2)
            RESULT["serving_isolation_p99_ms"] = round(
                _pct(lat_iso, 0.99) * 1000, 2)

            # Serving tier: same mix, all queries submitted up front.
            gov = mix_sessions[0]
            gov.conf.set(_SC.SERVING_MAX_CONCURRENCY, "2")
            gov.conf.set(_SC.SERVING_BATCHING_ENABLED, "false")
            fe = ServingFrontend(gov)
            t0 = time.perf_counter()
            pend = []
            for _ in range(rounds):
                for mix in mixes:
                    pend.extend(fe.submit(q) for q in mix)
            for p in pend:
                p.result(timeout=600)
            serve_s = time.perf_counter() - t0
            lat_srv = [p.latency_s for p in pend]
            RESULT["serving_qps"] = round(len(pend) / serve_s, 2)
            RESULT["serving_p50_ms"] = round(_pct(lat_srv, 0.5) * 1000, 2)
            RESULT["serving_p99_ms"] = round(_pct(lat_srv, 0.99) * 1000, 2)
            RESULT["serving_qps_vs_isolation"] = round(
                RESULT["serving_qps"] / RESULT["serving_isolation_qps"]
                if RESULT["serving_isolation_qps"] else float("inf"), 3)

            # Literal-batch collapse: 8 q3 literal variants -> how many
            # batched invocations (1 = full collapse).
            gov.conf.set(_SC.SERVING_BATCHING_ENABLED, "true")
            gov.conf.set(_SC.SERVING_BATCHING_WINDOW, "0.3")
            gov.conf.set(_SC.SERVING_MAX_CONCURRENCY, "1")
            variants = [build_q3_variant(gov, li_dir, od_dir, i)
                        for i in range(8)]
            serial = [v.to_pandas() for v in variants]
            before = fe.stats()
            vpend = [fe.submit(v) for v in variants]
            vres = [p.result(timeout=600).to_pandas() for p in vpend]
            after = fe.stats()
            identical = all(a.round(6).equals(b.round(6))
                            for a, b in zip(serial, vres))
            RESULT["serving_batch_identical"] = bool(identical)
            if not identical:
                RESULT["errors"].append(
                    "serving: batched literal-variant answers differ "
                    "from serial")
            # Collapse = members per executed batch (8.0 = the full
            # N->1 collapse). One batch runs one vmapped invocation PER
            # swept Filter position (q3 has two: l_shipdate, o_orderdate)
            # — reported separately.
            batches = max(after["batches"] - before["batches"], 1)
            RESULT["serving_batch_members"] = (
                after["batched_queries"] - before["batched_queries"])
            RESULT["serving_batch_collapse"] = round(
                RESULT["serving_batch_members"] / batches, 2)
            RESULT["serving_batch_sweep_invocations"] = (
                after["sweep_invocations"] - before["sweep_invocations"])
            RESULT["serving_shared_scan_hits"] = (
                after["shared_scan_hits"] - before["shared_scan_hits"])
            bank = fe.stats()["program_bank"]
            RESULT["serving_program_bank_hits"] = bank["hits"]
            RESULT["serving_program_bank_programs"] = bank["programs"]

    # ---- observability: tracing overhead + live serving latency ----
    # The r13 acceptance pair: (a) trace_overhead_pct — the same warm
    # q3/q17 timed traced vs untraced, alternating best-of-two (the
    # same A/B discipline as join_reorder; tracing must cost <= ~3% on
    # and ~0 off), and (b) serving_live_p99_ms — the rolling-window
    # latency histogram the serving frontend fed during the serving
    # phase just above, read back through the metrics registry (the
    # LIVE percentiles ROADMAP item 1 asked for, vs the bench-computed
    # ones). Runs BEFORE the hybrid appends so the traced queries see
    # the same sources the untraced timings did.
    if not _backend_dead():
        with _phase("observability"):
            from hyperspace_tpu.telemetry.constants import \
                TelemetryConstants as _TC
            from hyperspace_tpu.telemetry.metrics import get_registry

            def _tracing(on: bool):
                session.conf.set(_TC.TRACE_ENABLED,
                                 "true" if on else "false")

            def _ab_overhead_pct(tq, rounds: int) -> float:
                """ONE timing methodology for both trace arms:
                alternating off/on, best-of-``rounds`` each side,
                ending off; percent on-over-off."""
                off_best = on_best = float("inf")
                for _ in range(rounds):
                    _tracing(False)
                    off_best = min(off_best,
                                   timed_best(lambda: tq.to_arrow(), 1))
                    _tracing(True)
                    on_best = min(on_best,
                                  timed_best(lambda: tq.to_arrow(), 1))
                _tracing(False)
                return ((on_best - off_best) / off_best * 100.0) \
                    if off_best > 0 else 0.0

            # Histogram first: its window slides (samples landed during
            # the serving phase just above; the trace A/B below could
            # age them out at large scales).
            hist = get_registry().snapshot()["histograms"].get(
                "serving.latency_ms")
            if hist and hist.get("count"):
                RESULT["serving_live_p50_ms"] = round(hist["p50"], 2)
                RESULT["serving_live_p99_ms"] = round(hist["p99"], 2)
                RESULT["serving_live_qps"] = hist["qps"]
                RESULT["serving_live_window_s"] = hist["window_s"]
            else:
                RESULT["errors"].append(
                    "observability: serving latency histogram empty "
                    "(serving phase skipped or failed)")
            session.disable_hyperspace()
            overheads = []
            for qn in ("q3", "q17"):
                tq = queries.get(qn)
                if tq is None:
                    continue
                tq.to_arrow()  # warm the untraced path's programs
                _tracing(True)
                tq.to_arrow()  # warm the traced path (same programs)
                pct = _ab_overhead_pct(tq, 2)
                overheads.append(pct)
                RESULT[f"trace_overhead_{qn}_pct"] = round(pct, 2)
                RESULT[f"trace_spans_{qn}"] = len(getattr(
                    session, "_last_trace").spans)
            if overheads:
                RESULT["trace_overhead_pct"] = round(
                    sum(overheads) / len(overheads), 2)

            # Sampled (default-ON production) arm: tracing on at
            # sampleRate=0.1 vs enabled=false, same alternating
            # best-of-two. Recording always happens while enabled (the
            # tail-keep contract), so this bounds the always-on cost;
            # the acceptance bar is the r13 ~2% traced bar.
            from hyperspace_tpu.api import Hyperspace as _HS
            _hs_obs = _HS(session)
            m_before = _hs_obs.metrics()
            session.conf.set(_TC.TRACE_SAMPLE_RATE, "0.1")
            sampled = []
            for qn in ("q3", "q17"):
                tq = queries.get(qn)
                if tq is None:
                    continue
                # One more alternation than the full-trace arm: this
                # pct gates an acceptance bar, so buy extra noise
                # immunity.
                pct = _ab_overhead_pct(tq, 3)
                sampled.append(pct)
                RESULT[f"trace_sampled_overhead_{qn}_pct"] = round(pct, 2)
            session.conf.unset(_TC.TRACE_SAMPLE_RATE)
            if sampled:
                RESULT["trace_sampled_overhead_pct"] = round(
                    sum(sampled) / len(sampled), 2)
                if RESULT["trace_sampled_overhead_pct"] > 2.0:
                    RESULT["errors"].append(
                        "observability: default-on sampled tracing "
                        f"overhead {RESULT['trace_sampled_overhead_pct']}"
                        "% exceeds the r13 ~2% traced bar")
            # Retention counters over the whole A/B, via the
            # metrics_delta API (no more hand-diffing snapshots).
            RESULT["trace_retention_deltas"] = {
                k.split("counters.", 1)[1]: v
                for k, v in _hs_obs.metrics_delta(m_before).items()
                if k.startswith("counters.trace.")}
            # Flight-recorder dump cost (the ring holds the traced
            # queries just above).
            t0 = time.perf_counter()
            dump_text = _hs_obs.dump_flight_recorder()
            RESULT["flight_recorder_dump_ms"] = round(
                (time.perf_counter() - t0) * 1000.0, 2)
            RESULT["flight_recorder_dump_bytes"] = len(dump_text)

    # ---- robustness: disarmed overhead, deadline lag, crash recovery ----
    # The r11-robustness acceptance trio. (a) Fault-point overhead on
    # warm q3/q17, alternating best-of-two (r13 trace-overhead
    # discipline): the truly-disarmed side IS the default the whole
    # bench ran under, so the A/B arms every query-path point at p=0 —
    # the armed-but-silent configuration does strictly MORE work than
    # disarmed (registry build + per-hit bookkeeping), bounding the
    # disarmed overhead from above (target ≈0%). (b) Deadline
    # enforcement: a warm q3 submitted with a 50 ms deadline; the
    # reported lag is how far past the deadline the cooperative
    # cancellation landed (stage/io boundary granularity). (c) Crash
    # recovery: a subprocess kill -9'd mid-create at the op-log fault
    # point, then the recovery sweep — both wall-clocks reported.
    if not _backend_dead():
        with _phase("robustness"):
            from hyperspace_tpu.exceptions import QueryDeadlineError
            from hyperspace_tpu.robustness import fault_names as _FNM
            from hyperspace_tpu.robustness.constants import \
                RobustnessConstants as _RCN
            from hyperspace_tpu.serving.frontend import ServingFrontend

            arm_keys = [f"{_RCN.FAULTS_PREFIX}.{p}" for p in (
                _FNM.IO_POOLED_READ, _FNM.SCAN_PARQUET_DECODE,
                _FNM.SPMD_DISPATCH, _FNM.BANK_COMPILE)]

            def _arm(on: bool) -> None:
                for k in arm_keys:
                    if on:
                        session.conf.set(k, "error:p=0")
                    else:
                        session.conf.unset(k)

            overheads = []
            for qn in ("q3", "q17"):
                tq = queries.get(qn)
                if tq is None:
                    continue
                tq.to_arrow()  # warm
                off_best = on_best = float("inf")
                for _ in range(2):  # alternating A/B, best-of-two
                    _arm(False)
                    off_best = min(off_best,
                                   timed_best(lambda: tq.to_arrow(), 1))
                    _arm(True)
                    on_best = min(on_best,
                                  timed_best(lambda: tq.to_arrow(), 1))
                _arm(False)
                pct = ((on_best - off_best) / off_best * 100.0) \
                    if off_best > 0 else 0.0
                overheads.append(pct)
                RESULT[f"robustness_disarmed_overhead_{qn}_pct"] = \
                    round(pct, 2)
            if overheads:
                RESULT["robustness_disarmed_overhead_pct"] = round(
                    sum(overheads) / len(overheads), 2)

            # (b) deadline-enforcement latency.
            q3w = queries.get("q3")
            if q3w is not None:
                fe = ServingFrontend(session)
                t0 = time.perf_counter()
                p = fe.submit(q3w, deadline_ms=50)
                try:
                    p.result(timeout=300)
                    RESULT["errors"].append(
                        "robustness: 50ms-deadline q3 was not cancelled")
                except QueryDeadlineError:
                    wall_ms = (time.perf_counter() - t0) * 1000.0
                    RESULT["robustness_deadline_lag_ms"] = round(
                        max(wall_ms - 50.0, 0.0), 1)
                fe.drain()

            # (c) crash-recovery wall clock (kill -9 mid-create at the
            # op-log fault point, then the recovery sweep).
            import textwrap as _tw

            import numpy as _rnp
            import pandas as _rpd
            crash_root = os.path.join(root, "crash_lake")
            crash_data = os.path.join(crash_root, "data")
            os.makedirs(crash_data, exist_ok=True)
            _rpd.DataFrame({
                "k": _rnp.arange(4000, dtype=_rnp.int64) % 40,
                "v": _rnp.arange(4000, dtype=_rnp.int64) % 9,
            }).to_parquet(os.path.join(crash_data, "p0.parquet"))
            child_src = _tw.dedent("""
                import sys
                import hyperspace_tpu as hst
                from hyperspace_tpu.api import Hyperspace, IndexConfig
                data_dir, sys_dir = sys.argv[1:3]
                s = hst.Session(system_path=sys_dir)
                s.conf.set("hyperspace.index.numBuckets", 4)
                s.conf.set("hyperspace.tpu.distributed.enabled", "false")
                s.conf.set(
                    "hyperspace.tpu.robustness.faults.log.write",
                    "kill:nth=2")
                t = s.read.parquet(data_dir)
                Hyperspace(s).create_index(
                    t, IndexConfig("cx", ["k"], ["v"]))
            """)
            script = os.path.join(crash_root, "crash_child.py")
            with open(script, "w") as f:
                f.write(child_src)
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
            env["PYTHONPATH"] = (
                os.path.dirname(os.path.abspath(__file__))
                + os.pathsep + env.get("PYTHONPATH", ""))
            t0 = time.perf_counter()
            proc = subprocess.run(
                [sys.executable, script, crash_data,
                 os.path.join(crash_root, "indexes")],
                env=env, capture_output=True, text=True, timeout=600)
            RESULT["robustness_crash_child_s"] = round(
                time.perf_counter() - t0, 2)
            if proc.returncode != -9:
                RESULT["errors"].append(
                    f"robustness: crash child rc={proc.returncode} "
                    f"(expected SIGKILL); stderr={_tail(proc.stderr)}")
            else:
                from hyperspace_tpu.api import Hyperspace as _HS
                rs = hst.Session(
                    system_path=os.path.join(crash_root, "indexes"))
                t0 = time.perf_counter()
                summary = _HS(rs).recover()
                RESULT["robustness_crash_recover_s"] = round(
                    time.perf_counter() - t0, 3)
                RESULT["robustness_crash_recovered"] = bool(
                    summary["cancelled"] == ["cx"]
                    and not summary["errors"])
                if not RESULT["robustness_crash_recovered"]:
                    RESULT["errors"].append(
                        f"robustness: recovery sweep unexpected: "
                        f"{summary}")

    # ---- whole-plan fusion: fused vs staged execution (r15) ----
    # One banked XLA program per fusible region vs operator-at-a-time
    # staged execution, on a fresh session with hyperspace disabled and
    # the distributed tier off (the fusion tier only runs where the mesh
    # declined; isolating it here makes the A/B deterministic). Emits
    # q3/q17 dispatch counts (exec.stage + exec.fused span totals),
    # fused-vs-staged latency (alternating best-of-two), identity flags,
    # and the warm-path compile count (second run through the
    # ProgramBank must compile 0). On this 1-core sandbox the LATENCY
    # pair is parity-bound (r09/r12 precedent: the fused program does
    # the same FLOPs on the same silicon; what fusion removes — per-stage
    # dispatch + host-sync overhead — is a fixed cost that shrinks
    # relative to compute as data grows, and the real win is on
    # accelerators where each staged hop is a host↔device round trip);
    # dispatch counts, span counts, and warm-compile counts are the
    # signal.
    if not _backend_dead():
        with _phase("fusion"):
            from hyperspace_tpu.execution import fusion as _fusion
            from hyperspace_tpu.index.constants import \
                IndexConstants as _IC
            from hyperspace_tpu.telemetry.constants import \
                TelemetryConstants as _FTC
            fsession = hst.Session(
                system_path=os.path.join(root, "fusion_indexes"))
            fsession.conf.set("hyperspace.tpu.distributed.enabled",
                              "false")
            # Snapshot: fusion defaults on for the whole bench, so the
            # process-global counters already hold earlier phases' fused
            # executions — this phase reports its own DELTA.
            _fst0 = _fusion.stats()
            fqueries = {"q3": build_q3(fsession, li_dir, od_dir),
                        "q17": build_q17(fsession, li_dir, pt_dir)}

            def _fuse(on: bool):
                fsession.conf.set(_IC.TPU_FUSION_ENABLED,
                                  "true" if on else "false")

            def _ftrace(on: bool):
                fsession.conf.set(_FTC.TRACE_ENABLED,
                                  "true" if on else "false")

            def _span_counts(tr):
                stage = sum(1 for s in tr.spans if s.name == "exec.stage")
                fused = sum(1 for s in tr.spans if s.name == "exec.fused")
                return stage, fused

            speedups = []
            for qn, tq in fqueries.items():
                _fuse(True)
                c0 = _compile_counter()
                fused_tbl = tq.to_arrow()  # cold fused (compiles regions)
                RESULT[f"{qn}_fusion_compiles_first_run"] = \
                    _compile_counter() - c0
                c0 = _compile_counter()
                tq.to_arrow()
                RESULT[f"{qn}_fusion_compiles_second_run"] = \
                    _compile_counter() - c0
                _ftrace(True)
                tq.to_arrow()
                stage_f, fused_f = _span_counts(fsession._last_trace)
                _fuse(False)
                staged_tbl = tq.to_arrow()
                stage_s, fused_s = _span_counts(fsession._last_trace)
                _ftrace(False)
                RESULT[f"{qn}_dispatches_fused"] = stage_f + fused_f
                RESULT[f"{qn}_dispatches_staged"] = stage_s + fused_s
                RESULT[f"{qn}_exec_fused_spans"] = fused_f
                RESULT[f"{qn}_fusion_identical"] = bool(
                    fused_tbl.equals(staged_tbl))
                if stage_f + fused_f >= stage_s:
                    RESULT["errors"].append(
                        f"fusion: {qn} fused dispatches not fewer "
                        f"({stage_f}+{fused_f} vs {stage_s})")
                # Alternating best-of-two latency pair (both warm).
                _fuse(True)
                tq.to_arrow()
                on_best = off_best = float("inf")
                for _ in range(2):
                    _fuse(False)
                    off_best = min(off_best,
                                   timed_best(lambda: tq.to_arrow(), 1))
                    _fuse(True)
                    on_best = min(on_best,
                                  timed_best(lambda: tq.to_arrow(), 1))
                RESULT[f"{qn}_fused_s"] = round(on_best, 4)
                RESULT[f"{qn}_staged_s"] = round(off_best, 4)
                sp = off_best / on_best if on_best > 0 else float("inf")
                RESULT[f"{qn}_fusion_speedup"] = round(sp, 3)
                speedups.append(sp)
            st = _fusion.stats()
            RESULT["fusion_executions"] = (st["fused_executions"]
                                           - _fst0["fused_executions"])
            f0 = _fst0["fallbacks"]
            RESULT["fusion_fallbacks"] = {
                k: v - f0.get(k, 0)
                for k, v in sorted(st["fallbacks"].items())
                if v - f0.get(k, 0) > 0}
            if speedups:
                RESULT["fusion_speedup_mean"] = round(
                    sum(speedups) / len(speedups), 3)

    # ---- BASELINE config #5: Hybrid Scan over appended source files ----
    # Runs LAST: the appends invalidate plain signatures, so every other
    # query pair must be timed first.
    if not _backend_dead():
        from hyperspace_tpu.execution import executor as _exec

        hybrid_ok = False  # _phase swallows failures; unbound would crash
        with _phase("hybrid_prep"):
            n_new = append_lineitem_files(li_dir, n_li)
            RESULT["hybrid_appended_rows"] = n_new
            session.conf.set(
                IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "true")
            hybrid_q = build_filter_query(session, li_dir)
            session.enable_hyperspace()
            hybrid_ok = any(
                "IndexScan" in l.simple_string()
                for l in hybrid_q.optimized_plan().collect_leaves())
            session.disable_hyperspace()
            if not hybrid_ok:
                RESULT["errors"].append(
                    "hybrid scan did not keep the index after appends")
        if hybrid_ok and not _backend_dead():
            with _phase("time_hybrid"):
                merges_before = _exec.HYBRID_MERGE_COUNT
                session.enable_hyperspace()
                hybrid_q.to_arrow()
                session.disable_hyperspace()
                hybrid_q.to_arrow()
                scan_s = timed_best(lambda: hybrid_q.to_arrow(),
                                    args.repeats)
                session.enable_hyperspace()
                idx_s = timed_best(lambda: hybrid_q.to_arrow(),
                                   args.repeats)
                session.disable_hyperspace()
                RESULT["hybrid_scan_s"] = round(scan_s, 4)
                RESULT["hybrid_indexed_s"] = round(idx_s, 4)
                RESULT["hybrid_speedup"] = round(
                    scan_s / idx_s if idx_s > 0 else float("inf"), 3)
                RESULT["hybrid_merge_preserved_order"] = \
                    _exec.HYBRID_MERGE_COUNT > merges_before
        session.conf.set(IndexConstants.INDEX_HYBRID_SCAN_ENABLED, "false")

    # Attribution: how many query executions took the SPMD program (on the
    # one real chip the `auto` single-device gate fuses eligible plans
    # into one program — zero here on CPU is the designed behavior).
    # Recorded after EVERY timed phase, hybrid included.
    try:
        from hyperspace_tpu.execution import spmd as _spmd
        RESULT["spmd_dispatch_count"] = _spmd.DISPATCH_COUNT
    except Exception:
        pass


def _run_lake_phase(args, root: str) -> None:
    """Sketch indexes at LAKE scale (VERDICT r3 #5): planning-time pruning
    only visibly pays when the file count is large (thousands of small
    files — the lake shape the native probe loop exists for). Generates a
    ≥1000-file lake, builds one skipping index carrying BOTH sketches
    (MinMax on the time column, Bloom on the high-cardinality id), then
    measures (a) the sketch-probe planning cost over all files, C++ vs
    numpy on identical inputs, and (b) the end-to-end skipping speedup
    vs the unskipped scan."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu import native
    from hyperspace_tpu.api import (BloomFilterSketch,
                                    DataSkippingIndexConfig, Hyperspace,
                                    MinMaxSketch)
    from hyperspace_tpu.plan.expr import col, sum_
    from hyperspace_tpu.rules import data_skipping_rule as dsr
    from hyperspace_tpu.rules.apply_hyperspace import active_indexes

    n_files = 1600 if args.scale >= 0.1 else 128
    rows_per_file = 1500
    rng = np.random.default_rng(17)
    lake_dir = os.path.join(root, "lake")
    os.makedirs(lake_dir)
    for i in range(n_files):
        # Time-ordered across files (MinMax prunable), ids key-contiguous
        # per file (Bloom refutes the other files exactly).
        ts = (8000 + i * 2
              + np.sort(rng.integers(0, 3, rows_per_file))).astype(np.int64)
        eid = (i * rows_per_file
               + rng.permutation(rows_per_file)).astype(np.int64)
        pq.write_table(pa.table({
            "ts": pa.array(ts),
            "event_id": pa.array(eid),
            "amount": pa.array(np.round(rng.uniform(1, 500, rows_per_file),
                                        2)),
        }), os.path.join(lake_dir, f"f{i:05d}.parquet"))
    RESULT["lake_files"] = n_files
    RESULT["lake_rows"] = n_files * rows_per_file

    session = hst.Session(system_path=os.path.join(root, "lake_idx"))
    hs = Hyperspace(session)
    lake = session.read.parquet(lake_dir)
    t0 = time.perf_counter()
    # Bloom sized to the per-file cardinality (default expected_items of
    # 100k would build a ~117KB bitset per 1500-row file — ~190MB of
    # sketch for the lake, drowning the measurement in bitset IO).
    hs.create_index(lake, DataSkippingIndexConfig(
        "lake_skip", [MinMaxSketch("ts"),
                      BloomFilterSketch("event_id",
                                        expected_items=rows_per_file)]))
    RESULT["lake_sketch_build_s"] = round(time.perf_counter() - t0, 3)

    # Queries: a ~1%-of-files time window, and 3 id point lookups.
    mid = 8000 + n_files  # middle of the ts range
    q_mm = (lake.filter((col("ts") >= mid) & (col("ts") <= mid + 30))
            .agg(sum_(col("amount")).alias("s")))
    ids = [rows_per_file * (n_files // 3) + 7,
           rows_per_file * (n_files // 2) + 11,
           rows_per_file * (4 * n_files // 5) + 13]
    q_bloom = lake.filter(col("event_id").isin(ids)) \
        .select("event_id", "amount")

    session.enable_hyperspace()
    for qname, q in (("lake_minmax", q_mm), ("lake_bloom", q_bloom)):
        leaves = [l for l in q.optimized_plan().collect_leaves()
                  if hasattr(l, "relation")]
        kept = min(len(l.relation.all_files()) for l in leaves)
        RESULT[f"{qname}_files_kept"] = kept
        if kept >= n_files:
            RESULT["errors"].append(f"{qname}: nothing pruned")

    # Planning-cost A/B on identical inputs: the sketch-probe evaluation
    # over all files, native C++ vs the numpy fallback (the sketch table
    # is cached after the warm-up call, so this times pure probe work).
    entry = next(e for e in active_indexes(session)
                 if e.name == "lake_skip")
    scan_plan = lake.plan
    while hasattr(scan_plan, "child"):
        scan_plan = scan_plan.child
    all_files = scan_plan.relation.all_files()
    schema = scan_plan.relation.schema
    cond = (col("event_id") == ids[0]) & \
        (col("ts") >= mid) & (col("ts") <= mid + 30)
    probe = lambda: dsr.evaluate_sketch_predicate(
        entry, cond, all_files, schema)
    probe()  # warm: loads + caches the sketch table
    reps = max(args.repeats, 3)
    # The C++ probe is opt-in since round 5 (numpy measured 2-3x faster
    # at every lake scale — native.probe_native_enabled docstring) and
    # file-count-gated since round 7: below probe_min_files() the native
    # path auto-disables so it can never lose to the numpy fallback. The
    # forced A/B stays in the bench so the decision re-measures every
    # round; the headline speedup is only emitted when the gate would
    # actually dispatch native for this lake's shape.
    gated = n_files < native.probe_min_files()
    RESULT["lake_plan_native_auto_disabled"] = bool(
        gated or not native.available())
    RESULT["lake_plan_native_min_files"] = native.probe_min_files()
    if native.available():
        prior = os.environ.get("HST_NATIVE_PROBE")
        os.environ["HST_NATIVE_PROBE"] = "force"
        try:
            RESULT["lake_plan_native_forced_ms"] = round(
                timed_best(probe, reps) * 1000, 3)
        finally:
            if prior is None:
                os.environ.pop("HST_NATIVE_PROBE", None)
            else:
                os.environ["HST_NATIVE_PROBE"] = prior
    RESULT["lake_plan_numpy_ms"] = round(
        timed_best(probe, reps) * 1000, 3)
    forced = RESULT.get("lake_plan_native_forced_ms", 0)
    if forced:
        RESULT["lake_plan_native_forced_speedup"] = round(
            RESULT["lake_plan_numpy_ms"] / forced, 2)
    if not RESULT["lake_plan_native_auto_disabled"] and forced:
        # Gate open for this shape: the forced timing IS the native path.
        RESULT["lake_plan_native_ms"] = forced
        RESULT["lake_plan_native_speedup"] = \
            RESULT["lake_plan_native_forced_speedup"]

    # End-to-end: the same queries with skipping on vs the raw scan.
    for qname, q in (("lake_minmax", q_mm), ("lake_bloom", q_bloom)):
        session.enable_hyperspace()
        q.to_arrow()
        skip_s = timed_best(lambda: q.to_arrow(), args.repeats)
        session.disable_hyperspace()
        q.to_arrow()
        scan_s = timed_best(lambda: q.to_arrow(), args.repeats)
        RESULT[f"{qname}_skip_s"] = round(skip_s, 4)
        RESULT[f"{qname}_scan_s"] = round(scan_s, 4)
        RESULT[f"{qname}_speedup"] = round(
            scan_s / skip_s if skip_s > 0 else float("inf"), 3)


def _run_streaming_phase(args, root: str) -> None:
    """Streaming ingestion (ISSUE r17): sustained append throughput with
    indexes kept fresh at load time vs append-then-full-refresh, query
    latency staying flat across many commits, and op-log compaction's
    entry folding. Emits streaming_append_qps, streaming_latency_flat,
    compaction_entries_folded (+ supporting detail)."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.plan.expr import col

    n_commits = 50 if args.scale >= 0.5 else 16
    rows = 2000
    rng = np.random.default_rng(7)

    def frame(n):
        return pa.table({
            "k": pa.array(rng.integers(0, 400, n).astype(np.int64)),
            "v": pa.array(rng.integers(0, 97, n).astype(np.int64))})

    def make_lake(tag):
        d = os.path.join(root, f"stream_{tag}")
        os.makedirs(d)
        pq.write_table(frame(2 * rows), os.path.join(d, "p0.parquet"))
        session = hst.Session(
            system_path=os.path.join(root, f"stream_{tag}_idx"))
        session.conf.set("hyperspace.index.numBuckets", 8)
        session.conf.set("hyperspace.tpu.distributed.enabled", "false")
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(d),
                        IndexConfig(f"s_{tag}", ["k"], ["v"]))
        session.enable_hyperspace()
        return session, hs, d

    def probe_ms(session, d):
        q = session.read.parquet(d).filter(col("k") == 7).select("k", "v")
        q.to_pandas()  # warm (compile/caches)
        t0 = time.perf_counter()
        q.to_pandas()
        return (time.perf_counter() - t0) * 1000.0

    def ratio(latencies):
        third = max(len(latencies) // 3, 1)
        first = sum(latencies[:third]) / third
        last = sum(latencies[-third:]) / third
        return first, last, (last / first if first > 0 else None)

    # --- load-time indexing, NO maintenance: append+commit only. Each
    # commit adds one delta version of small bucket files, so the
    # IndexScan's file count — and with it latency — grows: the control
    # arm showing why compaction exists.
    probe_every = max(n_commits // 10, 1)
    session, hs, d = make_lake("nomaint")
    lat_nomaint = []
    # elapsed covers ONLY append+commit: the probe queries (2 runs
    # each, incl. a compile) would otherwise inflate the per-commit
    # cost the full-refresh baseline below is compared against.
    elapsed = 0.0
    for i in range(n_commits):
        t0 = time.perf_counter()
        hs.append(d, frame(rows))
        hs.commit(d)
        elapsed += time.perf_counter() - t0
        if (i + 1) % probe_every == 0:
            lat_nomaint.append(probe_ms(session, d))
    RESULT["streaming_commits"] = n_commits
    RESULT["streaming_append_qps"] = round(n_commits / elapsed, 3)
    RESULT["streaming_rows_per_s"] = round(n_commits * rows / elapsed, 1)
    _f, _l, nomaint_ratio = ratio(lat_nomaint)
    RESULT["streaming_latency_nomaint_ratio"] = round(nomaint_ratio, 3) \
        if nomaint_ratio is not None else None

    # --- WITH compaction riding along: optimize_index (index-data
    # compaction, merges the per-commit delta files) every probe window
    # + compact() (op-log folding) at the same cadence. Latency stays
    # flat across the whole commit history.
    session2, hs2, d2 = make_lake("maint")
    lat_maint = []
    folded_total = 0
    for i in range(n_commits):
        hs2.append(d2, frame(rows))
        hs2.commit(d2)
        if (i + 1) % probe_every == 0:
            hs2.optimize_index("s_maint", "quick")
            out = hs2.compact(None)
            folded_total += sum(v["entries_folded"]
                                for v in out["compacted"].values())
            lat_maint.append(probe_ms(session2, d2))
    first, last, flat = ratio(lat_maint)
    RESULT["streaming_latency_first_ms"] = round(first, 2)
    RESULT["streaming_latency_last_ms"] = round(last, 2)
    # ~1.0 = flat across 50 commits (fresh indexes, merged delta files,
    # folded op logs, and the op-log lookup cache keep per-query cost
    # O(1) in commit count).
    RESULT["streaming_latency_flat"] = round(flat, 3) \
        if flat is not None else None
    RESULT["compaction_entries_folded"] = folded_total

    # --- baseline: the same ingestion as append-then-FULL-refresh.
    b_commits = min(6, n_commits)
    session_b, hs_b, d_b = make_lake("refresh")
    t0 = time.perf_counter()
    for i in range(b_commits):
        pq.write_table(frame(rows),
                       os.path.join(d_b, f"extra{i:03d}.parquet"))
        hs_b.refresh_index("s_refresh", "full")
    refresh_per_commit = (time.perf_counter() - t0) / b_commits
    fresh_per_commit = elapsed / n_commits
    RESULT["streaming_full_refresh_s_per_commit"] = round(
        refresh_per_commit, 4)
    RESULT["streaming_fresh_s_per_commit"] = round(fresh_per_commit, 4)
    RESULT["streaming_vs_full_refresh_speedup"] = round(
        refresh_per_commit / fresh_per_commit, 3) \
        if fresh_per_commit > 0 else None


def _run_streaming_scale_phase(args, root: str) -> None:
    """Streaming at traffic scale (ISSUE r22): group-commit QPS vs
    wave width, concurrent-committer coalescing (waves vs commit
    calls), and standing-query fan-out latency at 10/100/1000
    subscriptions riding one shared scan per template group. Emits
    streaming_append_qps_w{1,4,16}, streaming_waves_vs_commits,
    streaming_fanout_p99_ms_{10,100,1000}. 1-core parity bound: the
    publication wave is host-I/O + identity work, so the width-16 win
    comes from amortizing op-log entries and delta landings, not from
    parallelism — wave/op-log and batcher counters are the honest
    signal on a 1-core sandbox (same reading as the r09/r12 phases)."""
    import threading as _threading

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.plan.expr import col, sum_
    from hyperspace_tpu.streaming.ingest import get_coordinator

    rows = 500
    total_batches = 32 if args.scale < 0.5 else 64
    rng = np.random.default_rng(11)

    def frame(n):
        return pa.table({
            "k": pa.array(rng.integers(0, 400, n).astype(np.int64)),
            "v": pa.array(rng.integers(0, 97, n).astype(np.int64))})

    def make_lake(tag, enable=True):
        d = os.path.join(root, f"sscale_{tag}")
        os.makedirs(d)
        pq.write_table(frame(2 * rows), os.path.join(d, "p0.parquet"))
        session = hst.Session(
            system_path=os.path.join(root, f"sscale_{tag}_idx"))
        session.conf.set("hyperspace.index.numBuckets", 4)
        session.conf.set("hyperspace.tpu.distributed.enabled", "false")
        hs = Hyperspace(session)
        hs.create_index(session.read.parquet(d),
                        IndexConfig(f"ss_{tag}", ["k"], ["v"]))
        if enable:
            session.enable_hyperspace()
        return session, hs, d

    # --- append QPS vs wave width: W appends per commit. Width 1 pays
    # a full publication (op-log entry + delta landing per index) per
    # batch; width 16 amortizes it 16 ways.
    qps = {}
    for width in (1, 4, 16):
        session, hs, d = make_lake(f"w{width}")
        done = 0
        t0 = time.perf_counter()
        while done < total_batches:
            take = min(width, total_batches - done)
            for _ in range(take):
                hs.append(d, frame(rows))
            hs.commit(d)
            done += take
        elapsed = time.perf_counter() - t0
        qps[width] = done / elapsed
        RESULT[f"streaming_append_qps_w{width}"] = round(qps[width], 3)
    RESULT["streaming_scale_w16_vs_w1"] = round(qps[16] / qps[1], 3) \
        if qps[1] > 0 else None

    # --- concurrent committers coalescing into waves: 8 threads each
    # stage and commit; the coordinator ledger says how many actual
    # publication waves the 8 commit calls became.
    session, hs, d = make_lake("waves")
    coord0 = get_coordinator().stats()
    n_threads = 8

    def committer(i):
        hs.append(d, frame(rows))
        hs.commit(d)

    threads = [_threading.Thread(target=committer, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    coord1 = get_coordinator().stats()
    calls = coord1["commit_calls"] - coord0["commit_calls"]
    waves = coord1["waves"] - coord0["waves"]
    RESULT["streaming_commit_calls"] = calls
    RESULT["streaming_waves"] = waves
    RESULT["streaming_waves_vs_commits"] = round(calls / waves, 3) \
        if waves else None

    # --- standing-query fan-out: N same-template subscriptions, one
    # commit, one shared scan + one vmapped sweep per template group.
    # p99 is commit-start -> delivery. Hyperspace stays DISABLED on
    # this lake so the fires execute raw literal-sweepable scans (a
    # covering-index rewrite would serve each member from IndexScan
    # and never exercise the shared-scan seam being measured).
    from hyperspace_tpu.serving.frontend import ServingFrontend
    session, hs, d = make_lake("fanout", enable=False)
    session.conf.set("hyperspace.tpu.streaming.subscriptions.max",
                     "1200")
    fe = ServingFrontend(session)
    sizes = (10, 100, 1000) if args.scale >= 0.05 else (10, 100)
    for n_subs in sizes:
        subs = []
        for i in range(n_subs):
            q = session.read.parquet(d) \
                .filter(col("k") < (i % 37) + 2).group_by("k") \
                .agg(sum_(col("v")).alias("sv")).sort("k")
            subs.append(fe.subscribe(q, session=session,
                                     client=f"fan{i}"))
        base = {s.sub_id: s.delivered_total for s in subs}
        hs.append(d, frame(rows))
        t0 = time.perf_counter()
        hs.commit(d)
        lat = []
        for s in subs:
            s.wait_for(base[s.sub_id] + 1, timeout=600.0)
            d_last = max(s.deliveries(), key=lambda x: x.seq)
            lat.append((d_last.at_s - t0) * 1000.0)
        lat.sort()
        p99 = lat[min(len(lat) - 1, int(len(lat) * 0.99))]
        RESULT[f"streaming_fanout_p99_ms_{n_subs}"] = round(p99, 2)
        for s in subs:
            s.unsubscribe()
    fe.drain(timeout=120)
    st = fe.stats()
    RESULT["streaming_fanout_shared_scans"] = st["shared_scans"]
    RESULT["streaming_fanout_batched_queries"] = st["batched_queries"]


def _run_adaptive_phase(args, root: str) -> None:
    """Adaptive control plane (ISSUE r19): the three closed loops,
    measured. Emits adaptive_qerror_first_half/_second_half (feedback-
    corrected estimation over a replayed workload), adaptive_p99_
    overload_on_ms/_off_ms (SLO-degrade admission under an armed,
    breached objective), and adaptive_builder_built/_retired."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.adaptive.admission import get_controller
    from hyperspace_tpu.adaptive.builder import (AdaptiveBuilder,
                                                 BuilderLedger)
    from hyperspace_tpu.adaptive.constants import AdaptiveConstants as AC
    from hyperspace_tpu.adaptive.feedback import get_store
    from hyperspace_tpu.advisor.constants import AdvisorConstants
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.optimizer.constants import OptimizerConstants
    from hyperspace_tpu.plan.expr import col, count, sum_
    from hyperspace_tpu.serving.frontend import ServingFrontend
    from hyperspace_tpu.telemetry.constants import TelemetryConstants

    rng = np.random.default_rng(23)

    def session_for(tag, adaptive=True):
        s = hst.Session(system_path=os.path.join(root, f"adp_{tag}_idx"))
        s.conf.set("hyperspace.index.numBuckets", 4)
        s.conf.set("hyperspace.tpu.distributed.enabled", "false")
        s.conf.set(OptimizerConstants.JOIN_REORDER_ENABLED, "true")
        if adaptive:
            s.conf.set(AC.ENABLED, "true")
        return s

    # --- loop 1: feedback-corrected estimation over a replayed
    # workload. A skewed star (95% of fact rows hit ONE dim key, and
    # the selective dim category selects exactly that key) makes the
    # uniform-NDV estimate miss by ~10x; the correction store must
    # close that gap over the replay. Re-planning is off so the halves
    # isolate the learning effect.
    n_f, n_d1, n_d2 = 4000, 50, 20
    f_d1 = np.zeros(n_f, dtype=np.int64)
    f_d1[:200] = np.arange(200) % (n_d1 - 1) + 1
    rng.shuffle(f_d1)
    star = os.path.join(root, "adp_star")
    for name, t in (
            ("fact", pa.table({
                "f_d1": pa.array(f_d1),
                "f_d2": pa.array(rng.integers(0, n_d2, n_f)
                                 .astype(np.int64)),
                "f_val": pa.array(np.round(rng.uniform(0, 100, n_f), 3)),
            })),
            ("dim1", pa.table({
                "d1_key": pa.array(np.arange(n_d1, dtype=np.int64)),
                "d1_cat": pa.array(
                    ["b" if i == 0 else f"c{i % 9}"
                     for i in range(n_d1)]),
            })),
            ("dim2", pa.table({
                "d2_key": pa.array(np.arange(n_d2, dtype=np.int64)),
                "d2_cat": pa.array(rng.choice(["x", "y"], n_d2)),
            }))):
        os.makedirs(os.path.join(star, name))
        pq.write_table(t, os.path.join(star, name, "p0.parquet"))

    def star_query(s):
        fact = s.read.parquet(os.path.join(star, "fact"))
        d1 = s.read.parquet(os.path.join(star, "dim1")) \
            .filter(col("d1_cat") == "b")
        d2 = s.read.parquet(os.path.join(star, "dim2"))
        return (fact.join(d2, on=col("f_d2") == col("d2_key"))
                .join(d1, on=col("f_d1") == col("d1_key"))
                .select("d1_cat", "d2_cat", "f_val"))

    def worst_q_error(s):
        star_query(s).to_arrow()
        qs = [1.0]
        for rec in (s._last_join_order or []):
            for st in rec["steps"]:
                actual = s._join_actuals.get(st["key"])
                if actual is None:
                    continue
                est = max(float(st["est_rows"]), 1.0)
                act = max(float(actual), 1.0)
                qs.append(max(est / act, act / est))
        return max(qs)

    session = session_for("star")
    session.conf.set(AC.REPLAN_ENABLED, "false")
    get_store().clear()
    runs = 8
    qerrs = [worst_q_error(session) for _ in range(runs)]
    half = runs // 2
    RESULT["adaptive_qerror_first_half"] = round(
        sum(qerrs[:half]) / half, 3)
    RESULT["adaptive_qerror_second_half"] = round(
        sum(qerrs[half:]) / half, 3)
    if RESULT["adaptive_qerror_second_half"] >= \
            RESULT["adaptive_qerror_first_half"]:
        RESULT["errors"].append(
            "adaptive: feedback did not shrink q-error over the replay")
    get_store().clear()

    # --- loop 3 (admission): p99 under an armed objective nothing can
    # meet, controller off (exact answers) vs on (eligible aggregates
    # degrade to the sampled tier with a stated bound).
    wide = os.path.join(root, "adp_wide")
    os.makedirs(wide)
    wt = pa.table({
        "k": pa.array(np.arange(16000, dtype=np.int64)),
        "v": pa.array(rng.integers(0, 1000, 16000).astype(np.int64)),
    })
    for i in range(4):
        pq.write_table(wt.slice(i * 4000, 4000),
                       os.path.join(wide, f"p{i}.parquet"))

    def overload_p99_ms(adaptive_on):
        s = session_for("adm_on" if adaptive_on else "adm_off",
                        adaptive=adaptive_on)
        s.conf.set(TelemetryConstants.SLO_P99_MS, "0.001")
        s.conf.set(TelemetryConstants.SLO_MIN_COUNT, "1")
        agg = s.read.parquet(wide).agg(sum_(col("v")).alias("sv"),
                                       count().alias("n"))
        fe = ServingFrontend(s)
        get_controller().reset()
        fe.submit(agg).result(timeout=300)  # warm + seed the window
        lat = []
        for _ in range(12):
            t0 = time.perf_counter()
            fe.submit(agg).result(timeout=300)
            lat.append((time.perf_counter() - t0) * 1000.0)
        get_controller().reset()
        lat.sort()
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    RESULT["adaptive_p99_overload_off_ms"] = round(
        overload_p99_ms(False), 3)
    RESULT["adaptive_p99_overload_on_ms"] = round(
        overload_p99_ms(True), 3)

    # --- loop 2 (the advisor acts): captured workload -> builder
    # materializes the top recommendation in a forced idle window; a
    # cold index with zero measured usage is retired after its
    # observation window.
    fact2 = os.path.join(root, "adp_fact")
    os.makedirs(fact2)
    ks = np.sort(rng.integers(0, 100, 4000)).astype(np.int64)
    ft = pa.table({
        "k": pa.array(ks),
        "v": pa.array(rng.integers(0, 9, 4000).astype(np.int64)),
        "w": pa.array(np.round(rng.uniform(0, 1, 4000), 3)),
    })
    pq.write_table(ft.slice(0, 2000), os.path.join(fact2, "p0.parquet"))
    pq.write_table(ft.slice(2000, 2000),
                   os.path.join(fact2, "p1.parquet"))
    dim = os.path.join(root, "adp_dim")
    os.makedirs(dim)
    pq.write_table(pa.table({
        "dk": pa.array(np.arange(100, dtype=np.int64)),
        "dv": pa.array(rng.integers(0, 5, 100).astype(np.int64)),
    }), os.path.join(dim, "p0.parquet"))

    s = session_for("builder")
    s.enable_hyperspace()
    hs = Hyperspace(s)
    q = s.read.parquet(fact2).filter(col("k") > 50).select("k", "v")
    s.conf.set(AdvisorConstants.CAPTURE_ENABLED, "true")
    q.to_arrow()
    s.conf.set(AdvisorConstants.CAPTURE_ENABLED, "false")
    builder = AdaptiveBuilder(hs, ledger=BuilderLedger())
    built = builder.run_once(force=True)["built"]
    q.to_arrow()  # the workload query now rides the built index
    used = sum(s._index_usage_counts.get(n, 0) for n in built)
    if built and not used:
        RESULT["errors"].append(
            "adaptive: built index never used by its workload query")
    hs.create_index(s.read.parquet(dim),
                    IndexConfig("adp_cold", ["dk"], ["dv"]))
    s.conf.set(AC.BUILDER_RETIRE_MIN_QUERIES, "1")
    s.conf.set(AC.BUILDER_MAX_BYTES, "1")  # budget spent: no new builds
    retired = list(builder.run_once(force=True)["retired"])
    q.to_arrow()  # one completed query inside the observation window
    retired += builder.run_once(force=True)["retired"]
    RESULT["adaptive_builder_built"] = len(built)
    RESULT["adaptive_builder_retired"] = len(retired)
    if "adp_cold" not in retired:
        RESULT["errors"].append(
            "adaptive: unused index was not retired")


def _gil_free_scaling() -> float:
    """2-thread vs serial throughput of GIL-free zlib decompression —
    the host's REAL parallel capacity (vCPU count lies on time-shared
    sandboxes; this box's 2 vCPUs measured ~1.1x)."""
    import threading
    import zlib

    import numpy as np
    comp = zlib.compress(np.random.default_rng(0)
                         .integers(0, 255, 4 * 1024 * 1024, dtype=np.uint8)
                         .tobytes(), 6)

    def work(n):
        for _ in range(n):
            zlib.decompress(comp)

    work(2)  # warm
    t0 = time.perf_counter()
    work(8)
    serial = time.perf_counter() - t0
    threads = [threading.Thread(target=work, args=(4,)) for _ in range(2)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    par = time.perf_counter() - t0
    return serial / par if par > 0 else 1.0


_ARTIFACTS_CHILD = r"""
import json, sys, time
data_dir, sys_dir, arts = sys.argv[1:4]

t_boot = time.perf_counter()
import hyperspace_tpu as hst
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.execution import shapes
from hyperspace_tpu.plan.expr import col, sum_

conf = {"hyperspace.index.numBuckets": "4"}
if arts == "on":
    conf["hyperspace.tpu.artifacts.enabled"] = "true"
    conf["hyperspace.tpu.artifacts.preload.enabled"] = "true"
session = hst.Session(conf=conf, system_path=sys_dir)
t = session.read.parquet(data_dir)
q = (t.filter(col("k") > 10)
     .group_by("g").agg(sum_(col("v")).alias("sv")).sort("g"))
out = q.to_arrow()
ttfq = time.perf_counter() - t_boot
stats = Hyperspace(session).artifact_stats()
if arts == "on":
    from hyperspace_tpu.artifacts.manager import flush_all
    flush_all()
print("ARTJSON " + json.dumps({
    "ttfq_s": round(ttfq, 4), "compiles": shapes.compile_count(),
    "rows": out.num_rows,
    "hits": stats.get("hits", 0),
    "persists": stats.get("persists", 0),
    "persist_bytes": stats.get("persist_bytes", 0),
    "preloaded": stats.get("preloaded", 0),
    "preload_bytes": stats.get("preload_bytes", 0)}))
"""


def _run_artifacts_phase(args, root: str) -> None:
    """Persistent artifact store (ISSUE r20): the cold-start compile
    storm, measured. Three SUBPROCESS cold boots over one lake — the
    bench process is warm, so time-to-first-query needs real fresh
    processes: artifacts off (the storm), process A with artifacts on
    (pays the storm once, persists), process B over the same lake
    (imports + boot preload). Emits coldboot_ttfq_off_s /
    coldboot_ttfq_on_s / coldboot_speedup, second_process_compiles,
    and the store's hit/persist byte counters."""
    import json
    import subprocess
    import sys as _sys

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    repo = os.path.dirname(os.path.abspath(__file__))
    data = os.path.join(root, "arts_data")
    os.makedirs(data)
    rng = np.random.default_rng(11)
    rows = 1500
    pq.write_table(pa.table({
        "k": pa.array(rng.integers(0, 50, rows).astype(np.int64)),
        "g": pa.array(rng.integers(0, 7, rows).astype(np.int64)),
        "v": pa.array(rng.integers(0, 1000, rows).astype(np.int64)),
    }), os.path.join(data, "p0.parquet"))
    script = os.path.join(root, "arts_child.py")
    with open(script, "w") as f:
        f.write(_ARTIFACTS_CHILD)

    def boot(sys_dir, arts):
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [_sys.executable, script, data, sys_dir, arts], env=env,
            capture_output=True, text=True, timeout=600, cwd=repo)
        if proc.returncode != 0:
            raise RuntimeError(
                f"artifacts child rc={proc.returncode}: "
                f"{proc.stderr[-1500:]}")
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("ARTJSON ")][0]
        return json.loads(line[len("ARTJSON "):])

    off = boot(os.path.join(root, "arts_idx_off"), "off")
    lake = os.path.join(root, "arts_idx_on")
    a = boot(lake, "on")
    b = boot(lake, "on")
    RESULT["coldboot_ttfq_off_s"] = off["ttfq_s"]
    RESULT["coldboot_ttfq_on_s"] = b["ttfq_s"]
    RESULT["coldboot_speedup"] = round(
        off["ttfq_s"] / b["ttfq_s"], 3) if b["ttfq_s"] > 0 else None
    RESULT["coldboot_off_compiles"] = off["compiles"]
    RESULT["first_process_compiles"] = a["compiles"]
    # THE acceptance number: a warm lake's second process re-compiles
    # (almost) nothing — measured 0 on the CPU harness.
    RESULT["second_process_compiles"] = b["compiles"]
    RESULT["artifacts_persist_bytes"] = a["persist_bytes"]
    RESULT["artifacts_second_process_hits"] = b["hits"]
    RESULT["artifacts_preloaded"] = b["preloaded"]
    RESULT["artifacts_preload_bytes"] = b["preload_bytes"]


_CLUSTER_CHILD = r"""
import json, os, sys, time
import numpy as np
import hyperspace_tpu as hst
from hyperspace_tpu.cluster import worker as cw
from hyperspace_tpu.cluster.constants import ClusterConstants as CC
from hyperspace_tpu.index.constants import IndexConstants
from hyperspace_tpu.plan.expr import col
from hyperspace_tpu.serving.constants import ServingConstants
from hyperspace_tpu.serving.frontend import get_frontend

LAKE, RUN, WID, ROLE = sys.argv[1:5]
DATA = os.path.join(LAKE, "tbl")
session = hst.Session(system_path=os.path.join(LAKE, "indexes"))
session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 4)
session.conf.set(ServingConstants.SERVING_ENABLED, "true")
session.conf.set(ServingConstants.RESULT_CACHE_ENABLED, "true")
session.conf.set(ServingConstants.RESULT_CACHE_MIN_COMPUTE_SECONDS, "0")
session.conf.set(CC.ENABLED, "true")
session.conf.set(CC.WORKER_ID, WID)
session.conf.set(CC.HEARTBEAT_MS, "200")
session.conf.set(CC.FORWARD_TIMEOUT_MS, "60000")

node = cw.get_node(session)
fe = get_frontend(session)

if ROLE == "owner":
    sub = fe.subscribe(session.read.parquet(DATA)
                       .filter(col("k") == 7).select("k", "v"))
    with open(os.path.join(RUN, "owner-ready"), "w") as f:
        f.write(json.dumps({"pid": os.getpid(),
                            "worker": node.worker_id}))
    sub.wait_for(1, timeout=180.0)
    with open(os.path.join(RUN, "owner-fired"), "w") as f:
        f.write(json.dumps({"t": time.time()}))
    while True:  # keep serving forwards until the parent kills us
        time.sleep(0.2)

# Driver role ("solo" or "fleet"): run the workload, print one
# CLUJSON line the bench parent parses.
from hyperspace_tpu.api import Hyperspace
from hyperspace_tpu.cluster.hashring import HashRing
from hyperspace_tpu.serving.fingerprint import compute_key

hs = Hyperspace(session)
want = 2 if ROLE == "fleet" else 1
deadline = time.time() + 120
while len(node.membership.live_members()) < want:
    assert time.time() < deadline, "fleet never formed"
    time.sleep(0.05)

t = session.read.parquet(DATA)


def variant(i):
    return t.filter(col("k") < 2 + i).select("k", "v")


def owned_variants(owner_wid, n, start):
    ids = [m.worker_id for m in node.membership.live_members()]
    ring = HashRing(ids, vnodes=session.hs_conf.cluster_vnodes())
    out = []
    for i in range(start, start + 300):
        q = variant(i)
        key = compute_key(session, q.plan)
        if key is not None and ring.owner(key.digest()) == owner_wid:
            out.append(q)
            if len(out) == n:
                break
    return out


def med_ms(samples):
    return round(sorted(samples)[len(samples) // 2] * 1000, 2)


# Warm pass: compile the filter/select programs so the QPS loop
# measures serving, not tracing.
fe.submit(variant(0)).result(timeout=180.0)

WORK = [variant(i) for i in range(1, 25)]
t0 = time.perf_counter()
for q in WORK:          # pass 1: execution (local, or forwarded to owner)
    fe.submit(q).result(timeout=180.0)
for q in WORK:          # pass 2: result-cache hits (local or on the owner)
    fe.submit(q).result(timeout=180.0)
elapsed = time.perf_counter() - t0
out = {"qps": round(2 * len(WORK) / elapsed, 2)}

# Latency pairs on FRESH variants (i >= 100: nothing above touched
# them, so the first submit is a real execution): per variant, time
# the local recompute (direct execution, no serving tier), one
# routed execution, then the repeat submit — in the fleet that repeat
# is the owner's result cache answering across the wire.
probe = owned_variants("hsb-owner" if ROLE == "fleet" else WID, 5, 100)
recompute, hit = [], []
for q in probe:
    t1 = time.perf_counter()
    q.to_arrow()
    recompute.append(time.perf_counter() - t1)
    fe.submit(q).result(timeout=180.0)
    t1 = time.perf_counter()
    fe.submit(q).result(timeout=180.0)
    hit.append(time.perf_counter() - t1)
out["local_recompute_ms"] = med_ms(recompute)
out["repeat_hit_ms"] = med_ms(hit)

if ROLE == "fleet":
    # Broadcast fan-out: one local commit -> the OWNER's standing
    # query fires over the commit broadcast; latency is the gap
    # between commit return and the owner stamping its fired file
    # (same host, same clock).
    fe.subscribe(t.filter(col("k") == 7).select("k", "v"))
    rng = np.random.default_rng(4)
    import pandas as pd
    hs.append(DATA, pd.DataFrame(
        {"k": rng.integers(0, 40, 80).astype(np.int64),
         "v": rng.integers(0, 9, 80).astype(np.int64)}))
    t_commit = time.time()
    hs.commit(DATA)
    fired = os.path.join(RUN, "owner-fired")
    deadline = time.time() + 120
    while not os.path.exists(fired) and time.time() < deadline:
        time.sleep(0.01)
    if os.path.exists(fired):
        t_fired = json.loads(open(fired).read())["t"]
        out["broadcast_ms"] = round((t_fired - t_commit) * 1000, 2)

stats = node.stats()
for k in ("forwarded", "forward_hits", "forward_fallbacks"):
    out[k] = stats[k]
print("CLUJSON " + json.dumps(out))
"""


def _run_cluster_phase(args, root: str) -> None:
    """Shared-nothing serving cluster (ISSUE r21): QPS with 1 vs 2
    workers, forwarded-cache-hit latency vs local recompute, and
    commit-broadcast fan-out latency — over REAL worker processes
    sharing a lake, like tests/test_cluster.py's fleet test.

    1-core parity bound: on this sandbox both workers time-share one
    physical core, so cluster_qps_2w ~ cluster_qps_1w is the healthy
    reading (the spmd-phase precedent) — aggregate QPS scales with
    hosts, not with co-scheduled processes. The signals that do not
    depend on core count: forwarded > 0 with forward_fallbacks == 0
    (routing worked), cluster_forward_hit_ms (one framed round trip to
    the owner's result cache) well under cluster_local_recompute_ms,
    and cluster_broadcast_ms (one commit fanning out to a peer's
    standing query)."""
    import numpy as np
    import pyarrow as pa

    repo = os.path.dirname(os.path.abspath(__file__))
    rng = np.random.default_rng(17)
    rows = 4000
    script = os.path.join(root, "cluster_child.py")
    with open(script, "w") as f:
        f.write(_CLUSTER_CHILD)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # Children pin to CPU: two processes must not contend for the
    # accelerator, and the phase measures the serving/network tier,
    # not device compute.
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("BENCH_CHILD_PARTIAL", None)

    def make_lake(name):
        lake = os.path.join(root, name)
        data = os.path.join(lake, "tbl")
        os.makedirs(data)
        import pyarrow.parquet as pq
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 40, rows).astype(np.int64)),
            "v": pa.array(rng.integers(0, 9, rows).astype(np.int64)),
        }), os.path.join(data, "p0.parquet"))
        run = os.path.join(lake, "run")
        os.makedirs(run)
        return lake, run

    def drive(lake, run, wid, role):
        proc = subprocess.run(
            [sys.executable, script, lake, run, wid, role], env=env,
            capture_output=True, text=True, timeout=600, cwd=repo)
        if proc.returncode != 0:
            raise RuntimeError(f"cluster {role} child rc="
                               f"{proc.returncode}: {proc.stderr[-1500:]}")
        line = [ln for ln in proc.stdout.splitlines()
                if ln.startswith("CLUJSON ")][0]
        return json.loads(line[len("CLUJSON "):])

    lake1, run1 = make_lake("clu_solo")
    solo = drive(lake1, run1, "hsb-solo", "solo")
    RESULT["cluster_qps_1w"] = solo["qps"]
    RESULT["cluster_local_recompute_ms"] = solo["local_recompute_ms"]
    RESULT["cluster_local_hit_ms"] = solo["repeat_hit_ms"]

    lake2, run2 = make_lake("clu_fleet")
    owner = subprocess.Popen(
        [sys.executable, script, lake2, run2, "hsb-owner", "owner"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, cwd=repo)
    try:
        ready = os.path.join(run2, "owner-ready")
        deadline = time.time() + 180
        while not os.path.exists(ready):
            if owner.poll() is not None:
                raise RuntimeError("cluster owner died early: "
                                   f"{_tail(owner.stdout.read())}")
            if time.time() > deadline:
                raise RuntimeError("cluster owner never came up")
            time.sleep(0.1)
        fleet = drive(lake2, run2, "hsb-client", "fleet")
    finally:
        if owner.poll() is None:
            owner.kill()
        owner.wait(timeout=30)
    RESULT["cluster_qps_2w"] = fleet["qps"]
    RESULT["cluster_forward_hit_ms"] = fleet["repeat_hit_ms"]
    RESULT["cluster_broadcast_ms"] = fleet.get("broadcast_ms")
    RESULT["cluster_forwarded"] = fleet["forwarded"]
    RESULT["cluster_forward_hits"] = fleet["forward_hits"]
    RESULT["cluster_forward_fallbacks"] = fleet["forward_fallbacks"]
    if fleet["forwarded"] < 1:
        RESULT["errors"].append(
            "cluster phase: no submission was forwarded to the owner")
    if fleet["forward_fallbacks"] > 0:
        RESULT["errors"].append(
            "cluster phase: forwards fell back to local "
            f"({fleet['forward_fallbacks']}x) with the owner alive")
    if fleet.get("broadcast_ms") is None:
        RESULT["errors"].append(
            "cluster phase: owner standing query never fired "
            "(commit broadcast lost)")


def _run_io_phase(args, root: str) -> None:
    """Parallel-I/O A/B (parallel/io.py): cold multi-file scan and
    per-file sketch-build wall clock at `io.threads=1` (the sequential
    baseline) vs auto (pooled fan-out + prefetch pipeline), plus the
    read-vs-wait split from the pool counters. Fresh session per side
    (nothing cached between them beyond the OS page cache, which a
    warm-up pass levels for both); distributed off like the other
    phases.

    The phase also CALIBRATES the host: a GIL-free 2-thread zlib
    scaling probe (`io_host_parallel_scaling`). On a host whose vCPUs
    time-share ~one physical core (this sandbox measured 1.0-1.25x) and
    whose fs is fully page-cached (9p: no I/O wait to overlap), NO
    read-parallelism can beat ~1.3x — total CPU work is conserved and
    the device IS the CPU, so the consumer's compute contends with the
    readers. `io_env_serial` marks that condition so a flat speedup
    reads as an environment bound, not a subsystem failure (the
    r07 lake_plan_native_auto_disabled precedent). The wait split is
    the direct evidence the pipeline works: `io_wait_seconds` ~ 0 with
    `io_read_seconds` >> 0 means ~all read time was hidden behind
    compute."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import (DataSkippingIndexConfig, Hyperspace,
                                    MinMaxSketch)
    from hyperspace_tpu.index.constants import IndexConstants
    from hyperspace_tpu.parallel import io as pio
    from hyperspace_tpu.plan.expr import col, sum_

    RESULT["io_host_parallel_scaling"] = round(_gil_free_scaling(), 3)
    RESULT["io_env_serial"] = RESULT["io_host_parallel_scaling"] < 1.5

    # Files sized so the READ genuinely dominates (the per-file device
    # reductions cost ~constant dispatch time, so tiny files measure jax
    # overhead, not I/O) and zstd-compressed so decode is real GIL-free
    # CPU work on any healthy host.
    n_files = 48
    rows_per_file = 100_000 if args.scale >= 0.1 else 20_000
    rng = np.random.default_rng(23)
    io_dir = os.path.join(root, "io_bench")
    os.makedirs(io_dir)
    for i in range(n_files):
        ts = (10_000 + i * 10
              + np.sort(rng.integers(0, 12, rows_per_file))).astype(np.int64)
        eid = (i * rows_per_file
               + rng.permutation(rows_per_file)).astype(np.int64)
        pq.write_table(pa.table({
            "ts": pa.array(ts),
            "event_id": pa.array(eid),
            "amount": pa.array(np.round(
                rng.uniform(1, 500, rows_per_file), 2)),
        }), os.path.join(io_dir, f"f{i:05d}.parquet"), compression="zstd")
    RESULT["io_files"] = n_files
    RESULT["io_rows"] = n_files * rows_per_file

    def side(tag: str, threads: int):
        session = hst.Session(
            system_path=os.path.join(root, f"io_idx_{tag}"))
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        session.conf.set(IndexConstants.TPU_IO_THREADS, threads)
        hs = Hyperspace(session)
        df = session.read.parquet(io_dir)
        q = df.filter(col("ts") >= 0).agg(
            sum_(col("ts")).alias("st"),
            sum_(col("event_id")).alias("se"),
            sum_(col("amount")).alias("sa"))
        q.to_arrow()  # warm: compiled programs + OS page cache
        scan_s = timed_best(lambda: q.to_arrow(), max(args.repeats, 2))
        pio.reset_stats()

        def timed_build() -> float:
            t0 = time.perf_counter()
            hs.create_index(df, DataSkippingIndexConfig(
                "io_skip", [MinMaxSketch("ts"), MinMaxSketch("event_id")]))
            return time.perf_counter() - t0

        # Best of two builds (delete+vacuum between), mirroring
        # timed_best: a single cold pass is at the mercy of host noise.
        build_s = timed_build()
        hs.delete_index("io_skip")
        hs.vacuum_index("io_skip")
        build_s = min(build_s, timed_build())
        stats = pio.pool_stats()
        RESULT[f"io_scan_{tag}_s"] = round(scan_s, 4)
        RESULT[f"io_sketch_build_{tag}_s"] = round(build_s, 4)
        return scan_s, build_s, stats

    scan_1t, build_1t, _ = side("1t", 1)
    scan_auto, build_auto, auto_stats = side("auto", 0)
    RESULT["io_pool_threads"] = auto_stats["pool_threads"]
    RESULT["io_scan_speedup"] = round(
        scan_1t / scan_auto if scan_auto > 0 else 0.0, 3)
    RESULT["io_sketch_build_speedup"] = round(
        build_1t / build_auto if build_auto > 0 else 0.0, 3)
    # Wait-vs-compute split of the pooled sketch build: in-worker
    # read+decode seconds vs the consumer's blocked-on-pool seconds —
    # their gap is read time hidden behind the device reductions.
    RESULT["io_read_seconds"] = round(auto_stats["read_seconds"], 4)
    RESULT["io_wait_seconds"] = round(auto_stats["wait_seconds"], 4)


def _run_buffer_pool_phase(args, root: str) -> None:
    """Tiered buffer pool A/B (execution/buffer_pool.py): a sweep of
    LITERAL-VARIANT aggregations over one multi-file parquet source —
    every variant is a result-cache miss by construction (different
    plan fingerprint) but the same scan (same files, columns, pushed
    filter), so pool-on serves every scan after the first from the
    device tier while pool-off re-reads and re-ships per query.

    The honest 1-core reading (the r09/r12 parity precedent): on this
    sandbox the device IS the CPU and per-query compute dominates, so
    the wall-clock speedup is parity-bounded (~1x is healthy, not a
    failure). The counters are the signal: `bp_hit_ratio` (>= 0.9 over
    the sweep), `bp_decode_bytes_saved` > 0, `bp_warm_read_tasks` == 0
    (the warm sweep touched NO files), and `bp_warm_transfers` == 0
    (zero host→device scan uploads). On real HBM hardware the saved
    decode+transfer is the win; here it is proven, not timed."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    import hyperspace_tpu as hst
    from hyperspace_tpu.execution import buffer_pool
    from hyperspace_tpu.index.constants import IndexConstants
    from hyperspace_tpu.parallel import io as pio
    from hyperspace_tpu.plan.expr import col, sum_

    n_files = 24
    rows_per_file = 50_000 if args.scale >= 0.1 else 10_000
    rng = np.random.default_rng(31)
    bp_dir = os.path.join(root, "bp_bench")
    os.makedirs(bp_dir)
    for i in range(n_files):
        pq.write_table(pa.table({
            "k": pa.array(rng.integers(0, 10_000,
                                       rows_per_file).astype(np.int64)),
            "v": pa.array(rng.uniform(0, 100, rows_per_file)),
            "w": pa.array(rng.uniform(0, 1, rows_per_file)),
        }), os.path.join(bp_dir, f"f{i:04d}.parquet"),
            compression="zstd")
    RESULT["bp_files"] = n_files
    RESULT["bp_rows"] = n_files * rows_per_file
    variants = 6

    def side(tag: str, enabled: str):
        session = hst.Session(
            system_path=os.path.join(root, f"bp_idx_{tag}"))
        session.conf.set(IndexConstants.TPU_DISTRIBUTED_ENABLED, "false")
        session.conf.set(IndexConstants.TPU_BUFFER_POOL_ENABLED, enabled)
        df = session.read.parquet(bp_dir)

        def q(i):
            return df.filter(col("k") >= 0).agg(
                sum_(col("v") * float(1 + i)).alias("a"),
                sum_(col("w") * float(2 + i)).alias("b"))

        # Sweep 1 compiles every variant's program (and, pool-on,
        # admits the shared scan); sweep 2 is the steady state the
        # timing reports.
        first = [q(i).to_arrow() for i in range(variants)]
        pio.reset_stats()
        bp0 = buffer_pool.pool_stats()
        t0 = time.perf_counter()
        second = [q(i).to_arrow() for i in range(variants)]
        sweep_s = time.perf_counter() - t0
        bp1 = buffer_pool.pool_stats()
        RESULT[f"bp_sweep_{tag}_s"] = round(sweep_s, 4)
        assert all(a.equals(b) for a, b in zip(first, second))
        return sweep_s, second, pio.pool_stats(), \
            bp1["transfers"] - bp0["transfers"]

    pool = buffer_pool.get_pool()
    pool.clear()
    pool.reset_stats()
    on_s, on_res, on_io, warm_transfers = side("on", "true")
    stats = buffer_pool.pool_stats()
    probes = stats["hits"] + stats["misses"]
    RESULT["bp_hit_ratio"] = round(
        stats["hits"] / probes if probes else 0.0, 4)
    RESULT["bp_decode_bytes_saved"] = stats["decode_bytes_saved"]
    RESULT["bp_transfers"] = stats["transfers"]
    RESULT["bp_warm_read_tasks"] = on_io["read_tasks"]
    RESULT["bp_warm_transfers"] = warm_transfers
    off_s, off_res, _, _ = side("off", "false")
    after_off = buffer_pool.pool_stats()
    RESULT["bp_off_untouched"] = (
        after_off["hits"] == stats["hits"]
        and after_off["misses"] == stats["misses"])
    RESULT["bp_identical"] = all(
        a.equals(b) for a, b in zip(on_res, off_res))
    RESULT["bp_repeat_scan_speedup"] = round(
        off_s / on_s if on_s > 0 else 0.0, 3)
    pool.clear()


def main():
    parser = argparse.ArgumentParser()
    # Default 0.5 (3M lineitem rows): at 0.2 the on-chip query pairs were
    # still tunnel-round-trip-bound (filter scan 0.39 s vs indexed 0.35 s —
    # fixed per-query latency swamps the bytes saved); 0.5 gives each round
    # trip 2.5x the compute while keeping the full run (probe + builds + 6
    # query pairs + mesh phase) well inside the 3300 s child watchdog on
    # both backends (compile time, the cold-run majority, is
    # scale-independent).
    parser.add_argument("--scale", type=float,
                        default=float(os.environ.get("BENCH_SCALE", "0.5")))
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--mesh", action="store_true",
                        help="internal: run the multi-device phase")
    parser.add_argument("--spmd-devices", type=int, default=0,
                        help="internal: run the spmd phase child on this "
                             "many forced-host devices")
    parser.add_argument("--multichip", action="store_true",
                        help="write MULTICHIP_r06.json: spmd children at "
                             "forced-host device counts {1,2,4}")
    parser.add_argument("--keep", action="store_true")
    parser.add_argument("--backend-timeout", type=float, default=float(
        os.environ.get("BENCH_BACKEND_TIMEOUT", "540")))
    parser.add_argument("--total-timeout", type=float, default=float(
        os.environ.get("BENCH_TOTAL_TIMEOUT", "3300")))
    parser.add_argument("--no-watchdog", action="store_true")
    args = parser.parse_args()
    RESULT["scale"] = args.scale

    if args.mesh:
        mesh_main(args)
        return
    if args.spmd_devices:
        spmd_main(args)
        return
    if args.multichip:
        multichip_main(args)
        return

    global _PARTIAL_PATH
    _PARTIAL_PATH = os.environ.get("BENCH_CHILD_PARTIAL")
    if _PARTIAL_PATH is None and not args.no_watchdog:
        child_argv = sys.argv[1:] + ["--no-watchdog"]
        sys.exit(_run_with_watchdog(child_argv, args.total_timeout))

    backend_ok = _ensure_backend(args.backend_timeout)

    try:
        import jax
        if not backend_ok:
            # In-process platform switch: the env var would not be honored
            # (axon plugin captured it). The persistent-cache policy keys
            # on the resolved backend at Session creation, so this switch
            # also turns the crash-prone CPU cache off (execution/__init__).
            jax.config.update("jax_platforms", "cpu")
        import hyperspace_tpu  # noqa: F401 — import smoke-test
        RESULT["device"] = str(jax.devices()[0])
        RESULT["backend"] = jax.default_backend()
        RESULT["jax_version"] = jax.__version__
        try:
            import jaxlib
            RESULT["jaxlib_version"] = jaxlib.__version__
        except Exception:
            pass
    except Exception as e:
        RESULT["errors"].append(f"backend init: {type(e).__name__}: {e}")
        _emit_and_exit(0)

    # Pallas kernels: verify they compile under Mosaic AND match the jnp
    # reference on this backend; auto-disable (fall back to jnp) otherwise.
    with _phase("pallas_self_check"):
        from hyperspace_tpu.ops import pallas_kernels
        RESULT["pallas"] = pallas_kernels.self_check(auto_disable=True)

    root = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        try:
            _single_device_phases(args, root)
        except _SkipToMesh:
            pass
        if not _backend_dead():
            with _phase("lake"):
                try:
                    _run_lake_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"lake phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("io"):
                try:
                    _run_io_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"io phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("buffer_pool"):
                try:
                    _run_buffer_pool_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"buffer_pool phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("streaming"):
                try:
                    _run_streaming_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"streaming phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("streaming_scale"):
                try:
                    _run_streaming_scale_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"streaming_scale phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("adaptive"):
                try:
                    _run_adaptive_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"adaptive phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("artifacts"):
                try:
                    _run_artifacts_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"artifacts phase: {type(e).__name__}: {e}")
        if not _backend_dead():
            with _phase("cluster"):
                try:
                    _run_cluster_phase(args, root)
                except Exception as e:
                    RESULT["errors"].append(
                        f"cluster phase: {type(e).__name__}: {e}")
        with _phase("mesh"):
            # Multi-device numbers ride along at a bounded scale (the
            # virtual CPU mesh measures path health + collective overhead,
            # not ICI bandwidth).
            mesh_scale = float(os.environ.get(
                "BENCH_MESH_SCALE", str(min(args.scale, 0.05))))
            _run_mesh_phase(mesh_scale, timeout_s=float(
                os.environ.get("BENCH_MESH_TIMEOUT", "900")))
        with _phase("spmd"):
            # Partitioned-jit SPMD A/B at device_count {1, 8}: identity,
            # dispatch, and collective counts are the signal; wall-clock
            # parity is the healthy reading on a 1-core sandbox (see
            # spmd_main).
            spmd_scale = float(os.environ.get(
                "BENCH_SPMD_SCALE", str(min(args.scale, 0.05))))
            _run_spmd_phase(spmd_scale, timeout_s=float(
                os.environ.get("BENCH_SPMD_TIMEOUT", "900")))
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)

    _emit_and_exit(0)


if __name__ == "__main__":
    main()
