"""Benchmark: TPC-H-shaped covering-index build + Q3 wall-clock, indexed vs
full scan, on whatever accelerator JAX provides (the real TPU under the
driver; CPU if forced).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...extras}

``vs_baseline`` is the Q3 speedup of the index-rewritten query over the
non-indexed scan on the same engine/hardware — the honest analogue of the
reference's value proposition (plan rewrite vs no rewrite), since the repo
publishes no absolute numbers to compare against (BASELINE.md).
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


OD_PARTS = 16  # orders part files (skipping granularity).


def make_tpch_like(root: str, scale: float, seed: int = 0):
    """Deterministic TPC-H-shaped lineitem + orders parquet datasets."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    n_li = max(int(6_000_000 * scale), 10_000)
    n_od = max(n_li // 4, 2_500)
    n_pt = max(n_li // 30, 200)

    # Days since unix epoch (date32 semantics).
    base = (datetime.date(1992, 1, 1) - datetime.date(1970, 1, 1)).days
    od_dir = os.path.join(root, "orders")
    li_dir = os.path.join(root, "lineitem")
    pt_dir = os.path.join(root, "part")
    os.makedirs(od_dir)
    os.makedirs(li_dir)
    os.makedirs(pt_dir)

    # Orders arrive time-ordered (sorted by o_orderdate before splitting):
    # each part file covers a date range, which is what makes per-file
    # MinMax sketches prunable — the data-skipping benchmark shape.
    o_orderdate = np.sort(rng.integers(0, 2400, n_od) + base).astype(np.int32)
    orders = pa.table({
        "o_orderkey": pa.array(np.arange(n_od, dtype=np.int64)),
        "o_custkey": pa.array(rng.integers(0, max(n_od // 10, 1), n_od).astype(np.int64)),
        "o_orderdate": pa.array(o_orderdate, type=pa.int32()).cast(pa.date32()),
        "o_shippriority": pa.array(np.zeros(n_od, dtype=np.int32)),
    })
    n_parts = 4
    step = n_od // OD_PARTS
    for i in range(OD_PARTS):
        lo, hi = i * step, (i + 1) * step if i < OD_PARTS - 1 else n_od
        pq.write_table(orders.slice(lo, hi - lo),
                       os.path.join(od_dir, f"part{i:02d}.parquet"))

    l_orderkey = rng.integers(0, n_od, n_li).astype(np.int64)
    l_shipdate = (rng.integers(0, 2520, n_li) + base).astype(np.int32)
    lineitem = pa.table({
        "l_orderkey": pa.array(l_orderkey),
        "l_partkey": pa.array(rng.integers(0, n_pt, n_li).astype(np.int64)),
        "l_quantity": pa.array(rng.integers(1, 51, n_li).astype(np.int64)),
        "l_extendedprice": pa.array(np.round(rng.uniform(900, 105000, n_li), 2)),
        "l_discount": pa.array(np.round(rng.uniform(0, 0.1, n_li), 2)),
        "l_shipdate": pa.array(l_shipdate, type=pa.int32()).cast(pa.date32()),
    })
    step = n_li // n_parts
    for i in range(n_parts):
        lo, hi = i * step, (i + 1) * step if i < n_parts - 1 else n_li
        pq.write_table(lineitem.slice(lo, hi - lo),
                       os.path.join(li_dir, f"part{i}.parquet"))

    part = pa.table({
        "p_partkey": pa.array(np.arange(n_pt, dtype=np.int64)),
        "p_brand": pa.array(rng.choice(
            ["Brand#11", "Brand#23", "Brand#34", "Brand#45", "Brand#52"], n_pt)),
        "p_container": pa.array(rng.choice(
            ["SM BOX", "MED BOX", "LG BOX", "SM CASE", "MED CASE",
             "LG CASE", "JUMBO PKG"], n_pt)),
    })
    pq.write_table(part, os.path.join(pt_dir, "part0.parquet"))
    return li_dir, od_dir, pt_dir, n_li, n_od


def build_filter_query(session, li_dir: str):
    """BASELINE config #1: l_shipdate range scan over a covering index whose
    within-bucket sort order makes parquet row-group pruning sharp."""
    from hyperspace_tpu.plan.expr import col

    li = session.read.parquet(li_dir)
    return li.filter(col("l_shipdate").between(
        datetime.date(1995, 3, 1), datetime.date(1995, 3, 31))) \
        .select("l_orderkey", "l_extendedprice")


def build_q3(session, li_dir: str, od_dir: str):
    from hyperspace_tpu.plan.expr import col, sum_

    li = session.read.parquet(li_dir)
    od = session.read.parquet(od_dir)
    cutoff = datetime.date(1995, 3, 15)
    return (li.filter(col("l_shipdate") > cutoff)
            .join(od.filter(col("o_orderdate") < cutoff),
                  on=col("l_orderkey") == col("o_orderkey"))
            .group_by("l_orderkey", "o_orderdate", "o_shippriority")
            .agg(sum_(col("l_extendedprice") * (1 - col("l_discount")))
                 .alias("revenue"))
            .sort(("revenue", False), "o_orderdate")
            .limit(10))


def build_q17(session, li_dir: str, pt_dir: str):
    """TPC-H Q17 shape (small-quantity-order revenue): the correlated avg
    subquery becomes a group-by + rejoin in the DataFrame IR."""
    from hyperspace_tpu.plan.expr import avg, col, sum_

    li = session.read.parquet(li_dir)
    pt = session.read.parquet(pt_dir)
    thr = (li.group_by("l_partkey")
           .agg(avg(col("l_quantity")).alias("avg_qty"))
           .select(col("l_partkey").alias("t_partkey"),
                   (col("avg_qty") * 0.2).alias("qty_thr")))
    return (li.join(pt.filter((col("p_brand") == "Brand#23")
                              & (col("p_container") == "MED BOX")),
                    on=col("l_partkey") == col("p_partkey"))
            .join(thr, on=col("l_partkey") == col("t_partkey"))
            .filter(col("l_quantity") < col("qty_thr"))
            .agg(sum_(col("l_extendedprice")).alias("price_sum"))
            .select((col("price_sum") / 7.0).alias("avg_yearly")))


def build_skipping_query(session, od_dir: str):
    """Month-range scan over the time-ordered orders files: per-file MinMax
    sketches prune most of the 16 parts."""
    from hyperspace_tpu.plan.expr import col

    od = session.read.parquet(od_dir)
    return od.filter(col("o_orderdate").between(
        datetime.date(1994, 6, 1), datetime.date(1994, 7, 31))) \
        .select("o_orderkey", "o_custkey")


def timed_best(fn, repeats: int) -> float:
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--scale", type=float,
                        default=float(os.environ.get("BENCH_SCALE", "0.05")))
    parser.add_argument("--repeats", type=int, default=3)
    parser.add_argument("--keep", action="store_true")
    args = parser.parse_args()

    import hyperspace_tpu as hst
    from hyperspace_tpu.api import Hyperspace, IndexConfig
    from hyperspace_tpu.index.constants import IndexConstants

    root = tempfile.mkdtemp(prefix="hs_bench_")
    try:
        li_dir, od_dir, pt_dir, n_li, n_od = make_tpch_like(root, args.scale)
        session = hst.Session(system_path=os.path.join(root, "indexes"))
        session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)
        hs = Hyperspace(session)

        li = session.read.parquet(li_dir)
        od = session.read.parquet(od_dir)

        # ---- index build (the BASELINE "index build time" metric) ----
        row_group = max(4096, int(n_li / 32 / 8))
        session.conf.set(IndexConstants.INDEX_ROW_GROUP_SIZE, row_group)

        def build_all():
            hs.create_index(li, IndexConfig(
                "li_idx", ["l_orderkey"],
                ["l_extendedprice", "l_discount", "l_shipdate"]))
            hs.create_index(od, IndexConfig(
                "od_idx", ["o_orderkey"],
                ["o_custkey", "o_orderdate", "o_shippriority"]))
            # Filter index: fewer, larger buckets → more row groups to prune.
            session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 8)
            hs.create_index(li, IndexConfig(
                "li_ship_idx", ["l_shipdate"],
                ["l_orderkey", "l_extendedprice"]))
            session.conf.set(IndexConstants.INDEX_NUM_BUCKETS, 32)

        # Cold pass compiles the build programs (XLA/Pallas per shape — cached
        # persistently via HST_XLA_CACHE); timed pass measures steady-state
        # build throughput, the quantity comparable to the JVM baseline's
        # warmed executors.
        t0 = time.perf_counter()
        build_all()
        cold_build_s = time.perf_counter() - t0
        for name in ("li_idx", "od_idx", "li_ship_idx"):
            hs.delete_index(name)
            hs.vacuum_index(name)
        t0 = time.perf_counter()
        build_all()
        build_s = time.perf_counter() - t0

        # Q17 covering indexes + the data-skipping index on time-ordered
        # orders (BASELINE configs #3-#4: sketch-based skipping).
        from hyperspace_tpu.api import (DataSkippingIndexConfig,
                                        MinMaxSketch)
        pt = session.read.parquet(pt_dir)
        hs.create_index(pt, IndexConfig(
            "pt_idx", ["p_partkey"], ["p_brand", "p_container"]))
        hs.create_index(li, IndexConfig(
            "li_pk_idx", ["l_partkey"], ["l_quantity", "l_extendedprice"]))
        hs.create_index(od, DataSkippingIndexConfig(
            "od_skip", [MinMaxSketch("o_orderdate")]))

        fq = build_filter_query(session, li_dir)
        q3 = build_q3(session, li_dir, od_dir)
        q17 = build_q17(session, li_dir, pt_dir)
        sq = build_skipping_query(session, od_dir)

        # Warm up both paths (compile caches) + sanity-check rewrites.
        session.enable_hyperspace()
        for q, name in ((fq, "filter query"), (q3, "Q3"), (q17, "Q17")):
            assert any("IndexScan" in l.simple_string()
                       for l in q.optimized_plan().collect_leaves()), \
                f"{name} was not rewritten to use an index"
            q.to_arrow()
        skip_leaves = sq.optimized_plan().collect_leaves()
        skip_kept = min(len(l.relation.all_files()) for l in skip_leaves)
        assert skip_kept < OD_PARTS, "data-skipping pruned nothing"
        sq.to_arrow()
        session.disable_hyperspace()
        fq.to_arrow()
        q3.to_arrow()
        q17.to_arrow()
        sq.to_arrow()

        # ---- timed runs ----
        session.disable_hyperspace()
        f_scan_s = timed_best(lambda: fq.to_arrow(), args.repeats)
        q3_scan_s = timed_best(lambda: q3.to_arrow(), args.repeats)
        q17_scan_s = timed_best(lambda: q17.to_arrow(), args.repeats)
        sq_scan_s = timed_best(lambda: sq.to_arrow(), args.repeats)
        session.enable_hyperspace()
        f_idx_s = timed_best(lambda: fq.to_arrow(), args.repeats)
        q3_idx_s = timed_best(lambda: q3.to_arrow(), args.repeats)
        q17_idx_s = timed_best(lambda: q17.to_arrow(), args.repeats)
        sq_idx_s = timed_best(lambda: sq.to_arrow(), args.repeats)

        f_speedup = f_scan_s / f_idx_s if f_idx_s > 0 else float("inf")
        q3_speedup = q3_scan_s / q3_idx_s if q3_idx_s > 0 else float("inf")
        q17_speedup = q17_scan_s / q17_idx_s if q17_idx_s > 0 else float("inf")
        sq_speedup = sq_scan_s / sq_idx_s if sq_idx_s > 0 else float("inf")
        import jax
        result = {
            "metric": "tpch_filter_wallclock_speedup_indexed_vs_scan",
            "value": round(f_speedup, 3),
            "unit": "x",
            "vs_baseline": round(f_speedup, 3),
            "filter_scan_s": round(f_scan_s, 4),
            "filter_indexed_s": round(f_idx_s, 4),
            "q3_speedup": round(q3_speedup, 3),
            "q3_scan_s": round(q3_scan_s, 4),
            "q3_indexed_s": round(q3_idx_s, 4),
            "q17_speedup": round(q17_speedup, 3),
            "q17_scan_s": round(q17_scan_s, 4),
            "q17_indexed_s": round(q17_idx_s, 4),
            "skipping_speedup": round(sq_speedup, 3),
            "skipping_files_kept": skip_kept,
            "skipping_files_total": OD_PARTS,
            "index_build_s": round(build_s, 3),
            "index_build_cold_s": round(cold_build_s, 3),
            "index_build_scope": "warm rebuild of all 3 indexes (cold pass incl. compiles reported separately)",
            "lineitem_rows": n_li,
            "build_rows_per_s": round(n_li / build_s, 1),
            "scale": args.scale,
            "device": str(jax.devices()[0]),
        }
        print(json.dumps(result))
    finally:
        if not args.keep:
            shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
