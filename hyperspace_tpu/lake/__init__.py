"""Versioned lakehouse table formats (transaction-logged parquet tables).

Two formats mirror the reference's two lake integrations:
- ``delta``: commit-log tables (hyperspace_tpu.lake.delta.DeltaTable) — the
  Delta Lake analogue (reference: sources/delta/).
- ``iceberg``: snapshot/manifest tables (hyperspace_tpu.lake.iceberg) — the
  Iceberg analogue (reference: sources/iceberg/).
"""

from .delta import DeltaTable  # noqa: F401
