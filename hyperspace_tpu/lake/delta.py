"""Commit-log versioned parquet tables — the Delta Lake analogue.

A table directory holds parquet part files plus a ``_delta_log/`` of
newline-delimited-JSON commit files, one per version::

    <table>/part-<uuid>.parquet
    <table>/_delta_log/00000000000000000000.json   (version 0)
    <table>/_delta_log/00000000000000000001.json   (version 1)

Each commit file is a list of actions: ``metaData`` (schema), ``add`` (a data
file enters the table), ``remove`` (a file leaves), ``commitInfo``
(operation tag + timestamp). A snapshot at version v is the fold of all
actions in commits 0..v. Commits are written create-exclusive (O_EXCL) so
concurrent writers conflict instead of clobbering — the same optimistic
protocol the index op log uses (index/log_manager.py).

This module is the storage layer only; query/index integration lives in
sources/delta.py (reference behavior mirrored there:
sources/delta/DeltaLakeFileBasedSource.scala:40, DeltaLakeRelation.scala:34).
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException

LOG_DIR = "_delta_log"


class DeltaConcurrentModificationException(HyperspaceException):
    pass


def _commit_path(table_path: str, version: int) -> str:
    return os.path.join(table_path, LOG_DIR, f"{version:020d}.json")


class Snapshot:
    """Resolved state of a table at one version."""

    def __init__(self, table_path: str, version: int,
                 files: Dict[str, dict], schema_str: Optional[str]):
        self.table_path = table_path
        self.version = version
        self._files = files              # rel path -> add-action payload
        self.schema_string = schema_str

    @property
    def file_paths(self) -> List[str]:
        return sorted(os.path.join(self.table_path, p) for p in self._files)

    @property
    def file_infos(self) -> List[Tuple[str, int, int]]:
        """(abs path, size, modificationTime ms) straight from the log — no
        filesystem stat needed (the lake metadata is authoritative)."""
        out = []
        for rel in sorted(self._files):
            a = self._files[rel]
            out.append((os.path.join(self.table_path, rel),
                        int(a.get("size", 0)),
                        int(a.get("modificationTime", 0))))
        return out

    def arrow_schema(self) -> Optional[pa.Schema]:
        if self.schema_string is None:
            return None
        import pyarrow.ipc as ipc
        import base64
        buf = base64.b64decode(self.schema_string)
        return ipc.read_schema(pa.BufferReader(buf))


class DeltaTable:
    """Reader/writer for commit-log tables."""

    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # -- log plumbing ------------------------------------------------------

    def _log_versions(self) -> List[int]:
        log_dir = os.path.join(self.path, LOG_DIR)
        if not os.path.isdir(log_dir):
            return []
        out = []
        for name in os.listdir(log_dir):
            if name.endswith(".json"):
                try:
                    out.append(int(name[:-5]))
                except ValueError:
                    continue
        return sorted(out)

    def exists(self) -> bool:
        return bool(self._log_versions())

    def latest_version(self) -> int:
        versions = self._log_versions()
        if not versions:
            raise HyperspaceException(f"Not a delta table: {self.path}")
        return versions[-1]

    def _read_commit(self, version: int) -> List[dict]:
        with open(_commit_path(self.path, version)) as f:
            return [json.loads(line) for line in f if line.strip()]

    def _write_commit(self, version: int, actions: List[dict]) -> None:
        log_dir = os.path.join(self.path, LOG_DIR)
        os.makedirs(log_dir, exist_ok=True)
        path = _commit_path(self.path, version)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise DeltaConcurrentModificationException(
                f"Version {version} of {self.path} was committed concurrently")
        with os.fdopen(fd, "w") as f:
            for a in actions:
                f.write(json.dumps(a) + "\n")

    # -- snapshots ---------------------------------------------------------

    def snapshot(self, version: Optional[int] = None) -> Snapshot:
        versions = self._log_versions()
        if not versions:
            raise HyperspaceException(f"Not a delta table: {self.path}")
        if version is None:
            version = versions[-1]
        if version not in versions:
            raise HyperspaceException(
                f"Version {version} does not exist for {self.path} "
                f"(available: {versions[0]}..{versions[-1]})")
        files: Dict[str, dict] = {}
        schema_str = None
        for v in versions:
            if v > version:
                break
            for action in self._read_commit(v):
                if "add" in action:
                    files[action["add"]["path"]] = action["add"]
                elif "remove" in action:
                    files.pop(action["remove"]["path"], None)
                elif "metaData" in action:
                    schema_str = action["metaData"].get("schemaString",
                                                        schema_str)
        return Snapshot(self.path, version, files, schema_str)

    def history(self) -> List[dict]:
        out = []
        for v in self._log_versions():
            for action in self._read_commit(v):
                if "commitInfo" in action:
                    info = dict(action["commitInfo"])
                    info["version"] = v
                    out.append(info)
        return out

    # -- writes ------------------------------------------------------------

    @staticmethod
    def _schema_string(schema: pa.Schema) -> str:
        import base64
        return base64.b64encode(schema.serialize().to_pybytes()).decode()

    def _write_parts(self, table: pa.Table, max_rows_per_file: Optional[int]
                     ) -> List[dict]:
        os.makedirs(self.path, exist_ok=True)
        adds = []
        n = table.num_rows
        chunk = max_rows_per_file or max(n, 1)
        offset = 0
        while offset == 0 or offset < n:
            part = table.slice(offset, chunk)
            rel = f"part-{uuid.uuid4().hex}.parquet"
            abs_path = os.path.join(self.path, rel)
            pq.write_table(part, abs_path)
            st = os.stat(abs_path)
            adds.append({"add": {
                "path": rel, "size": st.st_size,
                "modificationTime": int(st.st_mtime * 1000),
                "dataChange": True}})
            offset += chunk
            if n == 0:
                break
        return adds

    def create(self, table: pa.Table,
               max_rows_per_file: Optional[int] = None) -> int:
        """Create version 0. Fails if the table already exists."""
        if self.exists():
            raise HyperspaceException(f"Delta table already exists: {self.path}")
        actions = [{"metaData": {"id": uuid.uuid4().hex,
                                 "schemaString": self._schema_string(table.schema),
                                 "partitionColumns": []}}]
        actions += self._write_parts(table, max_rows_per_file)
        actions.append({"commitInfo": {"operation": "WRITE",
                                       "timestamp": int(time.time() * 1000)}})
        self._write_commit(0, actions)
        return 0

    def append(self, table: pa.Table,
               max_rows_per_file: Optional[int] = None) -> int:
        version = self.latest_version() + 1
        actions = self._write_parts(table, max_rows_per_file)
        actions.append({"commitInfo": {"operation": "APPEND",
                                       "timestamp": int(time.time() * 1000)}})
        self._write_commit(version, actions)
        return version

    def remove_files(self, abs_paths: List[str]) -> int:
        """Remove data files from the table (file-granularity delete)."""
        snap = self.snapshot()
        version = snap.version + 1
        actions = []
        for p in abs_paths:
            rel = os.path.relpath(os.path.abspath(p), self.path)
            if rel not in snap._files:
                raise HyperspaceException(f"{p} is not part of {self.path}")
            actions.append({"remove": {"path": rel,
                                       "deletionTimestamp": int(time.time() * 1000),
                                       "dataChange": True}})
        actions.append({"commitInfo": {"operation": "DELETE",
                                       "timestamp": int(time.time() * 1000)}})
        self._write_commit(version, actions)
        return version

    def overwrite(self, table: pa.Table,
                  max_rows_per_file: Optional[int] = None) -> int:
        snap = self.snapshot()
        version = snap.version + 1
        actions = [{"remove": {"path": rel,
                               "deletionTimestamp": int(time.time() * 1000),
                               "dataChange": True}}
                   for rel in sorted(snap._files)]
        actions.append({"metaData": {"id": uuid.uuid4().hex,
                                     "schemaString": self._schema_string(table.schema),
                                     "partitionColumns": []}})
        actions += self._write_parts(table, max_rows_per_file)
        actions.append({"commitInfo": {"operation": "OVERWRITE",
                                       "timestamp": int(time.time() * 1000)}})
        self._write_commit(version, actions)
        return version
