"""Snapshot/manifest versioned parquet tables — the Iceberg analogue.

Layout (HadoopTables-style, self-contained on the filesystem)::

    <table>/data/part-<uuid>.parquet
    <table>/metadata/v1.metadata.json       (table metadata, one per commit)
    <table>/metadata/snap-<id>.manifest.json (immutable file manifest)
    <table>/metadata/version-hint.text      (points at latest metadata v)

Unlike the commit-log delta format (fold of add/remove actions), every
snapshot's manifest lists the table's *complete* file set — the Iceberg
model: metadata versions chain table states, snapshots are immutable and
addressable by id for time travel. Commits write metadata create-exclusive
(O_EXCL) for optimistic concurrency.

Storage layer only; query/index integration is sources/iceberg.py
(reference: sources/iceberg/IcebergFileBasedSource.scala, snapshot-id-based
signatures and partition-aware hybrid scan).
"""

from __future__ import annotations

import json
import os
import random
import time
import uuid
from typing import List, Optional, Tuple

import pyarrow as pa
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException

METADATA_DIR = "metadata"
DATA_DIR = "data"


class IcebergConcurrentModificationException(HyperspaceException):
    pass


class IcebergSnapshot:
    def __init__(self, table_path: str, snapshot_id: int, manifest: dict):
        self.table_path = table_path
        self.snapshot_id = snapshot_id
        self._manifest = manifest

    @property
    def file_infos(self) -> List[Tuple[str, int, int]]:
        out = []
        for f in sorted(self._manifest["files"], key=lambda x: x["path"]):
            out.append((os.path.join(self.table_path, f["path"]),
                        int(f["size"]), int(f["modificationTime"])))
        return out

    @property
    def file_paths(self) -> List[str]:
        return [p for p, _, _ in self.file_infos]

    def arrow_schema(self) -> Optional[pa.Schema]:
        s = self._manifest.get("schemaString")
        if s is None:
            return None
        import base64
        import pyarrow.ipc as ipc
        return ipc.read_schema(pa.BufferReader(base64.b64decode(s)))


class IcebergTable:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    # -- metadata chain ----------------------------------------------------

    def _meta_dir(self) -> str:
        return os.path.join(self.path, METADATA_DIR)

    def _hint_path(self) -> str:
        return os.path.join(self._meta_dir(), "version-hint.text")

    def _metadata_path(self, v: int) -> str:
        return os.path.join(self._meta_dir(), f"v{v}.metadata.json")

    def exists(self) -> bool:
        return os.path.isfile(self._hint_path())

    def _latest_metadata_version(self) -> int:
        if not self.exists():
            raise HyperspaceException(f"Not an iceberg table: {self.path}")
        with open(self._hint_path()) as f:
            return int(f.read().strip())

    def _read_metadata(self, v: Optional[int] = None) -> dict:
        if v is None:
            v = self._latest_metadata_version()
        with open(self._metadata_path(v)) as f:
            return json.load(f)

    def _commit_metadata(self, meta: dict) -> int:
        os.makedirs(self._meta_dir(), exist_ok=True)
        v = meta["metadataVersion"]
        path = self._metadata_path(v)
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            raise IcebergConcurrentModificationException(
                f"Metadata v{v} of {self.path} was committed concurrently")
        with os.fdopen(fd, "w") as f:
            json.dump(meta, f, indent=1)
        # The hint is a pointer update, last-writer-wins (the O_EXCL metadata
        # write above is the linearization point).
        tmp = self._hint_path() + f".tmp{uuid.uuid4().hex}"
        with open(tmp, "w") as f:
            f.write(str(v))
        os.replace(tmp, self._hint_path())
        return v

    # -- snapshots ---------------------------------------------------------

    def current_snapshot_id(self) -> int:
        return int(self._read_metadata()["currentSnapshotId"])

    def snapshot_ids(self) -> List[int]:
        return [int(s["snapshotId"])
                for s in self._read_metadata()["snapshots"]]

    def snapshot(self, snapshot_id: Optional[int] = None) -> IcebergSnapshot:
        meta = self._read_metadata()
        if snapshot_id is None:
            snapshot_id = int(meta["currentSnapshotId"])
        for s in meta["snapshots"]:
            if int(s["snapshotId"]) == snapshot_id:
                with open(os.path.join(self.path, s["manifest"])) as f:
                    return IcebergSnapshot(self.path, snapshot_id,
                                           json.load(f))
        raise HyperspaceException(
            f"Snapshot {snapshot_id} not found in {self.path}")

    # -- writes ------------------------------------------------------------

    @staticmethod
    def _schema_string(schema: pa.Schema) -> str:
        import base64
        return base64.b64encode(schema.serialize().to_pybytes()).decode()

    def _write_parts(self, table: pa.Table,
                     max_rows_per_file: Optional[int]) -> List[dict]:
        data_dir = os.path.join(self.path, DATA_DIR)
        os.makedirs(data_dir, exist_ok=True)
        out = []
        n = table.num_rows
        chunk = max_rows_per_file or max(n, 1)
        offset = 0
        while offset == 0 or offset < n:
            part = table.slice(offset, chunk)
            rel = os.path.join(DATA_DIR, f"part-{uuid.uuid4().hex}.parquet")
            abs_path = os.path.join(self.path, rel)
            pq.write_table(part, abs_path)
            st = os.stat(abs_path)
            out.append({"path": rel, "size": st.st_size,
                        "modificationTime": int(st.st_mtime * 1000),
                        "recordCount": part.num_rows})
            offset += chunk
            if n == 0:
                break
        return out

    def _new_snapshot(self, files: List[dict], schema: pa.Schema,
                      operation: str, parent: Optional[int]) -> Tuple[int, dict]:
        snap_id = random.getrandbits(62)
        manifest = {"schemaString": self._schema_string(schema),
                    "files": files}
        rel = os.path.join(METADATA_DIR, f"snap-{snap_id}.manifest.json")
        with open(os.path.join(self.path, rel), "w") as f:
            json.dump(manifest, f, indent=1)
        return snap_id, {"snapshotId": snap_id, "manifest": rel,
                         "timestampMs": int(time.time() * 1000),
                         "operation": operation,
                         "parentSnapshotId": parent}

    def create(self, table: pa.Table,
               max_rows_per_file: Optional[int] = None) -> int:
        if self.exists():
            raise HyperspaceException(
                f"Iceberg table already exists: {self.path}")
        os.makedirs(self._meta_dir(), exist_ok=True)
        files = self._write_parts(table, max_rows_per_file)
        snap_id, snap_entry = self._new_snapshot(files, table.schema,
                                                 "append", None)
        self._commit_metadata({
            "metadataVersion": 1, "location": self.path,
            "currentSnapshotId": snap_id, "snapshots": [snap_entry]})
        return snap_id

    def _commit_new_state(self, files: List[dict], schema: pa.Schema,
                          operation: str) -> int:
        meta = self._read_metadata()
        snap_id, snap_entry = self._new_snapshot(
            files, schema, operation, int(meta["currentSnapshotId"]))
        new_meta = {
            "metadataVersion": meta["metadataVersion"] + 1,
            "location": self.path,
            "currentSnapshotId": snap_id,
            "snapshots": meta["snapshots"] + [snap_entry]}
        self._commit_metadata(new_meta)
        return snap_id

    def append(self, table: pa.Table,
               max_rows_per_file: Optional[int] = None) -> int:
        snap = self.snapshot()
        new_files = self._write_parts(table, max_rows_per_file)
        all_files = snap._manifest["files"] + new_files
        return self._commit_new_state(all_files, table.schema, "append")

    def remove_files(self, abs_paths: List[str]) -> int:
        snap = self.snapshot()
        drop = {os.path.relpath(os.path.abspath(p), self.path)
                for p in abs_paths}
        existing = {f["path"] for f in snap._manifest["files"]}
        missing = drop - existing
        if missing:
            raise HyperspaceException(
                f"Not part of {self.path}: {sorted(missing)}")
        kept = [f for f in snap._manifest["files"] if f["path"] not in drop]
        schema = snap.arrow_schema()
        return self._commit_new_state(kept, schema, "delete")
