"""Cost-based join reordering over inner-equi-join chains.

The SQL front-end lowers comma-joined FROM lists (and chained
DataFrame ``.join`` calls) in text order; on star-schema workloads the
first join frequently produces the largest possible intermediate and
every downstream kernel pays for it in real rows hashed, sorted, and
padded. This pass runs inside ``Session.optimize`` AFTER the
normalization passes (filter pushdown, column pruning) and BEFORE the
hyperspace index rules, so FilterIndexRule/JoinIndexRule and the
advisor's what-if hooks match the reordered tree exactly as they would
the original.

Scope is deliberately conservative — semantics-preserving by
construction:

  * only chains of INNER joins whose conditions are conjunctions of
    column=column equalities are reordered (cross/semi/anti/outer joins
    and non-equi conditions are barriers; their subtrees are recursed
    independently);
  * the rewritten chain is a left-deep linear order chosen by estimated
    intermediate size (exhaustive left-deep DP below
    ``optimizer.joinReorder.dpThreshold`` tables, greedy
    smallest-intermediate-first above);
  * a trailing Project restores the original output column order, so
    results equal the reorder-off plan modulo row order;
  * if any chain member's cardinality cannot be estimated (no parquet
    footers, exotic operators), the chain is left in its original
    order.

The cost model is deliberately index-unaware: orders are ranked purely
by estimated intermediate rows, so a reorder can demote a join that
JoinIndexRule would have served at leaf level in the text order (the
rule needs both sides linear). Measured in this sandbox, the
intermediate-row reduction beats the bucketed-index byte discount when
they conflict; when the chosen order keeps an index-servable pair at
leaf level, the rules rewrite it exactly as they would the original
tree (tests/test_join_reorder.py::TestIndexRuleInterplay pins both
directions).

Estimates come from optimizer/stats.py + optimizer/cardinality.py; each
evaluated chain leaves a record on ``session._last_join_order`` that the
explain "Join order:" section and bench's q-error report read back.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..exceptions import HyperspaceException
from ..plan import expr as E
from ..plan.nodes import (Aggregate, Filter, Join, Limit, LogicalPlan,
                          Project, Scan, Sort, Union, Window)
from . import cardinality
from .stats import provider_for


def _is_chain_join(node: LogicalPlan) -> bool:
    return (isinstance(node, Join) and node.join_type == "inner"
            and node.condition is not None
            and E.extract_equi_join_keys(node.condition) is not None)


def _is_passthrough_project(node: LogicalPlan) -> bool:
    """A pure column-pruning Project directly above a chain join (the
    shape prune_columns interposes between joins): safe to flatten
    through — no renames, no computed columns. The dropped pruning is
    recovered by the trailing Project the rebuild adds (and the
    executor's needed-set propagation never materializes the extras)."""
    return (isinstance(node, Project)
            and all(isinstance(e, E.Col) for e in node.exprs)
            and _is_chain_join(node.child))


def _flatten(node: LogicalPlan, items: List[LogicalPlan],
             conjuncts: List[E.Expr]) -> None:
    if _is_chain_join(node):
        _flatten(node.left, items, conjuncts)
        _flatten(node.right, items, conjuncts)
        conjuncts.extend(E.split_conjunctive_predicates(node.condition))
    elif _is_passthrough_project(node):
        _flatten(node.child, items, conjuncts)
    else:
        items.append(node)


def _item_label(node: LogicalPlan, idx: int) -> str:
    for leaf in node.collect_leaves():
        relation = getattr(leaf, "relation", None)
        if relation is not None and relation.root_paths:
            return os.path.basename(
                relation.root_paths[0].rstrip("/")) or f"item#{idx}"
    return f"{node.node_name.lower()}#{idx}"


# ---------------------------------------------------------------------------
# Per-item cardinality estimation.
# ---------------------------------------------------------------------------

@dataclass
class _Est:
    rows: float
    ndv: Dict[str, Optional[float]] = field(default_factory=dict)


def _estimate_item(session, node: LogicalPlan,
                   needed: frozenset) -> Optional[_Est]:
    """Estimated output rows of ``node`` plus NDV for the ``needed``
    columns, or None when no estimate is possible."""
    provider = provider_for(session)
    if isinstance(node, Scan):
        ts = provider.table_stats(node.relation)
        if ts is None:
            return None
        ndv = {c: ts.ndv(c) for c in needed if c in node.schema}
        return _Est(float(max(ts.row_count, 1)), ndv)
    if isinstance(node, Filter):
        child = _estimate_item(session, node.child, needed)
        if child is None:
            return None
        ts = None
        cap = None
        if isinstance(node.child, Scan):
            ts = provider.table_stats(node.child.relation)
            cap = provider.sketch_row_fraction(node.child.relation,
                                               node.condition)
        sel = cardinality.filter_selectivity(ts, node.condition, cap)
        rows = max(1.0, child.rows * sel)
        return _Est(rows, _cap_ndv(child.ndv, rows))
    if isinstance(node, Project):
        renames = {}
        for e in node.exprs:
            inner = e.child if isinstance(e, E.Alias) else e
            if isinstance(inner, E.Col):
                renames[e.name] = inner.column
        child_needed = frozenset(renames.get(c, c) for c in needed)
        child = _estimate_item(session, node.child, child_needed)
        if child is None:
            return None
        ndv = {c: child.ndv.get(renames.get(c, c)) for c in needed}
        return _Est(child.rows, ndv)
    if isinstance(node, Aggregate):
        groups = frozenset(node.group_cols)
        child = _estimate_item(session, node.child, needed | groups)
        if child is None:
            return None
        if not node.group_cols:
            return _Est(1.0, {c: 1.0 for c in needed})
        rows = 1.0
        for g in node.group_cols:
            nd = child.ndv.get(g)
            rows *= nd if nd is not None else child.rows ** 0.5
        rows = max(1.0, min(rows, child.rows))
        ndv = {c: child.ndv.get(c) for c in needed}
        return _Est(rows, _cap_ndv(ndv, rows))
    if isinstance(node, Limit):
        child = _estimate_item(session, node.child, needed)
        if child is None:
            return None
        rows = max(1.0, min(float(node.n), child.rows))
        return _Est(rows, _cap_ndv(child.ndv, rows))
    if isinstance(node, (Sort, Window)):
        return _estimate_item(session, node.children[0], needed)
    if isinstance(node, Union):
        rows = 0.0
        ndv: Dict[str, Optional[float]] = {c: None for c in needed}
        for c in node.children:
            child = _estimate_item(session, c, needed)
            if child is None:
                return None
            rows += child.rows
        return _Est(max(1.0, rows), ndv)
    if isinstance(node, Join):
        return _estimate_join(session, node, needed)
    return None


def _estimate_join(session, node: Join,
                   needed: frozenset) -> Optional[_Est]:
    keys = E.extract_equi_join_keys(node.condition) \
        if node.condition is not None else []
    key_cols = frozenset(c for pair in (keys or []) for c in pair)
    left = _estimate_item(session, node.left, needed | key_cols)
    right = _estimate_item(session, node.right, needed | key_cols)
    if left is None or right is None:
        return None
    if node.join_type in ("semi", "anti"):
        rows = max(1.0, left.rows * 0.5)
        return _Est(rows, _cap_ndv(left.ndv, rows))
    if node.join_type == "cross":
        rows = left.rows * right.rows
        return _Est(rows, _cap_ndv({**left.ndv, **right.ndv}, rows))
    rows = cardinality.equi_join_rows(
        left.rows, right.rows,
        [(left.ndv.get(a, right.ndv.get(a)),
          right.ndv.get(b, left.ndv.get(b))) for a, b in (keys or [])])
    if node.join_type in ("left", "full"):
        rows = max(rows, left.rows)
    if node.join_type in ("right", "full"):
        rows = max(rows, right.rows)
    rows = max(1.0, rows)
    return _Est(rows, _cap_ndv({**left.ndv, **right.ndv}, rows))


def _cap_ndv(ndv: Dict[str, Optional[float]],
             rows: float) -> Dict[str, Optional[float]]:
    return {c: (None if v is None else max(1.0, min(v, rows)))
            for c, v in ndv.items()}


# ---------------------------------------------------------------------------
# Order enumeration.
# ---------------------------------------------------------------------------

class _Corrector:
    """Adaptive-feedback hook (adaptive/feedback.py): scales each
    enumeration step's estimate by the learned actual/estimate ratio of
    its table pair, and substitutes the EMA'd observed cardinality for a
    rebuilt join that executed before. Built only while
    ``adaptive.feedback.enabled`` is on — absent, the cost model is
    byte-for-byte the uncorrected one. Side signatures use the same
    rewrite-stable leaf identities the executors key actuals by
    (serving/context.join_actual_key), so estimate-time and
    execution-time keys pair even though this pass runs BEFORE index
    substitution and partition pruning."""

    def __init__(self, session, items: List[LogicalPlan]):
        from ..adaptive.feedback import get_store
        from ..serving.context import _leaf_identity
        self._store = get_store()
        self._ids: List[List[str]] = []
        for it in items:
            try:
                self._ids.append(
                    [_leaf_identity(leaf) for leaf in it.collect_leaves()])
            except Exception:
                self._ids.append([])
        self._sig_cache: Dict[frozenset, str] = {}

    def _sig(self, idxs) -> str:
        key = frozenset(idxs)
        s = self._sig_cache.get(key)
        if s is None:
            parts: List[str] = []
            for i in key:
                parts.extend(self._ids[i])
            s = "+".join(sorted(parts))
            self._sig_cache[key] = s
        return s

    def adjust(self, joined, t: int, est: float) -> float:
        return self._store.corrected_rows(
            self._sig(joined), self._sig([t]), est)

    def exact(self, key: str) -> Optional[float]:
        return self._store.exact_rows(key)


def _step(rows: float, ndv: Dict[str, Optional[float]], item: _Est,
          conds: List[Tuple[str, str]]) -> Tuple[float, Dict]:
    """One left-deep join step: current intermediate x ``item`` over the
    equality pairs in ``conds``. Returns (output rows, merged ndvs)."""
    resolved = [(ndv.get(a, item.ndv.get(a)),
                 item.ndv.get(b, ndv.get(b)), a, b) for a, b in conds]
    out = max(1.0, cardinality.equi_join_rows(
        rows, item.rows, [(l, r) for l, r, _, _ in resolved]))
    merged = dict(ndv)
    merged.update(item.ndv)
    for l, r, a, b in resolved:
        merged[a] = merged[b] = min(l if l is not None else rows,
                                    r if r is not None else item.rows)
    return out, _cap_ndv(merged, out)


def _edge_conds(edges, joined: frozenset, t: int) -> List[Tuple[str, str]]:
    out = []
    for a, b, la, lb in edges:
        if a in joined and b == t:
            out.append((la, lb))
        elif b in joined and a == t:
            out.append((lb, la))
    return out


def _enumerate_greedy(ests: List[_Est], edges,
                      corr: Optional[_Corrector] = None) -> List[int]:
    n = len(ests)
    best_pair = None
    for i in range(n):
        for j in range(i + 1, n):
            conds = _edge_conds(edges, frozenset([i]), j)
            if not conds:
                continue
            rows, _ = _step(ests[i].rows, ests[i].ndv, ests[j], conds)
            if corr is not None:
                rows = corr.adjust([i], j, rows)
            if best_pair is None or rows < best_pair[0]:
                best_pair = (rows, i, j)
    if best_pair is None:
        return list(range(n))
    _, i, j = best_pair
    order = [i, j]
    joined = frozenset(order)
    rows, ndv = _step(ests[i].rows, ests[i].ndv, ests[j],
                      _edge_conds(edges, frozenset([i]), j))
    if corr is not None:
        rows = corr.adjust([i], j, rows)
    while len(order) < n:
        best = None
        for t in range(n):
            if t in joined:
                continue
            conds = _edge_conds(edges, joined, t)
            if not conds:
                continue
            out, nd = _step(rows, ndv, ests[t], conds)
            if corr is not None:
                out = corr.adjust(joined, t, out)
            if best is None or out < best[0]:
                best = (out, t, nd)
        if best is None:
            # Disconnected remainder (cannot happen for a chain that came
            # from a valid join tree): keep the original order.
            return list(range(n))
        rows, ndv = best[0], best[2]
        order.append(best[1])
        joined = joined | {best[1]}
    return order


def _enumerate_dp(ests: List[_Est], edges,
                  corr: Optional[_Corrector] = None) -> List[int]:
    """Exhaustive left-deep search over connected subsets (Selinger-style
    DP): state per subset keeps the cheapest cumulative intermediate-row
    total. Falls back to greedy on any gap (disconnected subsets)."""
    n = len(ests)
    # subset (frozenset) -> (cost, rows, ndv, order)
    states: Dict[frozenset, Tuple[float, float, Dict, List[int]]] = {}
    for i in range(n):
        for j in range(n):
            if i == j:
                continue
            conds = _edge_conds(edges, frozenset([i]), j)
            if not conds:
                continue
            rows, ndv = _step(ests[i].rows, ests[i].ndv, ests[j], conds)
            if corr is not None:
                rows = corr.adjust([i], j, rows)
            key = frozenset((i, j))
            if key not in states or rows < states[key][0]:
                states[key] = (rows, rows, ndv, [i, j])
    for _size in range(2, n):
        additions: Dict[frozenset, Tuple] = {}
        for subset, (cost, rows, ndv, order) in states.items():
            if len(subset) != _size:
                continue
            for t in range(n):
                if t in subset:
                    continue
                conds = _edge_conds(edges, subset, t)
                if not conds:
                    continue
                out, nd = _step(rows, ndv, ests[t], conds)
                if corr is not None:
                    out = corr.adjust(subset, t, out)
                key = subset | {t}
                cand = (cost + out, out, nd, order + [t])
                prev = additions.get(key) or states.get(key)
                if prev is None or cand[0] < prev[0]:
                    additions[key] = cand
        states.update(additions)
    full = states.get(frozenset(range(n)))
    if full is None:
        return _enumerate_greedy(ests, edges, corr)
    return full[3]


# ---------------------------------------------------------------------------
# The rewrite.
# ---------------------------------------------------------------------------

def reorder_joins(session, plan: LogicalPlan,
                  diagnostic: bool = False) -> LogicalPlan:
    """Rewrite every eligible inner-equi-join chain of ``plan`` to its
    cheapest estimated linear order. Leaves a list of chain records on
    ``session._last_join_order`` (explain/bench read it back); emits
    JoinReorderEvent/CardinalityEstimateEvent telemetry on non-diagnostic
    passes that changed an order."""
    from ..telemetry import span_names as SN
    from ..telemetry import trace as _trace
    records: List[dict] = []
    with _trace.span(SN.JOIN_REORDER) as sp:
        out = _rewrite(session, plan, records)
        if sp is not None:
            sp.attrs["chains"] = len(records)
            sp.attrs["reordered"] = sum(
                1 for r in records if r["reordered"])
    session._last_join_order = records
    if not diagnostic and any(r["reordered"] for r in records):
        _emit_events(session, records)
    return out


def _rewrite(session, node: LogicalPlan, records: List[dict]) -> LogicalPlan:
    if _is_chain_join(node):
        items: List[LogicalPlan] = []
        conjuncts: List[E.Expr] = []
        _flatten(node, items, conjuncts)
        new_items = [_rewrite(session, it, records) for it in items]
        mapping = {id(old): new for old, new in zip(items, new_items)}
        if len(new_items) < 3:
            # A 2-table chain has one linear order; nothing to choose.
            return _rebuild_same(node, mapping)
        return _reorder_chain(session, node, new_items, conjuncts,
                              mapping, records)
    new_children = [_rewrite(session, c, records) for c in node.children]
    if all(a is b for a, b in zip(new_children, node.children)):
        return node
    return node.with_children(new_children)


def _rebuild_same(node: LogicalPlan, mapping: Dict[int, LogicalPlan]
                  ) -> LogicalPlan:
    """The original chain structure (interposed pruning Projects
    included) with (possibly rewritten) items substituted back in."""
    if _is_chain_join(node):
        left = _rebuild_same(node.left, mapping)
        right = _rebuild_same(node.right, mapping)
        if left is node.left and right is node.right:
            return node
        return Join(left, right, node.condition, "inner")
    if _is_passthrough_project(node):
        child = _rebuild_same(node.child, mapping)
        if child is node.child:
            return node
        return Project(node.exprs, child)
    return mapping[id(node)]


def _reorder_chain(session, node: Join, items: List[LogicalPlan],
                   conjuncts: List[E.Expr],
                   mapping: Dict[int, LogicalPlan],
                   records: List[dict]) -> LogicalPlan:
    labels = [_item_label(it, i) for i, it in enumerate(items)]
    record = {"labels": labels, "order": labels, "reordered": False,
              "base": [], "steps": []}
    records.append(record)

    owner: Dict[str, int] = {}
    for i, it in enumerate(items):
        for name in it.schema.names:
            if name in owner:
                record["note"] = "ambiguous columns"
                return _rebuild_same(node, mapping)
            owner[name] = i

    # Edges: (item_a, item_b, col_a, col_b) per equality conjunct, plus
    # the original Expr so the rebuilt conditions reuse the user's
    # spelling/orientation.
    edges: List[Tuple[int, int, str, str]] = []
    exprs: Dict[Tuple[int, int, str, str], E.Expr] = {}
    for c in conjuncts:
        la, lb = c.left.column, c.right.column
        a, b = owner.get(la), owner.get(lb)
        if a is None or b is None or a == b:
            record["note"] = "non-cross-table equality"
            return _rebuild_same(node, mapping)
        edges.append((a, b, la, lb))
        exprs[(a, b, la, lb)] = c

    needed = frozenset(la for _, _, la, _ in edges) | \
        frozenset(lb for _, _, _, lb in edges)
    ests: List[Optional[_Est]] = [
        _estimate_item(session, it, needed) for it in items]
    if any(e is None for e in ests):
        record["note"] = "no statistics for at least one table"
        return _rebuild_same(node, mapping)
    record["base"] = [
        {"label": labels[i], "est_rows": ests[i].rows}
        for i in range(len(items))]

    corr = _Corrector(session, items) \
        if session.hs_conf.adaptive_feedback_enabled() else None
    threshold = session.hs_conf.join_reorder_dp_threshold()
    if len(items) <= threshold:
        order = _enumerate_dp(ests, edges, corr)
    else:
        order = _enumerate_greedy(ests, edges, corr)
    if order == list(range(len(items))):
        record["note"] = "original order already cheapest"
        return _rebuild_same(node, mapping)

    # Rebuild left-deep in the chosen order; each step conjoins every
    # original equality conjunct both of whose sides are now present.
    # Any constructor rejection (e.g. an ambiguity an interposed pruning
    # Project used to resolve) falls back to the original order.
    # Step keys are the composite join_actual_key strings — the very
    # keys the executors will record actuals under for the Join nodes
    # built here, so explain/bench q-error pairing (and the adaptive
    # feedback/replan loops) never cross table pairs.
    from ..serving.context import join_actual_key
    joined = frozenset([order[0]])
    cur = items[order[0]]
    rows, ndv = ests[order[0]].rows, ests[order[0]].ndv
    steps: List[dict] = []
    try:
        for t in order[1:]:
            conds = [exprs[e] for e in edges
                     if (e[0] in joined and e[1] == t)
                     or (e[1] in joined and e[0] == t)]
            if not conds:
                record["note"] = "chosen order lost connectivity"
                return _rebuild_same(node, mapping)
            rows, ndv = _step(rows, ndv, ests[t],
                              _edge_conds(edges, joined, t))
            condition = E.conjoin(conds)
            key = join_actual_key(condition, cur, items[t])
            if corr is not None:
                rows = corr.adjust(joined, t, rows)
                exact = corr.exact(key)
                if exact is not None:
                    rows = exact
            cur = Join(cur, items[t], condition, "inner",
                       reorder_note=f"reordered, est~{rows:.0f} rows")
            steps.append({"right": labels[t], "key": key,
                          "est_rows": rows})
            joined = joined | {t}

        original_names = list(node.schema.names)
        if list(cur.schema.names) != original_names:
            cur = Project(original_names, cur)
    except HyperspaceException:
        record["note"] = "rebuild rejected; original order kept"
        record["steps"] = []
        return _rebuild_same(node, mapping)
    record["order"] = [labels[i] for i in order]
    record["reordered"] = True
    record["steps"] = steps
    return cur


def _emit_events(session, records: List[dict]) -> None:
    from ..telemetry.events import (CardinalityEstimateEvent,
                                    JoinReorderEvent)
    from ..telemetry.logging import get_logger
    logger = get_logger(session.hs_conf.event_logger_class())
    for r in records:
        if not r["reordered"]:
            continue
        logger.log_event(JoinReorderEvent(
            message="Join chain reordered.",
            tables=list(r["labels"]), order=list(r["order"]),
            estimated_rows=[s["est_rows"] for s in r["steps"]]))
        for s in r["steps"]:
            logger.log_event(CardinalityEstimateEvent(
                message="Equi-join output estimate.",
                subject=s["key"], estimated_rows=s["est_rows"]))
