"""Config keys for the cost-based optimizer layer (optimizer/).

No reference analogue: the reference delegates all plan optimization to
Spark Catalyst (SURVEY §1 L1); here the framework IS the engine, so the
statistics provider and the join-reorder pass get their own
``hyperspace.tpu.optimizer.*`` conf family, read exclusively through
config.py accessors (the scripts/lint.py env-read gate applies).
"""

from __future__ import annotations


class OptimizerConstants:
    # Table/column statistics provider (optimizer/stats.py): lazy parquet
    # footer harvesting + per-relation cache keyed on the relation's file
    # (size, mtime, path) signature — source changes invalidate exactly
    # like the serving result cache's source component.
    STATS_ENABLED = "hyperspace.tpu.optimizer.stats.enabled"
    STATS_ENABLED_DEFAULT = "true"

    # Rows sampled (from the first file) for NDV estimation of columns
    # whose min/max span cannot bound distinctness (strings, floats).
    # 0 disables sampling: such columns report no NDV at all, and join
    # estimation then divides by the side's full row count (keys
    # treated as distinct), shrinking equality/join estimates.
    STATS_SAMPLE_ROWS = "hyperspace.tpu.optimizer.stats.sampleRows"
    STATS_SAMPLE_ROWS_DEFAULT = "65536"

    # LRU bound of cached per-relation statistics entries.
    STATS_CACHE_ENTRIES = "hyperspace.tpu.optimizer.stats.cacheEntries"
    STATS_CACHE_ENTRIES_DEFAULT = "64"

    # Cost-based join reordering (optimizer/join_order.py): rewrite
    # inner-equi-join chains to the cheapest estimated linear order
    # before the hyperspace index rules run. Semantics-preserving (inner
    # joins only; output column order restored by a trailing Project).
    JOIN_REORDER_ENABLED = "hyperspace.tpu.optimizer.joinReorder.enabled"
    JOIN_REORDER_ENABLED_DEFAULT = "false"

    # Chains with at most this many tables are enumerated exhaustively
    # (left-deep dynamic programming over connected subsets); larger
    # chains use greedy smallest-intermediate-first.
    JOIN_REORDER_DP_THRESHOLD = \
        "hyperspace.tpu.optimizer.joinReorder.dpThreshold"
    JOIN_REORDER_DP_THRESHOLD_DEFAULT = "8"
