"""Cost-based optimizer layer: table/column statistics, cardinality
estimation, and join reordering (no reference analogue — the reference
delegates plan optimization to Spark Catalyst; here the framework is the
engine)."""

from .constants import OptimizerConstants  # noqa: F401
