"""Selectivity and cardinality estimation over the statistics layer.

Textbook estimators (System R lineage), fed by optimizer/stats.py:

  * range conjuncts  → covered fraction of the column's [min, max] span;
  * equality         → 1 / NDV (uniformity assumption);
  * IN lists         → |list| / NDV, capped at 1;
  * IS [NOT] NULL    → the footer-exact null fraction;
  * equi-joins       → containment of keys: |L| x |R| / max(NDV_l, NDV_r).

Sketch refutation (Bloom membership / MinMax, via
StatsProvider.sketch_row_fraction) caps equality/IN selectivity from
above: rows in files every sketch refutes cannot match. Unknown shapes
estimate 1.0 — conservative for join ordering (an unknown predicate
never makes a table look artificially small).
"""

from __future__ import annotations

import datetime
from typing import Optional

from ..plan import expr as E
from .stats import TableStats, numeric_span_fraction

# Selectivity floor: keeps products non-zero so downstream ratios and
# q-errors stay finite even when an estimator reports "nothing survives".
MIN_SELECTIVITY = 1e-4

# Fixed fallbacks for shapes the statistics cannot see through
# (the classic System R defaults, biased conservative).
EQUALITY_FALLBACK = 0.1
RANGE_FALLBACK = 1.0 / 3.0
LIKE_SELECTIVITY = 0.2

_RANGE_OPS = (E.LessThan, E.LessThanOrEqual,
              E.GreaterThan, E.GreaterThanOrEqual)


def _clamp(s: float) -> float:
    return max(MIN_SELECTIVITY, min(1.0, s))


def _coerce_literal(value, cs):
    """Date columns accept ISO strings in the expression language."""
    if isinstance(value, str) and isinstance(cs.minimum, datetime.date):
        try:
            return datetime.date.fromisoformat(value)
        except ValueError:
            return value
    return value


def _col_lit(e) -> Optional[tuple]:
    """(column, op-name, literal) for Col <op> Lit in either order."""
    if not isinstance(e, _RANGE_OPS + (E.EqualTo,)):
        return None
    left, right = e.left, e.right
    op = type(e).__name__
    if isinstance(left, E.Lit) and isinstance(right, E.Col):
        left, right = right, left
        op = {"EqualTo": "EqualTo", "LessThan": "GreaterThan",
              "LessThanOrEqual": "GreaterThanOrEqual",
              "GreaterThan": "LessThan",
              "GreaterThanOrEqual": "LessThanOrEqual"}[op]
    if isinstance(left, E.Col) and isinstance(right, E.Lit):
        return left.column, op, right.value
    return None


def conjunct_selectivity(stats: Optional[TableStats], e: E.Expr) -> float:
    """Estimated selectivity of one predicate node (not clamped —
    callers clamp the final product)."""
    if isinstance(e, E.And):
        return conjunct_selectivity(stats, e.left) * \
            conjunct_selectivity(stats, e.right)
    if isinstance(e, E.Or):
        sl = conjunct_selectivity(stats, e.left)
        sr = conjunct_selectivity(stats, e.right)
        return min(1.0, sl + sr - sl * sr)
    if isinstance(e, E.Not):
        child = conjunct_selectivity(stats, e.child)
        # An opaque child estimates 1.0; its negation is equally opaque —
        # returning 1 - 1.0 = 0 would make the table look artificially
        # tiny, the exact failure the conservative default exists to
        # prevent.
        return 1.0 if child >= 1.0 else 1.0 - child
    if isinstance(e, E.IsNull) and isinstance(e.child, E.Col):
        if stats is None:
            return 0.5
        nf = stats.null_fraction(e.child.column)
        return (1.0 - nf) if e.negated else nf
    if isinstance(e, E.Like):
        return (1.0 - LIKE_SELECTIVITY) if e.negated else LIKE_SELECTIVITY
    if isinstance(e, E.In) and isinstance(e.value, E.Col) \
            and all(isinstance(o, E.Lit) for o in e.options):
        ndv = stats.ndv(e.value.column) if stats is not None else None
        if ndv is None:
            return min(1.0, len(e.options) * EQUALITY_FALLBACK)
        return min(1.0, len(set(o.value for o in e.options)) / ndv)
    cl = _col_lit(e)
    if cl is None:
        return 1.0  # opaque shape: assume it keeps everything
    column, op, value = cl
    cs = stats.column(column) if stats is not None else None
    if op == "EqualTo":
        ndv = stats.ndv(column) if stats is not None else None
        if ndv is None:
            return EQUALITY_FALLBACK
        sel = 1.0 / ndv
        if cs is not None and cs.has_minmax:
            v = _coerce_literal(value, cs)
            try:
                if v < cs.minimum or v > cs.maximum:
                    return 0.0
            except TypeError:
                pass
        return sel
    if cs is None:
        return RANGE_FALLBACK
    v = _coerce_literal(value, cs)
    if op in ("LessThan", "LessThanOrEqual"):
        frac = numeric_span_fraction(cs, None, v)
    else:
        frac = numeric_span_fraction(cs, v, None)
    if frac is None:
        return RANGE_FALLBACK
    return frac * (1.0 - (stats.null_fraction(column)
                          if stats is not None else 0.0))


def filter_selectivity(stats: Optional[TableStats], condition: E.Expr,
                       sketch_cap: Optional[float] = None) -> float:
    """Estimated fraction of rows ``condition`` keeps, in
    [MIN_SELECTIVITY, 1]. ``sketch_cap`` (rows in sketch-unrefuted
    files / total rows) caps the estimate from above."""
    sel = 1.0
    for conjunct in E.split_conjunctive_predicates(condition):
        sel *= conjunct_selectivity(stats, conjunct)
    if sketch_cap is not None:
        sel = min(sel, sketch_cap)
    return _clamp(sel)


def equi_join_rows(left_rows: float, right_rows: float,
                   pair_ndvs) -> float:
    """Multi-key equi-join output estimate: the cross product divided,
    per key pair, by max(NDV_l, NDV_r) — containment of keys with
    independence across pairs. ``pair_ndvs`` is a sequence of
    (left_ndv, right_ndv); a missing NDV falls back to the side's row
    count (keys assumed distinct — the foreign-key-to-primary-key
    common case). THE estimator the reorderer's step/base-item
    calculations use."""
    out = left_rows * right_rows
    for lndv, rndv in pair_ndvs:
        out /= max(1.0,
                   lndv if lndv is not None else left_rows,
                   rndv if rndv is not None else right_rows)
    return out


def join_output_rows(left_rows: float, right_rows: float,
                     left_ndv: Optional[float],
                     right_ndv: Optional[float]) -> float:
    """Single-key convenience form of :func:`equi_join_rows`."""
    return equi_join_rows(left_rows, right_rows,
                          [(left_ndv, right_ndv)])
