"""Cached table/column statistics provider (the cost model's substrate).

The framework already persists exactly the metadata a cost model needs —
parquet footer row-group statistics (row counts, per-column min/max/null
counts; "Only Aggressive Elephants are Fast Elephants", arXiv:1208.0287)
and per-file MinMax/Bloom sketch tables (Extensible Data Skipping,
arXiv:2009.08150) — and, before this module, used none of it at plan
time. ``StatsProvider`` harvests them lazily on first request and caches
per relation, keyed on the relation's (size, mtime, path) file signature
so in-place source changes invalidate by construction, exactly like the
serving result cache's source-signature component.

Everything here is planning-time host work: footer reads only (no data
pages except the bounded NDV sample), no device interaction.
"""

from __future__ import annotations

import datetime
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..schema import BOOL, DATE


@dataclass
class ColumnStats:
    """Footer-harvested facts about one physical column."""

    dtype: str
    minimum: object = None
    maximum: object = None
    null_count: int = 0
    has_minmax: bool = False


@dataclass
class TableStats:
    """Statistics for one relation snapshot. NDV estimates are computed
    (and cached) per column on demand — row counts and min/max come free
    with the footers, distinctness may need the bounded sample read."""

    row_count: int
    files: List[str]
    file_rows: List[int]
    columns: Dict[str, ColumnStats]
    sample_rows: int = 0
    _ndv_cache: Dict[str, float] = field(default_factory=dict)

    def column(self, name: str) -> Optional[ColumnStats]:
        return self.columns.get(name)

    def null_fraction(self, name: str) -> float:
        cs = self.columns.get(name)
        if cs is None or self.row_count <= 0:
            return 0.0
        return min(1.0, cs.null_count / self.row_count)

    def ndv(self, name: str) -> Optional[float]:
        """Estimated number of distinct (non-null) values of ``name``:
        the min of the integer/date/bool min-max span bound and the
        sample-extrapolated estimate; None when neither applies."""
        if name in self._ndv_cache:
            return self._ndv_cache[name]
        cs = self.columns.get(name)
        if cs is None:
            return None
        nonnull = max(1, self.row_count - cs.null_count)
        candidates: List[float] = [float(nonnull)]
        span = _span_count(cs)
        if span is not None:
            candidates.append(span)
        sampled = self._sampled_ndv(name, nonnull)
        if sampled is not None:
            candidates.append(sampled)
        if span is None and sampled is None:
            self._ndv_cache[name] = None
            return None
        out = max(1.0, min(candidates))
        self._ndv_cache[name] = out
        return out

    def _sampled_ndv(self, name: str, nonnull: int) -> Optional[float]:
        """Distinct-ratio extrapolation over (up to) ``sample_rows`` rows
        of the first file: a saturated sample (few distincts) means the
        domain is small — report the sample's distinct count; a mostly-
        distinct sample scales linearly with the table."""
        if self.sample_rows <= 0 or not self.files:
            return None
        try:
            import pyarrow.parquet as pq
            pf = pq.ParquetFile(self.files[0])
            if name not in pf.schema_arrow.names:
                return None
            batch = next(pf.iter_batches(batch_size=self.sample_rows,
                                         columns=[name]), None)
        except Exception:
            return None
        if batch is None or batch.num_rows == 0:
            return None
        col = batch.column(0)
        s = batch.num_rows - col.null_count
        if s <= 0:
            return None
        d = len(col.drop_null().unique())
        if d <= 0:
            return None
        if s >= nonnull or d / s < 0.1:
            return float(d)
        return float(min(nonnull, d * (nonnull / s)))


def _span_count(cs: ColumnStats) -> Optional[float]:
    """Distinct-count upper bound from the min/max span of discrete
    domains (integers, dates, booleans)."""
    if not cs.has_minmax or cs.minimum is None or cs.maximum is None:
        return None
    if cs.dtype == BOOL:
        return 2.0
    lo, hi = cs.minimum, cs.maximum
    if cs.dtype == DATE or isinstance(lo, datetime.date):
        try:
            return float(hi.toordinal() - lo.toordinal() + 1)
        except AttributeError:
            return None
    if isinstance(lo, int) and isinstance(hi, int) \
            and not isinstance(lo, bool):
        return float(hi - lo + 1)
    return None


def numeric_span_fraction(cs: ColumnStats, lo, hi) -> Optional[float]:
    """Fraction of the column's [min, max] span covered by [lo, hi]
    (either bound may be None = open). Works for numerics and dates;
    None when the column has no usable min/max or is non-numeric."""
    if not cs.has_minmax or cs.minimum is None or cs.maximum is None:
        return None
    cmin = _as_number(cs.minimum)
    cmax = _as_number(cs.maximum)
    nlo = _as_number(lo) if lo is not None else cmin
    nhi = _as_number(hi) if hi is not None else cmax
    if None in (cmin, cmax, nlo, nhi):
        return None
    width = cmax - cmin
    if width <= 0:
        # Single-valued column: the range either covers it or not.
        return 1.0 if nlo <= cmin <= nhi else 0.0
    covered = min(nhi, cmax) - max(nlo, cmin)
    return max(0.0, min(1.0, covered / width))


def _as_number(v) -> Optional[float]:
    if isinstance(v, bool):
        return 1.0 if v else 0.0
    if isinstance(v, (int, float)):
        return float(v)
    if isinstance(v, datetime.date):
        return float(v.toordinal())
    if isinstance(v, str):
        try:
            return float(datetime.date.fromisoformat(v).toordinal())
        except ValueError:
            return None
    return None


class StatsProvider:
    """Per-session lazy statistics cache. ``harvest_count`` counts actual
    footer-reading passes (the laziness contract's observable: plans
    with fewer than two joins must leave it untouched)."""

    def __init__(self, session):
        self._session = session
        self._cache: "OrderedDict[Tuple, Optional[TableStats]]" = \
            OrderedDict()
        # Advisor costing (and reorder under it) runs on the
        # multi-threaded serving path: unlocked OrderedDict
        # move_to_end/popitem interleavings can raise KeyError (the
        # same hazard session._join_actuals_lock guards).
        self._lock = threading.Lock()
        self.harvest_count = 0

    def table_stats(self, relation) -> Optional[TableStats]:
        """Statistics for ``relation``'s current file snapshot, or None
        when the relation's physical format has no parquet footers."""
        hs_conf = self._session.hs_conf
        if not hs_conf.optimizer_stats_enabled():
            return None
        try:
            key = (tuple(relation.root_paths), relation.file_format,
                   relation.signature())
        except Exception:
            return None
        with self._lock:
            if key in self._cache:
                self._cache.move_to_end(key)
                return self._cache[key]
        # Footer I/O outside the lock: two racing misses both harvest
        # (idempotent), the second insert wins.
        stats = self._harvest(relation, hs_conf)
        if stats is None:
            # Don't cache failures: a transient footer-read error would
            # otherwise pin None under the current file signature until
            # the source physically changes. Re-probing is cheap (the
            # non-parquet case is a format check, no I/O).
            return None
        with self._lock:
            self._cache[key] = stats
            limit = max(1, hs_conf.optimizer_stats_cache_entries())
            while len(self._cache) > limit:
                self._cache.popitem(last=False)
        return stats

    def _harvest(self, relation, hs_conf) -> Optional[TableStats]:
        if relation.data_file_format != "parquet":
            return None
        import pyarrow.parquet as pq
        self.harvest_count += 1
        files = relation.all_files()
        columns: Dict[str, ColumnStats] = {}
        for f in relation.schema.fields:
            columns[f.name] = ColumnStats(dtype=f.dtype)
        file_rows: List[int] = []
        total = 0
        # Footer opens fan out over the r09 pooled ordered reader (the
        # executor's schema-probe idiom); any unreadable file poisons
        # the whole harvest, matching the serial loop's early return.
        from ..parallel import io as pio
        try:
            footers = pio.map_ordered(
                lambda p: pq.ParquetFile(p).metadata, list(files),
                label="stats_footer")
        except Exception:
            return None
        for md in footers:
            file_rows.append(md.num_rows)
            total += md.num_rows
            for rg in range(md.num_row_groups):
                group = md.row_group(rg)
                for ci in range(group.num_columns):
                    col = group.column(ci)
                    cs = columns.get(col.path_in_schema)
                    if cs is None:
                        continue
                    st = col.statistics
                    if st is None:
                        continue
                    if st.null_count is not None:
                        cs.null_count += st.null_count
                    if not st.has_min_max:
                        continue
                    if st.min is not None and \
                            (cs.minimum is None or st.min < cs.minimum):
                        cs.minimum = st.min
                    if st.max is not None and \
                            (cs.maximum is None or st.max > cs.maximum):
                        cs.maximum = st.max
                    if cs.minimum is not None and cs.maximum is not None:
                        cs.has_minmax = True
        return TableStats(row_count=total, files=files,
                          file_rows=file_rows, columns=columns,
                          sample_rows=hs_conf.optimizer_stats_sample_rows())

    def sketch_row_fraction(self, relation, condition) -> Optional[float]:
        """Row-weighted fraction of the relation's files an ACTIVE
        data-skipping index cannot refute for ``condition`` — an upper
        bound on the predicate's selectivity (Bloom membership /
        MinMax refutation at planning time). None when no applicable
        sketch index exists."""
        from ..index.constants import States
        from ..plan.nodes import Scan
        from ..rules.data_skipping_rule import evaluate_sketch_predicate
        from ..rules.rule_utils import _plan_signature

        try:
            entries = self._session.index_collection_manager.get_indexes(
                [States.ACTIVE])
        except Exception:
            return None
        entries = [e for e in entries
                   if e.derivedDataset.kind == "DataSkippingIndex"]
        if not entries:
            return None
        ts = self.table_stats(relation)
        scan = Scan(relation)
        all_files = relation.all_files()
        best: Optional[float] = None
        for entry in entries:
            sig = _plan_signature(entry, scan)
            recorded = entry.signature.signatures[0].value \
                if entry.signature.signatures else None
            if sig is None or recorded is None or sig != recorded:
                continue
            verdict = evaluate_sketch_predicate(entry, condition,
                                                all_files, relation.schema)
            if verdict is None:
                continue
            if ts is not None and ts.row_count > 0 \
                    and len(ts.file_rows) == len(all_files):
                kept = sum(r for r, k in zip(ts.file_rows, verdict) if k)
                frac = kept / ts.row_count
            else:
                frac = float(verdict.sum()) / max(1, len(all_files))
            best = frac if best is None else min(best, frac)
        return best


_ATTACH_LOCK = threading.Lock()


def provider_for(session) -> StatsProvider:
    """The session's (lazily created) statistics provider. Attach under
    a lock: an unlocked check-then-set on concurrent serving threads
    could hand out two providers, double-harvesting every footer."""
    provider = getattr(session, "_stats_provider", None)
    if provider is None:
        with _ATTACH_LOCK:
            provider = getattr(session, "_stats_provider", None)
            if provider is None:
                provider = StatsProvider(session)
                session._stats_provider = provider
    return provider
