"""Append/commit ingestion with aggressive load-time indexing.

The write path of the lake ("Only Aggressive Elephants are Fast
Elephants", arxiv 1208.0287 — index work rides the upload for near-zero
marginal cost):

- ``append(session, table, batch)`` writes the batch as a parquet file
  into the table's hidden staging dir (invisible to every scan: the
  data-path filter skips ``_``-prefixed names) and, while the rows are
  hot on device, prebuilds one delta per ACTIVE index over the table —
  bucket-routed + sorted part files for covering indexes (the previous
  entry's bucket count keeps them bucket-aligned), MinMax/Bloom/
  ValueList sketch rows for skipping indexes.
- ``commit(session, table)`` publishes everything atomically through
  the existing op-log protocol: one per-table streaming log entry
  (put-if-absent decides concurrent-commit races) brackets the batch
  file renames and the per-index delta landings, each of which is
  itself a 2-phase op-log action. The hybrid-scan path would pick the
  files up anyway; with load-time indexing the indexes' own entries
  already cover them, so queries serve from fresh indexes with no
  refresh pass, and the r06 result-cache log-version keys invalidate by
  construction.
- Group commit (``CommitCoordinator``, on by default): concurrent
  ``commit()`` callers coalesce into one publication WAVE, so N
  coalesced appends cost one op-log entry, one delta landing per
  index, one standing-query fire, and one cluster broadcast — append
  QPS scales with batch width instead of being flat per commit.

Crash safety (undo/redo over the table log, proven by the kill -9
harness in tests/test_streaming.py): a commit that died before all its
batch files landed is UNDONE by ``recover()`` (landed files deleted,
log cancelled, staged files swept — the pre-commit lake, byte for
byte); one that died after every batch file landed is REDONE (the final
entry is written; index deltas that missed the crash window are simply
absent and hybrid scan covers their files until the next commit or
refresh). Index-delta wrecks recover through the ordinary index sweep.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from typing import Dict, List, Optional

from ..actions.action import Action
from ..exceptions import HyperspaceException
from ..index.constants import STABLE_STATES, States
from ..index.data_manager import IndexDataManager
from ..index.log_entry import (Content, FileIdTracker, FileInfo, Hdfs,
                               IndexLogEntry, IngestedTable,
                               LogicalPlanFingerprint, Relation, Signature,
                               Source, SourcePlan)
from ..index.log_manager import IndexLogManager
from ..index.path_resolver import PathResolver
from ..robustness import fault_names as _fn
from ..robustness import faults as _faults
from ..schema import Schema
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from ..util import file_utils, hashing
from .constants import StreamingConstants as SC


# ---------------------------------------------------------------------------
# Staged-batch model.
# ---------------------------------------------------------------------------

class _CoveringDelta:
    """Prebuilt bucket-aligned part files for one covering index,
    written to the index's staging dir at append() time. ``layout``
    pins the (num_buckets, indexed, included) the delta was routed
    with: a full refresh/recreate between append and commit can change
    any of them, and landing 8-bucket files into a 16-bucket index
    would silently break query-time bucket pruning."""

    __slots__ = ("index_name", "index_path", "staged_dir", "lineage_id",
                 "layout")

    def __init__(self, index_name: str, index_path: str, staged_dir: str,
                 lineage_id: Optional[int], layout: tuple):
        self.index_name = index_name
        self.index_path = index_path
        self.staged_dir = staged_dir
        self.lineage_id = lineage_id
        self.layout = layout


def _covering_layout(entry: IndexLogEntry) -> tuple:
    # The lineage flag is part of the layout: a delta prebuilt without
    # the _data_file_id column must not land in a lineage index (and
    # vice versa).
    return (entry.num_buckets, tuple(entry.indexed_columns),
            tuple(entry.included_columns), entry.has_lineage_column())


class _SketchDelta:
    """One precomputed sketch row (per batch file) for a skipping
    index; the row's file id is assigned at commit time. ``layout``
    pins the sketch set the row was computed for (see _CoveringDelta:
    a recreated index's sketch table must not take rows shaped for the
    old one)."""

    __slots__ = ("index_name", "index_path", "values", "layout")

    def __init__(self, index_name: str, index_path: str, values: Dict,
                 layout: tuple):
        self.index_name = index_name
        self.index_path = index_path
        self.values = values  # sketch column -> value (FILE_COL included)
        self.layout = layout


def _sketch_layout(entry: IndexLogEntry) -> tuple:
    return tuple(sorted(
        (s.kind, s.column, tuple(sorted(s.properties.items())))
        for s in entry.derivedDataset.sketches))


class StagedBatch:
    __slots__ = ("batch_id", "table_path", "staged_path", "final_path",
                 "rows", "nbytes", "mtime_ms", "schema", "covering",
                 "sketches")

    def __init__(self, batch_id: str, table_path: str, staged_path: str,
                 final_path: str, rows: int, nbytes: int, mtime_ms: int,
                 schema: Schema):
        self.batch_id = batch_id
        self.table_path = table_path
        self.staged_path = staged_path
        self.final_path = final_path
        self.rows = rows
        self.nbytes = nbytes
        self.mtime_ms = mtime_ms
        self.schema = schema
        self.covering: List[_CoveringDelta] = []
        self.sketches: List[_SketchDelta] = []


class CommitQueue:
    """Process-wide staging state of the ingestion tier: staged batches
    per table, per-table append serialization (lineage-id assignment
    must see a stable staged count), and the tier's counters. One
    instance per process (``get_queue``), shared by every session —
    appends from the 8-thread serving path land here concurrently, so
    every mutation holds ``_lock`` (HS301-registered)."""

    def __init__(self):
        self._lock = threading.Lock()
        # Blocking-backpressure waiters (push(block=True) /
        # wait_for_space) park here; every pending-reducing mutation
        # (land / abandon / drop_table) notifies. Shares ``_lock`` so a
        # wait releases the same mutex the mutations hold.
        self._space = threading.Condition(self._lock)
        self._staged: Dict[str, List[StagedBatch]] = {}
        # Batches popped by an in-flight commit still count toward the
        # lineage base of concurrent appends until they land or requeue.
        self._inflight: Dict[str, List[StagedBatch]] = {}
        self._table_locks: Dict[str, threading.Lock] = {}
        self._commit_locks: Dict[str, threading.Lock] = {}
        # Table schema memo: the schema check must not re-walk a
        # 10k-file table per append (schemas are append-invariant by
        # this very check; recovery drops the memo with drop_table).
        self._schemas: Dict[str, object] = {}
        self._stats = {
            "appends": 0, "commits": 0, "batches_committed": 0,
            "rows_staged": 0, "rows_committed": 0,
            "covering_deltas": 0, "sketch_deltas": 0,
            "commit_conflicts": 0, "subscription_fires": 0,
        }

    def table_lock(self, table: str) -> threading.Lock:
        with self._lock:
            return self._table_locks.setdefault(table, threading.Lock())

    def commit_lock(self, table: str) -> threading.Lock:
        with self._lock:
            return self._commit_locks.setdefault(table, threading.Lock())

    def push(self, batch: StagedBatch, max_staged: int,
             block: bool = False,
             timeout_s: Optional[float] = None) -> None:
        """Stage one batch. The API DEFAULT on a full table
        (``staged + in-flight >= max_staged``) is raise-on-full;
        ``block=True`` (continuous sources) parks until a commit frees
        budget or ``timeout_s`` elapses (then the same exception)."""
        with self._lock:
            if block:
                self._await_space(batch.table_path, max_staged,
                                  timeout_s)
            staged = self._staged.setdefault(batch.table_path, [])
            pending = len(staged) + \
                len(self._inflight.get(batch.table_path, []))
            if pending >= max_staged:
                # Unreachable from append() (it pre-checks under the
                # per-table lock) — kept so the queue enforces its own
                # invariant for any future caller.
                raise HyperspaceException(
                    f"{batch.table_path}: {pending} staged/in-flight "
                    f"batches reach "
                    "hyperspace.tpu.streaming.maxStagedBatches; "
                    "commit() before appending more")
            staged.append(batch)
            self._stats["appends"] += 1
            self._stats["rows_staged"] += batch.rows
            self._stats["covering_deltas"] += len(batch.covering)
            self._stats["sketch_deltas"] += len(batch.sketches)

    def wait_for_space(self, table: str, max_staged: int,
                       timeout_s: Optional[float] = None) -> None:
        """Park until ``table`` has staged-batch budget (the blocking
        analogue of append()'s raise-on-full pre-check)."""
        with self._lock:
            self._await_space(table, max_staged, timeout_s)

    def _await_space(self, table: str, max_staged: int,
                     timeout_s: Optional[float]) -> None:
        # Caller holds _lock; the wait releases it so land/abandon/
        # drop_table can drain the table under us.
        deadline = None if timeout_s is None \
            else time.monotonic() + timeout_s
        while len(self._staged.get(table, [])) + \
                len(self._inflight.get(table, [])) >= max_staged:
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise HyperspaceException(
                    f"{table}: blocked append timed out after "
                    f"{timeout_s:.1f}s waiting for staged-batch budget "
                    "(hyperspace.tpu.streaming.maxStagedBatches; "
                    "is anything committing?)")
            self._space.wait(remaining)

    def pop_wave(self, table: str, limit: Optional[int] = None):
        """Move up to ``limit`` staged batches (all of them when None)
        into the in-flight set, FIFO order preserved. Returns
        ``(batches, truncated)`` — truncated means more batches stayed
        staged, and the group-commit leader drains them as another
        bounded sub-wave."""
        with self._lock:
            staged = self._staged.get(table, [])
            if limit is None or limit >= len(staged):
                batches = self._staged.pop(table, [])
                truncated = False
            else:
                batches = staged[:limit]
                self._staged[table] = staged[limit:]
                truncated = True
            if batches:
                self._inflight.setdefault(table, []).extend(batches)
            return batches, truncated

    def pop_all(self, table: str) -> List[StagedBatch]:
        batches, _ = self.pop_wave(table)
        return batches

    def land(self, table: str, batches: List[StagedBatch]) -> None:
        with self._lock:
            flight = self._inflight.get(table, [])
            for b in batches:
                if b in flight:
                    flight.remove(b)
            self._stats["commits"] += 1
            self._stats["batches_committed"] += len(batches)
            self._stats["rows_committed"] += sum(b.rows for b in batches)
            self._space.notify_all()

    def requeue(self, table: str, batches: List[StagedBatch]) -> None:
        """Put batches a conflicted commit never started back at the
        FRONT of the queue (order preserved for lineage determinism)."""
        with self._lock:
            flight = self._inflight.get(table, [])
            for b in batches:
                if b in flight:
                    flight.remove(b)
            self._staged[table] = batches + self._staged.get(table, [])
            self._stats["commit_conflicts"] += 1

    def abandon(self, table: str, batches: List[StagedBatch]) -> None:
        """Forget batches a commit failed MID-PROTOCOL (op started:
        some files may be published, the table log is a wreck only
        recover() can resolve). Leaving them in-flight would poison the
        backpressure count and lineage offsets for the process
        lifetime; their staged files stay on disk for the recovery
        sweep."""
        with self._lock:
            flight = self._inflight.get(table, [])
            for b in batches:
                if b in flight:
                    flight.remove(b)
            self._space.notify_all()

    def drop_table(self, table: str) -> List[StagedBatch]:
        """Forget a table's staged state (recovery swept its staging
        dir out from under us)."""
        with self._lock:
            dropped = self._staged.pop(table, [])
            dropped += self._inflight.pop(table, [])
            self._schemas.pop(table, None)
            self._space.notify_all()
            return dropped

    def has_staged(self, table: str) -> bool:
        """Any batches still STAGED (not in-flight) for ``table``? The
        group-commit leader election consults this so batches pushed
        outside append() (no coordinator note) still get a wave."""
        with self._lock:
            return bool(self._staged.get(table))

    def table_schema(self, table: str, loader):
        """Memoized table schema; ``loader()`` runs once per table and
        provides the authoritative schema (the first batch's own schema
        bootstraps a still-empty table — see ``forget_schema_if_unused``
        for the discarded-bootstrap case)."""
        with self._lock:
            sch = self._schemas.get(table)
        if sch is not None:
            return sch
        sch = loader()
        if sch is not None:
            with self._lock:
                sch = self._schemas.setdefault(table, sch)
        return sch

    def has_pending(self, table: str) -> bool:
        """Any staged or in-flight batches for ``table``? (The cheap
        gate in front of forget_schema_if_unused's directory walk.)"""
        with self._lock:
            return bool(self._staged.get(table)
                        or self._inflight.get(table))

    def forget_schema_if_unused(self, table: str) -> None:
        """Drop the schema memo when NOTHING backs it anymore: the
        bootstrap batch that seeded it was discarded before any other
        batch staged, so a fresh first batch may define a different
        schema (a memo backed by on-disk files or live staged batches
        stays)."""
        with self._lock:
            if not self._staged.get(table) and \
                    not self._inflight.get(table):
                self._schemas.pop(table, None)

    def staged_delta_count(self, table: str, index_name: str) -> int:
        """How many staged/in-flight batches already carry a delta for
        ``index_name`` — the lineage-id offset of the next append."""
        with self._lock:
            n = 0
            for b in self._staged.get(table, []) + \
                    self._inflight.get(table, []):
                if any(d.index_name == index_name for d in b.covering):
                    n += 1
            return n

    def staged_count(self, table: str) -> int:
        with self._lock:
            return len(self._staged.get(table, [])) + \
                len(self._inflight.get(table, []))

    def note(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                self._stats[k] += v

    def stats(self) -> dict:
        from ..index.log_manager import get_lookup_cache
        with self._lock:
            out = dict(self._stats)
            out["tables_staged"] = sum(
                1 for v in self._staged.values() if v)
            out["batches_staged"] = sum(
                len(v) for v in self._staged.values())
        out["oplog_cache"] = get_lookup_cache().stats()
        out["group_commit"] = get_coordinator().stats()
        return out


_QUEUE: Optional[CommitQueue] = None
_QUEUE_LOCK = threading.Lock()


def get_queue() -> CommitQueue:
    """The process-wide commit queue; first use registers the
    "streaming" collector in the metrics registry."""
    global _QUEUE
    with _QUEUE_LOCK:
        if _QUEUE is None:
            _QUEUE = CommitQueue()
            from ..telemetry.metrics import get_registry
            get_registry().register_collector("streaming", _QUEUE.stats)
        return _QUEUE


# ---------------------------------------------------------------------------
# Group commit.
# ---------------------------------------------------------------------------

class _WaveState:
    """One table's group-commit ledger (every field guarded by
    CommitCoordinator._cv). Sequence numbers count successful pushes:
    ``push_seq`` is the head, ``pop_mark`` the head snapshot the
    in-flight wave popped at, ``done_seq`` the head published through
    by landed waves. A commit() call targeting ``push_seq <= pop_mark``
    rides the in-flight wave; one targeting ``<= done_seq`` is already
    published."""

    __slots__ = ("push_seq", "pop_mark", "done_seq", "leader",
                 "generation", "riders", "outcomes")

    def __init__(self):
        self.push_seq = 0
        self.pop_mark = 0
        self.done_seq = 0
        self.leader = False
        self.generation = 0
        self.riders = 0
        self.outcomes: Dict[int, tuple] = {}


class CommitCoordinator:
    """Per-table group commit: concurrent ``commit()`` callers coalesce
    into publication WAVES. One caller leads — pops the queue (bounded
    sub-waves of ``groupCommit.maxWave``) and runs the op-log protocol —
    while every caller whose staged batches the wave covers parks on
    the ledger and returns the wave's outcome when it lands. However
    many appends joined, a wave costs ONE op-log entry per table, one
    delta landing per index, ONE standing-query fire, and ONE cluster
    broadcast (the r21 per-commit broadcast, coalesced). Only ledger
    flips hold ``_cv`` (HS301-registered); the op-log work runs outside
    it. A failed wave raises in the leader AND every rider — their
    batches are requeued (pre-op conflict) or abandoned for recover()
    (mid-protocol wreck), exactly the r17 contract."""

    def __init__(self):
        self._cv = threading.Condition()
        self._tables: Dict[str, _WaveState] = {}
        self._stats = {
            "commit_calls": 0, "waves": 0, "sub_waves": 0,
            "led": 0, "joined": 0, "wave_batches": 0,
        }

    def note_push(self, table: str) -> None:
        """One batch staged for ``table`` (append() calls this after a
        successful push, group commit enabled or not — the ledger must
        not miss pushes made while the flag was off)."""
        with self._cv:
            self._tables.setdefault(table, _WaveState()).push_seq += 1

    def forget(self, table: str) -> None:
        """Drop a table's wave ledger (recovery swept its staged state
        out from under us); parked committers are released with an
        empty outcome."""
        with self._cv:
            self._tables.pop(table, None)
            self._cv.notify_all()

    def stats(self) -> dict:
        with self._cv:
            return dict(self._stats)

    def commit_grouped(self, session, table_path: str) -> dict:
        """The group-commit entry: returns when every batch staged for
        ``table_path`` BEFORE this call is published (or the wave that
        carried them failed — the failure propagates to every rider).
        Exactly one caller per wave runs the op-log protocol."""
        with self._cv:
            self._stats["commit_calls"] += 1
            st = self._tables.setdefault(table_path, _WaveState())
            target = st.push_seq
            while True:
                if st.leader:
                    if st.pop_mark < target:
                        # The in-flight wave popped before our batches
                        # staged: wait it out, then lead (or ride) the
                        # next one.
                        self._cv.wait()
                        continue
                    # Ride the in-flight wave — it covers everything
                    # this caller staged.
                    gen = st.generation
                    st.riders += 1
                    self._stats["joined"] += 1
                    while st.generation == gen and \
                            self._tables.get(table_path) is st:
                        self._cv.wait()
                    res, err = st.outcomes.get(gen, (None, None))
                    if err is not None:
                        raise err
                    if res is None:
                        # forget() reset the ledger mid-wave (recovery
                        # swept the table): nothing left to publish.
                        return _empty_commit_summary()
                    out = dict(res)
                    out["files"] = list(res["files"])
                    out["indexes_updated"] = list(res["indexes_updated"])
                    out["indexes_skipped"] = list(res["indexes_skipped"])
                    out["joined_wave"] = True
                    return out
                if st.done_seq >= target and \
                        not get_queue().has_staged(table_path):
                    # Published by a wave that landed before we got
                    # here — same shape as an empty-queue commit.
                    return _empty_commit_summary()
                st.leader = True
                st.riders = 0
                self._stats["led"] += 1
                break
        return self._lead(session, st, table_path)

    def _lead(self, session, st: _WaveState, table_path: str) -> dict:
        # Leader path — NO _cv held except at the marked flips. Any
        # outcome (return or raise) MUST finalize the generation, or
        # riders park forever: everything sits inside try/finally.
        t0 = time.perf_counter()
        agg: Optional[dict] = None
        error: Optional[BaseException] = None
        sub_waves = 0
        try:
            window_s = \
                session.hs_conf.streaming_group_commit_window_ms() / 1000.0
            max_wave = session.hs_conf.streaming_group_commit_max_wave()
            if window_s > 0:
                # Linger: let appends (and the committers carrying
                # them) pile into this wave before the single
                # publication.
                time.sleep(window_s)
            with _trace.maintenance_trace(session, "ingest"), \
                    _trace.span(SN.INGEST_WAVE) as sp:
                while True:
                    with self._cv:
                        st.pop_mark = st.push_seq
                        self._cv.notify_all()
                    res, truncated = _commit_once(session, table_path,
                                                  limit=max_wave)
                    sub_waves += 1
                    agg = res if agg is None \
                        else _merge_commit_summary(agg, res)
                    if not truncated:
                        break
                if agg["committed_batches"]:
                    agg["subscriptions_fired"] = _fire_subscriptions(
                        session, table_path,
                        batches=agg["committed_batches"])
                agg["sub_waves"] = sub_waves
                agg["seconds"] = time.perf_counter() - t0
                if sp is not None:
                    sp.attrs["batches"] = agg["committed_batches"]
                    sp.attrs["sub_waves"] = sub_waves
                    sp.attrs["joined"] = st.riders
            return agg
        except BaseException as e:
            error = e
            raise
        finally:
            with self._cv:
                gen = st.generation
                st.generation = gen + 1
                st.leader = False
                riders = st.riders
                st.outcomes[gen] = \
                    (agg if error is None else None, error)
                # Outcomes are read once per rider; keep only a short
                # tail so the ledger never grows with wave count.
                for old in [g for g in st.outcomes if g < gen - 3]:
                    del st.outcomes[old]
                if error is None:
                    st.done_seq = max(st.done_seq, st.pop_mark)
                    self._stats["waves"] += 1
                    self._stats["sub_waves"] += sub_waves
                    self._stats["wave_batches"] += \
                        agg["committed_batches"] if agg else 0
                self._cv.notify_all()
            if error is None and agg is not None \
                    and agg["committed_batches"]:
                _emit_wave(session, table_path, agg, riders, sub_waves)


def _empty_commit_summary() -> dict:
    # Same shape as a non-empty commit: callers read these keys
    # unconditionally (retry loops, timer-driven committers).
    return {"committed_batches": 0, "rows": 0, "files": [],
            "indexes_updated": [], "indexes_skipped": [],
            "subscriptions_fired": 0, "seconds": 0.0}


def _merge_commit_summary(agg: dict, res: dict) -> dict:
    agg["committed_batches"] += res["committed_batches"]
    agg["rows"] += res["rows"]
    agg["files"].extend(res["files"])
    for key in ("indexes_updated", "indexes_skipped"):
        for name in res[key]:
            if name not in agg[key]:
                agg[key].append(name)
    agg["seconds"] += res["seconds"]
    return agg


def _emit_wave(session, table_path: str, agg: dict, riders: int,
               sub_waves: int) -> None:
    try:
        from ..telemetry.events import StreamingWaveEvent
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            StreamingWaveEvent(
                message=(f"wave of {agg['committed_batches']} batches "
                         f"({riders} committers rode it)"),
                table=table_path, batches=agg["committed_batches"],
                rows=agg["rows"], joined=riders, sub_waves=sub_waves,
                seconds=agg["seconds"]))
    except Exception:
        pass


_COORD: Optional[CommitCoordinator] = None
_COORD_LOCK = threading.Lock()


def get_coordinator() -> CommitCoordinator:
    """The process-wide group-commit coordinator (one ledger per
    table, lazily created)."""
    global _COORD
    with _COORD_LOCK:
        if _COORD is None:
            _COORD = CommitCoordinator()
        return _COORD


# ---------------------------------------------------------------------------
# Table plumbing.
# ---------------------------------------------------------------------------

def table_key(table_path: str) -> str:
    """Stable directory-safe identity of a table path (the streaming
    log's directory name under <systemPath>/_streaming/)."""
    table_path = os.path.abspath(table_path)
    return (os.path.basename(table_path.rstrip(os.sep)) + "-"
            + hashing.md5_hex(table_path)[:10])


def table_log_dir(session, table_path: str) -> str:
    return os.path.join(session.hs_conf.system_path(), SC.STREAMING_DIR,
                        table_key(table_path))


def _staged_marker_dir(session) -> str:
    return os.path.join(session.hs_conf.system_path(), SC.STREAMING_DIR,
                        "_staged")


def _note_staged_table(session, table_path: str) -> None:
    """Record WHERE a table with staged batches lives, so the recovery
    sweep can find staging leftovers even for a table no commit ever
    gave a streaming log (the dead-before-first-commit appender)."""
    marker = os.path.join(_staged_marker_dir(session),
                          table_key(table_path))
    if not os.path.exists(marker):
        file_utils.makedirs(_staged_marker_dir(session))
        tmp = marker + ".tmp"
        with open(tmp, "w") as f:
            f.write(table_path)
        os.replace(tmp, marker)


def _to_arrow(batch):
    """Accept a pyarrow Table/RecordBatch, a pandas DataFrame, or a
    dict of columns; return a pyarrow Table."""
    import pyarrow as pa
    if isinstance(batch, pa.Table):
        return batch
    if isinstance(batch, pa.RecordBatch):
        return pa.Table.from_batches([batch])
    if isinstance(batch, dict):
        return pa.table(batch)
    try:
        import pandas as pd
        if isinstance(batch, pd.DataFrame):
            return pa.Table.from_pandas(batch, preserve_index=False)
    except ImportError:  # pandas is ubiquitous here, but stay honest
        pass
    raise HyperspaceException(
        f"append() cannot convert {type(batch).__name__} to a record "
        "batch (pass a pyarrow Table/RecordBatch, pandas DataFrame, or "
        "dict of columns)")


def _indexes_for_table(session, table_path: str) -> List[IndexLogEntry]:
    """ACTIVE indexes whose single source relation is exactly this
    parquet table directory."""
    out = []
    for entry in session.index_collection_manager.get_indexes(
            [States.ACTIVE]):
        try:
            rel = entry.relation
        except (AssertionError, AttributeError, IndexError):
            continue
        if rel.fileFormat == "parquet" and \
                [os.path.abspath(p) for p in rel.rootPaths] == [table_path]:
            out.append(entry)
    return out


def _prev_source_max_id(entry: IndexLogEntry) -> int:
    return max((f.id for f in entry.source_file_info_set), default=-1)


def _staging_dir(base: str) -> str:
    path = os.path.join(base, SC.STAGING_DIR)
    file_utils.makedirs(path)
    return path


# ---------------------------------------------------------------------------
# append().
# ---------------------------------------------------------------------------

def append(session, table_path: str, batch, block: bool = False) -> dict:
    """Stage one record batch for ``table_path`` and prebuild its index
    deltas on device. Returns a summary dict; nothing is visible to
    queries until ``commit()``. The API default on a full staging
    budget is raise-on-full; ``block=True`` (continuous sources) parks
    until a commit frees budget or ``backpressure.timeoutMs`` elapses."""
    if not session.hs_conf.streaming_enabled():
        raise HyperspaceException(
            "hyperspace.tpu.streaming.enabled is false; enable it to use "
            "the append/commit ingestion tier")
    table_path = os.path.abspath(table_path)
    queue = get_queue()
    timeout_s = \
        session.hs_conf.streaming_backpressure_timeout_ms() / 1000.0
    with queue.table_lock(table_path), \
            _faults.scope_for(session.hs_conf), \
            _trace.maintenance_trace(session, "ingest"), \
            _trace.span(SN.INGEST_APPEND) as sp:
        t0 = time.perf_counter()
        # Backpressure FIRST: a rejected (or parked) append must not pay
        # the parquet write and the on-device delta builds (push()
        # re-checks under the lock for race-tightness). The blocking
        # wait holds only the per-table append lock — commits take the
        # commit lock, so they drain the table under us and wake us.
        max_staged = session.hs_conf.streaming_max_staged_batches()
        if block:
            queue.wait_for_space(table_path, max_staged, timeout_s)
        elif queue.staged_count(table_path) >= max_staged:
            raise HyperspaceException(
                f"{table_path}: staged batches reach "
                "hyperspace.tpu.streaming.maxStagedBatches; commit() "
                "before appending more")
        at = _to_arrow(batch)
        if at.num_rows == 0:
            raise HyperspaceException("append() got an empty batch")
        file_utils.makedirs(table_path)
        _check_schema(queue, table_path, at)
        batch_id = uuid.uuid4().hex[:12]
        staging = _staging_dir(table_path)
        _note_staged_table(session, table_path)
        staged_path = os.path.join(
            staging, f"{SC.INGEST_FILE_PREFIX}{batch_id}.parquet")
        final_path = os.path.join(
            table_path, f"{SC.INGEST_FILE_PREFIX}{batch_id}.parquet")
        import pyarrow.parquet as pq
        staged = None
        try:
            _faults.fault_point(_fn.INGEST_STAGE)
            pq.write_table(at, staged_path)
            _, nbytes, mtime_ms = file_utils.file_info_triple(staged_path)
            staged = StagedBatch(batch_id, table_path, staged_path,
                                 final_path, at.num_rows, nbytes, mtime_ms,
                                 Schema.from_arrow(at.schema))
            if session.hs_conf.streaming_load_time_indexing():
                # Same kernel/io scoping as Action.run: the bucket
                # sorts and sketch reductions read this session's
                # shapeBucketing conf and attribute their reads to it.
                from ..execution import shapes
                from ..parallel import io as pio
                with shapes.use_conf(session.hs_conf), \
                        pio.use_session(session):
                    _prebuild_deltas(session, queue, staged, at)
            queue.push(staged, max_staged, block=block,
                       timeout_s=timeout_s if block else None)
            get_coordinator().note_push(table_path)
        except BaseException:
            # A failed append must not leak invisible staging files —
            # including the partial parquet of a failed write — until
            # the next recover() sweep, nor pin a schema memo its own
            # (discarded) batch bootstrapped on an empty table.
            # Queue state first: while other batches back the memo the
            # directory walk (O(table files)) is never paid.
            if staged is not None:
                _discard_staged(staged)
            else:
                try:
                    os.unlink(staged_path)
                except OSError:
                    pass
            if not queue.has_pending(table_path) and \
                    not any(f.endswith(".parquet")
                            for f in file_utils.list_leaf_files(table_path)):
                queue.forget_schema_if_unused(table_path)
            raise
        seconds = time.perf_counter() - t0
        if sp is not None:
            sp.attrs["rows"] = staged.rows
            sp.attrs["covering_deltas"] = len(staged.covering)
            sp.attrs["sketch_deltas"] = len(staged.sketches)
        _emit_append(session, staged, seconds)
        return {"batch_id": batch_id, "rows": staged.rows,
                "staged_batches": queue.staged_count(table_path),
                "covering_deltas": len(staged.covering),
                "sketch_deltas": len(staged.sketches)}


def _check_schema(queue: CommitQueue, table_path: str, at) -> None:
    """An appended batch must carry the table's columns AND types —
    extra columns or a type fork are refused loudly rather than
    silently forked across files (a scan over mixed-type parquet fails
    at read time, far from the append that caused it). The table schema
    is memoized per table: the check is append-invariant, and a
    directory walk per append would grow with every commit."""
    import pyarrow.parquet as pq

    def load():
        existing = [f for f in file_utils.list_leaf_files(table_path)
                    if f.endswith(".parquet")]
        return pq.read_schema(existing[0]) if existing else at.schema

    have = queue.table_schema(table_path, load)
    names = set(have.names)
    got = set(at.schema.names)
    if got != names:
        raise HyperspaceException(
            f"append() schema mismatch for {table_path}: table has "
            f"{sorted(names)}, batch has {sorted(got)}")
    forked = [(n, str(have.field(n).type), str(at.schema.field(n).type))
              for n in sorted(names)
              if have.field(n).type != at.schema.field(n).type]
    if forked:
        raise HyperspaceException(
            f"append() schema mismatch for {table_path}: column type "
            f"fork {forked} (table type vs batch type)")


def _discard_staged(staged: StagedBatch) -> None:
    """Best-effort removal of one staged batch's files (the failed-
    append path; crashes still rely on the recovery sweep)."""
    import shutil
    try:
        os.unlink(staged.staged_path)
    except OSError:
        pass
    for delta in staged.covering:
        shutil.rmtree(delta.staged_dir, ignore_errors=True)


def _prebuild_deltas(session, queue: CommitQueue, staged: StagedBatch,
                     at) -> None:
    """The aggressive-elephants step: while the batch is in memory,
    bucket-route it for every covering index and sketch it for every
    skipping index over this table. Indexes whose columns the batch
    cannot serve are skipped (hybrid scan covers their files)."""
    from ..execution.columnar import Table as ExecTable
    entries = _indexes_for_table(session, staged.table_path)
    if not entries:
        return
    resolver = PathResolver(session.hs_conf)
    exec_table = ExecTable.from_arrow(at)
    for entry in entries:
        index_path = resolver.get_index_path(entry.name)
        kind = getattr(entry.derivedDataset, "kind", "")
        if kind == "CoveringIndex":
            delta = _prebuild_covering(session, queue, staged, exec_table,
                                       entry, index_path)
        elif kind == "DataSkippingIndex":
            delta = _prebuild_sketch(staged, exec_table, entry, index_path)
        else:
            delta = None
        if delta is not None:
            if isinstance(delta, _CoveringDelta):
                staged.covering.append(delta)
            else:
                staged.sketches.append(delta)


def _prebuild_covering(session, queue: CommitQueue, staged: StagedBatch,
                       exec_table, entry: IndexLogEntry,
                       index_path: str) -> Optional[_CoveringDelta]:
    import jax.numpy as jnp

    from ..actions.create import _write_bucket_files
    from ..execution.columnar import Column
    from ..index.constants import IndexConstants
    from ..ops import index_build
    from ..schema import INT64
    cols = list(entry.indexed_columns) + list(entry.included_columns)
    if any(c not in exec_table.names for c in cols):
        return None
    table = exec_table.select(cols)
    lineage_id = None
    if entry.has_lineage_column():
        # Deterministic id prediction: the seeded tracker at commit time
        # assigns prev_max+1, +2, ... in batch order; staged/in-flight
        # batches ahead of us occupy the earlier slots (appends are
        # serialized per table, so the count cannot move under us).
        lineage_id = _prev_source_max_id(entry) + 1 + \
            queue.staged_delta_count(staged.table_path, entry.name)
        table = table.with_column(
            IndexConstants.DATA_FILE_NAME_ID,
            Column(INT64, jnp.full((table.num_rows,), lineage_id,
                                   dtype=jnp.int64)))
    sorted_table, bounds = index_build.build_sorted_buckets(
        table, list(entry.indexed_columns), entry.num_buckets)
    staged_dir = os.path.join(_staging_dir(index_path), staged.batch_id)
    file_utils.makedirs(staged_dir)
    suffix = staged.batch_id[:8]

    def name_for(bucket: int) -> str:
        return index_build.bucket_file_name(bucket).replace(
            ".parquet", f"-{suffix}.parquet")

    try:
        _write_bucket_files(sorted_table.to_host(), bounds, 0,
                            entry.num_buckets, staged_dir,
                            session.hs_conf.index_row_group_size(),
                            file_name=name_for)
    except BaseException:
        # The delta never reaches staged.covering, so append()'s
        # cleanup can't see it — remove the partial dir here or it
        # leaks until an operator-run recover().
        import shutil
        shutil.rmtree(staged_dir, ignore_errors=True)
        raise
    return _CoveringDelta(entry.name, index_path, staged_dir, lineage_id,
                          _covering_layout(entry))


def _prebuild_sketch(staged: StagedBatch, exec_table,
                     entry: IndexLogEntry,
                     index_path: str) -> Optional[_SketchDelta]:
    from ..actions import create_skipping as cs
    from ..ops import sketches as sk
    sketch_list = entry.derivedDataset.sketches
    if any(s.column not in exec_table.names for s in sketch_list):
        return None
    values: Dict = {cs.FILE_COL: staged.final_path}
    for s in sketch_list:
        col = exec_table.column(s.column)
        if s.kind == "MinMax":
            lo, hi = cs.minmax_cols(s.column)
            mn, mx = sk.minmax_values(col)
            values[lo] = mn
            values[hi] = mx
        elif s.kind == "ValueList":
            values[cs.valuelist_col(s.column)] = sk.value_list(
                col, int(s.properties["maxValues"]))
        elif s.kind == "BloomFilter":
            values[cs.bloom_col(s.column)] = sk.bloom_build(
                col, int(s.properties["numBits"]),
                int(s.properties["numHashes"])).tobytes()
        else:
            return None  # unknown sketch kind: leave it to hybrid scan
    return _SketchDelta(entry.name, index_path, values,
                        _sketch_layout(entry))


# ---------------------------------------------------------------------------
# commit(): the op-log protocol around publish + delta landing.
# ---------------------------------------------------------------------------

def _pinned_source(session, table_path: str, prev: IndexLogEntry,
                   batch_infos: List[FileInfo]) -> Source:
    """Source descriptor over EXACTLY the previous entry's files plus
    this commit's batch files — not a live re-listing, so a foreign file
    landing concurrently can never be claimed as covered (it stays a
    hybrid-scan append). The fingerprint is computed by the standard
    provider over a relation pinned to that file set, so a fresh query
    whose listing matches applies the index with a plain exact-match
    IndexScan."""
    from ..index.signatures import IndexSignatureProvider
    from ..plan.nodes import Scan
    from ..sources.default import DefaultFileBasedRelation
    prev_infos = sorted(prev.source_file_info_set, key=lambda f: f.name)
    paths = sorted([f.name for f in prev_infos]
                   + [f.name for f in batch_infos])
    # Schema pinned from the prev entry (footer-derived when the index
    # was built): a live build_relation().with_files() would re-walk the
    # whole table dir and re-read a footer per index per commit, on the
    # write path that must stay O(batch).
    relation = DefaultFileBasedRelation.pinned(
        [table_path], "parquet", {}, paths, prev.relation.dataSchema)
    content = _content_over(prev_infos + list(batch_infos))
    rel_meta = Relation(rootPaths=[table_path], data=Hdfs(content),
                        dataSchema=relation.schema, fileFormat="parquet",
                        options={})
    provider = IndexSignatureProvider()
    fingerprint = LogicalPlanFingerprint(
        [Signature(provider.name(), provider.signature(Scan(relation)))])
    return Source(SourcePlan([rel_meta], fingerprint))


def _content_over(infos: List[FileInfo]) -> Content:
    from ..actions.refresh import content_from_file_infos
    content = content_from_file_infos(list(infos))
    if content is None:
        raise HyperspaceException("cannot build content over zero files")
    return content


def _rewrite_lineage(staged_dir: str, fid: int,
                     row_group_size: int) -> None:
    """Repair a drifted lineage prediction: rewrite each staged bucket
    file's constant ``_data_file_id`` column to the committed id. Rare
    (only when another writer moved the index's id base between append
    and commit) and cheap (per-batch files are small). Rewritten with
    the configured index row-group size so a repaired file keeps the
    same row-group layout as its untouched siblings."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ..index.constants import IndexConstants
    col_name = IndexConstants.DATA_FILE_NAME_ID
    for fname in sorted(os.listdir(staged_dir)):
        path = os.path.join(staged_dir, fname)
        table = pq.read_table(path, partitioning=None)
        if col_name not in table.schema.names:
            continue
        idx = table.schema.get_field_index(col_name)
        fixed = pa.array([fid] * table.num_rows,
                         type=table.schema.field(idx).type)
        pq.write_table(table.set_column(idx, col_name, fixed), path,
                       row_group_size=row_group_size)


def _carry_props(prev: IndexLogEntry) -> Dict[str, str]:
    """Entry properties a streaming delta carries forward — currently
    the compaction generation, so post-compaction entries keep pinning
    it into their bytes (no key aliasing across a compaction)."""
    gen = prev.properties.get(SC.COMPACTION_GENERATION_PROPERTY)
    return {SC.COMPACTION_GENERATION_PROPERTY: gen} \
        if gen is not None else {}


class _LandDeltasBase(Action):
    """Shared frame of the per-index delta-landing actions: both kinds
    run inside one streaming commit, re-anchor on the index's latest
    ACTIVE entry, and 2-phase through the index's own op log, so a
    crash here recovers through the ordinary index sweep."""

    transient_state = States.REFRESHING
    final_state = States.ACTIVE

    def __init__(self, session, log_manager: IndexLogManager,
                 data_manager: IndexDataManager, table_path: str,
                 pairs: List[tuple]):
        super().__init__(session, log_manager)
        self.data_manager = data_manager
        self.table_path = table_path
        self.pairs = pairs  # [(StagedBatch, delta)] in batch order
        self.index_name = pairs[0][1].index_name
        self._prev: Optional[IndexLogEntry] = None
        self._entry: Optional[IndexLogEntry] = None

    @property
    def prev_entry(self) -> IndexLogEntry:
        if self._prev is None:
            entry = self.log_manager.get_latest_stable_log()
            if entry is None or entry.state != States.ACTIVE:
                raise HyperspaceException(
                    f"cannot land a streaming delta on {self.index_name}:"
                    " index is not ACTIVE (deleted or mutated between "
                    "append and commit)")
            self._prev = entry
        return self._prev

    def validate(self) -> None:
        """Pre-begin checks: the index must still be ACTIVE, and its
        layout must still match what the deltas were built against — a
        full refresh or delete/recreate between append and commit may
        have changed it, and landing old-layout files would silently
        corrupt the index (e.g. bucket pruning reading the wrong
        files). Raising here (before begin writes anything) routes the
        index to indexes_skipped — hybrid scan covers the committed
        files until the next refresh catches the index up."""
        prev = self.prev_entry
        want = self._entry_layout(prev)
        for _batch, delta in self.pairs:
            if delta.layout != want:
                raise HyperspaceException(
                    f"{self.index_name}: index layout changed between "
                    f"append and commit ({delta.layout} -> {want}); "
                    "skipping the staged delta")
        # A refresh racing into the publish->land window may have
        # already indexed this commit's batch files (they were visible
        # in the table dir); landing their deltas again would put the
        # same rows in the index twice. Drop covered batches (their
        # staged files are dead weight) and skip entirely when the
        # racing refresh covered them all.
        import shutil
        covered = {f.name for f in prev.source_file_info_set}
        fresh = [(b, d) for (b, d) in self.pairs
                 if b.final_path not in covered]
        for b, d in self.pairs:
            if b.final_path in covered:
                staged_dir = getattr(d, "staged_dir", None)
                if staged_dir:
                    shutil.rmtree(staged_dir, ignore_errors=True)
        if not fresh:
            raise HyperspaceException(
                f"{self.index_name}: a concurrent refresh already "
                "covers every batch of this commit; nothing to land")
        self.pairs = fresh

    @staticmethod
    def _entry_layout(prev: IndexLogEntry) -> tuple:
        raise NotImplementedError

    @property
    def log_entry(self) -> IndexLogEntry:
        if self._entry is not None:
            return self._entry
        return self.prev_entry  # begin() placeholder, like refresh

    def event(self, message: str):
        from ..telemetry.events import StreamingIndexDeltaEvent
        return StreamingIndexDeltaEvent(
            message=message, index_name=self.index_name)


class _LandCoveringDeltas(_LandDeltasBase):
    """Land the prebuilt bucket-aligned part files of one covering index
    for every batch of one commit: rename the staged files into a new
    immutable data version and commit an entry whose content is the old
    files ∪ the delta files — RefreshIncrementalAction's append-only
    layout, minus the build (it already ran at append time)."""

    _entry_layout = staticmethod(_covering_layout)

    def op(self) -> None:
        prev = self.prev_entry
        latest = self.data_manager.get_latest_version_id()
        version = 0 if latest is None else latest + 1
        out_dir = self.data_manager.get_path(version)
        file_utils.makedirs(out_dir)
        # Commit-time file ids FIRST (the batch files were published by
        # the outer commit before this action runs): the append-time
        # lineage prediction is only a fast path — a refresh/commit
        # racing between append and commit moves the id base, and a
        # drifted delta is REPAIRED in place (its lineage column is a
        # per-batch constant) rather than wrecking the commit.
        tracker = FileIdTracker()
        tracker.add_file_info(prev.source_file_info_set)
        batch_infos = []
        for batch, delta in self.pairs:
            full, size, mtime = file_utils.file_info_triple(
                batch.final_path)
            fid = tracker.add_file(full, size, mtime)
            if delta.lineage_id is not None and fid != delta.lineage_id:
                _rewrite_lineage(
                    delta.staged_dir, fid,
                    self.session.hs_conf.index_row_group_size())
            batch_infos.append(FileInfo(full, size, mtime, fid))
        for _batch, delta in self.pairs:
            for fname in sorted(os.listdir(delta.staged_dir)):
                os.replace(os.path.join(delta.staged_dir, fname),
                           os.path.join(out_dir, fname))
            try:
                os.rmdir(delta.staged_dir)
            except OSError:
                pass
        index_content = prev.content.merge(
            Content.from_directory(out_dir, tracker))
        source = _pinned_source(self.session, self.table_path, prev,
                                batch_infos)
        entry = IndexLogEntry.create(prev.name, prev.derivedDataset,
                                     index_content, source,
                                     _carry_props(prev))
        self._entry = entry.with_log_version(version)


class _LandSketchDeltas(_LandDeltasBase):
    """Merge precomputed sketch rows for one skipping index into a new
    sketch-table version (kept rows + one appended row per batch file)
    — RefreshDataSkippingIncrementalAction's shape with the device
    reductions already paid at append time."""

    _entry_layout = staticmethod(_sketch_layout)

    def op(self) -> None:
        import pyarrow as pa
        import pyarrow.parquet as pq

        from ..actions import create_skipping as cs
        from ..index import data_store
        prev = self.prev_entry
        _fs, old_path = data_store.fs_and_path(cs._sketch_file(prev))
        # partitioning=None: the v__=<n> path component must not be
        # hive-inferred as a phantom column (same guard as the
        # incremental skipping refresh).
        old = pq.read_table(old_path, filesystem=_fs, partitioning=None)
        tracker = FileIdTracker()
        tracker.add_file_info(prev.source_file_info_set)
        batch_infos = []
        rows: Dict[str, list] = {f.name: [] for f in old.schema}
        for batch, delta in self.pairs:
            full, size, mtime = file_utils.file_info_triple(
                batch.final_path)
            fid = tracker.add_file(full, size, mtime)
            batch_infos.append(FileInfo(full, size, mtime, fid))
            values = dict(delta.values)
            values[cs.FILE_ID_COL] = fid
            for f in old.schema:
                rows[f.name].append(values.get(f.name))
        appended = pa.table(
            {f.name: pa.array(rows[f.name], type=f.type)
             for f in old.schema}, schema=old.schema)
        merged = pa.concat_tables([old, appended])
        latest = self.data_manager.get_latest_version_id()
        version = 0 if latest is None else latest + 1
        out_dir = self.data_manager.get_path(version)
        file_utils.makedirs(out_dir)
        _fs2, merged_path = data_store.fs_and_path(
            os.path.join(out_dir, cs.SKETCH_FILE_NAME))
        pq.write_table(merged, merged_path, filesystem=_fs2)
        index_content = Content.from_directory(out_dir, tracker)
        source = _pinned_source(self.session, self.table_path, prev,
                                batch_infos)
        entry = IndexLogEntry.create(prev.name, prev.derivedDataset,
                                     index_content, source,
                                     _carry_props(prev))
        self._entry = entry.with_log_version(version)


class _StreamingCommitAction(Action):
    """One atomic commit of every staged batch for one table, bracketed
    by the table's streaming op log: begin writes a transient entry
    listing the files about to publish (put-if-absent decides
    concurrent-commit races), op renames the batch files into the table
    dir and lands the per-index deltas, end commits the ACTIVE entry.
    recover() resolves any crash in between: undo while batch files are
    partially published, redo once all of them landed (see
    ``recover_streaming``)."""

    transient_state = States.REFRESHING
    final_state = States.ACTIVE

    def __init__(self, session, log_manager: IndexLogManager,
                 table_path: str, batches: List[StagedBatch]):
        super().__init__(session, log_manager)
        self.table_path = table_path
        self.batches = batches
        self.op_started = False
        self.indexes_updated: List[str] = []
        self.indexes_skipped: List[str] = []

    def validate(self) -> None:
        latest_id = self.log_manager.get_latest_id()
        if latest_id is None:
            return
        # Lenient: a torn (unparseable) tip is a wreck to recover, not
        # a parse error to crash commit() with forever.
        latest = self.log_manager._get_log_lenient(latest_id)
        if latest is None or latest.state not in STABLE_STATES:
            raise HyperspaceException(
                f"streaming log for {self.table_path} is mid-commit or "
                "wrecked; run Hyperspace.recover() first")

    def _stable(self) -> Optional[IndexLogEntry]:
        entry = self.log_manager.get_latest_stable_log()
        if entry is not None and entry.state != States.ACTIVE:
            return None  # DOESNOTEXIST after a cancelled first commit
        return entry

    @property
    def log_entry(self) -> IndexLogEntry:
        prev = self._stable()
        infos = [FileInfo(b.final_path, b.nbytes, b.mtime_ms)
                 for b in self.batches]
        new_content = _content_over(infos)
        if prev is not None and prev.content is not None \
                and prev.content.files:
            content = prev.content.merge(new_content)
        else:
            content = new_content
        props = _carry_props(prev) if prev is not None else {}
        derived = IngestedTable(schema=self.batches[0].schema)
        rel = Relation(rootPaths=[self.table_path], data=Hdfs(content),
                       dataSchema=self.batches[0].schema,
                       fileFormat="parquet", options={})
        fingerprint = LogicalPlanFingerprint(
            [Signature("streaming.ingest", table_key(self.table_path))])
        return IndexLogEntry.create(
            table_key(self.table_path), derived, content,
            Source(SourcePlan([rel], fingerprint)), props)

    def op(self) -> None:
        self.op_started = True
        _faults.fault_point(_fn.INGEST_PUBLISH)
        for b in self.batches:
            os.replace(b.staged_path, b.final_path)
        resolver = PathResolver(self.session.hs_conf)
        cov: Dict[str, List[tuple]] = {}
        sk: Dict[str, List[tuple]] = {}
        for b in self.batches:
            for d in b.covering:
                cov.setdefault(d.index_name, []).append((b, d))
            for d in b.sketches:
                sk.setdefault(d.index_name, []).append((b, d))
        for name in sorted(cov):
            path = resolver.get_index_path(name)
            self._land(name, _LandCoveringDeltas(
                self.session, IndexLogManager(path),
                IndexDataManager(path), self.table_path, cov[name]))
        for name in sorted(sk):
            path = resolver.get_index_path(name)
            self._land(name, _LandSketchDeltas(
                self.session, IndexLogManager(path),
                IndexDataManager(path), self.table_path, sk[name]))

    def _land(self, name: str, action: Action) -> None:
        """One index's delta landing must not fail the COMMIT: the
        batch files are already published, and an index that lost its
        delta (deleted between append and commit, a log-id race with a
        concurrent refresh/compact) just doesn't cover them — hybrid
        scan does, and the next commit or refresh catches it up. A
        wreck the failure left in the INDEX's own log recovers through
        the ordinary index sweep. Kills/cancellation still propagate."""
        try:
            action.run()
        except Exception:
            self.indexes_skipped.append(name)
        else:
            self.indexes_updated.append(name)

    def event(self, message: str):
        from ..telemetry.events import StreamingCommitEvent
        return StreamingCommitEvent(
            message=message, table=self.table_path,
            batches=len(self.batches), files=len(self.batches),
            rows=sum(b.rows for b in self.batches),
            indexes_updated=list(self.indexes_updated))


def commit(session, table_path: str) -> dict:
    """Publish every staged batch for ``table_path`` atomically. Returns
    a summary dict ({committed_batches, rows, files, indexes_updated});
    a commit that lost the put-if-absent race (another process committed
    concurrently) re-queues its batches and raises — retry after the
    winner finishes. With ``groupCommit.enabled`` (the default)
    concurrent callers coalesce into one publication wave — one op-log
    entry, one delta landing per index, one subscription fire, one
    cluster broadcast — and riders' summaries carry ``joined_wave``.
    Off, every call publishes its own batches exactly as before."""
    if not session.hs_conf.streaming_enabled():
        raise HyperspaceException(
            "hyperspace.tpu.streaming.enabled is false; enable it to use "
            "the append/commit ingestion tier")
    table_path = os.path.abspath(table_path)
    if session.hs_conf.streaming_group_commit_enabled():
        return get_coordinator().commit_grouped(session, table_path)
    res, _ = _commit_once(session, table_path)
    if res["committed_batches"]:
        res["subscriptions_fired"] = _fire_subscriptions(
            session, table_path, batches=res["committed_batches"])
    return res


def _commit_once(session, table_path: str,
                 limit: Optional[int] = None):
    """One publication through the op-log protocol: pop (up to
    ``limit``) staged batches and land them as ONE table-log entry plus
    one delta landing per index. Does NOT fire subscriptions — the
    callers (legacy per-commit path, group-commit wave leader) fire
    once per publication wave. Returns ``(summary, truncated)``."""
    queue = get_queue()
    with queue.commit_lock(table_path):
        batches, truncated = queue.pop_wave(table_path, limit)
        if not batches:
            return _empty_commit_summary(), False
        t0 = time.perf_counter()
        log_mgr = IndexLogManager(table_log_dir(session, table_path))
        action = _StreamingCommitAction(session, log_mgr, table_path,
                                        batches)
        try:
            with _trace.maintenance_trace(session, "ingest"), \
                    _trace.span(SN.INGEST_COMMIT) as sp:
                action.run()
                if sp is not None:
                    sp.attrs["batches"] = len(batches)
                    sp.attrs["indexes"] = len(action.indexes_updated)
        except BaseException:
            if not action.op_started:
                # Nothing landed (validation / begin conflict): the
                # staged batches are intact — retryable.
                queue.requeue(table_path, batches)
            else:
                # Mid-protocol failure: only recover() can resolve the
                # wreck; drop the batches from the in-flight accounting
                # so backpressure and lineage offsets stay honest.
                queue.abandon(table_path, batches)
            raise
        queue.land(table_path, batches)
        # Landed entries changed index state under the caching manager.
        session.index_collection_manager.clear_cache()
        seconds = time.perf_counter() - t0
    return ({"committed_batches": len(batches),
             "rows": sum(b.rows for b in batches),
             "files": [b.final_path for b in batches],
             "indexes_updated": list(action.indexes_updated),
             "indexes_skipped": list(action.indexes_skipped),
             "subscriptions_fired": 0,
             "seconds": seconds}, truncated)


def _fire_subscriptions(session, table_path: str,
                        batches: int = 0) -> int:
    from ..serving import frontend as fe
    fired = 0
    for front in fe.all_frontends():
        try:
            fired += front.notify_commit(session, table_path)
        except Exception:
            # The commit already published durably; a notification
            # failure must not make the committer believe it failed
            # (per-fire errors are delivered on the subscriptions).
            continue
    if fired:
        get_queue().note(subscription_fires=fired)
    # Cluster broadcast (cluster/worker.py): the registries above are
    # process-local, so ship the notice to every live peer too —
    # standing queries fire on EVERY worker from this one commit. A
    # delivery failure degrades (that peer misses a firing), never
    # fails the already-durable commit. Disabled clusters pay one conf
    # read.
    if session.hs_conf.cluster_broadcast_enabled():
        from ..cluster import worker as _cluster
        try:
            _cluster.broadcast_commit(session, table_path,
                                      batches=batches)
        except Exception:
            pass  # the commit is durable; fan-out is best-effort
    return fired


# ---------------------------------------------------------------------------
# Crash recovery (driven by robustness/recovery.recover_indexes).
# ---------------------------------------------------------------------------

def recover_streaming(session, summary: Dict) -> None:
    """Sweep the per-table streaming logs: UNDO commits that died with
    batch files partially published (delete what landed, cancel the
    log), REDO commits that died after every batch file landed (write
    the final entry — the data is durably on disk and the transient
    entry records the intent), and clear staging leftovers everywhere.
    Runs under recover()'s operator contract: no live writer."""
    s = summary.setdefault("streaming", {
        "tables": [], "rolled_back": {}, "completed": [],
        "torn_entries": 0, "staging_swept": 0})
    root = os.path.join(session.hs_conf.system_path(), SC.STREAMING_DIR)
    from ..index.constants import IndexConstants
    if os.path.isdir(root):
        for name in sorted(os.listdir(root)):
            path = os.path.join(root, name)
            if not os.path.isdir(os.path.join(
                    path, IndexConstants.HYPERSPACE_LOG)):
                continue
            s["tables"].append(name)
            try:
                _recover_table_log(session, path, name, s)
            except Exception as e:
                summary.setdefault("errors", {})[
                    f"streaming:{name}"] = f"{type(e).__name__}: {e}"
    # Tables that staged batches but never earned a streaming log (the
    # appender died before its first commit): the staged-table markers
    # name them, so their invisible staging files still get swept.
    marker_dir = _staged_marker_dir(session)
    if os.path.isdir(marker_dir):
        for name in sorted(os.listdir(marker_dir)):
            marker = os.path.join(marker_dir, name)
            try:
                with open(marker) as f:
                    table_path = f.read().strip()
            except OSError:
                continue
            if table_path:
                stage = os.path.join(table_path, SC.STAGING_DIR)
                if os.path.isdir(stage):
                    s["staging_swept"] += _sweep_staging(stage)
                get_queue().drop_table(os.path.abspath(table_path))
                get_coordinator().forget(os.path.abspath(table_path))
            try:
                os.unlink(marker)
            except OSError:
                pass
    # Index-side staging leftovers (prebuilt deltas of batches that will
    # never commit — their table staging was swept with them).
    sys_root = session.hs_conf.system_path()
    if os.path.isdir(sys_root):
        for name in sorted(os.listdir(sys_root)):
            if name == SC.STREAMING_DIR:
                continue
            stage = os.path.join(sys_root, name, SC.STAGING_DIR)
            if os.path.isdir(stage):
                s["staging_swept"] += _sweep_staging(stage)


def _recover_table_log(session, path: str, name: str, s: Dict) -> None:
    mgr = IndexLogManager(path)
    latest_id = mgr.get_latest_id()
    if latest_id is None:
        return
    latest = mgr._get_log_lenient(latest_id)
    stable = mgr.get_latest_stable_log()
    stable_files = set(stable.content.files) \
        if stable is not None and stable.content is not None else set()
    if latest is None:
        # Torn (unparseable) tip: the crash struck mid entry upload —
        # either the begin write (nothing published yet) or the END
        # write (transient entry beneath it, files already landed).
        # Delete the torn file, then RE-EXAMINE the new tip in this
        # same pass: a torn end must fall through to the redo branch,
        # not force the operator to run recover() twice.
        mgr.delete_log(latest_id)
        s["torn_entries"] += 1
        _recover_table_log(session, path, name, s)
        return
    if latest.state not in STABLE_STATES:
        torn = [f for f in (latest.content.files
                            if latest.content is not None else [])
                if f not in stable_files and os.path.basename(f)
                .startswith(SC.INGEST_FILE_PREFIX)]
        landed = [f for f in torn if os.path.isfile(f)]
        if torn and len(landed) == len(torn):
            # REDO: publication finished before the crash; finalize.
            entry = IndexLogEntry.from_json(latest.to_json())
            entry.state = States.ACTIVE
            mgr.delete_latest_stable_log()
            if mgr.write_log(latest_id + 1, entry):
                mgr.create_latest_stable_log(latest_id + 1)
            s["completed"].append(name)
        else:
            # UNDO: roll the staged batch back out of the table.
            for f in landed:
                try:
                    os.unlink(f)
                except OSError:
                    pass
            from ..actions.lifecycle import CancelAction
            CancelAction(session, mgr, IndexDataManager(path)).run()
            s["rolled_back"][name] = len(landed)
    table_path = None
    for e in (latest, stable):
        if e is None:
            continue
        try:
            table_path = os.path.abspath(e.relation.rootPaths[0])
            break
        except (AssertionError, AttributeError, IndexError):
            continue
    if table_path is not None:
        stage = os.path.join(table_path, SC.STAGING_DIR)
        if os.path.isdir(stage):
            s["staging_swept"] += _sweep_staging(stage)
        get_queue().drop_table(table_path)
        get_coordinator().forget(table_path)


def _sweep_staging(path: str) -> int:
    import shutil
    n = 0
    for _root, _dirs, files in os.walk(path):
        n += len(files)
    shutil.rmtree(path, ignore_errors=True)
    return n


def _emit_append(session, staged: StagedBatch, seconds: float) -> None:
    try:
        from ..telemetry.events import StreamingAppendEvent
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            StreamingAppendEvent(
                message=(f"staged {staged.rows} rows "
                         f"({len(staged.covering)} covering, "
                         f"{len(staged.sketches)} sketch deltas)"),
                table=staged.table_path, rows=staged.rows,
                nbytes=staged.nbytes,
                covering_deltas=len(staged.covering),
                sketch_deltas=len(staged.sketches),
                seconds=seconds))
    except Exception:
        pass
