"""Op-log compaction + vacuum: bound what a long-lived lake accumulates.

Every action appends two entries to its op log, and every streaming
commit appends two more per table plus two per landed index delta — a
sustained append workload grows every log without bound, and the
serving hot path pays for it (each query's result-cache key re-lists
each log; see index/log_manager.LogLookupCache for the read-side
mitigation). ``compact()`` is the write-side fix: for every log whose
tip is STABLE, it folds all superseded entries into one CHECKPOINT
entry — a copy of the tip carrying ``compactionGeneration``/
``compactedThrough`` properties — then deletes the folded entry files
and vacuums index data versions no remaining entry references
(``robustness/recovery._vacuum_orphan_versions``, the same sweep crash
recovery runs).

Aliasing safety: the checkpoint lands at a NEW log id with NEW bytes,
so the result cache's ``(latest id, entry-bytes md5)`` component flips
by construction, and the generation property keeps every later entry's
bytes distinct from any pre-compaction history. Caches keyed on
``(index name, entry id)`` (the sketch-table memo) can never alias
because ids only grow; the optimizer stats provider keys on SOURCE
file signatures, which compaction leaves untouched.

Crash safety (no new protocol): the checkpoint is an ordinary
put-if-absent entry write. A crash before it leaves the log unchanged;
a crash after it but mid-delete leaves extra superseded entries the
next compact() folds — every intermediate state is a valid log, and
``recover()`` has nothing to do.

Concurrency: the checkpoint's put-if-absent decides races against live
actions — a concurrent action that claimed the id first wins and the
log is skipped this round. The VACUUM half, however, inherits the
recover()/vacuumIndex OPERATOR CONTRACT: superseded entries are what
protected historical data versions, so deleting a version only they
referenced can fail a reader that planned against a stale (TTL-cached
or cross-process) entry mid-scan. Run compact() in a quiet window —
log folding alone is always safe, but the version vacuum is not
concurrent-reader-proof (nothing in this lake is, once bytes are
deleted; same contract as VacuumAction).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..index.constants import IndexConstants, STABLE_STATES
from ..index.log_entry import IndexLogEntry
from ..index.log_manager import IndexLogManager
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from .constants import StreamingConstants as SC


def compact(session, names: Optional[List[str]] = None) -> Dict:
    """Compact every op-log under the session's system path (indexes
    AND per-table streaming logs), or just ``names``. Returns a summary
    dict ({compacted, skipped, errors}); per-log failures are collected
    so one wrecked log cannot block the sweep."""
    summary: Dict = {"compacted": {}, "skipped": {}, "errors": {}}
    root = session.hs_conf.system_path()
    min_entries = session.hs_conf.streaming_compaction_min_entries()
    for name, path in _log_dirs(root):
        if names is not None and name not in names \
                and name.replace("streaming:", "", 1) not in names:
            continue
        try:
            with _trace.maintenance_trace(session, "compact"), \
                    _trace.span(SN.INGEST_COMPACT) as sp:
                _compact_one(session, name, path, min_entries, summary)
                if sp is not None and name in summary["compacted"]:
                    sp.attrs["folded"] = \
                        summary["compacted"][name]["entries_folded"]
        except Exception as e:
            summary["errors"][name] = f"{type(e).__name__}: {e}"
    # Checkpoints changed entries under the caching metadata manager.
    session.index_collection_manager.clear_cache()
    return summary


def _log_dirs(root: str) -> List[tuple]:
    """(display name, dir) of every op-log under the system path."""
    out: List[tuple] = []
    if not os.path.isdir(root):
        return out
    for name in sorted(os.listdir(root)):
        if name == SC.STREAMING_DIR:
            continue
        path = os.path.join(root, name)
        if os.path.isdir(os.path.join(path,
                                      IndexConstants.HYPERSPACE_LOG)):
            out.append((name, path))
    sroot = os.path.join(root, SC.STREAMING_DIR)
    if os.path.isdir(sroot):
        for name in sorted(os.listdir(sroot)):
            path = os.path.join(sroot, name)
            if os.path.isdir(os.path.join(
                    path, IndexConstants.HYPERSPACE_LOG)):
                out.append((f"streaming:{name}", path))
    return out


def _compact_one(session, name: str, path: str, min_entries: int,
                 summary: Dict) -> None:
    mgr = IndexLogManager(path)
    latest_id = mgr.get_latest_id()
    if latest_id is None:
        summary["skipped"][name] = "empty log"
        return
    tip = mgr._get_log_lenient(latest_id)
    if tip is None or tip.state not in STABLE_STATES:
        summary["skipped"][name] = ("tip is transient (live action or "
                                    "wreck); recover() first")
        return
    superseded = sorted(i for i in mgr.get_all_ids() if i < latest_id)
    if len(superseded) < min_entries:
        summary["skipped"][name] = (
            f"{len(superseded)} superseded entries below "
            "streaming.compaction.minEntries")
        return
    generation = int(tip.properties.get(
        SC.COMPACTION_GENERATION_PROPERTY, "0")) + 1
    checkpoint = IndexLogEntry.from_json(tip.to_json())
    checkpoint.properties[SC.COMPACTION_GENERATION_PROPERTY] = \
        str(generation)
    checkpoint.properties[SC.COMPACTED_THROUGH_PROPERTY] = str(latest_id)
    import time as _time
    checkpoint.timestamp = int(_time.time() * 1000)
    if not mgr.write_log(latest_id + 1, checkpoint):
        summary["skipped"][name] = ("lost the log id race to a "
                                    "concurrent action")
        return
    mgr.create_latest_stable_log(latest_id + 1)
    folded = 0
    for i in superseded + [latest_id]:
        if mgr.delete_log(i):
            folded += 1
    from ..robustness.recovery import _vacuum_orphan_versions
    orphans = _vacuum_orphan_versions(mgr, path)
    summary["compacted"][name] = {
        "entries_folded": folded,
        "generation": generation,
        "versions_vacuumed": len(orphans),
    }
    _emit(session, name, folded, generation, len(orphans))


def _emit(session, name: str, folded: int, generation: int,
          vacuumed: int) -> None:
    try:
        from ..telemetry.events import StreamingCompactionEvent
        from ..telemetry.logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            StreamingCompactionEvent(
                message=(f"folded {folded} op-log entries into "
                         f"checkpoint generation {generation}"),
                subject=name, entries_folded=folded,
                generation=generation, versions_vacuumed=vacuumed))
    except Exception:
        pass
