"""Continuous ingestion sources: tailing daemons that drive append/commit.

ROADMAP item 5(b): ingestion at traffic scale needs a writer that is
not a caller invoking ``append()`` in a loop. A :class:`ContinuousSource`
is a small tailing daemon (on the sanctioned ``parallel/io.spawn_daemon``
seam — the lint gate's one thread-construction site) that discovers new
input, stages it through the ordinary ``append()`` path (load-time
indexing and all), and drives group commits itself every
``source.commitBatches`` appends, plus a trailing commit when input
goes idle. Backpressure is BLOCKING, not raise-on-full: a full
staged-batch budget parks the tailer inside ``append(block=True)``
(bounded by ``backpressure.timeoutMs``), and an overloaded admission
verdict (adaptive/admission.should_pause_ingest) pauses input pulls
entirely — under load, serving drains first and ingest waits, never
the reverse.

Fault posture: each poll body fires the ``streaming.source`` fault
point; ANY poll failure — injected, a torn input file, a backpressure
timeout — is counted, backed off one poll interval, and retried. Work
items are acknowledged only AFTER their append succeeds, so a failed
poll re-discovers exactly the unconsumed input; the daemon itself
never dies to a poll error.

Two concrete tailers:

- :class:`DirectoryTailSource` — watches a drop directory for new
  ``*.parquet`` files (producers must land them atomically, e.g. write
  to ``*.tmp`` then rename; ``*.tmp`` names are skipped) and appends
  each file as one batch.
- :class:`LogTailSource` — byte-offset tail of a JSONL log; each poll
  appends the complete new lines as one dict-of-columns batch.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException
from ..parallel import io as pio
from ..robustness import fault_names as _fn
from ..robustness import faults as _faults
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from . import ingest


class ContinuousSource:
    """Base tailing daemon. Subclasses implement ``_discover()`` (new
    opaque work items, oldest first), ``_load(item)`` (item -> record
    batch append() accepts), and ``_ack(item)`` (mark consumed — called
    only after the append landed in staging). All mutable state behind
    ``_lock`` (HS301): the poll loop mutates from the daemon thread
    while ``stats()``/``stop()`` read from callers."""

    def __init__(self, session, table_path: str,
                 name: Optional[str] = None):
        self._session = session
        self._table_path = os.path.abspath(table_path)
        self._name = name or type(self).__name__
        self._lock = threading.Lock()
        self._stop_flag = threading.Event()
        # Daemon handle from pio.spawn_daemon (the one sanctioned
        # thread-construction seam).
        self._thread = None
        self._pending = 0  # appends not yet covered by a commit
        self._stats = {"polls": 0, "batches": 0, "rows": 0,
                       "commits": 0, "errors": 0, "waits": 0,
                       "pauses": 0}

    # -- subclass surface -------------------------------------------------

    def _discover(self) -> List:
        raise NotImplementedError

    def _load(self, item):
        raise NotImplementedError

    def _ack(self, item) -> None:
        raise NotImplementedError

    # -- lifecycle --------------------------------------------------------

    @property
    def name(self) -> str:
        return self._name

    @property
    def table_path(self) -> str:
        return self._table_path

    def start(self) -> "ContinuousSource":
        if not self._session.hs_conf.streaming_enabled():
            raise HyperspaceException(
                "hyperspace.tpu.streaming.enabled is false; enable it "
                "to run continuous sources")
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_flag.clear()
            self._thread = pio.spawn_daemon(
                f"hs-source-{self._name}", self._run)
        return self

    def running(self) -> bool:
        with self._lock:
            return self._thread is not None and self._thread.is_alive()

    def stop(self, drain: bool = True, timeout_s: float = 60.0) -> dict:
        """Signal the daemon, join it, and (``drain``, the default)
        commit whatever it staged but had not committed yet — a stopped
        source must not leave invisible staged batches behind. Returns
        the source's stats."""
        self._stop_flag.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout_s)
        if drain:
            with self._lock:
                pending = self._pending
                self._pending = 0
            if pending:
                try:
                    ingest.commit(self._session, self._table_path)
                except BaseException:
                    with self._lock:
                        self._pending += pending
                    raise
                with self._lock:
                    self._stats["commits"] += 1
        return self.stats()

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["pending"] = self._pending
        out["running"] = self.running()
        out["name"] = self._name
        out["table"] = self._table_path
        return out

    # -- the poll loop ----------------------------------------------------

    def _run(self) -> None:
        poll_s = \
            self._session.hs_conf.streaming_source_poll_ms() / 1000.0
        # ONE fault scope for the daemon's lifetime: ``nth=``/``times=``
        # counters span polls (a per-poll scope would reset them and
        # turn "times=2" into every-poll), matching the per-run arming
        # of queries and actions.
        with _faults.scope_for(self._session.hs_conf):
            while not self._stop_flag.is_set():
                try:
                    productive = self._poll_once()
                except Exception:
                    # Injected or real poll failure: count it, back off
                    # one interval, retry. Unacked input is
                    # re-discovered.
                    with self._lock:
                        self._stats["errors"] += 1
                    self._stop_flag.wait(poll_s)
                    continue
                if not productive:
                    self._idle_commit()
                    self._stop_flag.wait(poll_s)

    def _idle_commit(self) -> None:
        # A trickle must not sit staged (invisible to queries) until
        # commitBatches fills: idle polls flush the remainder.
        with self._lock:
            pending = self._pending
            self._pending = 0
        if pending:
            try:
                ingest.commit(self._session, self._table_path)
                with self._lock:
                    self._stats["commits"] += 1
            except Exception:
                # Restore the count: the batches are still staged and a
                # later flush must know to commit them.
                with self._lock:
                    self._pending += pending
                    self._stats["errors"] += 1

    def _poll_once(self) -> bool:
        with self._lock:
            self._stats["polls"] += 1
        _faults.fault_point(_fn.STREAMING_SOURCE)
        # Overload pause: while the SLO monitor reports breach, pull no
        # new input at all — staged work still commits, serving drains.
        from ..adaptive.admission import get_controller
        if get_controller().should_pause_ingest(self._session):
            with self._lock:
                self._stats["pauses"] += 1
            return False
        items = self._discover()
        if not items:
            return False
        session = self._session
        commit_every = session.hs_conf.streaming_source_commit_batches()
        max_staged = session.hs_conf.streaming_max_staged_batches()
        queue = ingest.get_queue()
        appended = rows = commits = waits = 0
        with _trace.maintenance_trace(session, "source"), \
                _trace.span(SN.INGEST_SOURCE) as sp:
            for item in items:
                if self._stop_flag.is_set():
                    break
                payload = self._load(item)
                if payload is None:
                    self._ack(item)
                    continue
                if queue.staged_count(self._table_path) >= max_staged:
                    waits += 1  # the blocking append will park
                res = ingest.append(session, self._table_path, payload,
                                    block=True)
                self._ack(item)
                appended += 1
                rows += res["rows"]
                with self._lock:
                    self._pending += 1
                    flushed = self._pending
                    flush = flushed >= commit_every
                    if flush:
                        self._pending = 0
                if flush:
                    try:
                        ingest.commit(session, self._table_path)
                    except BaseException:
                        # Still staged: restore the count so the next
                        # flush/idle commit covers these batches.
                        with self._lock:
                            self._pending += flushed
                        raise
                    commits += 1
            if sp is not None:
                sp.attrs["batches"] = appended
                sp.attrs["rows"] = rows
                sp.attrs["commits"] = commits
        if appended or commits:
            with self._lock:
                self._stats["batches"] += appended
                self._stats["rows"] += rows
                self._stats["commits"] += commits
                self._stats["waits"] += waits
            self._emit(appended, rows, commits, waits)
        return bool(appended)

    def _emit(self, batches: int, rows: int, commits: int,
              waits: int) -> None:
        try:
            from ..telemetry.events import StreamingSourceEvent
            from ..telemetry.logging import get_logger
            get_logger(
                self._session.hs_conf.event_logger_class()).log_event(
                StreamingSourceEvent(
                    message=(f"{self._name}: appended {batches} "
                             f"batches ({rows} rows), "
                             f"drove {commits} commits"),
                    source=self._name, table=self._table_path,
                    batches=batches, rows=rows, commits=commits,
                    waits=waits))
        except Exception:
            pass


class DirectoryTailSource(ContinuousSource):
    """Tail a drop directory: every new ``*.parquet`` file (atomic
    rename by the producer; ``*.tmp`` skipped) becomes one appended
    batch, oldest mtime first. Consumed names are remembered for the
    daemon's lifetime — producers must not reuse file names."""

    def __init__(self, session, watch_dir: str, table_path: str,
                 name: Optional[str] = None):
        super().__init__(session, table_path,
                         name=name or "dir-tail")
        self._watch_dir = os.path.abspath(watch_dir)
        self._seen: Dict[str, bool] = {}

    def _discover(self) -> List[str]:
        try:
            names = os.listdir(self._watch_dir)
        except OSError:
            return []
        with self._lock:
            fresh = [n for n in names
                     if n.endswith(".parquet") and n not in self._seen]
        paths = [os.path.join(self._watch_dir, n) for n in sorted(fresh)]
        paths.sort(key=lambda p: (os.path.getmtime(p)
                                  if os.path.isfile(p) else 0.0, p))
        return paths

    def _load(self, item: str):
        import pyarrow.parquet as pq
        try:
            table = pq.read_table(item)
        except OSError:
            return None  # vanished between listing and read: skip
        if table.num_rows == 0:
            return None
        return table

    def _ack(self, item: str) -> None:
        with self._lock:
            self._seen[os.path.basename(item)] = True


class LogTailSource(ContinuousSource):
    """Byte-offset tail of a JSONL log: each poll reads the COMPLETE
    new lines past the consumed offset and appends them as one
    dict-of-columns batch (every record must carry the table's exact
    column set — the append-side schema check refuses forks). The
    offset advances only after the append lands, so a failed poll
    replays the same lines."""

    def __init__(self, session, log_path: str, table_path: str,
                 name: Optional[str] = None):
        super().__init__(session, table_path,
                         name=name or "log-tail")
        self._log_path = os.path.abspath(log_path)
        self._offset = 0

    def _discover(self) -> List[tuple]:
        with self._lock:
            offset = self._offset
        try:
            with open(self._log_path, "rb") as f:
                f.seek(offset)
                chunk = f.read()
        except OSError:
            return []
        # Only complete (newline-terminated) lines are consumable; a
        # partial tail line is the producer mid-write.
        end = chunk.rfind(b"\n")
        if end < 0:
            return []
        complete = chunk[:end + 1]
        records = []
        for line in complete.splitlines():
            line = line.strip()
            if line:
                records.append(json.loads(line.decode("utf-8")))
        if not records:
            # Blank lines only: consume the offset without appending.
            with self._lock:
                self._offset = offset + end + 1
            return []
        columns = sorted(records[0])
        payload = {c: [r.get(c) for r in records] for c in columns}
        return [(offset + end + 1, payload)]

    def _load(self, item: tuple):
        return item[1]

    def _ack(self, item: tuple) -> None:
        with self._lock:
            self._offset = max(self._offset, item[0])


def tail_directory(session, watch_dir: str, table_path: str,
                   name: Optional[str] = None) -> DirectoryTailSource:
    """Construct AND start a directory tailer."""
    return DirectoryTailSource(session, watch_dir, table_path,
                               name=name).start()


def tail_log(session, log_path: str, table_path: str,
             name: Optional[str] = None) -> LogTailSource:
    """Construct AND start a JSONL log tailer."""
    return LogTailSource(session, log_path, table_path,
                         name=name).start()
