"""Standing queries: cached plans re-fired per streaming commit.

A standing query is nothing new in the engine's terms — it is a cached
plan plus the r06 invalidation hook. ``ServingFrontend.subscribe(df)``
registers the plan; every ``commit()`` re-submits it through the
serving worker pool (admission control, deadlines, and the degradation
ladders apply exactly as for ad-hoc queries), and the result-cache
log-version keys guarantee the re-fire recomputes iff the commit could
have changed the answer. Deliveries land asynchronously on the
subscription's bounded buffer; consumers block on ``wait_for``/
``latest`` or snapshot ``deliveries()``.

Shedding: a re-fire the frontend rejects (queue depth / byte budget)
is delivered as that fire's ERROR — a standing query observes overload
instead of silently skipping a commit.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..exceptions import HyperspaceException, ServingRejectedError


class Delivery:
    """One fire's outcome: ``result`` (an executed Table) or ``error``."""

    __slots__ = ("seq", "table", "result", "error", "at_s")

    def __init__(self, seq: int, table: str, result=None, error=None):
        self.seq = seq
        self.table = table
        self.result = result
        self.error = error
        self.at_s = time.perf_counter()

    @property
    def ok(self) -> bool:
        return self.error is None


class Subscription:
    """Handle returned by ``ServingFrontend.subscribe``. Deliveries are
    appended from serving worker threads (the PendingQuery completion
    callback), so every mutable field is guarded by ``_cv``."""

    def __init__(self, registry: "SubscriptionRegistry", sub_id: int,
                 plan, session, client: str,
                 deadline_ms: Optional[float], history: int):
        self._registry = registry
        self.sub_id = sub_id
        self.plan = plan
        self.session = session
        self.client = client or f"standing:{sub_id}"
        self.deadline_ms = deadline_ms
        # Source tables this plan reads (absolute root paths): a commit
        # to an unrelated table never burns a worker slot on this
        # subscription.
        self.tables = _source_roots(plan)
        self._cv = threading.Condition()
        self._deliveries: "deque[Delivery]" = deque(maxlen=history)
        self._delivered_total = 0
        self._fired_total = 0
        self._active = True

    def fresh_plan(self, relation_memo: Optional[dict] = None):
        """The subscribed plan with every file-based relation re-listed
        NOW: a standing query must observe the rows each commit
        published, not its subscribe-time file snapshot (relations pin
        their listing for consistency — correct for ad-hoc queries,
        wrong for a query whose point is to follow the stream). Falls
        back to the original plan when a leaf cannot refresh.
        ``relation_memo`` shares one refreshed listing per root-path
        set across a fire wave — the pin is per COMMIT, so N
        subscriptions on one table need one directory walk, not N."""
        from ..plan.nodes import Scan

        def refresh(node):
            if isinstance(node, Scan) and \
                    getattr(node, "relation", None) is not None:
                try:
                    key = (tuple(node.relation.root_paths),
                           node.relation.file_format)
                    fresh = None if relation_memo is None \
                        else relation_memo.get(key)
                    if fresh is None:
                        fresh = node.relation.refresh()
                        # Pin the listing AT FIRE TIME: the delivery
                        # answers the table as of the commit that fired
                        # it, not as of whenever a queued worker gets
                        # to execute.
                        fresh.all_files()
                        if relation_memo is not None:
                            relation_memo[key] = fresh
                    return Scan(fresh, skipping_note=node.skipping_note)
                except Exception:
                    return node
            return node

        try:
            return self.plan.transform_up(refresh)
        except Exception:
            return self.plan

    @property
    def active(self) -> bool:
        with self._cv:
            return self._active

    def _close(self) -> None:
        with self._cv:
            self._active = False
            self._cv.notify_all()

    def _next_seq(self) -> int:
        with self._cv:
            self._fired_total += 1
            return self._fired_total

    def _deliver(self, seq: int, table: str, result=None,
                 error=None) -> None:
        with self._cv:
            self._deliveries.append(Delivery(seq, table, result, error))
            self._delivered_total += 1
            self._cv.notify_all()

    def deliveries(self) -> List[Delivery]:
        with self._cv:
            return list(self._deliveries)

    @property
    def delivered_total(self) -> int:
        with self._cv:
            return self._delivered_total

    def wait_for(self, n: int, timeout: float = 30.0) -> List[Delivery]:
        """Block until ``n`` TOTAL deliveries have arrived; returns the
        buffered (most recent) deliveries. TimeoutError past timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._delivered_total < n:
                if not self._active:
                    # unsubscribe() wakes waiters (_close notifies);
                    # a delivery already in flight from an earlier fire
                    # may still land after this raises.
                    raise HyperspaceException(
                        f"subscription {self.sub_id} closed after "
                        f"{self._delivered_total}/{n} deliveries")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"subscription {self.sub_id}: "
                        f"{self._delivered_total}/{n} deliveries after "
                        f"{timeout}s")
                self._cv.wait(remaining)
            return list(self._deliveries)

    def latest(self, timeout: float = 30.0) -> Delivery:
        """The most recent FIRE's delivery, waiting for the first if
        none yet. Max-by-seq, not last-appended: deliveries land in
        completion order, and a slow earlier fire may finish after a
        later one — its answer must not shadow the newer commit's."""
        with self._cv:
            have = self._delivered_total
        if have == 0:
            self.wait_for(1, timeout)
        with self._cv:
            return max(self._deliveries, key=lambda d: d.seq)

    def unsubscribe(self) -> bool:
        return self._registry.unsubscribe(self)


class SubscriptionRegistry:
    """The frontend's standing-query registry: subscriptions are
    registered from client threads and fired from whichever thread runs
    a commit, so the table is lock-guarded (HS301-registered)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[int, Subscription] = {}
        self._next_id = 0
        self._stats = {
            "subscribed": 0, "unsubscribed": 0, "fires": 0,
            "fired_queries": 0, "rejected_queries": 0,
            "wave_groups": 0, "wave_members": 0,
        }

    def subscribe(self, frontend, query, session, client: str,
                  deadline_ms: Optional[float], max_subs: int,
                  history: int) -> Subscription:
        plan = getattr(query, "plan", query)
        with self._lock:
            # Everything in _subs is live: unsubscribe() pops before it
            # closes (and probing s.active here would nest each sub's
            # _cv under the registry lock).
            live = len(self._subs)
            if live >= max_subs:
                raise HyperspaceException(
                    f"{live} standing queries reach "
                    "hyperspace.tpu.streaming.subscriptions.max")
            self._next_id += 1
            sub = Subscription(self, self._next_id, plan, session, client,
                               deadline_ms, history)
            self._subs[sub.sub_id] = sub
            self._stats["subscribed"] += 1
        return sub

    def unsubscribe(self, sub: Subscription) -> bool:
        with self._lock:
            dropped = self._subs.pop(sub.sub_id, None) is not None
            if dropped:
                self._stats["unsubscribed"] += 1
        if dropped:
            sub._close()
        return dropped

    def fire(self, frontend, session, table: str) -> int:
        """Re-submit every live subscription's plan — re-listed fresh,
        so deliveries carry the committed rows — through the serving
        pool. Subscriptions whose source tables don't include the
        committed one are skipped (their answer cannot have changed).

        Fan-out shape (ROADMAP item 5(c)): subscriptions whose fresh
        plans share an r11 batching template (same shape, different
        Filter literals) fire as ONE preformed wave through
        ``frontend.submit_wave`` — one shared scan and one vmapped
        sweep per template group per commit, instead of N independent
        submissions racing for workers. Unique-template and unbatchable
        subscriptions keep the per-sub submit path. Returns how many
        fires were admitted; rejected fires are delivered as errors
        (observable shedding), per member — one shed never starves the
        rest of the wave."""
        with self._lock:
            subs = [s for s in self._subs.values()]
        subs = [s for s in subs if s.active
                and (not table or not s.tables or table in s.tables)]
        fired = rejected = 0
        relation_memo: dict = {}  # one listing per root set this wave
        batching = frontend.batching_enabled()
        # Group by batching template. key=None (batching off, template
        # fingerprint failed, or unbatchable plan) never groups.
        plans: List[tuple] = []  # (sub, seq, plan, key)
        for sub in subs:
            seq = sub._next_seq()
            plan = sub.fresh_plan(relation_memo)
            key = None
            if batching:
                try:
                    from ..serving import batcher
                    from ..serving.fingerprint import normalize
                    key = batcher.template_key(sub.session,
                                               normalize(plan))
                except Exception:
                    key = None
            plans.append((sub, seq, plan, key))
        buckets: Dict[object, List[tuple]] = {}
        for item in plans:
            buckets.setdefault(item[3], []).append(item)
        waves = 0
        for key, group in buckets.items():
            if key is not None and len(group) >= 2:
                waves += 1
                f, r = self._fire_wave(frontend, table, group)
            else:
                f, r = self._fire_singles(frontend, table, group)
            fired += f
            rejected += r
        with self._lock:
            self._stats["fires"] += 1 if subs else 0
            self._stats["fired_queries"] += fired
            self._stats["rejected_queries"] += rejected
            self._stats["wave_groups"] += waves
            if waves:
                self._stats["wave_members"] += sum(
                    len(g) for k, g in buckets.items()
                    if k is not None and len(g) >= 2)
        if subs:
            self._emit(session, table, fired, rejected, waves)
        return fired

    def _fire_singles(self, frontend, table: str,
                      group: List[tuple]) -> tuple:
        """The per-subscription path: one frontend.submit each."""
        fired = rejected = 0
        for sub, seq, plan, _key in group:
            try:
                pending = frontend.submit(
                    plan, session=sub.session,
                    client=sub.client, deadline_ms=sub.deadline_ms)
            except Exception as e:
                # ANY submit-time failure — shedding (the typed
                # rejection) or otherwise — is delivered as this fire's
                # error: it must never escape into the committer (the
                # commit already published durably) nor starve the
                # remaining subscriptions of their fires.
                sub._deliver(seq, table, error=e)
                if isinstance(e, ServingRejectedError):
                    rejected += 1
                continue
            pending.on_done(_delivery_callback(sub, seq, table))
            fired += 1
        return fired, rejected

    def _fire_wave(self, frontend, table: str,
                   group: List[tuple]) -> tuple:
        """One same-template group through submit_wave: the returned
        slots align with the group — a PendingQuery per admitted member
        or the exception its solo submit would have raised."""
        fired = rejected = 0
        try:
            results = frontend.submit_wave(
                [(plan, sub.session, sub.client, sub.deadline_ms)
                 for sub, _seq, plan, _key in group])
        except Exception as e:
            # submit_wave itself must not raise, but if it ever does,
            # every member observes the failure — exactly-once still.
            results = [e] * len(group)
        for (sub, seq, _plan, _key), res in zip(group, results):
            if isinstance(res, Exception):
                sub._deliver(seq, table, error=res)
                if isinstance(res, ServingRejectedError):
                    rejected += 1
                continue
            res.on_done(_delivery_callback(sub, seq, table))
            fired += 1
        return fired, rejected

    def _emit(self, session, table: str, fired: int,
              rejected: int, groups: int = 0) -> None:
        try:
            from ..telemetry.events import StandingQueryEvent
            from ..telemetry.logging import get_logger
            get_logger(session.hs_conf.event_logger_class()).log_event(
                StandingQueryEvent(
                    message=(f"commit re-fired {fired} standing "
                             f"quer{'y' if fired == 1 else 'ies'}"
                             + (f" in {groups} shared-scan "
                                f"group{'s' if groups != 1 else ''}"
                                if groups else "")
                             + (f", shed {rejected}" if rejected else "")),
                    table=table, fired=fired, rejected=rejected,
                    groups=groups))
        except Exception:
            pass

    def live_count(self) -> int:
        with self._lock:
            return len(self._subs)

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["live"] = len(self._subs)
        return out


def _source_roots(plan) -> frozenset:
    """Absolute root paths of every file-based relation leaf (empty
    when any leaf is opaque — such plans fire on every commit)."""
    import os
    roots = set()
    try:
        for leaf in plan.collect_leaves():
            relation = getattr(leaf, "relation", None)
            if relation is None or not hasattr(relation, "root_paths"):
                return frozenset()
            for p in relation.root_paths:
                roots.add(os.path.abspath(p))
    except Exception:
        return frozenset()
    return frozenset(roots)


def _delivery_callback(sub: Subscription, seq: int, table: str):
    """Completion hook run on the serving worker at query finish; the
    subscription state rides in as explicit arguments (never ambient
    context — pool threads inherit none, the r14 contract)."""

    def _on_done(pending) -> None:
        sub._deliver(seq, table, result=pending._result,
                     error=pending._error)

    return _on_done
