"""Streaming ingestion tier: append/commit with load-time indexing.

Three pillars (ROADMAP item 3, "Only Aggressive Elephants are Fast
Elephants" — indexes built during upload cost near nothing):

- **append/commit** (ingest.py): ``Hyperspace.append(table, batch)``
  stages record batches invisibly (hidden staging dir) and sketches +
  bucket-routes them on-device as they land; ``commit()`` publishes the
  batch files and the prebuilt index deltas atomically through the
  existing op-log protocol, so covering indexes and skipping sketches
  are fresh at commit time with no separate refresh pass.
- **compaction** (compaction.py): ``compact()`` folds superseded op-log
  entries into a checkpoint entry and vacuums unreferenced data
  versions, bounding what a long-lived append workload accumulates.
- **standing queries** (subscriptions.py): ``ServingFrontend.subscribe``
  registers a plan that re-fires per commit through the serving worker
  pool — a standing query is a cached plan plus the r06 invalidation
  hook.
"""

from .constants import StreamingConstants  # noqa: F401
