"""Streaming-ingestion tier constants: config keys + on-disk layout.

The `hyperspace.tpu.streaming.*` family configures the append/commit
ingestion path (streaming/ingest.py), op-log compaction
(streaming/compaction.py), and standing-query subscriptions
(streaming/subscriptions.py). Every key is documented in
docs/configuration.md §Streaming (the doc-drift lint gate enforces).
"""

from __future__ import annotations


class StreamingConstants:
    # Master switch for the append/commit API. Off, ``Hyperspace.append``
    # raises — the lake stays read-mostly exactly as before this tier.
    ENABLED = "hyperspace.tpu.streaming.enabled"
    ENABLED_DEFAULT = "true"

    # Backpressure: the most batches one table may stage before a
    # commit() must land them (append raises past it).
    MAX_STAGED_BATCHES = "hyperspace.tpu.streaming.maxStagedBatches"
    MAX_STAGED_BATCHES_DEFAULT = "64"

    # Load-time indexing: sketch + bucket-route every staged batch
    # on-device at append() time so covering indexes and skipping
    # sketches are fresh at commit with no separate refresh pass. Off,
    # commit() lands only the source files (hybrid scan still merges
    # them at query time; a later refresh_index catches the indexes up).
    LOAD_TIME_INDEXING = "hyperspace.tpu.streaming.loadTimeIndexing.enabled"
    LOAD_TIME_INDEXING_DEFAULT = "true"

    # compact() folds a log only when it holds at least this many
    # superseded (non-tip) entries — folding a near-empty log buys
    # nothing and costs a checkpoint write.
    COMPACTION_MIN_ENTRIES = "hyperspace.tpu.streaming.compaction.minEntries"
    COMPACTION_MIN_ENTRIES_DEFAULT = "2"

    # Group commit (streaming/ingest.CommitCoordinator): concurrent
    # commit() callers coalesce into one publication wave — one op-log
    # entry per table and one delta build per index per wave. Off,
    # every commit() publishes its own staged batches exactly as before
    # this tier (byte-identical results, just more op-log entries).
    GROUP_COMMIT_ENABLED = "hyperspace.tpu.streaming.groupCommit.enabled"
    GROUP_COMMIT_ENABLED_DEFAULT = "true"
    # Linger before the wave leader pops the queue, letting more appends
    # and committers pile into the same wave. 0 = publish immediately.
    GROUP_COMMIT_WINDOW_MS = "hyperspace.tpu.streaming.groupCommit.windowMs"
    GROUP_COMMIT_WINDOW_MS_DEFAULT = "0"
    # Most staged batches one publication wave may carry; a deeper queue
    # is drained as consecutive sub-waves so undo/redo stays bounded.
    GROUP_COMMIT_MAX_WAVE = "hyperspace.tpu.streaming.groupCommit.maxWave"
    GROUP_COMMIT_MAX_WAVE_DEFAULT = "256"

    # Continuous sources (streaming/sources.py): poll cadence for the
    # directory/log tailers and how many appends they buffer before
    # driving a commit themselves.
    SOURCE_POLL_MS = "hyperspace.tpu.streaming.source.pollMs"
    SOURCE_POLL_MS_DEFAULT = "50"
    SOURCE_COMMIT_BATCHES = "hyperspace.tpu.streaming.source.commitBatches"
    SOURCE_COMMIT_BATCHES_DEFAULT = "8"

    # Blocking backpressure: how long a blocking append (continuous
    # sources; CommitQueue.push(block=True)) waits for staged-batch
    # budget before giving up. The plain append() API keeps its
    # raise-on-full default and never waits.
    BACKPRESSURE_TIMEOUT_MS = \
        "hyperspace.tpu.streaming.backpressure.timeoutMs"
    BACKPRESSURE_TIMEOUT_MS_DEFAULT = "30000"

    # Standing-query subscriptions (serving/frontend.subscribe).
    SUBSCRIPTIONS_MAX = "hyperspace.tpu.streaming.subscriptions.max"
    SUBSCRIPTIONS_MAX_DEFAULT = "64"
    SUBSCRIPTION_HISTORY = \
        "hyperspace.tpu.streaming.subscriptions.historyDepth"
    SUBSCRIPTION_HISTORY_DEFAULT = "16"

    # On-disk layout. Staging dirs start with '_' so the data-path filter
    # (util/file_utils._is_hidden) keeps staged batches invisible to
    # every scan until commit() publishes them.
    STAGING_DIR = "_hst_staging"
    # Published batch files: part-ingest-<batch id>.parquet in the table
    # dir (recovery matches the prefix when rolling a torn commit back).
    INGEST_FILE_PREFIX = "part-ingest-"
    # Per-table streaming op-logs live under
    # <systemPath>/_streaming/<table key>/_hyperspace_log — the leading
    # '_' keeps recover_indexes' index sweep from treating the parent as
    # an index; streaming recovery sweeps it explicitly.
    STREAMING_DIR = "_streaming"

    # Checkpoint-entry properties written by compact().
    COMPACTION_GENERATION_PROPERTY = "compactionGeneration"
    COMPACTED_THROUGH_PROPERTY = "compactedThrough"
