"""Framework exceptions (parity: HyperspaceException.scala, actions/package.scala)."""

from __future__ import annotations


class HyperspaceException(Exception):
    """Base exception for all framework errors."""


class NoChangesException(HyperspaceException):
    """Raised by actions when there is nothing to do; aborts the transaction
    as a no-op (reference: actions/Action.scala NoChangesException handling)."""
