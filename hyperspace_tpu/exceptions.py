"""Framework exceptions (parity: HyperspaceException.scala, actions/package.scala)."""

from __future__ import annotations


class HyperspaceException(Exception):
    """Base exception for all framework errors."""


class NoChangesException(HyperspaceException):
    """Raised by actions when there is nothing to do; aborts the transaction
    as a no-op (reference: actions/Action.scala NoChangesException handling)."""


class ServingRejectedError(HyperspaceException):
    """Raised by ServingFrontend.submit when admission control refuses a
    query (queue at ``serving.queueDepth`` or in-flight input bytes past
    ``serving.admission.maxBytes``). Back off and resubmit — rejection is
    load shedding, not failure of the query itself."""


class QueryDeadlineError(HyperspaceException):
    """Raised when a query's cooperative deadline
    (``ServingFrontend.submit(deadline_ms=...)`` or
    ``hyperspace.tpu.robustness.deadlineMs``) expires: checked at the
    executor's per-node stage boundary, the parallel-io wait loops, and
    SPMD dispatch (robustness layer, serving/context.check_deadline).
    The query is cancelled, its serving slot freed — the answer was NOT
    computed, so the degradation ladders never absorb this error."""
