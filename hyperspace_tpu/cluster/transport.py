"""Length-framed TCP transport of the serving cluster.

The repo's first owned communication backend: 8-byte big-endian length
header + a pickled payload (the result-cache spill codec, pointed at a
socket instead of a file), one request/response per connection. The
server accept loop and each accepted connection run on
``parallel/io.spawn_daemon`` threads — the one sanctioned thread
spawner (HS211) — and this module plus telemetry/exposition.py's HTTP
exporter are the only sanctioned socket sites in the package (HS341):
every other module rides this transport, so framing, deadlines, and
r14 retry semantics live in exactly one place.

Request objects are plain dicts with an ``op`` key; the server's
handler returns the response object (any picklable). A handler error
becomes ``{"ok": False, "error": ...}`` so a sick worker degrades the
caller instead of wedging it.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Callable, Tuple

from ..parallel import io as pio
from ..robustness import retry

_HEADER = struct.Struct(">Q")
# Frames past this are protocol corruption, not data (forwarded host
# tables are far smaller; a garbage header must not drive a huge read).
MAX_FRAME_BYTES = 1 << 31


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(n - len(buf), 1 << 20))
        if not chunk:
            raise ConnectionError("cluster transport: peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def send_obj(sock: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def recv_obj(sock: socket.socket) -> Any:
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if length > MAX_FRAME_BYTES:
        raise ConnectionError(
            f"cluster transport: frame of {length} bytes over the cap")
    return pickle.loads(_recv_exact(sock, length))


def send_request(host: str, port: int, obj: Any, *,
                 timeout_s: float = 2.0, attempts: int = 1,
                 session=None) -> Any:
    """One framed request/response round trip. ``timeout_s`` bounds
    every socket operation of each attempt (the deadline contract);
    with ``attempts`` > 1 transient socket errors retry with r14
    backoff and the ORIGINAL error surfaces on exhaustion."""

    def _once() -> Any:
        with socket.create_connection((host, port),
                                      timeout=timeout_s) as sock:
            sock.settimeout(timeout_s)
            send_obj(sock, obj)
            return recv_obj(sock)

    if attempts <= 1:
        return _once()
    policy = retry.RetryPolicy(max_attempts=attempts)
    return retry.call(_once, where="cluster.transport", policy=policy,
                      session=session)


class Server:
    """Accept loop + per-connection daemon threads over one handler.

    ``handler(request) -> response`` runs on the connection's thread,
    so a blocking op (the gather hub waiting for every rank) stalls
    only its own connection. Start binds and returns immediately; the
    bound port is ``self.port`` (ephemeral bind publishes the real
    one). ``stop()`` closes the listener; in-flight connections finish
    on their own threads.
    """

    def __init__(self, bind: str, port: int,
                 handler: Callable[[Any], Any], *, name: str = "cluster"):
        self._handler = handler
        self._name = name
        self._stopped = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((bind, port))
            self._listener.listen(64)
        except BaseException:
            self._listener.close()
            raise
        self.host, self.port = self._listener.getsockname()[:2]
        pio.spawn_daemon(f"hst-{name}-accept", self._accept_loop)

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            pio.spawn_daemon(f"hst-{self._name}-conn",
                             lambda c=conn: self._serve_one(c))

    def _serve_one(self, conn: socket.socket) -> None:
        try:
            with conn:
                conn.settimeout(300.0)
                request = recv_obj(conn)
                try:
                    response = self._handler(request)
                except Exception as e:
                    response = {"ok": False,
                                "error": f"{type(e).__name__}: {e}"}
                send_obj(conn, response)
        except Exception:
            pass  # a torn connection is the peer's problem, not ours

    def stop(self) -> None:
        self._stopped.set()
        # shutdown() first: close() alone does not wake a thread blocked
        # in accept(), and the kernel keeps the port listening until
        # that syscall returns — a "stopped" server would still accept.
        try:
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass


def address_of(member) -> Tuple[str, int]:
    """(host, port) of a membership record (dict or MemberInfo)."""
    if isinstance(member, dict):
        return str(member["host"]), int(member["port"])
    return str(member.host), int(member.port)
