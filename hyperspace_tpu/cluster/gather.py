"""Host-side allgather seam: the one transport decision point.

Every host-side ``process_allgather`` in the engine (mesh assembly's
row-stats gather, distributed build's dictionary-union gathers) routes
through :func:`allgather`. Single process returns the array untouched —
byte-identical to ``multihost_utils.process_allgather`` (asserted by
tests). Multi-process picks a path per ``cluster.gather``:

- ``auto`` — try the backend's native collective once; when the backend
  lacks multiprocess collectives (this image's CPU jax without gloo),
  fall back to the host-TCP path below and remember the verdict.
- ``native`` — always ``multihost_utils.process_allgather`` (real
  ``jax.distributed`` keeps right of way).
- ``host`` — always the owned path: a star over the cluster transport.
  Rank 0 runs a gather hub (one blocking slot per sequence number);
  every rank — rank 0 included, via loopback — sends its array and
  blocks until the hub answers with all ``n`` parts stacked in rank
  order. Sequence numbers are per-process monotonic, and SPMD program
  order keeps them aligned across ranks. Rendezvous is a port file
  under the system temp dir keyed by the coordinator address.

The result always matches ``process_allgather``'s contract at N>1:
shape ``(nproc, *x.shape)``, parts stacked in process order.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
import time
from typing import List, Optional

import numpy as np

from ..parallel import io as pio
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from . import transport

_HUB_LOCK = threading.Lock()
_HUB = None          # rank 0's running (_GatherHub, Server) pair
_SEQ = 0             # per-process monotonic gather sequence number
_NATIVE_OK = None    # auto mode's cached native-collective verdict
_FORCED = None       # test seam: force "native"/"host" below the conf


def force_mode(mode: Optional[str]) -> None:
    """Pin the gather path ("native"/"host"), or None to un-pin; the
    test seam for exercising the owned path without conf plumbing."""
    global _FORCED
    with _HUB_LOCK:
        _FORCED = mode


def reset_for_tests() -> None:
    """Tear down the hub + caches so each test gets a fresh star."""
    global _HUB, _SEQ, _NATIVE_OK, _FORCED
    with _HUB_LOCK:
        if _HUB is not None:
            _HUB[1].stop()
        _HUB = None
        _SEQ = 0
        _NATIVE_OK = None
        _FORCED = None


def _mode() -> str:
    with _HUB_LOCK:
        if _FORCED is not None:
            return _FORCED
    session = pio.active_session()
    if session is not None:
        try:
            return session.hs_conf.cluster_gather_mode()
        except Exception:
            return "auto"
    return "auto"


def _gather_timeout_s() -> float:
    session = pio.active_session()
    if session is not None:
        try:
            return session.hs_conf.cluster_gather_timeout_ms() / 1000.0
        except Exception:
            return 60.0
    return 60.0


def allgather(x: np.ndarray) -> np.ndarray:
    """Stack ``x`` across every process: the engine's one allgather."""
    import jax
    n = jax.process_count()
    x = np.asarray(x)
    if n <= 1:
        return x  # process_allgather's own single-process identity
    mode = _mode()
    if mode == "native":
        return _native_allgather(x)
    if mode == "host":
        return _host_path(x, jax.process_index(), n)
    # auto: native keeps right of way; remember a backend that can't.
    global _NATIVE_OK
    with _HUB_LOCK:
        verdict = _NATIVE_OK
    if verdict is not False:
        try:
            out = _native_allgather(x)
            if verdict is None:
                with _HUB_LOCK:
                    _NATIVE_OK = True
            return out
        except Exception:
            if verdict is True:
                raise  # native worked before: this failure is real
            with _HUB_LOCK:
                _NATIVE_OK = False
    return _host_path(x, jax.process_index(), n)


def _native_allgather(x: np.ndarray) -> np.ndarray:
    from jax.experimental import multihost_utils as mhu
    return np.asarray(mhu.process_allgather(x))


def _host_path(x: np.ndarray, rank: int, n: int) -> np.ndarray:
    global _SEQ
    with _HUB_LOCK:
        _SEQ += 1
        seq = _SEQ
    with _trace.span(SN.CLUSTER_GATHER):
        return host_allgather(x, rank=rank, n=n, seq=seq,
                              rendezvous_dir=_rendezvous_dir(),
                              timeout_s=_gather_timeout_s())


def _rendezvous_dir() -> str:
    """One rendezvous dir per cluster, keyed by the coordinator address
    recorded at ``initialize_multihost`` time."""
    from ..parallel import multihost
    coord = multihost.last_coordinator_address() or "local"
    digest = hashlib.md5(coord.encode("utf-8")).hexdigest()[:12]
    return os.path.join(tempfile.gettempdir(), f"hst-gather-{digest}")


class _GatherHub:
    """Rank 0's accumulator: one slot per sequence number, each
    collecting ``n`` parts then answering every blocked rank."""

    def __init__(self, n: int):
        self._n = n
        self._cond = threading.Condition()
        self._slots = {}  # seq -> {"parts": {rank: array}, "served": int}

    def handle(self, request: dict) -> dict:
        if request.get("op") != "gather":
            return {"ok": False, "error": "gather hub: unknown op"}
        seq = int(request["seq"])
        rank = int(request["rank"])
        deadline = time.monotonic() + float(request.get("timeout_s", 60.0))
        with self._cond:
            slot = self._slots.setdefault(seq, {"parts": {}, "served": 0})
            slot["parts"][rank] = request["payload"]
            if len(slot["parts"]) >= self._n:
                self._cond.notify_all()
            while len(slot["parts"]) < self._n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return {"ok": False,
                            "error": f"gather hub: seq {seq} timed out at "
                                     f"{len(slot['parts'])}/{self._n} parts"}
                self._cond.wait(remaining)
            parts = [slot["parts"][r] for r in range(self._n)]
            slot["served"] += 1
            if slot["served"] >= self._n:
                del self._slots[seq]  # every rank answered: slot drained
        return {"ok": True, "parts": parts}


def host_allgather(x: np.ndarray, *, rank: int, n: int, seq: int,
                   rendezvous_dir: str,
                   timeout_s: float = 60.0) -> np.ndarray:
    """The owned star allgather. Explicit rank/n/seq so tests can run
    every rank as a thread of one process."""
    host, port = _hub_address(rank, n, rendezvous_dir, timeout_s)
    response = transport.send_request(
        host, port,
        {"op": "gather", "seq": seq, "rank": rank, "n": n,
         "payload": np.asarray(x), "timeout_s": timeout_s},
        timeout_s=timeout_s, attempts=3)
    if not response.get("ok"):
        raise RuntimeError(f"cluster gather failed: "
                           f"{response.get('error', 'unknown')}")
    parts: List[np.ndarray] = [np.asarray(p) for p in response["parts"]]
    return np.stack(parts)


def _hub_address(rank: int, n: int, rendezvous_dir: str,
                 timeout_s: float) -> tuple:
    """Rank 0 starts the hub (idempotently) and publishes its port;
    everyone reads the port file, polling until rank 0 shows up."""
    global _HUB
    portfile = os.path.join(rendezvous_dir, "hub-port")
    if rank == 0:
        with _HUB_LOCK:
            if _HUB is None:
                hub = _GatherHub(n)
                server = transport.Server("127.0.0.1", 0, hub.handle,
                                          name="cluster-gather")
                os.makedirs(rendezvous_dir, exist_ok=True)
                tmp = portfile + f".tmp.{os.getpid()}"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(f"{server.host} {server.port}")
                os.replace(tmp, portfile)
                _HUB = (hub, server)
            hub, server = _HUB
        return server.host, server.port
    deadline = time.monotonic() + timeout_s
    while True:
        try:
            with open(portfile, "r", encoding="utf-8") as f:
                text = f.read().strip()
            if text:
                host, port = text.split()
                return host, int(port)
        except OSError:
            pass  # rank 0 not up yet
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"cluster gather: no hub port file at {portfile} "
                f"within {timeout_s}s")
        time.sleep(0.02)
