"""Shared-nothing serving cluster: N processes over one lake, one
serving system.

Tiers (each its own module, bottom up):

- :mod:`.transport` — length-framed TCP request/response, the repo's
  first owned communication backend (the one sanctioned socket site,
  HS341, beside telemetry/exposition.py's HTTP exporter).
- :mod:`.membership` — lake-resident ``_hst_cluster/`` roster:
  register put-if-absent, heartbeat by refresh, expire by staleness.
- :mod:`.hashring` — consistent-hash sharding of the result cache by
  plan-fingerprint digest (~1/N keys move per membership change).
- :mod:`.gather` — the host-side allgather seam every
  ``process_allgather`` call site routes through (native collectives
  keep right of way; the owned host-TCP star revives multiprocess CPU
  backends without them).
- :mod:`.worker` — the node: server dispatch, router, commit
  broadcast, fleet surfaces.

Everything is governed by the ``hyperspace.tpu.cluster.*`` conf family
(docs/configuration.md §Cluster); disabled — the default — is a hard
no-op asserted byte-identical by tests.
"""

from .constants import ClusterConstants  # noqa: F401
