"""Lake-resident cluster membership: register, heartbeat, expire.

One JSON record per worker under ``<system path>/_hst_cluster/``,
following the op-log store's put-if-absent idiom: registration is an
O_EXCL create (a second claimant of the same id loses the race and must
pick another identity), heartbeat is an atomic refresh (tmp +
``os.replace``) of the record with a fresh timestamp, and expiry is
read-side staleness — a record whose heartbeat is older than
``cluster.staleness.ms`` is a dead worker and gets routed around (the
r14 degradation-ladder contract: death never needs a cleanup writer).

Readers tolerate torn or half-written records by skipping them; the
next heartbeat rewrite repairs the file.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from dataclasses import dataclass
from typing import List, Optional

from ..parallel import io as pio
from .constants import CLUSTER_DIR_NAME


@dataclass(frozen=True)
class MemberInfo:
    worker_id: str
    host: str
    port: int
    pid: int
    started_ms: float
    heartbeat_ms: float


def membership_dir(session) -> str:
    """The roster directory of the session's lake (conf override, else
    ``<index system path>/_hst_cluster``)."""
    override = session.hs_conf.cluster_dir()
    if override:
        return override
    return os.path.join(session.hs_conf.system_path(), CLUSTER_DIR_NAME)


def _record_path(root: str, worker_id: str) -> str:
    safe = re.sub(r"[^A-Za-z0-9_.-]", "_", worker_id)
    return os.path.join(root, f"member-{safe}.json")


def _now_ms() -> float:
    return time.time() * 1000.0


class Membership:
    """One worker's view of the roster: its own record plus reads of
    everyone else's, expiring by staleness."""

    def __init__(self, session, worker_id: str, host: str, port: int):
        self._session = session
        self._root = membership_dir(session)
        self.worker_id = worker_id
        self._host = host
        self._port = port
        self._started_ms = _now_ms()
        self._stop = threading.Event()

    # -- registration / heartbeat -------------------------------------

    def register(self) -> None:
        """Put-if-absent claim of this worker's identity. Raises
        FileExistsError when a LIVE record already holds the id; a
        stale corpse under the same id is reclaimed in place."""
        os.makedirs(self._root, exist_ok=True)
        path = _record_path(self._root, self.worker_id)
        record = self._record()
        try:
            with open(path, "x", encoding="utf-8") as f:
                f.write(record)
        except FileExistsError:
            existing = _read_record(path)
            if existing is not None and not self._is_stale(existing):
                raise
            _atomic_write(path, record)  # reclaim the corpse

    def start_heartbeat(self) -> None:
        interval_s = max(
            self._session.hs_conf.cluster_heartbeat_ms() / 1000.0, 0.05)

        def _loop() -> None:
            while not self._stop.wait(interval_s):
                try:
                    self.heartbeat()
                except OSError:
                    pass  # lake hiccup; the next beat retries

        pio.spawn_daemon("hst-cluster-heartbeat", _loop)

    def heartbeat(self) -> None:
        _atomic_write(_record_path(self._root, self.worker_id),
                      self._record())

    def leave(self) -> None:
        self._stop.set()
        try:
            os.remove(_record_path(self._root, self.worker_id))
        except OSError:
            pass  # already gone, or the lake will expire us by staleness

    def _record(self) -> str:
        return json.dumps({
            "worker_id": self.worker_id, "host": self._host,
            "port": self._port, "pid": os.getpid(),
            "started_ms": self._started_ms, "heartbeat_ms": _now_ms()})

    # -- roster reads -------------------------------------------------

    def _is_stale(self, info: MemberInfo) -> bool:
        horizon = self._session.hs_conf.cluster_staleness_ms()
        return _now_ms() - info.heartbeat_ms > horizon

    def live_members(self) -> List[MemberInfo]:
        """Every non-stale record, this worker's included, sorted by
        worker id (a stable roster order for the ring and the tests)."""
        out: List[MemberInfo] = []
        try:
            names = sorted(os.listdir(self._root))
        except OSError:
            return out
        for name in names:
            if not (name.startswith("member-") and name.endswith(".json")):
                continue
            info = _read_record(os.path.join(self._root, name))
            if info is not None and not self._is_stale(info):
                out.append(info)
        return sorted(out, key=lambda m: m.worker_id)

    def peers(self) -> List[MemberInfo]:
        return [m for m in self.live_members()
                if m.worker_id != self.worker_id]


def _read_record(path: str) -> Optional[MemberInfo]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            d = json.loads(f.read())
        return MemberInfo(
            worker_id=str(d["worker_id"]), host=str(d["host"]),
            port=int(d["port"]), pid=int(d["pid"]),
            started_ms=float(d["started_ms"]),
            heartbeat_ms=float(d["heartbeat_ms"]))
    except (OSError, ValueError, KeyError, TypeError):
        return None  # torn write or foreign file: skip, don't crash


def _atomic_write(path: str, text: str) -> None:
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)
