"""Consistent-hash ring sharding the result cache across the fleet.

Each member contributes ``vnodes`` virtual points (md5 of
``"<worker_id>#<i>"``) on a 2**128 ring; a key's owner is the first
point clockwise from the key's own md5 position. Membership churn
moves only the keys whose clockwise arcs changed — ~1/N of them per
joined/left member (the unit tests assert the bound) — so a worker
death invalidates one shard's routing, not the whole cache placement.

The ring is immutable: the router rebuilds one from the current live
roster per decision, which keeps routing a pure function of membership
(no locked mutable ring to keep coherent across threads).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import List, Optional, Sequence


def _point(token: str) -> int:
    return int(hashlib.md5(token.encode("utf-8")).hexdigest(), 16)


class HashRing:
    """Immutable consistent-hash ring over worker ids."""

    __slots__ = ("_points", "_owners", "_members")

    def __init__(self, member_ids: Sequence[str], vnodes: int = 64):
        pairs = []
        for wid in sorted(set(member_ids)):
            for i in range(max(int(vnodes), 1)):
                pairs.append((_point(f"{wid}#{i}"), wid))
        pairs.sort()
        self._points = [p for p, _ in pairs]
        self._owners = [w for _, w in pairs]
        self._members = tuple(sorted(set(member_ids)))

    @property
    def members(self) -> tuple:
        return self._members

    def __len__(self) -> int:
        return len(self._members)

    def owner(self, key: str) -> Optional[str]:
        """Worker id owning ``key`` (a digest string); None on an
        empty ring."""
        if not self._points:
            return None
        idx = bisect.bisect_right(self._points, _point(key))
        if idx == len(self._points):
            idx = 0  # wrap: first point clockwise from the top
        return self._owners[idx]

    def owners(self, key: str, n: int) -> List[str]:
        """First ``n`` DISTINCT owners clockwise from ``key`` — the
        replica set a future replication tier would write through."""
        if not self._points:
            return []
        out: List[str] = []
        idx = bisect.bisect_right(self._points, _point(key))
        for step in range(len(self._points)):
            wid = self._owners[(idx + step) % len(self._points)]
            if wid not in out:
                out.append(wid)
                if len(out) >= n:
                    break
        return out
