"""Config keys of the shared-nothing serving cluster.

Key literals live here (not inline) because the static-analysis env/
config gates treat config.py as the one sanctioned reader and require
every ``hyperspace.tpu.*`` literal to appear in docs/configuration.md
(scripts/analysis: HS202 / doc-drift) — see §Cluster there for
semantics and defaults.

No jax imports: config.py pulls this in at import time.
"""

from __future__ import annotations


# Directory name under the index system path holding the membership
# records (kept out of compaction/recovery's op-log walks: it contains
# no _hyperspace_log subdirectory, so the log sweeps skip it naturally).
CLUSTER_DIR_NAME = "_hst_cluster"


class ClusterConstants:
    # Master switch. Default OFF and a hard no-op: no sockets, no
    # membership records, no routing — byte-identical execution (tests
    # assert it).
    ENABLED = "hyperspace.tpu.cluster.enabled"
    ENABLED_DEFAULT = "false"

    # Stable worker identity; empty means an auto-generated
    # ``<host>-<pid>`` label. Shows up in membership records, forward/
    # broadcast events, and the OpenMetrics ``worker`` label.
    WORKER_ID = "hyperspace.tpu.cluster.worker.id"
    WORKER_ID_DEFAULT = ""

    # Transport bind address and port ("0" picks an ephemeral port; the
    # bound port is what membership publishes).
    BIND = "hyperspace.tpu.cluster.bind"
    BIND_DEFAULT = "127.0.0.1"
    PORT = "hyperspace.tpu.cluster.port"
    PORT_DEFAULT = "0"

    # Membership directory override; empty means
    # ``<index system path>/_hst_cluster`` (lake-resident — every
    # worker over the lake sees one roster).
    DIR = "hyperspace.tpu.cluster.dir"
    DIR_DEFAULT = ""

    # Heartbeat refresh cadence and the staleness horizon past which a
    # member is treated as dead and routed around.
    HEARTBEAT_MS = "hyperspace.tpu.cluster.heartbeat.ms"
    HEARTBEAT_MS_DEFAULT = "2000"
    STALENESS_MS = "hyperspace.tpu.cluster.staleness.ms"
    STALENESS_MS_DEFAULT = "10000"

    # Consistent-hash router on the serving frontend: forward a
    # submission to the result-cache shard owner. Effective only when
    # the cluster itself is enabled.
    ROUTING_ENABLED = "hyperspace.tpu.cluster.routing.enabled"
    ROUTING_ENABLED_DEFAULT = "true"

    # Forward deadline; an unreachable or slow owner degrades to local
    # execution (byte-identical) inside this bound.
    FORWARD_TIMEOUT_MS = "hyperspace.tpu.cluster.forward.timeoutMs"
    FORWARD_TIMEOUT_MS_DEFAULT = "2000"

    # Transport retry budget (r14 semantics: transient errors retry
    # with backoff, non-transient surface immediately).
    RETRY_MAX_ATTEMPTS = "hyperspace.tpu.cluster.retry.maxAttempts"
    RETRY_MAX_ATTEMPTS_DEFAULT = "2"

    # Commit-notification broadcast so standing queries fire on every
    # worker, not just the committer's process.
    BROADCAST_ENABLED = "hyperspace.tpu.cluster.broadcast.enabled"
    BROADCAST_ENABLED_DEFAULT = "true"

    # Virtual nodes per member on the hash ring (more vnodes = smoother
    # key spread, slightly larger ring).
    VNODES = "hyperspace.tpu.cluster.vnodes"
    VNODES_DEFAULT = "64"

    # Host-side allgather seam: "auto" tries the backend's native
    # collective once and falls back to the host-TCP path when the
    # backend lacks multiprocess collectives; "native"/"host" force a
    # path (tests pin "host" to exercise the shim).
    GATHER = "hyperspace.tpu.cluster.gather"
    GATHER_DEFAULT = "auto"

    # Host-TCP gather rendezvous deadline (seconds a rank waits for the
    # full stack before surfacing a timeout).
    GATHER_TIMEOUT_MS = "hyperspace.tpu.cluster.gather.timeoutMs"
    GATHER_TIMEOUT_MS_DEFAULT = "60000"
