"""Cluster node: one process's seat in the shared-nothing fleet.

A :class:`ClusterNode` ties the tiers together: the transport server
(dispatching ``ping``/``forward``/``commit``/``metrics`` requests), the
lake-resident membership record with its heartbeat, the consistent-hash
router the serving frontend consults per submission, and the commit
broadcast that makes standing queries fire on every worker.

The node is lazy and process-default: ``get_node(session)`` starts it
on first use when ``cluster.enabled`` is true and returns None
otherwise — the disabled path is one conf read and a hard no-op
(asserted byte-identical by tests). Every degradation follows the r14
ladder: an unreachable owner, a refused forward, or an injected
``cluster.forward`` fault falls back to local execution with identical
bytes; a failed ``cluster.broadcast`` costs only that peer's
standing-query firing, never the commit.
"""

from __future__ import annotations

import os
import pickle
import platform
import threading
import time
from typing import Optional

from ..robustness import fault_names as FN
from ..robustness import faults as _faults
from ..telemetry import span_names as SN
from ..telemetry import trace as _trace
from ..telemetry import metric_names as MN
from . import transport
from .hashring import HashRing
from .membership import Membership, MemberInfo

_NODE = None
_NODE_LOCK = threading.Lock()
# A forward handler's own submit must never re-route (membership drift
# could ping-pong a query between owners forever); thread-local because
# each handler runs on its own connection thread.
_HANDLING = threading.local()


def get_node(session) -> Optional["ClusterNode"]:
    """The process-default node, started lazily; None when the cluster
    is disabled (the ONE cheap check every off-path pays)."""
    global _NODE
    if not session.hs_conf.cluster_enabled():
        return None
    node = _NODE
    if node is not None:
        return node
    with _NODE_LOCK:
        if _NODE is None:
            _NODE = ClusterNode(session)
        return _NODE


def maybe_node() -> Optional["ClusterNode"]:
    """The running node, if any — never starts one (the exposition
    label and stats surfaces must not boot a cluster as a side
    effect)."""
    return _NODE


def shutdown_for_tests() -> None:
    global _NODE
    with _NODE_LOCK:
        node = _NODE
        _NODE = None
    if node is not None:
        node.stop()


class ClusterNode:
    """One worker: transport server + membership + router + broadcast."""

    def __init__(self, session):
        self._session = session
        conf = session.hs_conf
        self._lock = threading.Lock()
        self._stats = {
            "forwarded": 0, "forward_hits": 0, "forward_fallbacks": 0,
            "forward_served": 0, "forward_cache_hits": 0,
            "forward_executed": 0, "forward_refused": 0,
            "broadcasts_sent": 0, "broadcast_failures": 0,
            "broadcasts_received": 0,
        }
        self._server = transport.Server(
            conf.cluster_bind(), conf.cluster_port(), self._dispatch,
            name="cluster")
        wid = conf.cluster_worker_id() or f"{platform.node()}-{os.getpid()}"
        self.membership = Membership(session, wid, self._server.host,
                                     self._server.port)
        try:
            self.membership.register()
        except FileExistsError:
            # A LIVE record already holds the identity (two nodes, one
            # configured id): salt ours rather than hijack theirs.
            wid = f"{wid}-{self._server.port}"
            self.membership = Membership(session, wid, self._server.host,
                                         self._server.port)
            self.membership.register()
        self.worker_id = wid
        self.membership.start_heartbeat()
        from ..telemetry import metrics as _metrics
        _metrics.get_registry().register_collector(
            MN.COLLECTOR_CLUSTER, self.stats)
        from ..telemetry.events import ClusterJoinEvent
        self._emit(ClusterJoinEvent(
            message=f"cluster worker {wid} joined at "
                    f"{self._server.host}:{self._server.port}",
            worker_id=wid, host=self._server.host,
            port=self._server.port))

    def stop(self) -> None:
        from ..telemetry.events import ClusterLeaveEvent
        self._emit(ClusterLeaveEvent(
            message=f"cluster worker {self.worker_id} leaving",
            worker_id=self.worker_id))
        self.membership.leave()
        self._server.stop()

    # -- request dispatch ---------------------------------------------

    def _dispatch(self, request: dict):
        op = request.get("op")
        if op == "ping":
            return {"ok": True, "worker": self.worker_id}
        if op == "forward":
            return self._handle_forward(request)
        if op == "commit":
            return self._handle_commit(request)
        if op == "metrics":
            return self._handle_metrics(request)
        return {"ok": False, "error": f"cluster: unknown op {op!r}"}

    def _handle_forward(self, request: dict) -> dict:
        from ..serving.fingerprint import compute_key
        from ..serving.frontend import get_frontend
        plan = pickle.loads(request["plan"])
        key = compute_key(self._session, plan)
        if key is None or key.digest() != request.get("digest"):
            self._note(forward_refused=1)
            return {"ok": False,
                    "error": "fingerprint mismatch: sender and owner "
                             "disagree on the plan's cache key "
                             "(conf or lake drift)"}
        fe = get_frontend(self._session)
        cache = fe.result_cache()
        found = cache.get(key) if cache is not None else None
        if found is not None:
            table, _tier = found
            self._note(forward_served=1, forward_cache_hits=1)
            return {"ok": True, "hit": True, "table": table.to_host()}
        _HANDLING.active = True
        try:
            pending = fe.submit(plan, session=self._session,
                                client=request.get("client", ""),
                                deadline_ms=request.get("deadline_ms"))
        finally:
            _HANDLING.active = False
        table = pending.result(
            timeout=float(request.get("timeout_s", 30.0)))
        self._note(forward_served=1, forward_executed=1)
        return {"ok": True, "hit": False, "table": table.to_host()}

    def _handle_commit(self, request: dict) -> dict:
        from ..serving import frontend as _frontend
        table = str(request.get("table", ""))
        fired = 0
        for fe in _frontend.all_frontends():
            try:
                fe.notify_commit(self._session, table)
                fired += 1
            except Exception:
                pass  # one sick frontend must not mute the rest
        self._note(broadcasts_received=1)
        return {"ok": True, "frontends": fired}

    def _handle_metrics(self, request: dict) -> dict:
        from ..telemetry import metrics as _metrics
        return {"ok": True, "worker": self.worker_id,
                "metrics": _metrics.get_registry().snapshot()}

    # -- router -------------------------------------------------------

    def route_owner(self, digest: str) -> Optional[MemberInfo]:
        """The live member owning ``digest`` on the consistent-hash
        ring, or None when this worker (or nobody) owns it."""
        members = self.membership.live_members()
        if len(members) < 2:
            return None
        ring = HashRing([m.worker_id for m in members],
                        vnodes=self._session.hs_conf.cluster_vnodes())
        wid = ring.owner(digest)
        if wid is None or wid == self.worker_id:
            return None
        return next((m for m in members if m.worker_id == wid), None)

    def forward(self, owner: MemberInfo, plan, digest: str, *,
                client: str = "", deadline_ms: Optional[float] = None,
                est: int = 0):
        """Ship one submission to its shard owner; a finished
        PendingQuery on success, None to degrade to local execution."""
        from ..serving.context import next_query_id
        from ..serving.frontend import PendingQuery
        from ..telemetry.events import ClusterForwardEvent
        conf = self._session.hs_conf
        timeout_s = conf.cluster_forward_timeout_ms() / 1000.0
        t0 = time.perf_counter()
        try:
            with _trace.span(SN.CLUSTER_FORWARD) as sp:
                _faults.fault_point(FN.CLUSTER_FORWARD)
                response = transport.send_request(
                    owner.host, owner.port,
                    {"op": "forward", "digest": digest,
                     "plan": pickle.dumps(
                         plan, protocol=pickle.HIGHEST_PROTOCOL),
                     "client": client, "deadline_ms": deadline_ms,
                     "timeout_s": timeout_s, "origin": self.worker_id},
                    timeout_s=timeout_s,
                    attempts=conf.cluster_retry_max_attempts(),
                    session=self._session)
                if sp is not None:
                    sp.attrs["owner"] = owner.worker_id
                    sp.attrs["ok"] = bool(response.get("ok"))
            if not response.get("ok"):
                raise RuntimeError(
                    response.get("error", "forward refused"))
        except Exception as e:
            self._note(forward_fallbacks=1)
            _faults.note(cluster_forward_fallbacks=1)
            self._emit(ClusterForwardEvent(
                message=f"forward to {owner.worker_id} degraded to "
                        f"local execution: {type(e).__name__}: {e}",
                worker_id=self.worker_id, owner=owner.worker_id,
                key_digest=digest, ok=False,
                millis=(time.perf_counter() - t0) * 1000.0))
            return None
        hit = bool(response.get("hit"))
        pending = PendingQuery(query_id=next_query_id(), client=client,
                               estimated_bytes=est)
        pending._finish(result=response["table"])
        self._note(forwarded=1, forward_hits=int(hit))
        self._emit(ClusterForwardEvent(
            message=f"forwarded to {owner.worker_id} "
                    f"({'cache hit' if hit else 'executed'})",
            worker_id=self.worker_id, owner=owner.worker_id,
            key_digest=digest, ok=True, hit=hit,
            millis=(time.perf_counter() - t0) * 1000.0))
        return pending

    # -- commit broadcast ---------------------------------------------

    def broadcast_commit(self, table: str, batches: int = 0) -> int:
        """Send one commit notice to every live peer; delivered count.
        ``batches`` is the wave width — group commit coalesces a whole
        publication wave into this ONE notice, so a lost peer costs
        that peer one firing regardless of how many appends the wave
        carried. Per-peer failures degrade (that peer misses one
        firing) and are tallied, never raised."""
        from ..telemetry.events import ClusterBroadcastEvent
        conf = self._session.hs_conf
        if not conf.cluster_broadcast_enabled():
            return 0
        peers = self.membership.peers()
        if not peers:
            return 0
        timeout_s = conf.cluster_forward_timeout_ms() / 1000.0
        delivered = 0
        with _trace.span(SN.CLUSTER_BROADCAST) as sp:
            for peer in peers:
                try:
                    _faults.fault_point(FN.CLUSTER_BROADCAST)
                    response = transport.send_request(
                        peer.host, peer.port,
                        {"op": "commit", "table": table,
                         "origin": self.worker_id,
                         "batches": batches},
                        timeout_s=timeout_s,
                        attempts=conf.cluster_retry_max_attempts(),
                        session=self._session)
                    if response.get("ok"):
                        delivered += 1
                    else:
                        self._note(broadcast_failures=1)
                except Exception:
                    self._note(broadcast_failures=1)
            if sp is not None:
                sp.attrs["peers"] = len(peers)
                sp.attrs["delivered"] = delivered
        self._note(broadcasts_sent=delivered)
        self._emit(ClusterBroadcastEvent(
            message=f"commit notice for {table!r} "
                    + (f"({batches} batches) " if batches else "")
                    + f"delivered to {delivered}/{len(peers)} peers",
            worker_id=self.worker_id, table=table, peers=len(peers),
            delivered=delivered, batches=batches))
        return delivered

    # -- surfaces -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
        out["members"] = len(self.membership.live_members())
        return out

    def _note(self, **deltas) -> None:
        with self._lock:
            for k, v in deltas.items():
                self._stats[k] = self._stats.get(k, 0) + v

    def _emit(self, event) -> None:
        try:
            from ..telemetry.logging import get_logger
            get_logger(self._session.hs_conf.event_logger_class()
                       ).log_event(event)
        except Exception:
            pass  # observability must never fail the cluster op


def try_forward(session, plan, norm, *, client: str = "",
                deadline_ms: Optional[float] = None, est: int = 0):
    """The frontend's router hook: a finished PendingQuery when a
    remote shard owner answered, None to fall through to local
    execution (byte-identical). Called only when
    ``cluster_routing_enabled()`` already said yes."""
    if getattr(_HANDLING, "active", False):
        return None  # a forwarded execution never re-forwards
    node = get_node(session)
    if node is None:
        return None
    from ..serving.fingerprint import compute_key
    try:
        key = compute_key(session, plan, normalized=norm)
    except Exception:
        return None
    if key is None:
        return None  # uncacheable shape: no stable shard, run local
    digest = key.digest()
    owner = node.route_owner(digest)
    if owner is None:
        return None
    return node.forward(owner, plan, digest, client=client,
                        deadline_ms=deadline_ms, est=est)


def broadcast_commit(session, table: str, batches: int = 0) -> int:
    """The ingest hook: fan a commit notice out to the fleet (no-op
    when the cluster is disabled). One call per publication WAVE —
    ``batches`` says how many appends it carried."""
    node = get_node(session)
    if node is None:
        return 0
    return node.broadcast_commit(table, batches=batches)
