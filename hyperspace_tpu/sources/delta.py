"""Delta-analogue source provider: versioned commit-log tables.

Reference behavior mirrored (sources/delta/DeltaLakeFileBasedSource.scala:40,
DeltaLakeRelation.scala:34,187,152, DeltaLakeRelationMetadata.scala:25,45):

- signature = table version + path (no per-file hashing — the commit log
  version already fingerprints the file set);
- ``versionAsOf`` time-travel reads;
- index creation/refresh records a ``deltaVersionHistory`` property
  ("indexLogVer:deltaVer,…") via ``enrich_index_properties``;
- ``closest_index_log_version`` picks the index log version whose recorded
  delta version is nearest to the scanned snapshot (time-travel-aware index
  selection, DeltaLakeRelation.closestIndex semantics).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..exceptions import HyperspaceException
from ..lake.delta import DeltaTable, Snapshot
from ..schema import Schema
from ..util import hashing
from .interfaces import FileBasedRelation, FileBasedSourceProvider

DELTA_VERSION_HISTORY_PROPERTY = "deltaVersionHistory"
VERSION_AS_OF_OPTION = "versionAsOf"


class DeltaLakeRelation(FileBasedRelation):
    def __init__(self, path: str, options: Optional[Dict[str, str]] = None,
                 snapshot: Optional[Snapshot] = None):
        self._path = os.path.abspath(path)
        self._options = dict(options or {})
        self._table = DeltaTable(self._path)
        if snapshot is None:
            version = self._options.get(VERSION_AS_OF_OPTION)
            snapshot = self._table.snapshot(
                int(version) if version is not None else None)
        self._snapshot = snapshot
        self._schema: Optional[Schema] = None

    # -- identity ----------------------------------------------------------

    @property
    def root_paths(self) -> List[str]:
        return [self._path]

    @property
    def file_format(self) -> str:
        return "delta"

    @property
    def data_file_format(self) -> str:
        return "parquet"

    @property
    def options(self) -> Dict[str, str]:
        return dict(self._options)

    @property
    def delta_version(self) -> int:
        return self._snapshot.version

    def describe(self) -> str:
        return f"delta {self._path}@v{self._snapshot.version}"

    # -- files & schema ----------------------------------------------------

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            arrow = self._snapshot.arrow_schema()
            if arrow is None:
                import pyarrow.parquet as pq
                files = self.all_files()
                if not files:
                    raise HyperspaceException(
                        f"Empty delta table without schema: {self._path}")
                arrow = pq.read_schema(files[0])
            self._schema = Schema.from_arrow(arrow)
        return self._schema

    def all_files(self) -> List[str]:
        return self._snapshot.file_paths

    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        # Sizes/mtimes come from the commit log, not a filesystem walk.
        return self._snapshot.file_infos

    def signature(self) -> str:
        """Table version + path — the commit log version is the fingerprint
        (reference: DeltaLakeFileBasedSource signature semantics)."""
        return hashing.md5_hex(f"{self._snapshot.version}{self._path}")

    def refresh(self) -> "DeltaLakeRelation":
        opts = {k: v for k, v in self._options.items()
                if k != VERSION_AS_OF_OPTION}
        return DeltaLakeRelation(self._path, opts)

    def with_files(self, files: Sequence[str]) -> "DeltaLakeRelation":
        pruned_set = {os.path.abspath(f) for f in files}
        snap = self._snapshot
        kept = {rel: a for rel, a in snap._files.items()
                if os.path.join(self._path, rel) in pruned_set}
        pruned = DeltaLakeRelation(
            self._path, self._options,
            snapshot=Snapshot(self._path, snap.version, kept,
                              snap.schema_string))
        pruned._schema = self._schema
        return pruned

    # -- index metadata hooks ---------------------------------------------

    def enrich_index_properties(self, props: Dict[str, str],
                                index_log_version: int) -> Dict[str, str]:
        """Append (index log version → delta version) to the history property
        (reference: DeltaLakeRelationMetadata.enrichIndexProperties)."""
        out = dict(props)
        history = out.get(DELTA_VERSION_HISTORY_PROPERTY, "")
        pair = f"{index_log_version}:{self._snapshot.version}"
        out[DELTA_VERSION_HISTORY_PROPERTY] = \
            f"{history},{pair}" if history else pair
        return out

    @staticmethod
    def parse_version_history(props: Dict[str, str]) -> List[Tuple[int, int]]:
        """[(index log version, delta version), ...] from the property."""
        raw = props.get(DELTA_VERSION_HISTORY_PROPERTY, "")
        out = []
        for pair in raw.split(","):
            if ":" in pair:
                a, b = pair.split(":", 1)
                out.append((int(a), int(b)))
        return out

    def closest_index_log_version(self, props: Dict[str, str]
                                  ) -> Optional[int]:
        """The index log version whose recorded delta version is nearest to
        this snapshot's version, or None when the *latest* history entry
        already covers it. Prefers the latest version ≤ the scanned snapshot
        (an index of a *future* table version contains rows the snapshot
        must not see, so it only ties in via Hybrid Scan deletes); falls
        back to the overall nearest (reference:
        DeltaLakeRelation.closestIndex:187).

        Returning None (not the latest pair's log id) matters: actions that
        don't re-enrich the history (optimize, quick refresh) commit newer
        ACTIVE log ids than the last recorded pair, and swapping back to the
        recorded id would silently discard their work."""
        history = self.parse_version_history(props)
        if not history:
            return None
        at_or_before = [(lv, dv) for lv, dv in history
                        if dv <= self._snapshot.version]
        if at_or_before:
            chosen = max(at_or_before, key=lambda p: (p[1], p[0]))
        else:
            chosen = min(history,
                         key=lambda p: (abs(p[1] - self._snapshot.version),
                                        -p[0]))
        latest_dv = max(dv for _, dv in history)
        if chosen[1] == latest_dv:
            return None  # the current entry (possibly newer id) covers it.
        return chosen[0]


class DeltaLakeSourceBuilder(FileBasedSourceProvider):
    """Provider answering for ``format("delta")`` loads and delta Scan
    leaves (reference: sources/delta/DeltaLakeFileBasedSource.scala:40)."""

    def get_relation(self, plan_leaf) -> Optional[FileBasedRelation]:
        relation = getattr(plan_leaf, "relation", None)
        if isinstance(relation, DeltaLakeRelation):
            return relation
        return None

    def build_relation(self, paths: Sequence[str], fmt: str,
                       options: Dict[str, str]) -> Optional[FileBasedRelation]:
        if fmt != "delta":
            return None
        if len(paths) != 1:
            raise HyperspaceException(
                "Delta tables are single-rooted; got "
                f"{len(paths)} paths")
        return DeltaLakeRelation(paths[0], options)
