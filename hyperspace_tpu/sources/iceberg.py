"""Iceberg-analogue source provider: snapshot/manifest versioned tables.

Reference behavior mirrored (sources/iceberg/IcebergFileBasedSource.scala,
IcebergRelation.scala:37,53,65):

- signature = snapshot id + table location;
- ``snapshotId`` time-travel reads;
- file listing straight from the manifest (no filesystem walk);
- relations are lineage- and hybrid-scan-capable like any file-based source
  (the reference reconstructs the schema for partition-aware hybrid scan;
  partitioned manifests are not modeled yet).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

from ..exceptions import HyperspaceException
from ..lake.iceberg import IcebergSnapshot, IcebergTable
from ..schema import Schema
from ..util import hashing
from .interfaces import FileBasedRelation, FileBasedSourceProvider

SNAPSHOT_ID_OPTION = "snapshotId"


class IcebergRelation(FileBasedRelation):
    def __init__(self, path: str, options: Optional[Dict[str, str]] = None,
                 snapshot: Optional[IcebergSnapshot] = None):
        self._path = os.path.abspath(path)
        self._options = dict(options or {})
        self._table = IcebergTable(self._path)
        if snapshot is None:
            snap_id = self._options.get(SNAPSHOT_ID_OPTION)
            snapshot = self._table.snapshot(
                int(snap_id) if snap_id is not None else None)
        self._snapshot = snapshot
        self._schema: Optional[Schema] = None

    @property
    def root_paths(self) -> List[str]:
        return [self._path]

    @property
    def file_format(self) -> str:
        return "iceberg"

    @property
    def data_file_format(self) -> str:
        return "parquet"

    @property
    def options(self) -> Dict[str, str]:
        return dict(self._options)

    @property
    def snapshot_id(self) -> int:
        return self._snapshot.snapshot_id

    def describe(self) -> str:
        return f"iceberg {self._path}@snap{self._snapshot.snapshot_id}"

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            arrow = self._snapshot.arrow_schema()
            if arrow is None:
                import pyarrow.parquet as pq
                files = self.all_files()
                if not files:
                    raise HyperspaceException(
                        f"Empty iceberg table without schema: {self._path}")
                arrow = pq.read_schema(files[0])
            self._schema = Schema.from_arrow(arrow)
        return self._schema

    def all_files(self) -> List[str]:
        return self._snapshot.file_paths

    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        return self._snapshot.file_infos

    def signature(self) -> str:
        """Snapshot id + location (reference: IcebergFileBasedSource
        signature semantics — the snapshot id fingerprints the file set)."""
        return hashing.md5_hex(f"{self._snapshot.snapshot_id}{self._path}")

    def refresh(self) -> "IcebergRelation":
        opts = {k: v for k, v in self._options.items()
                if k != SNAPSHOT_ID_OPTION}
        return IcebergRelation(self._path, opts)

    def with_files(self, files: Sequence[str]) -> "IcebergRelation":
        keep = {os.path.abspath(f) for f in files}
        manifest = dict(self._snapshot._manifest)
        manifest = {**manifest,
                    "files": [f for f in manifest["files"]
                              if os.path.join(self._path, f["path"]) in keep]}
        pruned = IcebergRelation(
            self._path, self._options,
            snapshot=IcebergSnapshot(self._path, self._snapshot.snapshot_id,
                                     manifest))
        pruned._schema = self._schema
        return pruned


class IcebergSourceBuilder(FileBasedSourceProvider):
    """Provider answering for ``format("iceberg")`` loads and iceberg Scan
    leaves (reference: sources/iceberg/IcebergFileBasedSource.scala)."""

    def get_relation(self, plan_leaf) -> Optional[FileBasedRelation]:
        relation = getattr(plan_leaf, "relation", None)
        if isinstance(relation, IcebergRelation):
            return relation
        return None

    def build_relation(self, paths: Sequence[str], fmt: str,
                       options: Dict[str, str]) -> Optional[FileBasedRelation]:
        if fmt != "iceberg":
            return None
        if len(paths) != 1:
            raise HyperspaceException(
                f"Iceberg tables are single-rooted; got {len(paths)} paths")
        return IcebergRelation(paths[0], options)
