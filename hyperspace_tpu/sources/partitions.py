"""Hive-style partitioned directories: ``root/key=value/.../file``.

Parity reference: sources/interfaces.scala:43-247 (partitionSchema /
partitionBasePath on FileBasedRelation) and Spark's
PartitioningAwareFileIndex, which the reference's DefaultFileBasedRelation
delegates partition discovery + pruning to. Here the same three concerns
are explicit host-side functions:

- discovery: parse ``key=value`` path segments under the relation root into
  typed partition fields (int64 if every value parses as an integer, date
  for ISO dates, string otherwise);
- materialization: partition columns are not in the data files — they are
  attached per file as constant device columns at scan/build time;
- pruning: partition-column conjuncts are evaluated per file at planning
  time (always on, like Spark's native partition pruning — not gated on
  hyperspace being enabled).
"""

from __future__ import annotations

import datetime
import os
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..plan import expr as E
from ..schema import DATE, INT64, STRING, Field

HIVE_DEFAULT_PARTITION = "__HIVE_DEFAULT_PARTITION__"


def partition_segments(base: str, path: str) -> List[Tuple[str, str]]:
    """(key, raw value) pairs from the path's directory levels under base."""
    rel = os.path.relpath(os.path.dirname(os.path.abspath(path)),
                          os.path.abspath(base))
    out: List[Tuple[str, str]] = []
    if rel in (".", ""):
        return out
    for seg in rel.split(os.sep):
        if "=" in seg:
            k, _, v = seg.partition("=")
            out.append((k, v))
    return out


def infer_partition_fields(base: str, files: Sequence[str]
                           ) -> List[Field]:
    """Discover a consistent partition schema from the file paths, or []
    when the layout isn't hive-partitioned (no key=value levels, or
    inconsistent keys across files)."""
    keys: Optional[List[str]] = None
    values_by_key: Dict[str, List[str]] = {}
    for f in files:
        segs = partition_segments(base, f)
        ks = [k for k, _ in segs]
        if keys is None:
            keys = ks
        elif ks != keys:
            return []  # inconsistent layout → not partition-aware
        for k, v in segs:
            values_by_key.setdefault(k, []).append(v)
    if not keys:
        return []
    fields = []
    for k in keys:
        fields.append(Field(k, _infer_dtype(values_by_key[k]), False))
    return fields


def _infer_dtype(raw_values: Sequence[str]) -> str:
    def is_int(v):
        try:
            int(v)
            return True
        except ValueError:
            return False

    def is_date(v):
        try:
            datetime.date.fromisoformat(v)
            return True
        except ValueError:
            return False

    vals = [v for v in raw_values if v != HIVE_DEFAULT_PARTITION]
    if vals and all(is_int(v) for v in vals):
        return INT64
    if vals and all(is_date(v) for v in vals):
        return DATE
    return STRING


def partition_value(raw: str, dtype: str):
    if raw == HIVE_DEFAULT_PARTITION:
        return None
    if dtype == INT64:
        return int(raw)
    if dtype == DATE:
        return datetime.date.fromisoformat(raw)
    return raw


def file_partition_values(base: str, path: str, fields: Sequence[Field]):
    by_key = dict(partition_segments(base, path))
    return tuple(partition_value(by_key[f.name], f.dtype) for f in fields)


def attach_partition_columns(table, relation, files: Sequence[str],
                             wanted: Sequence[Field],
                             row_counts: Sequence[int]):
    """Append constant-per-file partition columns to a device table read
    from ``files`` (row_counts rows each, concatenated in order)."""
    from ..execution.columnar import Column

    base = relation.partition_base_path
    counts = np.asarray(row_counts, dtype=np.int64)
    for f in wanted:
        per_file = [file_partition_values(base, p, [f])[0] for p in files]
        if f.dtype == STRING:
            uniq = sorted({v for v in per_file if v is not None})
            dictionary = np.array(uniq, dtype=str) if uniq else \
                np.array([], dtype=str)
            codes = np.array([np.searchsorted(dictionary, v) if v is not None
                              else -1 for v in per_file], np.int32)
            data = np.repeat(codes, counts)
            validity = None
            if any(v is None for v in per_file):
                validity = jnp.asarray(np.repeat(
                    np.array([v is not None for v in per_file]), counts))
            col = Column(STRING, jnp.asarray(data), validity, dictionary)
        else:
            if f.dtype == DATE:
                epoch = datetime.date(1970, 1, 1)
                nums = [(v - epoch).days if v is not None else 0
                        for v in per_file]
                np_dtype = np.int32
            else:
                nums = [v if v is not None else 0 for v in per_file]
                np_dtype = np.int64
            data = np.repeat(np.asarray(nums, np_dtype), counts)
            validity = None
            if any(v is None for v in per_file):
                validity = jnp.asarray(np.repeat(
                    np.array([v is not None for v in per_file]), counts))
            col = Column(f.dtype, jnp.asarray(data), validity)
        table = table.with_column(f.name, col)
    return table


def read_relation_files(relation, files: Sequence[str],
                        cols: Optional[Sequence[str]], fmt: str,
                        filters=None, pad_to_class: bool = False):
    """Read ``files`` with partition columns attached (the single reader
    shared by the scan executor and the index build). Non-partitioned
    relations delegate straight to the columnar reader. ``pad_to_class``
    (executor scans only — never the build) class-pads host-side; the
    partition-attach paths stay exact and are padded on device by the
    executor instead."""
    from ..execution.columnar import (parquet_row_counts, read_parquet)

    fields = getattr(relation, "partition_fields", lambda: [])()
    part_names = {f.name for f in fields}
    if not fields or (cols is not None
                      and not any(c in part_names for c in cols)):
        return read_parquet(files, cols, fmt, filters=filters,
                            pad_to_class=pad_to_class)
    wanted = fields if cols is None else \
        [f for f in fields if f.name in cols]
    phys_cols = None if cols is None else \
        [c for c in cols if c not in part_names]
    if phys_cols is not None and not phys_cols:
        phys = [n for n in relation.schema.names if n not in part_names]
        phys_cols = [phys[0]] if phys else None
    if fmt == "parquet":
        # One bulk read; per-file row counts come from the footers. The
        # parquet-level filter is skipped (it would skew the counts);
        # partition pruning has already narrowed the file list.
        table = read_parquet(files, phys_cols, fmt)
        counts = parquet_row_counts(files)
        out = attach_partition_columns(table, relation, files, wanted,
                                       counts)
    else:
        # Non-parquet: no footers to pre-count rows per file, so partition
        # columns attach per GROUP instead — consecutive files sharing
        # identical partition values batch into ONE multi-file read
        # (pooled per file inside read_parquet) rather than N independent
        # root reads. File listings walk directory by directory, so runs
        # coincide with partitions; row order, attached values, and the
        # unified string dictionaries are identical to the per-file loop.
        from itertools import groupby

        from ..execution.columnar import Table
        base = relation.partition_base_path
        parts = []
        for _vals, group in groupby(
                files,
                key=lambda f: file_partition_values(base, f, wanted)):
            group = list(group)
            t = read_parquet(group, phys_cols, fmt)
            parts.append(attach_partition_columns(
                t, relation, [group[0]], wanted, [t.num_rows]))
        out = Table.concat(parts)
    if cols is not None:
        # Drop the dummy physical column read only for its row count (a
        # partition-columns-only projection would otherwise leak it, e.g.
        # into index files).
        out = out.select([c for c in cols if c in out.names])
    return out


# ---------------------------------------------------------------------------
# Planning-time pruning (always on, like Spark's native partition pruning).
# ---------------------------------------------------------------------------

def prune_partitions(plan):
    """Narrow Filter-over-Scan leaves of partition-aware relations to the
    files whose partition values can satisfy the filter."""
    from ..plan.nodes import Filter, Scan

    def rewrite(node):
        if isinstance(node, Filter) and isinstance(node.child, Scan):
            kept = _pruned_files(node.child.relation, node.condition)
            if kept is not None:
                return Filter(node.condition,
                              Scan(node.child.relation.with_files(kept)))
        return node

    return plan.transform_up(rewrite)


def _pruned_files(relation, condition) -> Optional[List[str]]:
    fields = getattr(relation, "partition_fields", lambda: [])()
    if not fields:
        return None
    by_name = {f.name: f for f in fields}
    files = relation.all_files()
    base = relation.partition_base_path
    keep = np.ones(len(files), dtype=bool)
    pruned_any = False
    for conjunct in E.split_conjunctive_predicates(condition):
        verdict = _eval_partition_predicate(conjunct, by_name, base, files)
        if verdict is not None:
            keep &= verdict
            pruned_any = True
    if not pruned_any or keep.all():
        return None
    return [f for f, k in zip(files, keep) if k]


_FLIP = {"EqualTo": "EqualTo", "LessThan": "GreaterThan",
         "LessThanOrEqual": "GreaterThanOrEqual",
         "GreaterThan": "LessThan",
         "GreaterThanOrEqual": "LessThanOrEqual"}


def _eval_partition_predicate(e, by_name, base, files
                              ) -> Optional[np.ndarray]:
    """Per-file keep mask for one conjunct over partition columns only;
    None = not a partition predicate (no pruning from this conjunct)."""
    if isinstance(e, E.Or):
        l = _eval_partition_predicate(e.left, by_name, base, files)
        r = _eval_partition_predicate(e.right, by_name, base, files)
        if l is None or r is None:
            return None
        return l | r
    if isinstance(e, E.In) and isinstance(e.value, E.Col) \
            and e.value.column in by_name \
            and all(isinstance(o, E.Lit) for o in e.options):
        field = by_name[e.value.column]
        wanted = {_norm(o.value, field.dtype) for o in e.options}
        vals = _column_values(field, base, files)
        return np.array([v in wanted for v in vals])
    if isinstance(e, (E.EqualTo, E.LessThan, E.LessThanOrEqual,
                      E.GreaterThan, E.GreaterThanOrEqual)):
        left, right = e.left, e.right
        op = type(e).__name__
        if isinstance(left, E.Lit) and isinstance(right, E.Col):
            left, right = right, left
            op = _FLIP[op]
        if not (isinstance(left, E.Col) and isinstance(right, E.Lit)
                and left.column in by_name):
            return None
        field = by_name[left.column]
        lit = _norm(right.value, field.dtype)
        vals = _column_values(field, base, files)
        out = np.zeros(len(files), dtype=bool)
        for i, v in enumerate(vals):
            if v is None:
                continue  # null partition never matches a comparison
            if op == "EqualTo":
                out[i] = v == lit
            elif op == "LessThan":
                out[i] = v < lit
            elif op == "LessThanOrEqual":
                out[i] = v <= lit
            elif op == "GreaterThan":
                out[i] = v > lit
            elif op == "GreaterThanOrEqual":
                out[i] = v >= lit
        return out
    return None


def _norm(value, dtype: str):
    if dtype == DATE and isinstance(value, str):
        return datetime.date.fromisoformat(value)
    if dtype == INT64 and not isinstance(value, bool):
        # A fractional literal must NOT be truncated (int(5.5) == 5 would
        # wrongly prune year=5 from `year < 5.5`): int/float comparisons
        # are exact enough in Python, so keep the float.
        if isinstance(value, float):
            return int(value) if value.is_integer() else value
        try:
            return int(value)
        except (TypeError, ValueError):
            return value
    return value


def _column_values(field: Field, base: str, files: Sequence[str]):
    return [file_partition_values(base, f, [field])[0] for f in files]
