"""Default file-based source provider: parquet (+ csv) directories on the
host filesystem.

Parity reference: sources/default/DefaultFileBasedSource.scala:37 and
DefaultFileBasedRelation.scala:38 — supported formats, signature computed from
the file listing, glob-pattern validation.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import pyarrow.dataset as pa_ds
import pyarrow.parquet as pq

from ..exceptions import HyperspaceException
from ..schema import Schema
from ..util import file_utils, hashing
from .interfaces import FileBasedRelation, FileBasedSourceProvider

# Parity: DefaultFileBasedSource.scala:37-44 — the full format set
# (avro via the built-in OCF reader in util/avro.py; the image ships no
# avro library).
SUPPORTED_FORMATS = ("parquet", "csv", "json", "orc", "text", "avro")

# File suffixes per format ("text" matches Spark's .txt convention too).
_FORMAT_SUFFIXES = {fmt: ("." + fmt,) for fmt in SUPPORTED_FORMATS}
_FORMAT_SUFFIXES["text"] = (".text", ".txt")


class DefaultFileBasedRelation(FileBasedRelation):
    def __init__(self, paths: Sequence[str], fmt: str = "parquet",
                 options: Optional[Dict[str, str]] = None,
                 schema: Optional[Schema] = None):
        if fmt not in SUPPORTED_FORMATS:
            raise HyperspaceException(f"Unsupported format: {fmt}")
        self._root_paths = [os.path.abspath(p) for p in paths]
        self._format = fmt
        self._options = dict(options or {})
        self._schema = schema
        self._files: Optional[List[str]] = None
        # Base for key=value partition parsing; with_files() keeps the
        # original base so pruned relations still see their partitions.
        self._partition_base = self._root_paths[0] if self._root_paths else ""
        self._partition_fields = None

    @property
    def root_paths(self) -> List[str]:
        return list(self._root_paths)

    @property
    def file_format(self) -> str:
        return self._format

    @property
    def options(self) -> Dict[str, str]:
        return dict(self._options)

    @property
    def schema(self) -> Schema:
        if self._schema is None:
            self._schema = self._physical_schema()
            for f in self.partition_fields():
                if f.name not in self._schema:
                    self._schema = self._schema.append(f)
        return self._schema

    def _physical_schema(self) -> Schema:
        files = self.all_files()
        if not files:
            raise HyperspaceException(
                f"No data files under {self._root_paths}")
        if self._format == "parquet":
            return Schema.from_arrow(pq.read_schema(files[0]))
        if self._format == "text":
            # Spark text-source schema: one non-null string column.
            from ..schema import STRING, Field
            return Schema([Field("value", STRING, False)])
        if self._format == "avro":
            from ..util.avro import read_avro_schema
            return Schema.from_arrow(read_avro_schema(files[0]))
        ds = pa_ds.dataset(files[0], format=self._format)
        return Schema.from_arrow(ds.schema)

    # -- hive-partitioned directories (parity: partitionSchema /
    # partitionBasePath, sources/interfaces.scala:43-247) --

    @property
    def partition_base_path(self) -> str:
        return self._partition_base

    def partition_fields(self):
        if self._partition_fields is None:
            from .partitions import infer_partition_fields
            self._partition_fields = infer_partition_fields(
                self._partition_base, self.all_files())
        return list(self._partition_fields)

    def all_files(self) -> List[str]:
        if self._files is None:
            out: List[str] = []
            suffixes = _FORMAT_SUFFIXES[self._format]
            for root in self._root_paths:
                if os.path.isfile(root):
                    out.append(os.path.abspath(root))
                    continue
                for f in file_utils.list_leaf_files(root):
                    if f.endswith(suffixes):
                        out.append(f)
            self._files = sorted(out)
        return list(self._files)

    def signature(self) -> str:
        """Fingerprint input: concatenated (size, mtime, path) per file
        (parity: DefaultFileBasedRelation signature semantics)."""
        parts = []
        for path, size, mtime in self.all_file_infos():
            parts.append(f"{size}{mtime}{path}")
        return hashing.md5_hex("".join(parts))

    def refresh(self) -> "DefaultFileBasedRelation":
        return DefaultFileBasedRelation(
            self._root_paths, self._format, self._options, schema=None)

    def with_files(self, files) -> "DefaultFileBasedRelation":
        pruned = DefaultFileBasedRelation(
            list(files), self._format, self._options, schema=self.schema)
        pruned._files = sorted(os.path.abspath(f) for f in files)
        pruned._partition_base = self._partition_base
        pruned._partition_fields = self._partition_fields \
            if self._partition_fields is not None \
            else (self.partition_fields() or [])
        return pruned

    @classmethod
    def pinned(cls, root_paths, fmt: str, options, files,
               schema: Schema) -> "DefaultFileBasedRelation":
        """A relation pinned to an explicit listing AND schema: unlike
        ``with_files`` on a freshly built relation, touches the
        filesystem for neither the schema (footer read) nor partition
        inference (directory walk) — the streaming commit path builds
        one of these per index per commit and already knows both."""
        rel = cls(list(root_paths), fmt, dict(options or {}), schema=schema)
        rel._files = sorted(os.path.abspath(f) for f in files)
        rel._partition_fields = []
        return rel


class DefaultFileBasedSourceBuilder(FileBasedSourceProvider):
    """The provider the conf points at by default."""

    def get_relation(self, plan_leaf) -> Optional[FileBasedRelation]:
        relation = getattr(plan_leaf, "relation", None)
        if isinstance(relation, DefaultFileBasedRelation):
            return relation
        return None

    def build_relation(self, paths: Sequence[str], fmt: str,
                       options: Dict[str, str]) -> Optional[FileBasedRelation]:
        if fmt in SUPPORTED_FORMATS:
            return DefaultFileBasedRelation(paths, fmt, options)
        return None
