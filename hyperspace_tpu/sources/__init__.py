from .default import DefaultFileBasedRelation, DefaultFileBasedSourceBuilder  # noqa: F401
from .interfaces import (  # noqa: F401
    FileBasedRelation, FileBasedSourceProvider, FileBasedSourceProviderManager)
