"""Source abstraction: pluggable relation providers.

Parity reference: sources/interfaces.scala:43-270 (FileBasedRelation,
FileBasedSourceProvider, FileBasedRelationMetadata) and
sources/FileBasedSourceProviderManager.scala:38-172.

A relation describes a file-based dataset (root paths + format + schema) and
exposes everything the rules/actions need: file listing, fingerprint input,
lineage pairs, and a way to reload ("refresh") for refresh actions.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..exceptions import HyperspaceException
from ..schema import Schema
from ..util import file_utils


class FileBasedRelation:
    """Abstract relation over lake files."""

    @property
    def root_paths(self) -> List[str]:
        raise NotImplementedError

    @property
    def schema(self) -> Schema:
        raise NotImplementedError

    @property
    def file_format(self) -> str:
        raise NotImplementedError

    @property
    def data_file_format(self) -> str:
        """Physical format of the leaf files (versioned table formats are
        logical wrappers over parquet parts)."""
        return self.file_format

    @property
    def options(self) -> Dict[str, str]:
        return {}

    def all_files(self) -> List[str]:
        """All leaf data files, absolute paths, deterministic order."""
        raise NotImplementedError

    def all_file_infos(self) -> List[Tuple[str, int, int]]:
        """(path, size, mtime_ms) for each file in all_files()."""
        return [file_utils.file_info_triple(p) for p in self.all_files()]

    def signature(self) -> str:
        """Relation fingerprint input (provider-specific)."""
        raise NotImplementedError

    @property
    def partition_schema(self) -> Schema:
        return Schema([])

    @property
    def partition_base_paths(self) -> List[str]:
        return list(self.root_paths)

    def describe(self) -> str:
        return f"{self.file_format} {','.join(self.root_paths)}"

    def lineage_pairs(self, file_id_tracker) -> List[Tuple[str, int]]:
        """(file path, file id) pairs for the lineage column build
        (parity: interfaces.scala lineagePairs)."""
        return [(p, file_id_tracker.add_file(p, size, mtime))
                for p, size, mtime in self.all_file_infos()]

    def refresh(self) -> "FileBasedRelation":
        """Re-list the underlying files (for refresh actions)."""
        raise NotImplementedError

    def enrich_index_properties(self, props: Dict[str, str],
                                index_log_version: int) -> Dict[str, str]:
        """Provider hook: add source-specific properties to an index log
        entry at create/refresh time (parity: FileBasedRelationMetadata.
        enrichIndexProperties — e.g. the delta version history)."""
        return props

    def with_files(self, files: Sequence[str]) -> "FileBasedRelation":
        """A copy of this relation restricted to ``files`` (data-skipping
        scan pruning). Schema is preserved even when files is empty."""
        raise NotImplementedError


class FileBasedSourceProvider:
    """Builds relations it understands; returns None for ones it doesn't."""

    def name(self) -> str:
        return type(self).__name__

    def get_relation(self, plan_leaf) -> Optional[FileBasedRelation]:
        """If the leaf Scan's relation belongs to this provider, return it."""
        raise NotImplementedError

    def build_relation(self, paths: Sequence[str], fmt: str,
                       options: Dict[str, str]) -> Optional[FileBasedRelation]:
        raise NotImplementedError


class FileBasedSourceProviderManager:
    """Runs each provider in turn; exactly one must answer
    (parity: FileBasedSourceProviderManager.scala:106-155)."""

    def __init__(self, providers: List[FileBasedSourceProvider]):
        if not providers:
            raise HyperspaceException("At least one source provider is required.")
        self._providers = providers

    @property
    def providers(self) -> List[FileBasedSourceProvider]:
        return list(self._providers)

    def _run(self, fn_name: str, *args):
        answers = []
        for p in self._providers:
            result = getattr(p, fn_name)(*args)
            if result is not None:
                answers.append((p, result))
        if len(answers) != 1:
            # A format typo is the common path here — name it, and the
            # providers that were asked, instead of a bare count.
            detail = ""
            if fn_name == "build_relation" and len(args) >= 2:
                detail = f" for format {args[1]!r}"
            names = ", ".join(type(p).__name__ for p in self._providers)
            raise HyperspaceException(
                f"Exactly one provider must respond to {fn_name}{detail}; "
                f"got {len(answers)} of {len(self._providers)} ({names}).")
        return answers[0][1]

    def get_relation(self, plan_leaf) -> FileBasedRelation:
        return self._run("get_relation", plan_leaf)

    def build_relation(self, paths: Sequence[str], fmt: str,
                       options: Dict[str, str]) -> FileBasedRelation:
        return self._run("build_relation", paths, fmt, options)

    def is_supported_relation(self, plan_leaf) -> bool:
        answers = [p.get_relation(plan_leaf) for p in self._providers]
        return sum(1 for a in answers if a is not None) == 1
