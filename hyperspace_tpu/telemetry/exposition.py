"""OpenMetrics text exposition of the process metrics registry.

The metrics registry (telemetry/metrics.py) unified every subsystem's
counters behind ONE in-process snapshot; this module makes that snapshot
consumable from OUTSIDE the process — the prerequisite for an external
scraper today and for ROADMAP item 5's router tier tomorrow:

- :func:`flatten` — the numeric leaves of a ``Hyperspace.metrics()``
  snapshot as one flat ``{dotted.path: number}`` dict (also the engine
  of ``Hyperspace.metrics_delta()``);
- :func:`render_text` — OpenMetrics text exposition (the Prometheus
  scrape format): counters as ``_total``-suffixed counter families,
  gauges as gauges, histograms as per-quantile gauges, every collector's
  numeric leaves as gauges, terminated by ``# EOF``. Round-trips through
  the strict OpenMetrics parser (asserted in tests).
- :func:`start_http_exporter` / :func:`stop_http_exporter` — an opt-in
  localhost-only scrape endpoint (``GET /metrics``) so nothing has to
  import the process to read it. The listener thread comes from
  parallel/io.py's sanctioned daemon spawner (the lint gate pins thread
  construction there).

Metric NAMES come from the frozen telemetry/metric_names.py registry
(lint-enforced at the instrument call sites); the exposition sanitizes
them to the OpenMetrics grammar (``hst_`` prefix, dots to underscores).
"""

from __future__ import annotations

import re
import threading
from typing import Dict, Optional

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PREFIX = "hst_"


def _sanitize(name: str) -> str:
    out = _NAME_BAD.sub("_", name)
    return _PREFIX + out


def flatten(snapshot: dict, prefix: str = "") -> Dict[str, float]:
    """Every numeric leaf of a (possibly nested) snapshot dict as
    ``{dotted.path: float}``. Booleans count (0/1); strings, lists and
    None are skipped — they are labels, not measurements."""
    out: Dict[str, float] = {}
    for key, value in snapshot.items():
        path = f"{prefix}.{key}" if prefix else str(key)
        if isinstance(value, bool):
            out[path] = float(value)
        elif isinstance(value, (int, float)):
            out[path] = float(value)
        elif isinstance(value, dict):
            out.update(flatten(value, path))
    return out


def delta(before: dict, after: dict) -> Dict[str, float]:
    """Numeric leaves that CHANGED between two snapshots (after -
    before; keys that vanished count as going to 0). The
    snapshot-vs-snapshot diff bench phases and tests used to hand-roll
    over whole ``metrics()`` dicts."""
    b = flatten(before)
    a = flatten(after)
    out: Dict[str, float] = {}
    for k, v in a.items():
        d = v - b.get(k, 0.0)
        if d != 0.0:
            out[k] = d
    for k, v in b.items():
        if k not in a and v != 0.0:
            out[k] = -v
    return out


def _fmt(value: float) -> str:
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(value: str) -> str:
    return value.replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def render_text(snapshot: dict, worker: str = "") -> str:
    """OpenMetrics text exposition of one registry/metrics() snapshot.

    Family names are first-wins in emission order — registry
    counters, then gauges, then histogram quantiles, then collector
    leaves — so when a collector re-exposes a quantity the registry
    already counts under the same sanitized name (e.g. the serving
    collector's ``sweep_invocations`` vs the ``serving.
    sweep_invocations`` counter), the REGISTRY instrument is the one
    exported; a family is never emitted twice (the OpenMetrics grammar
    forbids it).

    A non-empty ``worker`` stamps every sample with a
    ``worker="<id>"`` label — the cluster identity that keeps two
    workers' scrapes from colliding on identical series names. Empty
    (the single-process default) emits byte-identical text to the
    pre-label format."""
    lines = []
    seen = set()
    labels = f'{{worker="{_escape_label(worker)}"}}' if worker else ""

    def emit(name: str, mtype: str, value: float,
             help_text: str = "") -> None:
        if name in seen:
            return
        seen.add(name)
        if help_text:
            lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        sample = name + ("_total" if mtype == "counter" else "")
        lines.append(f"{sample}{labels} {_fmt(value)}")

    for name in sorted(snapshot.get("counters", {})):
        emit(_sanitize(name), "counter",
             snapshot["counters"][name],
             f"Process counter {name}")
    for name in sorted(snapshot.get("gauges", {})):
        emit(_sanitize(name), "gauge", snapshot["gauges"][name],
             f"Process gauge {name}")
    for name in sorted(snapshot.get("histograms", {})):
        hist = snapshot["histograms"][name] or {}
        for leaf, value in sorted(flatten(hist).items()):
            emit(_sanitize(f"{name}.{leaf}"), "gauge", value,
                 f"Live histogram {name} {leaf}")
    collectors = snapshot.get("collectors", {}) or {}
    for cname in sorted(collectors):
        payload = collectors[cname]
        if not isinstance(payload, dict):
            continue
        for leaf, value in sorted(flatten(payload).items()):
            emit(_sanitize(f"{cname}.{leaf}"), "gauge", value,
                 f"Collector {cname} {leaf}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def registry_text() -> str:
    """Exposition of the bare process registry (no session-scoped
    collectors) — what the HTTP endpoint serves when its governing
    session is gone."""
    from .metrics import get_registry
    return render_text(get_registry().snapshot())


# ---------------------------------------------------------------------------
# Opt-in localhost HTTP scrape endpoint.
# ---------------------------------------------------------------------------

_SERVER = None
_SERVER_LOCK = threading.Lock()

CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")


def _session_text(session) -> str:
    """The full Hyperspace.metrics_text() surface when the governing
    session is alive (weakly held), else the bare registry."""
    if session is None:
        return registry_text()
    from ..api import Hyperspace
    return Hyperspace(session).metrics_text()


def start_http_exporter(session, port: Optional[int] = None) -> int:
    """Start (or return) the process scrape endpoint on
    ``127.0.0.1:<port>`` — ``port=None`` reads
    ``telemetry.export.httpPort`` and raises while it is 0 (off, the
    default); an EXPLICIT ``port=0`` binds an ephemeral port. Returns
    the bound port. Localhost-only by construction: exposure beyond the
    host is a reverse proxy's job, not an embedded server's."""
    import weakref
    from http.server import BaseHTTPRequestHandler, HTTPServer

    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            # Idempotent while up: the live endpoint's port, whatever
            # this call asked for (one exporter per process).
            return _SERVER.server_address[1]
        if port is None:
            port = session.hs_conf.telemetry_export_http_port()
            if port == 0:
                # Conf 0 means OFF (the documented default) — only an
                # EXPLICIT port=0 argument asks for an ephemeral bind.
                from ..exceptions import HyperspaceException
                raise HyperspaceException(
                    "hyperspace.tpu.telemetry.export.httpPort is 0 "
                    "(off); set it, or pass an explicit port "
                    "(0 = ephemeral) to serve_metrics")

        session_ref = weakref.ref(session)

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server contract)
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404, "only /metrics is served")
                    return
                try:
                    body = _session_text(session_ref()).encode("utf-8")
                except Exception as e:  # a broken collector: say so
                    self.send_error(500, f"exposition failed: {e}")
                    return
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam stderr

        server = HTTPServer(("127.0.0.1", int(port)), _Handler)
        from ..parallel import io as pio
        pio.spawn_daemon("hst-metrics-http", server.serve_forever)
        _SERVER = server
        return server.server_address[1]


def stop_http_exporter() -> None:
    """Shut the scrape endpoint down (idempotent)."""
    global _SERVER
    with _SERVER_LOCK:
        server, _SERVER = _SERVER, None
    if server is not None:
        server.shutdown()
        server.server_close()
