"""Frozen registry of metric instrument + collector names.

Every push-side instrument ask (``counter_add`` / ``gauge_set`` /
``histogram``) and every ``register_collector`` site in the package must
name its metric with one of these constants (or a string literal
registered here) — free-form strings are rejected by the scripts/lint.py
metric-discipline gate, and every name registered here must be
referenced under tests/ (an unobserved metric is unverified
observability — the same contract the span-names / fault-names /
event-taxonomy gates enforce).

Keep the vocabulary SMALL and stable: the OpenMetrics exposition
(telemetry/exposition.py), ``Hyperspace.metrics_delta()``, dashboards,
and external scrapers all key on these strings. Variable detail belongs
in the collectors' dict payloads, never in new ad-hoc names.
"""

from __future__ import annotations

# -- push-side counters -----------------------------------------------------

# Retention outcome of each completed root trace (telemetry/trace.py):
# the head coin said keep / the tail-keep override rescued it (anomaly
# or live-latency threshold) / it was recorded provisionally and
# discarded at completion.
TRACE_SAMPLED = "trace.sampled"
TRACE_TAIL_KEPT = "trace.tail_kept"
TRACE_DISCARDED = "trace.discarded"

# Anomalies the flight recorder captured (telemetry/flight_recorder.py):
# deadline cancellations, fault-driven fallbacks, retry exhaustions,
# spill corruption, crash recovery, SLO breaches.
FLIGHT_ANOMALIES = "flight_recorder.anomalies"

# SLO objective transitions into breach (telemetry/slo.py).
SLO_BREACHES = "slo.breaches"

# Literal-sweep batched invocations (serving/batcher.py).
SERVING_SWEEP_INVOCATIONS = "serving.sweep_invocations"

# -- live histograms --------------------------------------------------------

# Per-completed-query latency through the serving frontend
# (serving/frontend.py; window: telemetry.serving.latencyWindow).
SERVING_LATENCY_MS = "serving.latency_ms"

# Per-query latency of EVERY Session.execute (telemetry/slo.py feeds
# it), frontend or not — the SLO monitors' p99 source and the adaptive
# tail-keep threshold's baseline.
QUERY_LATENCY_MS = "query.latency_ms"

# -- pull-side collectors ---------------------------------------------------

COLLECTOR_IO = "io"
COLLECTOR_PROGRAM_BANK = "program_bank"
COLLECTOR_SERVING = "serving"
COLLECTOR_ROBUSTNESS = "robustness"
COLLECTOR_STREAMING = "streaming"
COLLECTOR_FUSION = "fusion"
COLLECTOR_FLIGHT_RECORDER = "flight_recorder"
COLLECTOR_ARTIFACTS = "artifacts"
COLLECTOR_CLUSTER = "cluster"
COLLECTOR_BUFFER_POOL = "buffer_pool"

METRIC_NAMES = frozenset({
    TRACE_SAMPLED, TRACE_TAIL_KEPT, TRACE_DISCARDED, FLIGHT_ANOMALIES,
    SLO_BREACHES, SERVING_SWEEP_INVOCATIONS, SERVING_LATENCY_MS,
    QUERY_LATENCY_MS, COLLECTOR_IO, COLLECTOR_PROGRAM_BANK,
    COLLECTOR_SERVING, COLLECTOR_ROBUSTNESS, COLLECTOR_STREAMING,
    COLLECTOR_FUSION, COLLECTOR_FLIGHT_RECORDER, COLLECTOR_ARTIFACTS,
    COLLECTOR_CLUSTER, COLLECTOR_BUFFER_POOL,
})
