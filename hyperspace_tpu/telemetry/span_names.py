"""Frozen registry of trace span names.

Every ``trace.span(...)`` / ``trace.add_span(...)`` site in the package
must name its span with one of these constants — free-form strings are
rejected by the scripts/lint.py span-discipline gate, and every name
registered here must be referenced under tests/ (an unobserved span is
unverified observability, the same contract the event-taxonomy gate
enforces for telemetry/events.py).

Keep the vocabulary SMALL and stable: dashboards, the Chrome-trace
exporter, and the explain "Trace:" section all key on these strings.
Variable detail (node kinds, hit/miss, byte counts) rides in span
attributes, never in the name.
"""

from __future__ import annotations

# The per-query root span, opened by Session.execute (one per
# QueryContext; literal-sweep members nest under SERVING_SWEEP).
QUERY = "query"

# Plan normalization (push_filters + prune_columns) in Session.optimize.
PLAN_NORMALIZE = "plan.normalize"

# Cost-based join reordering (optimizer/join_order.reorder_joins).
JOIN_REORDER = "optimize.join_reorder"

# The hyperspace index-rewrite batch (rules/apply_hyperspace).
INDEX_REWRITE = "rewrite.index_rules"

# Result-cache key computation + probe (serving/result_cache).
CACHE_LOOKUP = "serving.cache_lookup"

# Program-bank lookup (serving/program_bank; attrs carry hit/miss) and
# the wrapper construction on a bank miss.
BANK_LOOKUP = "bank.lookup"
BANK_COMPILE = "bank.compile"

# One span per executed plan node (execution/executor._execute).
EXEC_STAGE = "exec.stage"

# One span per fused-region dispatch (execution/fusion.py): the whole
# filter/project/join-probe/aggregate region runs as ONE banked program;
# attrs carry ``fused_nodes`` (plan nodes collapsed) and output rows.
EXEC_FUSED = "exec.fused"

# Pooled multi-file read fan-out / prefetch stream (parallel/io.py),
# recorded on the consumer side of the r11 per-query io attribution.
IO_READ = "io.read"
IO_PREFETCH = "io.prefetch"

# SPMD mesh dispatch (execution/spmd) and the AOT compile of one mesh
# executable (parallel/sharding.MeshProgram).
SPMD_DISPATCH = "spmd.dispatch"
SPMD_COMPILE = "spmd.compile"

# The shared literal-sweep batch span (serving/frontend._run_batch);
# member queries' QUERY spans are its children.
SERVING_SWEEP = "serving.sweep"

# Streaming ingestion tier (streaming/). One INGEST_APPEND per staged
# batch (attrs carry rows + per-index prebuild counts), one
# INGEST_COMMIT per commit() publishing staged batches through the
# op-log protocol, one INGEST_COMPACT per compacted log.
INGEST_APPEND = "ingest.append"
INGEST_COMMIT = "ingest.commit"
INGEST_COMPACT = "ingest.compact"

# Group-commit publication wave (streaming/ingest.CommitCoordinator):
# one INGEST_WAVE per wave the leader publishes, wrapping its
# INGEST_COMMIT sub-waves (attrs carry batches, joined committers,
# sub-waves). One INGEST_SOURCE per productive continuous-source poll
# (streaming/sources.py; attrs carry appended batches / committed rows).
INGEST_WAVE = "ingest.wave"
INGEST_SOURCE = "ingest.source"

# Artifact store (artifacts/): one ARTIFACT_LOAD per lake probe (attrs
# carry hit/reason/nbytes), one ARTIFACT_EXPORT per serialize+publish,
# one ARTIFACT_WARMUP per boot preload pass (attrs carry loaded count
# and bytes).
ARTIFACT_LOAD = "artifact.load"
ARTIFACT_EXPORT = "artifact.export"
ARTIFACT_WARMUP = "artifact.warmup"

# Serving cluster (cluster/): one CLUSTER_FORWARD per routed submission
# shipped to its shard owner (attrs carry owner/hit/ok), one
# CLUSTER_BROADCAST per commit fan-out to the live peers, one
# CLUSTER_GATHER per host-TCP allgather round on the owned path.
CLUSTER_FORWARD = "cluster.forward"
CLUSTER_BROADCAST = "cluster.broadcast"
CLUSTER_GATHER = "cluster.gather"

SPAN_NAMES = frozenset({
    QUERY, PLAN_NORMALIZE, JOIN_REORDER, INDEX_REWRITE, CACHE_LOOKUP,
    BANK_LOOKUP, BANK_COMPILE, EXEC_STAGE, EXEC_FUSED, IO_READ,
    IO_PREFETCH, SPMD_DISPATCH, SPMD_COMPILE, SERVING_SWEEP,
    INGEST_APPEND, INGEST_COMMIT, INGEST_COMPACT,
    INGEST_WAVE, INGEST_SOURCE,
    ARTIFACT_LOAD, ARTIFACT_EXPORT, ARTIFACT_WARMUP,
    CLUSTER_FORWARD, CLUSTER_BROADCAST, CLUSTER_GATHER,
})
