from .events import (  # noqa: F401
    CancelActionEvent, CreateActionEvent, DeleteActionEvent, HyperspaceEvent,
    HyperspaceIndexUsageEvent, OptimizeActionEvent, RefreshActionEvent,
    RefreshIncrementalActionEvent, RefreshQuickActionEvent, RestoreActionEvent,
    VacuumActionEvent)
from .logging import EventLogger, HyperspaceEventLogging, NoOpEventLogger, get_logger  # noqa: F401
