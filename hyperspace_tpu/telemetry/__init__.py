from .events import (  # noqa: F401
    CancelActionEvent, CreateActionEvent, DeleteActionEvent, HyperspaceEvent,
    HyperspaceIndexUsageEvent, IndexCacheHitEvent, IndexCacheMissEvent,
    OptimizeActionEvent, RefreshActionEvent, RefreshIncrementalActionEvent,
    RefreshQuickActionEvent, RestoreActionEvent, ResultCacheAdmitEvent,
    ResultCacheEvictionEvent, ResultCacheHitEvent, ResultCacheMissEvent,
    VacuumActionEvent)
from .logging import EventLogger, HyperspaceEventLogging, NoOpEventLogger, get_logger  # noqa: F401
from .constants import TelemetryConstants  # noqa: F401
