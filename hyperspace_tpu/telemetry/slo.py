"""SLO monitors: named objectives over sliding windows of completed
queries — the sensor half of ROADMAP item 2c.

Three objectives, each armed by its own ``hyperspace.tpu.telemetry.slo.*``
conf key (0 = disarmed): **p99 latency** (ms), **error rate** (failed /
completed), and **degrade rate** (queries that rode a robustness
degradation ladder / completed). Every ``Session.execute`` — frontend
or not — feeds :func:`observe_query` with (latency, error flag, the
QueryContext's degraded flag) and the live ``query.latency_ms``
histogram; the monitor evaluates the armed objectives over
``slo.windowS`` (rate-limited on the feed path, always on demand via
``Hyperspace.health()``).

Breaches are EDGE-TRIGGERED per objective: the healthy→breached
transition emits one :class:`~.events.SloBreachEvent`, bumps the
``slo.breaches`` counter, and lands a flight-recorder anomaly; the
recovery transition re-arms silently. ``Hyperspace.health()`` returns
the verdict dict. The actuator half (shed/defer/AQP-degrade, arxiv
1805.05874) lives in adaptive/admission.py: with
``hyperspace.tpu.adaptive.admission.enabled`` the serving frontend
consumes exactly these verdicts at submit time.

The monitor also owns the cached live-p99 the trace sampler's adaptive
tail-keep threshold reads (:func:`adaptive_slow_threshold_ms`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from . import metric_names as MN
from .metrics import get_registry, percentile

_MAX_SAMPLES = 32768
# Samples older than this are gone for every consumer; windows larger
# than the horizon evaluate over what the horizon retains.
_RETENTION_S = 3600.0
_EVAL_INTERVAL_S = 5.0
_P99_CACHE_S = 5.0
# Adaptive tail-keep: 2x the live p99 once the window holds this many
# samples (below it the estimate is noise and no threshold applies).
_ADAPTIVE_FACTOR = 2.0
_ADAPTIVE_MIN_SAMPLES = 64

OBJECTIVE_P99 = "p99_latency_ms"
OBJECTIVE_ERROR_RATE = "error_rate"
OBJECTIVE_DEGRADE_RATE = "degrade_rate"


class SloMonitor:
    """Sliding-window query outcomes + edge-triggered breach state."""

    def __init__(self, max_samples: int = _MAX_SAMPLES):
        self._lock = threading.Lock()
        # (monotonic_t, latency_ms, error, degraded)
        self._samples: deque = deque(maxlen=max(int(max_samples), 16))
        self._breached = {}          # objective name -> bool
        self._last_eval_s = 0.0
        self._p99_cache: Optional[float] = None
        self._p99_cache_t = 0.0
        self.total = 0
        self.error_total = 0
        self.degraded_total = 0

    def record(self, latency_ms: float, error: bool, degraded: bool,
               now: Optional[float] = None) -> None:
        t = now if now is not None else time.monotonic()
        with self._lock:
            self._samples.append((t, float(latency_ms), bool(error),
                                  bool(degraded)))
            self.total += 1
            if error:
                self.error_total += 1
            if degraded:
                self.degraded_total += 1

    def _window(self, window_s: float, now: float):
        """Samples inside ``window_s``. Trimming is against the FIXED
        retention horizon, not the caller's window: the monitor is a
        process singleton but ``slo.windowS`` is per-session conf, so
        one session's short window must not destroy the history a
        longer window (or a later conf change) still needs."""
        with self._lock:
            while self._samples and \
                    self._samples[0][0] < now - _RETENTION_S:
                self._samples.popleft()
            cut = now - window_s
            return [s for s in self._samples if s[0] >= cut]

    def due(self, now: Optional[float] = None) -> bool:
        t = now if now is not None else time.monotonic()
        with self._lock:
            if t - self._last_eval_s < _EVAL_INTERVAL_S:
                return False
            self._last_eval_s = t
            return True

    # ------------------------------------------------------------------
    # Evaluation.
    # ------------------------------------------------------------------

    def evaluate(self, session, now: Optional[float] = None,
                 emit: bool = True) -> dict:
        """Evaluate the governing session's armed objectives over its
        window; emit SloBreachEvent per healthy→breached transition.
        Returns the health verdict dict."""
        t = now if now is not None else time.monotonic()
        conf = session.hs_conf
        window_s = conf.telemetry_slo_window_s()
        min_count = conf.telemetry_slo_min_count()
        samples = self._window(window_s, t)
        n = len(samples)
        lat = sorted(s[1] for s in samples)
        errors = sum(1 for s in samples if s[2])
        degraded = sum(1 for s in samples if s[3])
        p99 = percentile(lat, 0.99) if lat else None
        objectives = {}
        armed = (
            (OBJECTIVE_P99, conf.telemetry_slo_p99_ms(), p99),
            (OBJECTIVE_ERROR_RATE, conf.telemetry_slo_error_rate(),
             (errors / n) if n else None),
            (OBJECTIVE_DEGRADE_RATE, conf.telemetry_slo_degrade_rate(),
             (degraded / n) if n else None),
        )
        healthy = True
        for name, threshold, observed in armed:
            is_armed = threshold > 0
            breached = bool(
                is_armed and n >= min_count and observed is not None
                and observed > threshold)
            objectives[name] = {
                "armed": is_armed,
                "threshold": threshold if is_armed else None,
                "observed": observed,
                "breached": breached,
            }
            if breached:
                healthy = False
            # Edge state is per (objective, threshold) and updates only
            # for ARMED evaluations: the monitor is a process singleton
            # while thresholds are per-session conf, so neither a
            # disarmed session nor a session with a DIFFERENT armed
            # threshold can reset another session's breach edge and
            # turn one continuous incident into a stream of "new"
            # breaches.
            if is_armed:
                edge = (name, float(threshold))
                with self._lock:
                    was = self._breached.get(edge, False)
                    if len(self._breached) > 256 and edge not in \
                            self._breached:
                        # A threshold-scanning caller must not grow the
                        # edge table without bound.
                        self._breached.clear()
                    self._breached[edge] = breached
                if breached and not was:
                    get_registry().counter_add(MN.SLO_BREACHES)
                    if emit:
                        _emit_breach(session, name, threshold, observed,
                                     window_s, n)
        return {
            "healthy": healthy,
            "window_s": window_s,
            "count": n,
            "errors": errors,
            "degraded": degraded,
            "objectives": objectives,
        }

    def live_p99_ms(self, now: Optional[float] = None) -> Optional[float]:
        """Cached p99 of the LIVE ``query.latency_ms`` histogram (its
        sliding window, not this monitor's hour-long retention — a
        cold-start spike must age out of the adaptive threshold the way
        the docs promise), cheap enough for the per-query tail-keep
        check."""
        t = now if now is not None else time.monotonic()
        with self._lock:
            if self._p99_cache_t and t - self._p99_cache_t < _P99_CACHE_S:
                return self._p99_cache
            self._p99_cache_t = t
        snap = get_registry().histogram(MN.QUERY_LATENCY_MS).snapshot()
        p99 = snap.get("p99") \
            if snap.get("count", 0) >= _ADAPTIVE_MIN_SAMPLES else None
        with self._lock:
            self._p99_cache = p99
        return p99


def _emit_breach(session, objective: str, threshold: float,
                 observed, window_s: float, count: int) -> None:
    try:
        from .events import SloBreachEvent
        from .logging import get_logger
        get_logger(session.hs_conf.event_logger_class()).log_event(
            SloBreachEvent(
                message=(f"SLO breach: {objective} observed "
                         f"{observed:.4g} > objective {threshold:g} "
                         f"over {window_s:g}s ({count} queries)"),
                objective=objective, threshold=threshold,
                observed=float(observed), window_s=window_s,
                count=count))
    except Exception:
        pass  # observability must never fail a query


_MONITOR: Optional[SloMonitor] = None
_MONITOR_LOCK = threading.Lock()


def get_monitor() -> SloMonitor:
    """THE process SLO monitor (shared like the metrics registry)."""
    global _MONITOR
    if _MONITOR is None:
        with _MONITOR_LOCK:
            if _MONITOR is None:
                _MONITOR = SloMonitor()
    return _MONITOR


def observe_query(session, latency_ms: float, error: bool = False,
                  degraded: bool = False) -> None:
    """The per-query feed (Session.execute's finally): the live
    query-latency histogram plus the SLO window, with a rate-limited
    evaluation so breaches surface without anyone polling health()."""
    try:
        conf = session.hs_conf
        if conf.telemetry_metrics_enabled():
            get_registry().histogram(MN.QUERY_LATENCY_MS).record(
                latency_ms)
        # The window feeds BOTH the SLO objectives and the trace
        # sampler's adaptive tail-keep threshold, so it records
        # regardless of slo.enabled (bounded deque, one lock+append);
        # slo.enabled gates only the objective evaluation.
        mon = get_monitor()
        mon.record(latency_ms, error, degraded)
        if not conf.telemetry_slo_enabled():
            return
        if mon.due():
            mon.evaluate(session)
    except Exception:
        pass  # observability must never fail a query


def health(session) -> dict:
    """Evaluate now and return the verdict (Hyperspace.health)."""
    return get_monitor().evaluate(session)


def adaptive_slow_threshold_ms() -> Optional[float]:
    """The tail-keep latency threshold when ``tailSlowMs`` is auto (0):
    2x the live query-latency p99, None until the window is populated
    enough to mean anything."""
    p99 = get_monitor().live_p99_ms()
    if p99 is None:
        return None
    return p99 * _ADAPTIVE_FACTOR
