"""Anomaly flight recorder: bounded process-wide rings of recent
observability, dumpable as one Perfetto bundle.

Production incidents are diagnosed from what the process REMEMBERS, not
from what a developer re-runs: this module keeps small, hard-bounded
rings of (a) recently retained span-tree traces (telemetry/trace.py
hands every kept trace in via ``finish_root``), (b) recent telemetry
events (every ``HyperspaceEvent`` construction lands here — events are
built at their emit sites), (c) anomalies, and (d) periodic metrics
snapshots. ``dump()`` fuses them into one Chrome-trace-event /
Perfetto-compatible JSON document: span "X" events on a wall-clock
timeline plus instant ("i") markers for events and anomalies, with the
metrics snapshots riding in ``otherData``.

**Anomaly triggers** double as the tail-keep signal for trace sampling:
``note_anomaly`` marks the ACTIVE trace keep-worthy
(:func:`~.trace.keep_active`) so the trace of exactly the unlucky query
survives a negative sample coin, appends to the anomaly ring, bumps the
``flight_recorder.anomalies`` counter, and forces a metrics snapshot
(rate-limited). The classifier in :func:`note_event` recognizes:
QueryCancelledEvent (deadline breach), fault-driven
DistributedFallbackEvent, RetryEvent exhaustion (any RetryEvent marks
keep; only exhaustion is an anomaly), spill-corrupt cache misses, and
SloBreachEvent; robustness/recovery.py reports crash-recovery sweeps
explicitly.

Ring sizes are constants (events/anomalies/snapshots) or conf
(``telemetry.flightRecorder.maxTraces``); everything is O(ring) memory
by construction, so the recorder is safe to leave on in production —
which is the point.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Optional

from . import metric_names as MN
from .metrics import get_registry

_MAX_EVENTS = 512
_MAX_ANOMALIES = 128
_MAX_SNAPSHOTS = 8
_DEFAULT_MAX_TRACES = 32
# Anomalies force a metrics snapshot at most this often; healthy-path
# snapshots ride trace retention at the longer periodic interval.
_ANOMALY_SNAPSHOT_S = 1.0
_PERIODIC_SNAPSHOT_S = 30.0


class FlightRecorder:
    def __init__(self, max_traces: int = _DEFAULT_MAX_TRACES):
        self._lock = threading.Lock()
        self._traces: deque = deque(maxlen=max(int(max_traces), 1))
        self._events: deque = deque(maxlen=_MAX_EVENTS)
        self._anomalies: deque = deque(maxlen=_MAX_ANOMALIES)
        self._snapshots: deque = deque(maxlen=_MAX_SNAPSHOTS)
        self._last_snapshot_s = 0.0
        # Cumulative totals (ring depths alone hide churn).
        self.trace_count = 0
        self.event_count = 0
        self.anomaly_count = 0

    # ------------------------------------------------------------------
    # Feeds.
    # ------------------------------------------------------------------

    def note_trace(self, tr, cap: Optional[int] = None) -> None:
        """One retained trace (called by trace.finish_root). ``cap``
        re-sizes the ring when the governing conf changed."""
        with self._lock:
            if cap is not None and cap != self._traces.maxlen:
                self._traces = deque(self._traces, maxlen=max(cap, 1))
            self._traces.append(tr)
            self.trace_count += 1
        self._maybe_snapshot(_PERIODIC_SNAPSHOT_S)

    def note_event(self, name: str, message: str, trace_id: str,
                   span_id: str) -> None:
        with self._lock:
            self._events.append({
                "name": name, "message": message,
                "trace_id": trace_id, "span_id": span_id,
                "wall_ms": int(time.time() * 1000),
            })
            self.event_count += 1

    def note_anomaly(self, kind: str, detail: str = "",
                     trace_id: str = "") -> None:
        with self._lock:
            self._anomalies.append({
                "kind": kind, "detail": detail, "trace_id": trace_id,
                "wall_ms": int(time.time() * 1000),
            })
            self.anomaly_count += 1
        get_registry().counter_add(MN.FLIGHT_ANOMALIES)
        self._maybe_snapshot(_ANOMALY_SNAPSHOT_S)

    def _maybe_snapshot(self, min_interval_s: float) -> None:
        now = time.monotonic()
        with self._lock:
            if now - self._last_snapshot_s < min_interval_s:
                return
            self._last_snapshot_s = now
        # Snapshot OUTSIDE the ring lock: collectors take their own
        # locks (io pool, program bank, frontends).
        snap = get_registry().snapshot()
        with self._lock:
            self._snapshots.append({
                "wall_ms": int(time.time() * 1000), "metrics": snap})

    # ------------------------------------------------------------------
    # Surfaces.
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        """The ``flight_recorder`` collector payload."""
        with self._lock:
            return {
                "traces": len(self._traces),
                "events": len(self._events),
                "anomalies": len(self._anomalies),
                "snapshots": len(self._snapshots),
                "trace_total": self.trace_count,
                "event_total": self.event_count,
                "anomaly_total": self.anomaly_count,
            }

    def traces(self) -> list:
        with self._lock:
            return list(self._traces)

    def anomalies(self) -> list:
        with self._lock:
            return list(self._anomalies)

    def dump(self) -> dict:
        """One Perfetto/chrome://tracing-loadable document over every
        ring: retained traces' spans as complete ("X") events on a
        shared wall-clock timeline (each stamped with its trace_id),
        events/anomalies as instant ("i") markers, metrics snapshots +
        the anomaly log in ``otherData``."""
        pid = os.getpid()
        with self._lock:
            traces = list(self._traces)
            events = list(self._events)
            anomalies = list(self._anomalies)
            snapshots = list(self._snapshots)
        anchor_ms = min(
            [tr.created_wall_ms for tr in traces]
            + [e["wall_ms"] for e in events]
            + [a["wall_ms"] for a in anomalies]
            + [int(time.time() * 1000)])
        trace_events = []
        for tr in traces:
            base_us = (tr.created_wall_ms - anchor_ms) * 1000.0
            trace_events.extend(
                tr.span_events(base_us=base_us, with_trace_id=True))
        for e in events:
            trace_events.append({
                "name": e["name"], "cat": "hyperspace.event", "ph": "i",
                "ts": round((e["wall_ms"] - anchor_ms) * 1000.0, 3),
                "pid": pid, "tid": 0, "s": "p",
                "args": {"message": e["message"],
                         "trace_id": e["trace_id"],
                         "span_id": e["span_id"]},
            })
        for a in anomalies:
            trace_events.append({
                "name": f"anomaly:{a['kind']}", "cat": "hyperspace.anomaly",
                "ph": "i",
                "ts": round((a["wall_ms"] - anchor_ms) * 1000.0, 3),
                "pid": pid, "tid": 0, "s": "p",
                "args": {"detail": a["detail"],
                         "trace_id": a["trace_id"]},
            })
        return {
            "traceEvents": trace_events,
            "displayTimeUnit": "ms",
            "otherData": {
                "anchor_wall_ms": anchor_ms,
                "trace_ids": [tr.trace_id for tr in traces],
                "anomalies": anomalies,
                "metric_snapshots": snapshots,
                "stats": self.stats(),
            },
        }


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_recorder() -> FlightRecorder:
    """THE process flight recorder (shared like the metrics registry)."""
    global _RECORDER
    if _RECORDER is None:
        with _RECORDER_LOCK:
            if _RECORDER is None:
                _RECORDER = FlightRecorder()
    return _RECORDER


def _recorder_stats() -> dict:
    return get_recorder().stats()


get_registry().register_collector(MN.COLLECTOR_FLIGHT_RECORDER,
                                  _recorder_stats)


def note_anomaly(kind: str, detail: str = "") -> None:
    """Record one anomaly AND mark the active trace tail-keep — the one
    shared entry point every anomaly site funnels through."""
    from . import trace as _trace
    _trace.keep_active(kind)
    tid, _sid = _trace.active_ids()
    get_recorder().note_anomaly(kind, detail, trace_id=tid)


def note_event(event) -> None:
    """Event-construction hook (HyperspaceEvent.__post_init__): ring the
    event, then classify the anomaly/tail-keep signals."""
    name = type(event).__name__
    get_recorder().note_event(
        name, getattr(event, "message", ""),
        getattr(event, "trace_id", ""), getattr(event, "span_id", ""))
    if name == "RetryEvent":
        # Any retried sequence makes the query tail-keep-worthy; only
        # exhaustion is an anomaly.
        from . import trace as _trace
        _trace.keep_active("retry")
        if not getattr(event, "succeeded", True):
            note_anomaly("retry.exhausted", getattr(event, "message", ""))
    elif name == "QueryCancelledEvent":
        note_anomaly("query.cancelled", getattr(event, "message", ""))
    elif name == "DistributedFallbackEvent":
        # Structural fallbacks (small scans, unsupported shapes) are
        # ROUTINE on a small mesh; only the fault-absorbing degradation
        # ladder — the "fault: ..." reason prefix, the producing
        # convention — is an anomaly (a substring test would trip on
        # e.g. "default" inside arbitrary error text).
        if getattr(event, "reason", "").startswith("fault"):
            note_anomaly("distributed.fallback",
                         getattr(event, "message", ""))
    elif name == "ResultCacheMissEvent":
        if getattr(event, "reason", "") == "spill-corrupt":
            note_anomaly("spill.corrupt", getattr(event, "message", ""))
    elif name == "SloBreachEvent":
        note_anomaly("slo.breach", getattr(event, "message", ""))
